"""Window function execution (reference:
sql/core/.../execution/window/WindowExec.scala:87 and
WindowFunctionFrame.scala).

The reference streams each partition through per-frame processors row by
row. On a TPU the whole operator is one static-shape program: sort rows
by (partition, order) once, derive per-row segment/peer geometry with
scans, compute every window column as vectorized prefix-sum / gather
arithmetic, and scatter results back to the original row order. Output
capacity equals input capacity — no sizing syncs, fully fusable into the
surrounding stage.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_tpu import types as T
from spark_tpu.expr import compiler as C
from spark_tpu.expr import expressions as E
from spark_tpu.expr.compiler import Env, TV
from spark_tpu.physical import kernels as K
from spark_tpu.physical import operators as P
from spark_tpu.physical.operators import Pipe
from spark_tpu.types import Field, Schema

_BIG = jnp.iinfo(jnp.int64).max


def _seg_scan_max(seg: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Segmented inclusive running max (resets at segment changes)."""
    return K._seg_scan(seg, x, jnp.maximum)


def _seg_scan_min(seg: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    return K._seg_scan(seg, x, jnp.minimum)


@dataclass(eq=False)
class WindowExec(P.PhysicalPlan):
    """Compute all window columns for one (partition_by, order_by) spec
    group; multiple spec groups stack as multiple WindowExecs."""

    window_exprs: Tuple[E.Alias, ...]
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = list(cs.fields)
        for e in self.window_exprs:
            w = E.strip_alias(e)
            fields.append(Field(e.name, e.data_type(cs), e.nullable(cs),
                                E.window_dictionary(w, cs)))
        return Schema(tuple(fields))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        cap = pipe.capacity
        out_cols = dict(pipe.cols)
        out_order = list(pipe.order)

        # group exprs by identical (partition, order) spec — one sort per
        # distinct spec (the reference's WindowExec also requires one
        # sort per child ordering)
        groups: Dict[tuple, List[E.Alias]] = {}
        for alias in self.window_exprs:
            w = E.strip_alias(alias)
            key = (tuple(E.expr_key(p) for p in w.partition_by),
                   tuple(E.expr_key(o) for o in w.order_by))
            groups.setdefault(key, []).append(alias)

        for aliases in groups.values():
            spec = E.strip_alias(aliases[0])
            self._compute_group(pipe, spec, aliases, out_cols, out_order,
                                cap)
        return Pipe(out_cols, pipe.mask, out_order)

    # -- one (partition, order) spec group ------------------------------------

    def _compute_group(self, pipe: Pipe, spec: E.WindowExpr,
                       aliases: List[E.Alias], out_cols: Dict[str, TV],
                       out_order: List[str], cap: int) -> None:
        env = pipe.env()
        cs = self.child.schema
        part_tvs = [C.evaluate(p, env) for p in spec.partition_by]
        order_tvs = [(C.evaluate(o.child, env), o) for o in spec.order_by]

        sort_keys = [K.SortKey(tv.data, tv.validity, True, True)
                     for tv in part_tvs]
        sort_keys += [K.SortKey(tv.data, tv.validity, o.ascending,
                                o.nulls_first_resolved)
                      for tv, o in order_tvs]
        perm = (K.lexsort_permutation(sort_keys, pipe.mask) if sort_keys
                else K.compaction_permutation(pipe.mask))
        live = pipe.mask[perm]
        pos = jnp.arange(cap, dtype=jnp.int64)

        # partition segments over sorted order
        if part_tvs:
            skeys = [(tv.data[perm],
                      None if tv.validity is None else tv.validity[perm])
                     for tv in part_tvs]
            seg, _ = K.group_ids_from_sorted(skeys, live)
        else:
            # one global partition; dead rows (sorted to the back) get
            # their own segment so they never affect live geometry
            seg = jnp.where(live, 0, 1)
        seg = seg.astype(jnp.int32)

        # per-row partition geometry; seg is MONOTONE in sorted space so
        # boundaries come from binary search, not scatter reductions
        # (scatter is pathologically slow on TPU — see kernels.py)
        seg_start = K.searchsorted(seg, seg, side="left")
        seg_end = K.searchsorted(seg, seg, side="right") - 1
        # dead rows sort to the back; the last live row of the trailing
        # live segment is found by capping with the live count
        n_live = jnp.sum(live.astype(jnp.int64))
        seg_end = jnp.minimum(seg_end, jnp.maximum(n_live - 1, 0))
        rn0 = pos - seg_start  # 0-based row number within partition

        # peer groups: rows equal on ALL order keys (and partition)
        if order_tvs:
            part_change = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), seg[1:] != seg[:-1]])
            okeys = [(tv.data[perm],
                      None if tv.validity is None else tv.validity[perm])
                     for tv, _ in order_tvs]
            ochange = jnp.zeros((cap,), jnp.bool_)
            for data, validity in okeys:
                if jnp.issubdtype(data.dtype, jnp.floating):
                    # NaN != NaN would split each NaN row into its own
                    # peer group; all NaNs are mutual peers (they sort
                    # together, greatest) — canonicalize before comparing
                    data = jnp.where(jnp.isnan(data),
                                     jnp.finfo(data.dtype).max, data)
                neq = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), data[1:] != data[:-1]])
                if validity is not None:
                    vneq = jnp.concatenate(
                        [jnp.ones((1,), jnp.bool_),
                         validity[1:] != validity[:-1]])
                    both_null = jnp.concatenate(
                        [jnp.zeros((1,), jnp.bool_),
                         (~validity[1:]) & (~validity[:-1])])
                    neq = (neq & ~both_null) | vneq
                ochange = ochange | neq
            head = part_change | ochange
        else:
            head = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), seg[1:] != seg[:-1]])
        peer_id = (jnp.cumsum(head.astype(jnp.int32)) - 1)
        peer_last = K.searchsorted(peer_id, peer_id, side="right") - 1
        peer_last = jnp.minimum(peer_last, jnp.maximum(n_live - 1, 0))

        for alias in aliases:
            w = E.strip_alias(alias)
            data, validity, dictionary = self._eval_func(
                w, env, perm, live, pos, seg, seg_start, seg_end, rn0,
                head, peer_last, cap, cs)
            # scatter back to original row order
            odata = jnp.zeros((cap,), dtype=data.dtype).at[perm].set(data)
            ovalid = (None if validity is None else
                      jnp.zeros((cap,), jnp.bool_).at[perm].set(validity))
            dt = w.data_type(cs)
            out_cols[alias.name] = TV(odata, ovalid, dt, dictionary)
            out_order.append(alias.name)

    # -- individual functions (all in sorted coordinates) ---------------------

    def _eval_func(self, w: E.WindowExpr, env: Env, perm, live, pos, seg,
                   seg_start, seg_end, rn0, head, peer_last, cap,
                   cs) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        fn = w.func
        if isinstance(fn, E.RowNumber):
            return (rn0 + 1).astype(jnp.int32), None, None
        if isinstance(fn, E.Rank):
            if fn.dense:
                ch = jnp.cumsum(head.astype(jnp.int64))
                dense = ch - ch[jnp.clip(seg_start, 0, cap - 1)] + 1
                return dense.astype(jnp.int32), None, None
            hp = jnp.where(head, pos, 0)
            run = _seg_scan_max(seg, hp)
            return (run - seg_start + 1).astype(jnp.int32), None, None
        if isinstance(fn, E.NTile):
            cnt = seg_end - seg_start + 1
            tile = (rn0 * fn.n) // jnp.maximum(cnt, 1) + 1
            return tile.astype(jnp.int32), None, None
        if isinstance(fn, E.LagLead):
            tv = C.evaluate(fn.child, env)
            sdata = tv.data[perm]
            svalid = (None if tv.validity is None else tv.validity[perm])
            off = fn.offset if fn.lead else -fn.offset
            src = pos + off
            in_part = (src >= seg_start) & (src <= seg_end)
            srcc = jnp.clip(src, 0, cap - 1)
            data = sdata[srcc]
            valid = in_part
            if svalid is not None:
                valid = valid & svalid[srcc]
            if fn.default is not None:
                dtv = C.evaluate(fn.default, env)
                dval = (dtv.data if dtv.data.ndim == 0
                        else dtv.data[0])
                data = jnp.where(in_part, data,
                                 jnp.asarray(dval, dtype=data.dtype))
                # the default can itself be NULL (lag(v, 1, NULL))
                dvalid = dtv.valid_or_true(cap)
                dv0 = dvalid if dvalid.ndim == 0 else dvalid[0]
                valid = valid | (~in_part & dv0)
            return data, valid, tv.dictionary
        if isinstance(fn, E.AggregateExpression):
            return self._framed_agg(w, fn, env, perm, live, pos, seg,
                                    seg_start, seg_end, peer_last, cap, cs)
        raise NotImplementedError(f"window function {fn}")

    def _frame_bounds(self, w: E.WindowExpr, pos, seg_start, seg_end,
                      peer_last, env=None, perm=None, cap=None):
        """Per-row inclusive [lo, hi] frame positions in sorted space."""
        frame = w.frame
        if frame is None:
            if w.order_by:
                # SQL default: RANGE UNBOUNDED PRECEDING .. CURRENT ROW
                # (current row's peers included)
                return seg_start, peer_last
            return seg_start, seg_end
        mode, start, end = frame
        if mode == "rows":
            lo = seg_start if start is None else jnp.maximum(
                seg_start, pos + start)
            hi = seg_end if end is None else jnp.minimum(seg_end, pos + end)
            return lo, hi
        # range mode: unbounded / current-row shapes need no key values
        lo = seg_start if start is None else None
        hi = peer_last if (end == 0) else (seg_end if end is None else None)
        if lo is not None and hi is not None:
            return lo, hi
        # value offsets: per-row bounded binary search over the ORDER
        # key within each partition's sorted run (reference:
        # window/WindowExec.scala RangeBoundOrdering / BoundOrdering —
        # two searchsorteds per row, segment-bounded)
        if len(w.order_by) != 1:
            raise NotImplementedError(
                "RANGE frames with value offsets require exactly one "
                "ORDER BY key (the reference has the same restriction)")
        so = w.order_by[0]
        tv = C.evaluate(so.child, env)
        if isinstance(tv.dtype, T.StringType):
            raise NotImplementedError(
                "RANGE value offsets need a numeric/date ORDER key")
        scale = (10 ** tv.dtype.scale
                 if isinstance(tv.dtype, T.DecimalType) else 1)
        key = tv.data[perm]
        integral = jnp.issubdtype(key.dtype, jnp.integer)
        nan_mask = None
        if integral:
            # stay in the key's EXACT integer dtype: a float64 cast
            # loses distinct int64/decimal keys above 2^53 and corrupts
            # frame bounds silently
            key = key.astype(jnp.int64)
            off_lo = None if start is None else int(round(start * scale))
            off_hi = None if end is None else int(round(end * scale))
            neg_inf = jnp.iinfo(jnp.int64).min
            pos_inf = jnp.iinfo(jnp.int64).max
        else:
            key = key.astype(jnp.float64)
            off_lo = None if start is None else float(start) * scale
            off_hi = None if end is None else float(end) * scale
            neg_inf = -jnp.inf
            pos_inf = jnp.inf
            # NaN compares false on both sides of a binary search. It
            # sorts greatest but is a DISTINCT peer group from NULLs, so
            # map it to the largest FINITE float: the +/-inf null
            # sentinel then stays strictly beyond it under both sort
            # directions (desc negates this to -finfo.max, still inside
            # the -inf nulls-first sentinel).
            nan_mask = jnp.isnan(key)
            key = jnp.where(nan_mask, jnp.finfo(jnp.float64).max, key)
        if not so.ascending:
            key = -key  # DESC: PRECEDING means larger values
        if tv.validity is not None:
            # null keys are mutual peers; an infinity sentinel keeps
            # them matching (only) each other under +/- offsets. Its
            # SIGN must agree with where the sort PLACED the nulls in
            # the partition run (nulls-first -> below every effective
            # key; nulls-last -> above), or the run is non-monotone and
            # the binary search returns garbage bounds.
            sval = tv.validity[perm]
            sent = neg_inf if so.nulls_first_resolved else pos_inf
            key = jnp.where(sval, key, sent)
        def target(off):
            # sentinel rows keep their sentinel target (int64 sentinel
            # +/- offset would WRAP and break null-peer matching; a NaN
            # row's frame is exactly its NaN peers — NaN+off is NaN)
            fixed = (key == neg_inf) | (key == pos_inf)
            if nan_mask is not None:
                fixed = fixed | nan_mask
            return jnp.where(fixed, key, key + off)

        if lo is None:
            lo = self._bounded_search(
                key, target(off_lo), seg_start, seg_end, cap,
                side="left")
        if hi is None:
            hi = self._bounded_search(
                key, target(off_hi), seg_start, seg_end, cap,
                side="right") - 1
        return lo, hi

    @staticmethod
    def _bounded_search(sorted_key, targets, seg_start, seg_end, cap,
                        side: str):
        """Vectorized per-row binary search of targets[i] inside the
        row's own partition run [seg_start[i], seg_end[i]] (the global
        array is only sorted WITHIN partitions). ~log2(cap) gather
        rounds, fully traced."""
        import math as _math

        lo = seg_start
        hi = seg_end + 1  # exclusive
        for _ in range(max(1, _math.ceil(_math.log2(max(2, cap)))) + 1):
            mid = (lo + hi) // 2
            mv = sorted_key[jnp.clip(mid, 0, cap - 1)]
            go_right = (mv < targets) if side == "left" else \
                (mv <= targets)
            within = mid < hi
            lo = jnp.where(within & go_right, mid + 1, lo)
            hi = jnp.where(within & ~go_right, mid, hi)
        return lo

    def _framed_agg(self, w, fn, env, perm, live, pos, seg, seg_start,
                    seg_end, peer_last, cap, cs):
        lo, hi = self._frame_bounds(w, pos, seg_start, seg_end, peer_last,
                                    env=env, perm=perm, cap=cap)
        child = fn.child if getattr(fn, "child", None) is not None else None
        if child is not None:
            tv = C.evaluate(child, env)
            sdata = tv.data[perm]
            ok = live & tv.valid_or_true(cap)[perm]
        else:  # COUNT(*)
            sdata = jnp.ones((cap,), jnp.int64)
            ok = live

        loc = jnp.clip(lo, 0, cap - 1)
        hic = jnp.clip(hi, 0, cap - 1)
        empty = hi < lo

        def ranged_sum(x):
            """Segmented inclusive prefix sums -> arbitrary [lo, hi]."""
            contrib = jnp.where(ok, x, jnp.zeros((), x.dtype))
            csum = jnp.cumsum(contrib)
            pre_lo = jnp.where(lo > 0, csum[jnp.clip(lo - 1, 0, cap - 1)],
                               jnp.zeros((), csum.dtype))
            return csum[hic] - pre_lo

        cnt = ranged_sum(jnp.ones((cap,), jnp.int64))
        cnt = jnp.where(empty, 0, cnt)
        if isinstance(fn, E.Count):
            return cnt.astype(jnp.int64), None, None
        dt = fn.data_type(cs)
        if isinstance(fn, E.Sum):
            acc = sdata.astype(C._jnp_dtype(dt))
            s = jnp.where(empty, 0, ranged_sum(acc))
            return s, cnt > 0, None
        if isinstance(fn, E.Avg):
            if isinstance(tv.dtype, T.DecimalType):
                from spark_tpu.physical.operators import decimal_avg

                total = jnp.where(empty, 0, ranged_sum(sdata))
                data, _ = decimal_avg(total, cnt, tv.dtype)
                return data, cnt > 0, None
            s = jnp.where(empty, 0, ranged_sum(sdata.astype(jnp.float64)))
            return s / jnp.maximum(cnt, 1), cnt > 0, None
        if isinstance(fn, (E.Min, E.Max)):
            is_min = isinstance(fn, E.Min)
            sent = (K._pos_sentinel(sdata.dtype) if is_min
                    else K._neg_sentinel(sdata.dtype))
            masked = jnp.where(ok, sdata, sent)
            # prefix covers whole-partition too (hi = seg_end there);
            # scatter-based segment_min/max is never worth it (kernels.py)
            prefix = w.frame is None or w.frame[1] is None
            if prefix:
                scan = _seg_scan_min if is_min else _seg_scan_max
                run = scan(seg, masked)
                out = run[hic]  # hi is peer_last/seg_end: runs forward
                return out, cnt > 0, tv.dictionary
            # bounded frame: SPARSE-TABLE range min/max — log2(cap)
            # doubling-window levels, then each row's [lo, hi] answers
            # as the min of two overlapping power-of-two windows
            # (O(n log n) build fully vectorized; the reference walks
            # each frame row-by-row, WindowExec SlidingWindowFunctionFrame)
            if cap > (1 << 22):
                raise NotImplementedError(
                    "sliding min/max over > 4M-row batches (sparse "
                    "table would exceed the window memory budget)")
            import math as _math

            levels = max(1, _math.ceil(_math.log2(max(2, cap))))
            combine = jnp.minimum if is_min else jnp.maximum
            tabs = [masked]
            for k in range(1, levels + 1):
                half = 1 << (k - 1)
                prev = tabs[-1]
                shifted = jnp.concatenate(
                    [prev[half:], jnp.full((half,), sent, prev.dtype)])
                tabs.append(combine(prev, shifted))
            stacked = jnp.stack(tabs)  # (levels+1, cap)
            length = jnp.maximum(hic - lo + 1, 1).astype(jnp.int64)
            kk = (63 - jax.lax.clz(length)).astype(jnp.int32)
            kk = jnp.clip(kk, 0, levels)
            span = jnp.left_shift(jnp.ones((), jnp.int64), kk)
            a = stacked[kk, jnp.clip(lo, 0, cap - 1)]
            b = stacked[kk, jnp.clip(hic - span + 1, 0, cap - 1)]
            out = combine(a, b)
            return out, cnt > 0, tv.dictionary
        raise NotImplementedError(f"window aggregate {fn}")

    def node_string(self):
        return f"Window[{', '.join(str(e) for e in self.window_exprs)}]"

    def plan_key(self):
        return ("Window",
                tuple(E.expr_key(e) for e in self.window_exprs),
                self.child.plan_key())

"""Asynchronous chunk pipeline for out-of-HBM execution.

The serial chunk loop (decode chunk -> filter host-side -> ship ->
compute -> repeat) leaves the TPU idle during every decode/transfer and
the host idle during every device step — fatal on a ~34 MB/s tunneled
host->device link. This module is the producer/consumer overlap Spark's
shuffle fetch path gets from ShuffleBlockFetcherIterator's in-flight
request window (core/.../storage/ShuffleBlockFetcherIterator.scala:78):
a background producer thread pulls the next chunks off the parquet
stream, applies the host-side semi/Bloom key filters, narrows them, and
initiates the host->device transfer, while the caller thread merges the
previous chunks' partials on device.

Determinism: ONE producer thread feeding a FIFO queue, consumed in
source order — the device merge order is identical to the serial loop
at every depth, so float results are byte-identical (the acceptance
contract of tests/test_out_of_core.py's depth-sweep tests).

Bounds: ``spark.tpu.pipelineDepth`` caps the number of prepared chunks
in flight; ``spark.tpu.prefetchBytesMax`` caps their bytes (the
producer stalls before decoding the next chunk once in-flight bytes
reach the budget — at least one chunk is always admitted so a budget
smaller than a chunk degrades to serial instead of deadlocking).

``depth == 0`` runs the classic serial loop on the caller thread with
the same staging/timers, so the two paths share one code shape.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import deadline, faults, metrics, trace
from spark_tpu.metrics import PipelineStats

CHUNK_RETRY_ATTEMPTS = CF.register(
    "spark.tpu.chunkRetryAttempts", 3,
    "Bounded attempts for one chunk's decode/prepare/transfer in the "
    "out-of-HBM pipeline before the failure is relayed to the consumer "
    "(reference analogue: ShuffleBlockFetcherIterator retrying one "
    "block fetch instead of failing the stage).", int)

_SENTINEL = object()


class _Err:
    """Producer-side exception carrier (re-raised on the consumer)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class ChunkPipeline:
    """Bounded producer/consumer pipeline over an iterator of work items.

    ``source`` yields raw work items (arrow tables, partition ids);
    pulling the next item is timed as the *decode* stage. ``prepare``
    turns one item into a consumable result (timing its own filter/
    transfer stages against ``stats``) or returns None to skip the item
    (empty / fully filtered chunk). ``nbytes_of(prepared)`` feeds the
    in-flight byte budget.

    With ``depth >= 1`` the producer thread starts at construction, so
    chunk decode can overlap work the caller does before it starts
    consuming (e.g. sidecar materialization). Iterate the pipeline to
    consume results in source order.
    """

    def __init__(self, source: Iterable[Any],
                 prepare: Callable[[Any], Optional[Any]],
                 *, depth: int, byte_budget: int,
                 stats: PipelineStats,
                 nbytes_of: Optional[Callable[[Any], int]] = None,
                 conf=None):
        self._source = iter(source)
        self._prepare = prepare
        self._depth = max(0, int(depth))
        self._budget = max(1, int(byte_budget))
        self._stats = stats
        self._nbytes = nbytes_of or (lambda prepared: 0)
        self._conf = conf
        self._retry_attempts = max(1, int(
            conf.get(CHUNK_RETRY_ATTEMPTS) if conf is not None
            else CHUNK_RETRY_ATTEMPTS.default))
        self._thread: Optional[threading.Thread] = None
        # capture the caller's span context so producer-side chunk
        # spans (pipeline.decode/transfer) join the query's trace even
        # though they run on the background thread; the caller's
        # deadline and retry budget cross the same thread boundary so
        # producer-side retries stay bounded by the query's pool and
        # stop when the caller's window closes
        self._trace_ctx = metrics.trace_context()
        self._deadline = deadline.current()
        from spark_tpu import recovery

        self._retry_budget = recovery.current_budget()
        if self._depth >= 1:
            self._queue: queue.Queue = queue.Queue(maxsize=self._depth)
            self._cond = locks.named_condition("pipeline.cond")
            self._inflight_bytes = 0
            self._inflight_chunks = 0
            self._stop = False
            self._thread = threading.Thread(
                target=self._produce, daemon=True, name="chunk-pipeline")
            self._thread.start()

    # ---- shared pull/prepare step with bounded per-chunk retry -------------

    def _next_prepared(self) -> Any:
        """Pull the next item and prepare it, retrying an individual
        chunk's decode/prepare/transfer up to chunkRetryAttempts times
        on transient failures before relaying the error — so one
        dropped transfer costs one chunk retry, not the whole query.
        Returns ``(prepared, size)``, ``None`` for a skipped chunk, or
        ``_SENTINEL`` at end of source.

        Retry safety: a generator that raised is exhausted, so a
        decode-phase failure is only retryable when it is an injected
        fault (which fires *before* the source is touched); once the
        item is in hand, ``prepare`` is pure and always retryable.
        """
        from spark_tpu import recovery

        st = self._stats
        item: Any = _SENTINEL  # sentinel doubles as "not yet pulled"
        for attempt in range(self._retry_attempts):
            try:
                if item is _SENTINEL:
                    with trace.span("pipeline.decode"), \
                            st.timed("decode"):
                        faults.inject("pipeline.decode", self._conf)
                        nxt = next(self._source, _SENTINEL)
                    if nxt is _SENTINEL:
                        return _SENTINEL
                    item = nxt
                with trace.span("pipeline.transfer"):
                    faults.inject("pipeline.transfer", self._conf)
                    prepared = self._prepare(item)
                if attempt:
                    metrics.record("fault_recovered", point="pipeline",
                                   how="chunk_retry", attempts=attempt)
                if prepared is None:
                    return None
                return (prepared, self._nbytes(prepared))
            except Exception as e:
                retryable = recovery.is_transient(e) and (
                    item is not _SENTINEL
                    or isinstance(e, faults.InjectedFault))
                if not retryable or attempt + 1 >= self._retry_attempts:
                    raise
                deadline.check("pipeline.chunk")
                if not recovery.retry_allowed("pipeline.chunk"):
                    raise recovery.RetryBudgetExhausted(
                        "pipeline.chunk", recovery.current_budget()) from e
                metrics.record("chunk_retry", attempt=attempt + 1,
                               error=repr(e))
                time.sleep(deadline.cap_sleep(
                    min(0.05 * 2 ** attempt, 0.5)))
        raise AssertionError("unreachable")  # loop always returns/raises

    # ---- serial path (depth == 0) -----------------------------------------

    def _iter_serial(self) -> Iterator[Any]:
        st = self._stats
        while True:
            got = self._next_prepared()
            if got is _SENTINEL:
                return
            if got is None:
                continue
            prepared, size = got
            st.note_inflight(size, 1)
            yield prepared

    # ---- threaded path -----------------------------------------------------

    def _produce(self) -> None:
        from spark_tpu import recovery

        with trace.attach(self._trace_ctx), \
                deadline.bind(self._deadline), \
                recovery.bind_budget(self._retry_budget):
            self._produce_traced()

    def _produce_traced(self) -> None:
        st = self._stats
        try:
            while True:
                # byte-budget gate BEFORE decoding the next chunk: once
                # in-flight bytes reach the budget, prefetch pauses
                # (but one chunk is always admitted)
                t0 = time.perf_counter()
                with self._cond:
                    while (not self._stop
                           and self._inflight_chunks > 0
                           and self._inflight_bytes >= self._budget):
                        # notify-driven: the consumer notifies on every
                        # chunk release and close(); the timeout is a
                        # liveness backstop only
                        self._cond.wait(0.5)
                    if self._stop:
                        return
                waited = (time.perf_counter() - t0) * 1e3
                if waited > 0.05:
                    st.add("stall_producer", waited)
                got = self._next_prepared()
                if got is _SENTINEL:
                    break
                if got is None:
                    continue
                prepared, size = got
                with self._cond:
                    self._inflight_bytes += size
                    self._inflight_chunks += 1
                    st.note_inflight(self._inflight_bytes,
                                     self._inflight_chunks)
                self._put((prepared, size))
            self._put(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            # a full queue is the steady state of an active pipeline, so
            # the error must be relayed with the stop-aware blocking put:
            # it delivers to an active consumer and bails out via _stop
            # if the consumer abandoned the iterator
            self._put(_Err(e))

    def _put(self, obj: Any) -> None:
        """queue.put that stays responsive to consumer abandonment."""
        t0 = time.perf_counter()
        while True:
            with self._cond:
                if self._stop:
                    return
            try:
                self._queue.put(obj, timeout=0.1)
                waited = (time.perf_counter() - t0) * 1e3
                if waited > 0.1:
                    self._stats.add("stall_producer", waited)
                return
            except queue.Full:
                continue

    def _iter_threaded(self) -> Iterator[Any]:
        st = self._stats
        try:
            while True:
                t0 = time.perf_counter()
                got = self._queue.get()
                waited = (time.perf_counter() - t0) * 1e3
                if waited > 0.05:
                    st.add("stall_consumer", waited)
                if got is _SENTINEL:
                    return
                if isinstance(got, _Err):
                    raise got.exc
                prepared, size = got
                try:
                    yield prepared
                finally:
                    with self._cond:
                        self._inflight_bytes -= size
                        self._inflight_chunks -= 1
                        self._cond.notify_all()
        finally:
            self.close()

    def __iter__(self) -> Iterator[Any]:
        if self._depth == 0:
            return self._iter_serial()
        return self._iter_threaded()

    def close(self) -> None:
        """Stop the producer (idempotent; called automatically when the
        consuming iterator finishes or is abandoned)."""
        if self._thread is None:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        # drain so a producer blocked on put() can observe _stop
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

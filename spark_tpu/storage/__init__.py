"""HBM-resident columnar storage.

The device-resident analogue of the reference's storage tier:
InMemoryRelation / CachedBatch backed by the UnifiedMemoryManager's
storage/execution split (reference:
sql/core/.../execution/columnar/InMemoryRelation.scala,
core/.../memory/UnifiedMemoryManager.scala:56). Materialized device
``Batch``es (dict-encoded int32 codes + validity — exactly what
``columnar/arrow.from_arrow`` produces) live in a byte-accounted
``MemoryStore`` keyed by scan/plan structural identity; storage and
execution share ONE HBM byte budget
(``spark.tpu.scheduler.hbmBudgetBytes``) through the
``UnifiedMemoryManager``: execution admission may evict unpinned
storage entries down to ``spark.tpu.storage.minBytes``, and storage
can never evict a running query's admission grant.
"""

from spark_tpu.storage.lru import LruDict
from spark_tpu.storage.store import MemoryStore, StoreEntry, pin_scope
from spark_tpu.storage.unified import UnifiedMemoryManager

__all__ = [
    "LruDict",
    "MemoryStore",
    "StoreEntry",
    "UnifiedMemoryManager",
    "pin_scope",
]

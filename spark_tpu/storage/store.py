"""The HBM-resident columnar store.

The device-side analogue of the reference's block-manager storage tier
for cached relations (reference: InMemoryRelation.scala CachedBatch +
storage/memory/MemoryStore.scala:93): entries are fully materialized
device ``Batch``es (dict-encoded int32 string codes + validity arrays,
exactly the layout ``columnar/arrow.from_arrow`` ships to HBM), keyed
by the scan/plan structural key, byte-accounted against the unified
HBM budget (unified.py) and evicted LRU when storage or execution
needs the room.

Pinning: a query that is reading an entry pins it for the duration of
its execution (``pin_scope`` wraps ``DataFrame._execute``); pinned
entries are never evicted, so the bytes a running query depends on are
never double-counted as reclaimable. Eviction drops the store's
reference only — device buffers free when the last reader releases
theirs, which is exactly what the pin protocol guarantees has
happened by the time the accounting says the bytes are back.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional

from spark_tpu import metrics

#: per-execution list of (store, key) pins, released when the query's
#: pin_scope exits; None outside any scope (gets then don't pin)
_PINS: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "spark_tpu_storage_pins", default=None)


@contextlib.contextmanager
def pin_scope() -> Iterator[None]:
    """Pin every store entry read inside the block until it exits —
    one scope per query execution. Reentrant: an inner scope (cached
    plan materialization running a sub-query) folds into the outer."""
    if _PINS.get() is not None:
        yield  # already inside a query's scope
        return
    token = _PINS.set([])
    try:
        yield
    finally:
        pins = _PINS.get()
        _PINS.reset(token)
        for store, key in pins or ():
            store.unpin(key)


class StoreEntry:
    __slots__ = ("key", "batch", "nbytes", "pins", "hits", "created_t",
                 "last_access_t")

    def __init__(self, key, batch, nbytes: int):
        self.key = key
        self.batch = batch
        self.nbytes = int(nbytes)
        self.pins = 0
        self.hits = 0
        self.created_t = time.time()
        self.last_access_t = self.created_t


def batch_nbytes(batch) -> int:
    """Device bytes of a store candidate; falls back to a schema-width
    estimate for batch-likes without ``device_nbytes`` (mesh-sharded
    results in tests)."""
    try:
        return int(batch.device_nbytes())
    except Exception:
        try:
            return int(batch.capacity) * 8 * max(
                1, len(batch.schema.names))
        except Exception:
            return 0


class MemoryStore:
    """Byte-accounted LRU cache of device batches, sharing its lock and
    byte budget with the UnifiedMemoryManager it registers on."""

    def __init__(self, manager):
        self._m = manager
        self._lock = manager.lock
        self._entries: "OrderedDict[Any, StoreEntry]" = OrderedDict()
        self._bytes = 0
        # counters (read under the shared lock)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.hit_bytes = 0
        self.evicted_bytes = 0
        self.put_bytes = 0
        self.rejected_puts = 0
        self._known: set = set()  # keys ever stored: a miss on one of
        # these is a recompute-after-evict, worth an event
        manager.attach_store(self)

    # -- accounting (manager reads these under the shared lock) --------------

    def bytes_used(self) -> int:
        return self._bytes

    def unpinned_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values()
                   if e.pins == 0)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    # -- the cache surface ---------------------------------------------------

    def get(self, key, pin: bool = False):
        """Return the cached batch or None. ``pin=True`` holds the
        entry against eviction until the enclosing ``pin_scope`` exits
        (no-op outside a scope)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                if key in self._known:
                    metrics.record("storage", phase="miss",
                                   key=_short(key))
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            e.last_access_t = time.time()
            self.hits += 1
            self.hit_bytes += e.nbytes
            if pin:
                self._pin_locked(key, e)
            metrics.record("storage", phase="hit", key=_short(key),
                           bytes=e.nbytes)
            return e.batch

    def put(self, key, batch, pin: bool = False) -> bool:
        """Insert a materialized batch; False when it cannot fit under
        the unified budget even after evicting the store's own LRU
        tail (the caller keeps using its batch — the entry is simply
        not retained, and stays recomputable)."""
        nbytes = batch_nbytes(batch)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                if pin:
                    self._pin_locked(key, e)
                return True
            if not self._m.reserve_storage(nbytes):
                self.rejected_puts += 1
                metrics.record("storage", phase="rejected",
                               key=_short(key), bytes=nbytes)
                return False
            e = StoreEntry(key, batch, nbytes)
            self._entries[key] = e
            self._bytes += nbytes
            self._known.add(key)
            self.put_bytes += nbytes
            if pin:
                self._pin_locked(key, e)
            metrics.record("storage", phase="put", key=_short(key),
                           bytes=nbytes, storage_bytes=self._bytes)
            return True

    def update(self, key, batch, pin: bool = False) -> bool:
        """Replace an entry's batch IN PLACE, re-accounting the byte
        delta under the unified budget — the materialized-view refresh
        path (a refreshed view keeps its key, pins, and LRU identity;
        only the bytes change). Growth must fit like any other storage
        reservation; when it cannot, the STALE entry is dropped rather
        than kept (serving stale bytes is worse than recomputing) and
        False is returned — the caller keeps using its batch, exactly
        the ``put`` rejection contract. Absent keys fall through to
        ``put``."""
        nbytes = batch_nbytes(batch)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                delta = nbytes - e.nbytes
                if delta > 0:
                    # hold the entry against the eviction pass the
                    # reservation may trigger (evicting the entry being
                    # updated would corrupt the accounting below)
                    e.pins += 1
                    try:
                        ok = self._m.reserve_storage(delta)
                    finally:
                        e.pins -= 1
                    if not ok:
                        self._entries.pop(key)
                        self._bytes -= e.nbytes
                        self.rejected_puts += 1
                        metrics.record(
                            "storage", phase="update_rejected",
                            key=_short(key), bytes=nbytes,
                            storage_bytes=self._bytes)
                        return False
                e.batch = batch
                e.nbytes = nbytes
                self._bytes += delta
                self.put_bytes += max(0, delta)
                e.last_access_t = time.time()
                self._entries.move_to_end(key)
                if pin:
                    self._pin_locked(key, e)
                metrics.record("storage", phase="update",
                               key=_short(key), bytes=nbytes,
                               delta=delta, storage_bytes=self._bytes)
                return True
        return self.put(key, batch, pin=pin)

    def remove(self, key) -> int:
        """Drop an entry regardless of LRU position (uncache); returns
        the bytes released. Pinned entries drop from the table too —
        the running reader keeps its reference; the accounting is
        released because uncache is an explicit owner decision."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return 0
            self._bytes -= e.nbytes
            metrics.record("storage", phase="uncache", key=_short(key),
                           bytes=e.nbytes, storage_bytes=self._bytes)
            return e.nbytes

    def unpin(self, key) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.pins > 0:
                e.pins -= 1

    def clear(self) -> int:
        with self._lock:
            freed = self._bytes
            self._entries.clear()
            self._bytes = 0
            return freed

    # -- eviction (called by the manager under the shared lock) --------------

    def _evict_locked(self, want_bytes: int, floor: int,
                      reason: str) -> int:
        """Evict unpinned entries LRU-first until ``want_bytes`` are
        freed or the store is down to ``floor`` bytes; returns freed
        bytes. Caller holds the shared lock."""
        freed = 0
        for key in list(self._entries):
            if freed >= want_bytes or self._bytes <= floor:
                break
            e = self._entries[key]
            if e.pins > 0:
                continue
            del self._entries[key]
            self._bytes -= e.nbytes
            freed += e.nbytes
            self.evictions += 1
            self.evicted_bytes += e.nbytes
            if reason == "execution":
                self._m.evicted_for_execution += 1
            metrics.record("storage", phase="evict", key=_short(key),
                           bytes=e.nbytes, reason=reason,
                           storage_bytes=self._bytes)
        return freed

    def _pin_locked(self, key, e: StoreEntry) -> None:
        pins = _PINS.get()
        if pins is None:
            return  # no execution scope: serve unpinned
        e.pins += 1
        pins.append((self, key))

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes_used": self._bytes,
                "pinned_entries": sum(
                    1 for e in self._entries.values() if e.pins),
                "hits": self.hits,
                "misses": self.misses,
                "hit_bytes": self.hit_bytes,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "put_bytes": self.put_bytes,
                "rejected_puts": self.rejected_puts,
            }

    def entries_snapshot(self, n: int = 64) -> List[Dict[str, Any]]:
        """Newest-access-last entry listing for the UI."""
        with self._lock:
            return [{
                "key": _short(e.key),
                "bytes": e.nbytes,
                "pins": e.pins,
                "hits": e.hits,
                "age_s": round(time.time() - e.created_t, 1),
            } for e in list(self._entries.values())[-n:]]


def _short(key) -> str:
    s = str(key)
    return s if len(s) <= 120 else s[:117] + "..."

"""Bounded LRU mapping for jitted-stage caches.

The fused-stage caches (physical/planner._STAGE_CACHE and
parallel/executor._DIST_STAGE_CACHE) were unbounded dicts — a
long-serving process compiling thousands of distinct plans pinned
every compiled executable (and its leaf-stripped plan skeleton)
forever. This wrapper gives them LRU semantics with an entry cap read
LIVE from ``spark.tpu.jit.stageCacheEntries`` (active session conf, so
serving deployments tune it without restarts) and publishes the live
size as a metrics gauge.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any

from spark_tpu import metrics


class LruDict:
    """Dict-shaped (get / [] / len / clear) so existing call sites keep
    working; inserts evict oldest-accessed entries beyond the cap.
    Thread-safe: scheduler workers share these caches."""

    def __init__(self, name: str, cap_entry=None, cap: int = 512):
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._name = name
        self._cap_entry = cap_entry  # conf.ConfigEntry, read live
        self._cap = int(cap)
        self._lock = threading.Lock()
        self.evictions = 0

    def _capacity(self) -> int:
        if self._cap_entry is not None:
            try:
                from spark_tpu.api.session import SparkSession

                sess = SparkSession.getActiveSession()
                if sess is not None:
                    return max(1, int(sess.conf.get(self._cap_entry)))
                return max(1, int(self._cap_entry.default))
            except Exception:
                pass
        return max(1, self._cap)

    def get(self, key, default=None):
        with self._lock:
            try:
                v = self._d[key]
            except KeyError:
                return default
            self._d.move_to_end(key)
            return v

    def __getitem__(self, key):
        with self._lock:
            v = self._d[key]
            self._d.move_to_end(key)
            return v

    def __setitem__(self, key, value) -> None:
        cap = self._capacity()
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            evicted = 0
            while len(self._d) > cap:
                self._d.popitem(last=False)
                evicted += 1
            size = len(self._d)
        if evicted:
            self.evictions += evicted
            metrics.record("jit_cache_evict", cache=self._name,
                           evicted=evicted, size=size, cap=cap)
        metrics.set_gauge(f"jit_cache.{self._name}.entries", size)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
        metrics.set_gauge(f"jit_cache.{self._name}.entries", 0)

    def pop(self, key, default=None):
        with self._lock:
            return self._d.pop(key, default)

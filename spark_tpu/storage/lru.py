"""Bounded LRU mapping for jitted-stage and serve-tier caches.

The fused-stage caches (physical/planner._STAGE_CACHE and
parallel/executor._DIST_STAGE_CACHE) were unbounded dicts — a
long-serving process compiling thousands of distinct plans pinned
every compiled executable (and its leaf-stripped plan skeleton)
forever. This wrapper gives them LRU semantics with an entry cap read
LIVE from ``spark.tpu.jit.stageCacheEntries`` (active session conf, so
serving deployments tune it without restarts) and publishes the live
size as a metrics gauge.

The serve-tier result cache (serve/result_cache.py) reuses it with a
BYTE bound instead: pass ``weigher`` (value -> size) and a
``max_bytes`` cap (int, or a conf.ConfigEntry via ``max_bytes_entry``
read live) and inserts evict oldest-accessed entries until the total
weight fits.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from spark_tpu import locks
from spark_tpu import metrics


class LruDict:
    """Dict-shaped (get / [] / len / clear) so existing call sites keep
    working; inserts evict oldest-accessed entries beyond the cap.
    Thread-safe: scheduler workers share these caches."""

    def __init__(self, name: str, cap_entry=None, cap: int = 512,
                 max_bytes_entry=None, max_bytes: Optional[int] = None,
                 weigher: Optional[Callable[[Any], int]] = None,
                 conf=None):
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._name = name
        #: explicit RuntimeConf for live entry reads; None falls back
        #: to the active session's conf (the jit-cache call sites)
        self._conf = conf
        self._cap_entry = cap_entry  # conf.ConfigEntry, read live
        self._cap = int(cap)
        self._max_bytes_entry = max_bytes_entry  # ConfigEntry, read live
        self._max_bytes = max_bytes
        self._weigher = weigher
        self._weights: "OrderedDict[Any, int]" = OrderedDict()
        self._bytes = 0
        self._lock = locks.named_lock("storage.lru")
        self.evictions = 0

    def _conf_value(self, entry, fallback):
        try:
            if self._conf is not None:
                return int(self._conf.get(entry))
            from spark_tpu.api.session import SparkSession

            sess = SparkSession.getActiveSession()
            if sess is not None:
                return int(sess.conf.get(entry))
            return int(entry.default)
        except Exception:
            return fallback

    def _capacity(self) -> int:
        if self._cap_entry is not None:
            return max(1, self._conf_value(self._cap_entry, self._cap))
        return max(1, self._cap)

    def _byte_capacity(self) -> Optional[int]:
        """Live byte cap; None = no byte bound configured."""
        if self._max_bytes_entry is not None:
            default = self._max_bytes if self._max_bytes is not None \
                else int(self._max_bytes_entry.default)
            return max(0, self._conf_value(self._max_bytes_entry,
                                           default))
        if self._max_bytes is not None:
            return max(0, int(self._max_bytes))
        return None

    def get(self, key, default=None):
        with self._lock:
            try:
                v = self._d[key]
            except KeyError:
                return default
            self._d.move_to_end(key)
            if key in self._weights:
                self._weights.move_to_end(key)
            return v

    def __getitem__(self, key):
        with self._lock:
            v = self._d[key]
            self._d.move_to_end(key)
            if key in self._weights:
                self._weights.move_to_end(key)
            return v

    def __setitem__(self, key, value) -> None:
        cap = self._capacity()
        byte_cap = self._byte_capacity()
        w = int(self._weigher(value)) if self._weigher is not None else 0
        with self._lock:
            if self._weigher is not None and key in self._weights:
                self._bytes -= self._weights[key]
            self._d[key] = value
            self._d.move_to_end(key)
            if self._weigher is not None:
                self._weights[key] = w
                self._weights.move_to_end(key)
                self._bytes += w
            evicted = 0
            while len(self._d) > cap or (
                    byte_cap is not None and self._bytes > byte_cap
                    and self._d):
                old_key, _ = self._d.popitem(last=False)
                self._bytes -= self._weights.pop(old_key, 0)
                evicted += 1
            size = len(self._d)
            total = self._bytes
        if evicted:
            self.evictions += evicted
            metrics.record("jit_cache_evict", cache=self._name,
                           evicted=evicted, size=size, cap=cap)
        metrics.set_gauge(f"jit_cache.{self._name}.entries", size)
        if self._weigher is not None:
            metrics.set_gauge(f"jit_cache.{self._name}.bytes", total)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._weights.clear()
            self._bytes = 0
        metrics.set_gauge(f"jit_cache.{self._name}.entries", 0)
        if self._weigher is not None:
            metrics.set_gauge(f"jit_cache.{self._name}.bytes", 0)

    def pop(self, key, default=None):
        with self._lock:
            self._bytes -= self._weights.pop(key, 0)
            return self._d.pop(key, default)

    def keys(self) -> list:
        """Point-in-time key snapshot (LRU order, oldest first) —
        the serve-tier invalidation sweep iterates this and pops
        matches without holding the lock across the scan."""
        with self._lock:
            return list(self._d.keys())

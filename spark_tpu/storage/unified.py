"""Unified storage/execution HBM accounting.

The analogue of the reference's UnifiedMemoryManager (reference:
core/src/main/scala/org/apache/spark/memory/UnifiedMemoryManager.scala:56):
storage (cached device batches, spark_tpu/storage/store.py) and
execution (the scheduler's HBM admission grants,
spark_tpu/scheduler/admission.py) share ONE byte budget —
``spark.tpu.scheduler.hbmBudgetBytes`` — instead of each layer keeping
its own optimistic count.

Borrowing rules, mirroring the reference's asymmetric split:

- execution may EVICT unpinned storage entries (LRU) to make room,
  but never below ``spark.tpu.storage.minBytes`` — the protected
  storage region (the reference's ``spark.memory.storageFraction``
  floor);
- storage may grow into memory execution is not using, but can never
  evict a running query's grant — a cache insert that does not fit
  after evicting storage's own LRU tail is simply rejected (the entry
  stays recomputable, nothing blocks);
- the idle-device progress rule of admission control is preserved:
  with no query admitted, execution always gets a grant (capped at
  whatever the budget minus surviving storage bytes allows — possibly
  zero, in which case the query runs ungated and relies on the OOM
  degradation ladder), so storage can delay but never deadlock the
  device.

Invariant (held under one lock, asserted by the eviction stress test):
``storage_bytes + execution_in_use <= budget`` at every instant.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from spark_tpu import locks
from spark_tpu import conf as CF


class UnifiedMemoryManager:
    """Shared HBM byte-budget ledger. Construct with a static budget
    (standalone schedulers, tests) or with a ``conf`` whose
    ``spark.tpu.scheduler.hbmBudgetBytes`` / ``spark.tpu.storage.*``
    keys are read LIVE — a session can resize the budget between
    queries without rebuilding the session."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 conf=None, min_storage_bytes: Optional[int] = None,
                 max_storage_bytes: Optional[int] = None):
        if budget_bytes is None and conf is None:
            raise ValueError("need budget_bytes or conf")
        self._conf = conf
        self._budget = int(budget_bytes) if budget_bytes is not None \
            else None
        self._min_storage = min_storage_bytes
        self._max_storage = max_storage_bytes
        #: one lock for BOTH sides; the store shares it so an eviction
        #: decision and the byte accounting it is based on are atomic
        self.lock = locks.named_rlock("storage.unified")
        self._execution = 0
        self._admitted = 0
        self._store = None  # MemoryStore registers itself
        self.evicted_for_execution = 0  # entries evicted to admit queries
        # grant observability (all mutated under self.lock): a join/
        # query starved by storage pins must be visible from the
        # snapshot (surfaced via /api/v1/storage and
        # tracing.storage_profile), not only from its wall time
        self.grants = 0            # acquire_execution calls
        self.grant_bytes = 0       # total bytes actually granted
        self.grant_waits = 0       # fits_execution said "not yet"
        self.grant_denials = 0     # grants short of the request
        self.zero_grants = 0       # non-zero request granted 0 bytes
        self.grows = 0             # mid-execution try_grow successes
        self.grow_denials = 0      # try_grow found no free span
        #: callbacks fired AFTER release_execution drops the lock —
        #: the scheduler's gate condition registers here so grant
        #: releases by other tenants (hybrid join spill grants, direct
        #: manager users) wake its waiters without polling. Firing
        #: under self.lock would nest storage.unified -> scheduler.cond
        #: against the hierarchy; that inversion is exactly what the
        #: concurrency linter rejects.
        self._release_listeners = []

    # -- live-conf properties ------------------------------------------------

    @property
    def budget(self) -> int:
        if self._budget is not None:
            return max(1, self._budget)
        return max(1, int(self._conf.get(CF.SCHEDULER_HBM_BUDGET)))

    @property
    def min_storage(self) -> int:
        if self._min_storage is not None:
            return max(0, int(self._min_storage))
        if self._conf is not None:
            return max(0, int(self._conf.get(CF.STORAGE_MIN_BYTES)))
        return 0

    @property
    def max_storage(self) -> int:
        cap = self.budget
        if self._max_storage is not None:
            return min(cap, max(0, int(self._max_storage)))
        if self._conf is not None:
            return min(cap, max(0, int(self._conf.get(
                CF.STORAGE_MAX_BYTES))))
        return cap

    # -- wiring --------------------------------------------------------------

    def attach_store(self, store) -> None:
        self._store = store

    def storage_bytes(self) -> int:
        return self._store.bytes_used() if self._store is not None else 0

    # -- execution side (the scheduler's admission gate) ---------------------

    def charge_for(self, nbytes: int) -> int:
        """What an admission of ``nbytes`` may cost at most: capped at
        the whole budget so an over-budget query can still admit alone."""
        return min(max(1, int(nbytes)), self.budget)

    def fits_execution(self, nbytes: int) -> bool:
        with self.lock:
            if self._admitted == 0:
                return True  # idle device: always make progress
            charge = self.charge_for(nbytes)
            avail = self.budget - self._execution - self.storage_bytes()
            if charge <= avail:
                return True
            if charge <= avail + self._storage_freeable_locked():
                return True
            self.grant_waits += 1
            return False

    def acquire_execution(self, nbytes: int) -> int:
        """Charge the budget, evicting unpinned storage (LRU, down to
        the protected ``min_storage`` region) when the free span is
        short. Returns the actual charge for ``release_execution`` —
        capped so the invariant holds even when protected/pinned
        storage keeps the full request from fitting (the idle-progress
        case; the grant may then be 0 and the query runs ungated)."""
        with self.lock:
            charge = self.charge_for(nbytes)
            avail = self.budget - self._execution - self.storage_bytes()
            if charge > avail and self._store is not None:
                self._store._evict_locked(
                    charge - avail, floor=self.min_storage,
                    reason="execution")
                avail = self.budget - self._execution \
                    - self.storage_bytes()
            requested = charge
            charge = max(0, min(charge, avail))
            self._execution += charge
            self._admitted += 1
            self.grants += 1
            self.grant_bytes += charge
            if charge < requested:
                self.grant_denials += 1
            if charge == 0 and int(nbytes) > 0:
                self.zero_grants += 1
            return charge

    def try_grow(self, nbytes: int) -> int:
        """Grow a live execution grant by up to ``nbytes``, but ONLY
        from the genuinely free span — never by evicting storage (a
        mid-query grow must not churn the cache the way the initial
        grant may). Returns the bytes actually added (0 when storage/
        other queries hold everything); caller adds the return value to
        the charge it will ``release_execution``. This is the hybrid
        hash join's grow-when-idle step: resident partitions expand
        into memory nobody is using instead of spilling."""
        with self.lock:
            nbytes = max(0, int(nbytes))
            avail = max(0, self.budget - self._execution
                        - self.storage_bytes())
            got = min(nbytes, avail)
            if got > 0:
                self._execution += got
                self.grows += 1
                self.grant_bytes += got
            elif nbytes > 0:
                self.grow_denials += 1
            return got

    def add_release_listener(self, callback) -> None:
        """Register a callback invoked (outside the lock) every time an
        execution grant is released — i.e. whenever a blocked admission
        might now fit."""
        with self.lock:
            self._release_listeners.append(callback)

    def release_execution(self, charge: int) -> None:
        with self.lock:
            self._execution = max(0, self._execution - int(charge))
            self._admitted = max(0, self._admitted - 1)
            listeners = list(self._release_listeners)
        for cb in listeners:
            try:
                cb()
            except Exception:
                pass

    def _storage_freeable_locked(self) -> int:
        """Unpinned storage bytes execution could reclaim without
        dipping into the protected region."""
        if self._store is None:
            return 0
        unpinned = self._store.unpinned_bytes()
        return max(0, min(unpinned,
                          self.storage_bytes() - self.min_storage))

    # -- storage side --------------------------------------------------------

    def reserve_storage(self, nbytes: int) -> bool:
        """May the store take ``nbytes`` more? Evicts the store's own
        LRU tail to fit under ``min(max_storage, budget - execution)``;
        never touches execution grants. Caller (the store) inserts the
        entry under the same lock on True."""
        with self.lock:
            nbytes = int(nbytes)
            limit = min(self.max_storage, self.budget - self._execution)
            if nbytes > limit:
                return False
            used = self.storage_bytes()
            if used + nbytes > limit and self._store is not None:
                self._store._evict_locked(
                    used + nbytes - limit, floor=0, reason="storage")
                used = self.storage_bytes()
            return used + nbytes <= limit

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "budget_bytes": self.budget,
                "in_use_bytes": self._execution,
                "admitted": self._admitted,
                "storage_bytes": self.storage_bytes(),
                "storage_min_bytes": self.min_storage,
                "storage_max_bytes": self.max_storage,
                "free_bytes": max(0, self.budget - self._execution
                                  - self.storage_bytes()),
                "grants": {
                    "grants": self.grants,
                    "grant_bytes": self.grant_bytes,
                    "grant_waits": self.grant_waits,
                    "grant_denials": self.grant_denials,
                    "zero_grants": self.zero_grants,
                    "grows": self.grows,
                    "grow_denials": self.grow_denials,
                },
            }

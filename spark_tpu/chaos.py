"""Seeded chaos-campaign harness over the fault-injection switchboard.

faults.py gives every recovery seam a deterministic trigger; this
module drives ALL of them at once, the way the reference's
FailureSuite/DAGSchedulerSuite randomized kill tests prove recovery
composition rather than one seam at a time. A **campaign** is a
sequence of **schedules**, each derived purely from
``random.Random(f"chaos:{campaign_seed}")``: 1..max_points injection
points armed together, each with its own kind (transient / oom / hang
/ corrupt) and its own nth- or prob-mode spec (prob streams are salted
per point by faults._PointState, so a multi-point schedule reproduces
from the campaign seed alone).

Per schedule the harness asserts the fleet-grade resilience contract:

- **byte-identical or typed** — the workload either returns bytes
  equal to the clean (fault-free) run, or raises one of the TYPED
  errors the stack is allowed to surface (``is_typed_error``). A
  mangled result or an anonymous stack trace is a campaign failure.
- **zero hangs** — every schedule runs under a wall-clock alarm
  (SIGALRM on the main thread, a watchdog budget elsewhere); an
  expired alarm is a failure, never a silent stall.
- **attempts <= budget** — the unified retry budget's metrics deltas
  are checked against the per-query pool: draws never exceed
  ``queries x attempts`` (the old multiplicative per-layer stacking
  shows up here immediately).
- **memory invariant** — ``execution + storage <= hbmBudget`` from the
  UnifiedMemoryManager snapshot after every schedule.

A failing schedule is dumped as a replayable JSON artifact
(``schedule.to_dict`` round-trips through ``ChaosSchedule.from_dict``)
so one failing seed out of thousands re-runs in isolation.
"""

from __future__ import annotations

import json
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from spark_tpu import faults, metrics

#: fault kinds eligible for random schedules, weighted toward the
#: kinds with recovery paths (transient/hang retry; oom degrades;
#: corrupt must surface typed)
_KIND_WEIGHTS = (("transient", 5), ("hang", 2), ("oom", 1),
                 ("corrupt", 1))


class ChaosHang(RuntimeError):
    """A schedule exceeded its wall-clock alarm: the zero-hang
    guarantee failed (or the bound is too tight for the workload)."""


@dataclass(frozen=True)
class ChaosFault:
    """One armed injection point inside a schedule."""

    point: str
    mode: str  # "nth" | "prob"
    kind: str  # faults.KINDS
    k: int = 1
    p: float = 0.0
    seed: int = 0

    def spec(self) -> str:
        if self.mode == "nth":
            return f"nth:{self.k}:{self.kind}"
        return f"prob:{self.p:g}:{self.seed}:{self.kind}"

    def conf_key(self) -> str:
        return f"spark.tpu.faultInjection.{self.point}"

    def to_dict(self) -> dict:
        return {"point": self.point, "mode": self.mode,
                "kind": self.kind, "k": self.k, "p": self.p,
                "seed": self.seed}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosFault":
        return cls(point=d["point"], mode=d["mode"], kind=d["kind"],
                   k=int(d.get("k", 1)), p=float(d.get("p", 0.0)),
                   seed=int(d.get("seed", 0)))


@dataclass(frozen=True)
class ChaosSchedule:
    """One deterministic multi-point fault configuration."""

    index: int
    campaign_seed: int
    faults: Tuple[ChaosFault, ...]

    def conf_overrides(self) -> dict:
        return {f.conf_key(): f.spec() for f in self.faults}

    def describe(self) -> str:
        return " + ".join(
            f"{f.point}={f.spec()}" for f in self.faults) or "(clean)"

    def to_dict(self) -> dict:
        return {"index": self.index,
                "campaign_seed": self.campaign_seed,
                "faults": [f.to_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSchedule":
        return cls(index=int(d["index"]),
                   campaign_seed=int(d["campaign_seed"]),
                   faults=tuple(ChaosFault.from_dict(f)
                                for f in d["faults"]))


def generate_campaign(campaign_seed: int, n: int, *,
                      points: Sequence[str] = faults.POINTS,
                      max_points: int = 3,
                      prob_range: Tuple[float, float] = (0.2, 0.7),
                      ) -> List[ChaosSchedule]:
    """Derive ``n`` schedules purely from ``campaign_seed`` — same
    seed, same campaign, on any host/process (str seeding hashes via
    sha512, independent of PYTHONHASHSEED)."""
    rng = random.Random(f"chaos:{campaign_seed}")
    kinds = [k for k, w in _KIND_WEIGHTS for _ in range(w)]
    out: List[ChaosSchedule] = []
    for i in range(int(n)):
        npts = rng.randint(1, max(1, min(max_points, len(points))))
        chosen = rng.sample(list(points), npts)
        fs = []
        for pt in chosen:
            kind = rng.choice(kinds)
            if rng.random() < 0.5:
                fs.append(ChaosFault(pt, "nth", kind,
                                     k=rng.randint(1, 3)))
            else:
                fs.append(ChaosFault(
                    pt, "prob", kind,
                    p=round(rng.uniform(*prob_range), 3),
                    seed=rng.randrange(1 << 30)))
        out.append(ChaosSchedule(i, int(campaign_seed), tuple(fs)))
    return out


def is_typed_error(exc: BaseException) -> bool:
    """Is ``exc`` (or anything in its cause chain) one of the errors
    the stack is ALLOWED to surface under faults? Everything else —
    an AttributeError out of a half-recovered code path, a mangled
    arrow stream — is a chaos-campaign failure."""
    from spark_tpu import deadline, recovery

    def _typed_one(e: BaseException) -> bool:
        if isinstance(e, (faults.InjectedFault,
                          deadline.DeadlineExceeded,
                          recovery.RetryBudgetExhausted,
                          ChaosHang)):
            return True
        name = type(e).__name__
        if name in ("QueryCancelled", "SchedulerQueueFull",
                    "NoHealthyReplica", "FlightWaitTimeout",
                    "PlanAnalysisError", "EpochRetry",
                    "InfeasibleDeadline"):
            return True
        msg = str(e)
        return any(m in msg for m in (
            "DATA_LOSS", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED",
            "UNAVAILABLE", "RETRY_BUDGET_EXHAUSTED", "CANCELLED",
            "SchedulerQueueFull", "NoHealthyReplica", "EPOCH_RETRY",
            "INFEASIBLE_DEADLINE"))

    seen = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if _typed_one(e):
            return True
        e = e.__cause__ or e.__context__
    return False


class _Alarm:
    """Wall-clock bound for one schedule. On the main thread a real
    SIGALRM interrupts even a wedged C-level wait; elsewhere a timer
    thread can only flag the overrun, so ``expired`` is checked after
    the run (the run itself is still bounded by the caller's own
    pytest/campaign timeout)."""

    def __init__(self, seconds: float):
        self.seconds = float(seconds)
        self.expired = False
        self._main = (threading.current_thread()
                      is threading.main_thread())
        self._old = None
        self._timer: Optional[threading.Timer] = None

    def __enter__(self):
        if self.seconds <= 0:
            return self
        if self._main:
            def _fire(signum, frame):
                self.expired = True
                raise ChaosHang(
                    f"schedule exceeded {self.seconds:g}s wall bound")
            self._old = signal.signal(signal.SIGALRM, _fire)
            signal.setitimer(signal.ITIMER_REAL, self.seconds)
        else:
            self._timer = threading.Timer(
                self.seconds, lambda: setattr(self, "expired", True))
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc):
        if self.seconds <= 0:
            return False
        if self._main:
            signal.setitimer(signal.ITIMER_REAL, 0.0)
            if self._old is not None:
                signal.signal(signal.SIGALRM, self._old)
        elif self._timer is not None:
            self._timer.cancel()
        return False


@dataclass
class ScheduleResult:
    schedule: ChaosSchedule
    ok: bool
    outcome: str  # identical | typed_error | mismatch | untyped_error
    #             | hang | budget_overdraw | memory_violation
    error: Optional[str] = None
    elapsed_s: float = 0.0
    draws: int = 0
    fired: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"schedule": self.schedule.to_dict(), "ok": self.ok,
                "outcome": self.outcome, "error": self.error,
                "elapsed_s": round(self.elapsed_s, 3),
                "draws": self.draws, "fired": self.fired}


def run_schedule(conf, run_bytes: Callable[[], bytes],
                 schedule: ChaosSchedule, *,
                 clean_bytes: bytes,
                 alarm_s: float = 60.0,
                 queries: int = 1,
                 budget_attempts: Optional[int] = None,
                 memory_manager=None) -> ScheduleResult:
    """Arm ``schedule`` on ``conf``, run the workload once, disarm,
    and grade the outcome against the resilience contract. The
    workload must be deterministic: ``clean_bytes`` is its fault-free
    result."""
    from spark_tpu import recovery

    overrides = schedule.conf_overrides()
    before = metrics.retry_budget_stats()
    if budget_attempts is None:
        try:
            budget_attempts = int(conf.get(
                recovery.RETRY_BUDGET_ATTEMPTS))
        except Exception:
            budget_attempts = int(
                recovery.RETRY_BUDGET_ATTEMPTS.default)
    for key, spec in overrides.items():
        conf.set(key, spec)
    faults.reset(conf)
    t0 = time.perf_counter()
    outcome, err, ok = "identical", None, True
    try:
        with _Alarm(alarm_s) as alarm:
            blob = run_bytes()
        if alarm.expired:
            outcome, ok = "hang", False
            err = f"watchdog: exceeded {alarm_s:g}s off-main-thread"
        elif blob != clean_bytes:
            outcome, ok = "mismatch", False
            err = (f"result diverged from clean run "
                   f"({len(blob)} vs {len(clean_bytes)} bytes)")
    except ChaosHang as e:
        outcome, ok, err = "hang", False, repr(e)
    except BaseException as e:  # noqa: BLE001 — graded, not handled
        if is_typed_error(e):
            outcome, err = "typed_error", repr(e)
        else:
            outcome, ok = "untyped_error", False
            err = repr(e)
    finally:
        elapsed = time.perf_counter() - t0
        fired = {pt: faults.fire_count(conf, pt)
                 for pt in {f.point for f in schedule.faults}}
        for key in overrides:
            conf.unset(key)
        faults.reset(conf)
    after = metrics.retry_budget_stats()
    draws = (after.get("draws", 0) - before.get("draws", 0)
             + after.get("floor_draws", 0)
             - before.get("floor_draws", 0))
    if ok and draws > max(1, int(queries)) * int(budget_attempts):
        ok, outcome = False, "budget_overdraw"
        err = (f"{draws} retry draws > {queries} queries x "
               f"{budget_attempts} budget")
    if ok and memory_manager is not None:
        snap = memory_manager.snapshot()
        used = (int(snap.get("in_use_bytes", 0))
                + int(snap.get("storage_bytes", 0)))
        if used > int(snap.get("budget_bytes", 0)):
            ok, outcome = False, "memory_violation"
            err = (f"execution+storage {used} > budget "
                   f"{snap.get('budget_bytes')}")
    return ScheduleResult(schedule, ok, outcome, err, elapsed,
                          max(0, draws), fired)


@dataclass
class CampaignReport:
    campaign_seed: int
    results: List[ScheduleResult]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def failures(self) -> List[ScheduleResult]:
        return [r for r in self.results if not r.ok]

    def summary(self) -> dict:
        counts: dict = {}
        for r in self.results:
            counts[r.outcome] = counts.get(r.outcome, 0) + 1
        return {"campaign_seed": self.campaign_seed,
                "schedules": len(self.results),
                "ok": self.ok, "outcomes": counts,
                "total_draws": sum(r.draws for r in self.results),
                "elapsed_s": round(
                    sum(r.elapsed_s for r in self.results), 3)}


def run_campaign(conf, run_bytes: Callable[[], bytes],
                 schedules: Sequence[ChaosSchedule], *,
                 clean_bytes: bytes,
                 alarm_s: float = 60.0,
                 queries: int = 1,
                 memory_manager=None,
                 artifact_path: Optional[str] = None,
                 log: Optional[Callable[[str], None]] = None
                 ) -> CampaignReport:
    """Run every schedule; on the FIRST failure (if ``artifact_path``)
    write the replayable JSON artifact, then keep going so the report
    covers the whole campaign."""
    results: List[ScheduleResult] = []
    wrote_artifact = False
    seed = schedules[0].campaign_seed if schedules else 0
    for sch in schedules:
        r = run_schedule(conf, run_bytes, sch,
                         clean_bytes=clean_bytes, alarm_s=alarm_s,
                         queries=queries,
                         memory_manager=memory_manager)
        results.append(r)
        if log is not None:
            flag = "ok " if r.ok else "FAIL"
            log(f"[{flag}] #{sch.index:03d} {r.outcome:<13} "
                f"{r.elapsed_s:6.2f}s draws={r.draws:<3} "
                f"{sch.describe()}")
        if not r.ok and artifact_path and not wrote_artifact:
            wrote_artifact = True
            with open(artifact_path, "w") as f:
                json.dump(r.to_dict(), f, indent=2)
            if log is not None:
                log(f"  replay artifact -> {artifact_path}")
    return CampaignReport(seed, results)


def replay_artifact(path: str) -> ChaosSchedule:
    """Load the failing schedule back out of a campaign artifact."""
    with open(path) as f:
        d = json.load(f)
    return ChaosSchedule.from_dict(d["schedule"])

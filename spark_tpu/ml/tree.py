"""Decision trees + random forests (reference: ml/tree/ —
DecisionTreeClassifier.scala, RandomForest.scala level-wise training
over binned features, impurity/Variance.scala + Gini).

TPU-first formulation: the classic RandomForest.scala loop builds
per-(node, feature, bin) histograms by iterating rows on executors;
here the SAME level-wise histogram algorithm is a handful of MXU
matmuls — a one-hot (cells x rows) matrix times the (rows, 3) stats
matrix [1, y, y^2] yields every node's histogram in one shot, prefix
sums over bins give all candidate splits, and the argmax picks each
node's (feature, threshold) simultaneously. Rows never leave the
device during growth.

Prediction compiles the fitted tree into nested CASE expressions, so
scoring fuses into whatever query pipeline follows (the reference
walks Node objects per row on the JVM)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_tpu.expr import expressions as E
from spark_tpu.ml.pipeline import Estimator, Model, features_matrix

_BINS = 32


def _bin_features(x: jnp.ndarray):
    """Quantile-bin each feature column to int32 codes + edge values
    (reference: RandomForest.scala findSplits quantile sketching)."""
    qs = jnp.linspace(0.0, 1.0, _BINS + 1)[1:-1]
    edges = jnp.quantile(x, qs, axis=0)  # (B-1, d)
    binned = jnp.sum(x[None, :, :] > edges[:, None, :], axis=0)
    return binned.astype(jnp.int32), edges


def _grow_tree(xb: jnp.ndarray, edges: jnp.ndarray, y: jnp.ndarray,
               max_depth: int, min_rows: int, rng: np.random.Generator,
               sample_weight: Optional[np.ndarray] = None,
               feature_frac: float = 1.0):
    """Level-wise growth; returns a dict-shaped tree:
    {node_id: (feature, threshold_value)} for internal nodes and
    {node_id: leaf_value} for leaves (node ids heap-ordered: children
    of i are 2i+1 / 2i+2). Split criterion: variance reduction (squared
    loss — Gini for 0/1 labels is an affine transform of it, so one
    criterion serves both learners, impurity/Variance.scala)."""
    n, d = xb.shape
    feat_mask = np.ones((d,), bool)
    if feature_frac < 1.0:
        k = max(1, int(round(feature_frac * d)))
        feat_mask[:] = False
        feat_mask[rng.choice(d, size=k, replace=False)] = True
    w = (jnp.asarray(sample_weight.astype(np.float32))
         if sample_weight is not None else jnp.ones((n,), jnp.float32))

    node = jnp.zeros((n,), jnp.int32)  # heap position per row
    splits = {}  # node_id -> (feature, threshold_value, bin)
    leaves = {}  # node_id -> value
    level_nodes = [0]
    for depth in range(max_depth + 1):
        if not level_nodes:
            break
        n_level = len(level_nodes)
        # heap ids at a level are sparse (leaf siblings drop out):
        # map to dense local ids with a small where-chain
        local = jnp.full((n,), n_level, jnp.int32)
        for i, nid in enumerate(level_nodes):
            local = jnp.where(node == nid, i, local)
        in_level = local < n_level
        cells = n_level * _BINS
        stats = jnp.stack([w, w * y, w * y * y], axis=1)  # (n, 3)
        hists = []
        for f in range(d):
            if not feat_mask[f]:
                hists.append(None)
                continue
            key = jnp.where(in_level, local * _BINS + xb[:, f], cells)
            onehot = (key[:, None]
                      == jnp.arange(cells)[None, :]).astype(jnp.float32)
            hists.append((onehot.T @ stats).reshape(n_level, _BINS, 3))

        # per node: total stats (same for every feature)
        any_f = next(h for h in hists if h is not None)
        tot = any_f.sum(axis=1)  # (n_level, 3)
        best_gain = np.full((n_level,), 1e-12)
        best_feat = np.full((n_level,), -1, np.int64)
        best_bin = np.zeros((n_level,), np.int64)
        for f in range(d):
            if hists[f] is None:
                continue
            h = hists[f]
            lc = jnp.cumsum(h, axis=1)[:, :-1, :]  # left of bin b+1
            rc = tot[:, None, :] - lc
            ln, ls = lc[..., 0], lc[..., 1]
            rn, rs = rc[..., 0], rc[..., 1]
            ok = (ln >= min_rows) & (rn >= min_rows)
            # variance reduction == sum of per-side (sum^2/count) up to
            # a constant; maximize that
            gain = jnp.where(
                ok,
                ls * ls / jnp.maximum(ln, 1e-9)
                + rs * rs / jnp.maximum(rn, 1e-9),
                -jnp.inf)
            base_score = (tot[:, 1] ** 2
                          / jnp.maximum(tot[:, 0], 1e-9))
            g = np.asarray(jnp.max(gain, axis=1) - base_score)
            b = np.asarray(jnp.argmax(gain, axis=1))
            upd = g > best_gain
            best_gain = np.where(upd, g, best_gain)
            best_feat = np.where(upd, f, best_feat)
            best_bin = np.where(upd, b, best_bin)

        tot_np = np.asarray(tot)
        next_level = []
        for i, nid in enumerate(level_nodes):
            mean = (tot_np[i, 1] / tot_np[i, 0]
                    if tot_np[i, 0] > 0 else 0.0)
            if depth == max_depth or best_feat[i] < 0 \
                    or tot_np[i, 0] < 2 * min_rows:
                leaves[nid] = float(mean)
                continue
            f, b = int(best_feat[i]), int(best_bin[i])
            thr = float(np.asarray(edges)[b, f])
            splits[nid] = (f, thr, b)
            next_level.extend([2 * nid + 1, 2 * nid + 2])
        # reassign rows of split nodes
        new_node = node
        for i, nid in enumerate(level_nodes):
            if nid not in splits:
                continue
            f, _, b = splits[nid]
            here = node == nid
            left = xb[:, f] <= b
            new_node = jnp.where(here & left, 2 * nid + 1,
                                 jnp.where(here, 2 * nid + 2, new_node))
        node = new_node
        level_nodes = sorted(next_level)
    return splits, leaves


class _TreeFit:
    """One fitted tree as parallel dicts keyed by node id."""

    def __init__(self, splits, leaves, features: List[str]):
        self.splits = splits
        self.leaves = leaves
        self.features = features

    def to_expr(self, nid: int = 0) -> E.Expression:
        """Nested CASE over feature columns (fuses into the plan)."""
        if nid in self.leaves:
            return E.Literal(float(self.leaves[nid]))
        f, thr, _ = self.splits[nid]
        cond = E.Cmp("<=", E.Col(self.features[f]), E.Literal(thr))
        return E.Case(((cond, self.to_expr(2 * nid + 1)),),
                      self.to_expr(2 * nid + 2))


class DecisionTreeRegressor(Estimator):
    """CART regression tree (reference: ml/regression/
    DecisionTreeRegressor.scala)."""

    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction", maxDepth: int = 5,
                 minInstancesPerNode: int = 1, seed: int = 42):
        self.features_cols = list(featuresCols)
        self.label_col = labelCol
        self.prediction_col = predictionCol
        self.max_depth = maxDepth
        self.min_rows = minInstancesPerNode
        self.seed = seed
        self._classifier = False

    def _fit_trees(self, df, n_trees: int, feature_frac: float,
                   bootstrap: bool):
        xy = features_matrix(df, self.features_cols + [self.label_col])
        x, y = xy[:, :-1], xy[:, -1]
        xb, edges = _bin_features(x)
        rng = np.random.default_rng(self.seed)
        n = int(x.shape[0])
        fits = []
        for _ in range(n_trees):
            w = None
            if bootstrap:
                w = np.bincount(rng.integers(0, n, n),
                                minlength=n).astype(np.float32)
            s, lv = _grow_tree(xb, edges, y, self.max_depth,
                               self.min_rows, rng, sample_weight=w,
                               feature_frac=feature_frac)
            fits.append(_TreeFit(s, lv, self.features_cols))
        return fits

    def fit(self, df) -> "TreeEnsembleModel":
        fits = self._fit_trees(df, 1, 1.0, bootstrap=False)
        return TreeEnsembleModel(self, fits)


class DecisionTreeClassifier(DecisionTreeRegressor):
    """Binary classification tree: 0/1 labels make Gini an affine
    transform of variance, so the regression grower serves directly
    (reference: ml/classification/DecisionTreeClassifier.scala +
    impurity/Gini)."""

    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 maxDepth: int = 5, minInstancesPerNode: int = 1,
                 seed: int = 42):
        super().__init__(featuresCols, labelCol, predictionCol,
                         maxDepth, minInstancesPerNode, seed)
        self.probability_col = probabilityCol
        self._classifier = True


class RandomForestRegressor(DecisionTreeRegressor):
    """Bagged ensemble: bootstrap rows + feature subsampling per tree
    (reference: ml/regression/RandomForestRegressor.scala,
    RandomForest.scala)."""

    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction", numTrees: int = 20,
                 maxDepth: int = 5, minInstancesPerNode: int = 1,
                 featureSubsetStrategy: float = 0.7, seed: int = 42):
        super().__init__(featuresCols, labelCol, predictionCol,
                         maxDepth, minInstancesPerNode, seed)
        self.num_trees = numTrees
        self.feature_frac = float(featureSubsetStrategy)

    def fit(self, df) -> "TreeEnsembleModel":
        fits = self._fit_trees(df, self.num_trees, self.feature_frac,
                               bootstrap=True)
        return TreeEnsembleModel(self, fits)


class RandomForestClassifier(RandomForestRegressor):
    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 numTrees: int = 20, maxDepth: int = 5,
                 minInstancesPerNode: int = 1,
                 featureSubsetStrategy: float = 0.7, seed: int = 42):
        super().__init__(featuresCols, labelCol, predictionCol,
                         numTrees, maxDepth, minInstancesPerNode,
                         featureSubsetStrategy, seed)
        self.probability_col = probabilityCol
        self._classifier = True


class TreeEnsembleModel(Model):
    """Average of per-tree CASE expressions; classification thresholds
    the mean leaf probability at 0.5."""

    def __init__(self, est, fits: List[_TreeFit]):
        self.est = est
        self.fits = fits

    def transform(self, df):
        score: E.Expression = self.fits[0].to_expr()
        for f in self.fits[1:]:
            score = E.Arith("+", score, f.to_expr())
        if len(self.fits) > 1:
            score = E.Arith("/", score, E.Literal(float(len(self.fits))))
        if getattr(self.est, "_classifier", False):
            df = df.withColumn(self.est.probability_col, score)
            pred = E.Case(
                ((E.Cmp(">", E.Col(self.est.probability_col),
                        E.Literal(0.5)), E.Literal(1.0)),),
                E.Literal(0.0))
            return df.withColumn(self.est.prediction_col, pred)
        return df.withColumn(self.est.prediction_col, score)

"""Feature transformers (reference: ml/feature/StandardScaler.scala,
StringIndexer.scala)."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E
from spark_tpu.ml.pipeline import Estimator, Model


class StandardScaler(Estimator):
    """Column-wise (x - mean) / std over ``inputCols`` (the reference
    scales a vector column; here features are plain columns so the
    transform is ordinary fused arithmetic)."""

    def __init__(self, inputCols: Sequence[str],
                 outputCols: Optional[Sequence[str]] = None,
                 withMean: bool = True, withStd: bool = True):
        self.input_cols = list(inputCols)
        self.output_cols = list(outputCols or
                                [c + "_scaled" for c in inputCols])
        self.with_mean = withMean
        self.with_std = withStd

    def fit(self, df) -> "StandardScalerModel":
        aggs = []
        for c in self.input_cols:
            aggs.append(F.avg(c).alias(f"m_{c}"))
            aggs.append(F.stddev(c).alias(f"s_{c}"))
        row = df.agg(*aggs).collect()[0].asDict()
        means = [row[f"m_{c}"] for c in self.input_cols]
        stds = [row[f"s_{c}"] or 1.0 for c in self.input_cols]
        return StandardScalerModel(self, means, stds)


class StandardScalerModel(Model):
    def __init__(self, scaler: StandardScaler, means, stds):
        self.scaler = scaler
        self.means = means
        self.stds = stds

    def transform(self, df):
        for c, out, m, s in zip(self.scaler.input_cols,
                                self.scaler.output_cols,
                                self.means, self.stds):
            e: E.Expression = F.col(c)
            if self.scaler.with_mean:
                e = e - float(m)
            if self.scaler.with_std:
                e = e / float(s if s else 1.0)
            df = df.withColumn(out, e)
        return df


class StringIndexer(Estimator):
    """Label -> index by descending frequency (reference:
    StringIndexer.scala 'frequencyDesc')."""

    def __init__(self, inputCol: str, outputCol: Optional[str] = None):
        self.input_col = inputCol
        self.output_col = outputCol or inputCol + "_idx"

    def fit(self, df) -> "StringIndexerModel":
        rows = (df.groupBy(self.input_col)
                .agg(F.count("*").alias("__n")).collect())
        pairs = sorted(((r.asDict()[self.input_col], r.asDict()["__n"])
                        for r in rows if r.asDict()[self.input_col] is not None),
                       key=lambda t: (-t[1], t[0]))
        labels = [p[0] for p in pairs]
        return StringIndexerModel(self, labels)


class StringIndexerModel(Model):
    def __init__(self, indexer: StringIndexer, labels):
        self.indexer = indexer
        self.labels = list(labels)

    def transform(self, df):
        # label -> index via CASE over the dictionary (host-evaluated,
        # fuses as a gather)
        e: E.Expression = E.Case(
            tuple((E.Cmp("==", E.Col(self.indexer.input_col),
                         E.Literal(lbl)), E.Literal(float(i)))
                  for i, lbl in enumerate(self.labels)), None)
        return df.withColumn(self.indexer.output_col, e)

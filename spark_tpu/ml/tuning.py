"""Model selection (reference: ml/tuning/CrossValidator.scala:102
k-fold fit/eval loop, ParamGridBuilder.scala)."""

from __future__ import annotations

import copy
from typing import Dict, List, Sequence

import numpy as np

from spark_tpu.ml.pipeline import Estimator, Model


class ParamGridBuilder:
    """Cartesian product of (attribute-name, values) grids. Params are
    named by the ESTIMATOR ATTRIBUTE they set (the engine has no Param
    objects — estimators are plain-attribute configured)."""

    def __init__(self):
        self._grid: Dict[str, Sequence] = {}

    def addGrid(self, attr: str, values: Sequence) -> "ParamGridBuilder":
        self._grid[attr] = list(values)
        return self

    def build(self) -> List[Dict[str, object]]:
        maps: List[Dict[str, object]] = [{}]
        for attr, values in self._grid.items():
            maps = [{**m, attr: v} for m in maps for v in values]
        return maps


class CrossValidator(Estimator):
    """k-fold cross validation over a param grid; refits the best
    params on the full data (reference: CrossValidator.scala:102)."""

    def __init__(self, estimator: Estimator,
                 estimatorParamMaps: List[Dict[str, object]],
                 evaluator, numFolds: int = 3, seed: int = 7):
        self.estimator = estimator
        self.param_maps = estimatorParamMaps or [{}]
        self.evaluator = evaluator
        self.num_folds = max(2, int(numFolds))
        self.seed = seed
        self.avg_metrics: List[float] = []

    def _folds(self, df):
        tbl = df.toArrow()
        n = tbl.num_rows
        rng = np.random.default_rng(self.seed)
        fold = rng.integers(0, self.num_folds, n)
        session = df._session
        out = []
        for k in range(self.num_folds):
            train = session.createDataFrame(tbl.filter(fold != k))
            test = session.createDataFrame(tbl.filter(fold == k))
            out.append((train, test))
        return out

    def fit(self, df) -> "CrossValidatorModel":
        folds = self._folds(df)
        self.avg_metrics = []
        for params in self.param_maps:
            scores = []
            for train, test in folds:
                est = copy.deepcopy(self.estimator)
                for attr, v in params.items():
                    if not hasattr(est, attr):
                        raise AttributeError(
                            f"estimator has no param attribute {attr!r}")
                    setattr(est, attr, v)
                model = est.fit(train)
                scores.append(self.evaluator.evaluate(
                    model.transform(test)))
            self.avg_metrics.append(float(np.mean(scores)))
        pick = (int(np.argmax(self.avg_metrics))
                if self.evaluator.is_larger_better
                else int(np.argmin(self.avg_metrics)))
        best_est = copy.deepcopy(self.estimator)
        for attr, v in self.param_maps[pick].items():
            setattr(best_est, attr, v)
        best_model = best_est.fit(df)
        return CrossValidatorModel(best_model, self.param_maps[pick],
                                   list(self.avg_metrics))


class CrossValidatorModel(Model):
    def __init__(self, best_model: Model, best_params, avg_metrics):
        self.bestModel = best_model
        self.bestParams = best_params
        self.avgMetrics = avg_metrics

    def transform(self, df):
        return self.bestModel.transform(df)

"""KMeans (reference: ml/clustering/KMeans.scala — Lloyd's algorithm;
here every iteration is an (n,k) distance matmul + masked mean updates,
all inside one jitted `fori_loop` — the MXU does the assignment step)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.api import functions as F
from spark_tpu.ml.pipeline import Estimator, Model, features_matrix


class KMeans(Estimator):
    def __init__(self, featuresCols: Sequence[str], k: int,
                 predictionCol: str = "prediction",
                 maxIter: int = 50, seed: int = 13):
        self.features_cols = list(featuresCols)
        self.k = int(k)
        self.prediction_col = predictionCol
        self.max_iter = maxIter
        self.seed = seed

    def fit(self, df) -> "KMeansModel":
        x = features_matrix(df, self.features_cols)
        k = self.k

        @jax.jit
        def lloyd(x, init_idx):
            centers0 = x[init_idx]

            def assign(centers):
                # ||x - c||^2 = ||x||^2 - 2 x.c + ||c||^2; the cross
                # term is the (n,k) MXU matmul
                cross = x @ centers.T
                d2 = (jnp.sum(x * x, 1, keepdims=True) - 2.0 * cross
                      + jnp.sum(centers * centers, 1)[None, :])
                return jnp.argmin(d2, axis=1)

            def step(_, centers):
                a = assign(centers)
                onehot = (a[:, None] == jnp.arange(k)[None, :]).astype(
                    x.dtype)
                counts = onehot.sum(0)
                sums = onehot.T @ x
                new = sums / jnp.maximum(counts, 1.0)[:, None]
                return jnp.where((counts > 0)[:, None], new, centers)

            return jax.lax.fori_loop(0, self.max_iter, step, centers0)

        # k-means|| style greedy farthest-point init (reference:
        # KMeans.scala initKMeansParallel) — random init can drop two
        # seeds in one blob and converge to a bad local optimum
        rng = np.random.default_rng(self.seed)
        xn = np.asarray(x)
        idxs = [int(rng.integers(0, xn.shape[0]))]
        d2 = ((xn - xn[idxs[0]]) ** 2).sum(1)
        for _ in range(1, k):
            nxt = int(np.argmax(d2))
            idxs.append(nxt)
            d2 = np.minimum(d2, ((xn - xn[nxt]) ** 2).sum(1))
        centers = lloyd(x, jnp.asarray(np.array(idxs)))
        return KMeansModel(self, np.asarray(centers))


class KMeansModel(Model):
    def __init__(self, km: KMeans, centers: np.ndarray):
        self.km = km
        self.centers = centers

    def transform(self, df):
        centers = jnp.asarray(self.centers)

        @F.udf(returnType=T.INT32)
        def nearest(*cols):
            x = jnp.stack([c.astype(jnp.float32) for c in cols], axis=1)
            cross = x @ centers.T
            d2 = (jnp.sum(x * x, 1, keepdims=True) - 2.0 * cross
                  + jnp.sum(centers * centers, 1)[None, :])
            return jnp.argmin(d2, axis=1).astype(jnp.int32)

        return df.withColumn(self.km.prediction_col,
                             nearest(*self.km.features_cols))

"""Logistic regression (reference:
ml/classification/LogisticRegression.scala — LBFGS over breeze; here
full-batch gradient descent as one jitted `fori_loop` of MXU matmuls)."""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E
from spark_tpu.ml.pipeline import Estimator, Model, features_matrix


class LogisticRegression(Estimator):
    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 maxIter: int = 200, stepSize: float = 0.5,
                 regParam: float = 0.0):
        self.features_cols = list(featuresCols)
        self.label_col = labelCol
        self.prediction_col = predictionCol
        self.probability_col = probabilityCol
        self.max_iter = maxIter
        self.step = stepSize
        self.reg = regParam

    def fit(self, df) -> "LogisticRegressionModel":
        xy = features_matrix(df, self.features_cols + [self.label_col])
        x, y = xy[:, :-1], xy[:, -1]

        @partial(jax.jit, static_argnums=())
        def train(x, y):
            n, d = x.shape
            ones = jnp.ones((n, 1), x.dtype)
            xa = jnp.concatenate([x, ones], axis=1)

            def loss(w):
                z = xa @ w
                # numerically-stable logistic loss
                nll = jnp.mean(jnp.logaddexp(0.0, z) - y * z)
                return nll + self.reg * jnp.sum(w[:-1] ** 2)

            grad = jax.grad(loss)

            def step(_, w):
                return w - self.step * grad(w)

            return jax.lax.fori_loop(0, self.max_iter, step,
                                     jnp.zeros((d + 1,), x.dtype))

        w = train(x, y)
        coef = [float(v) for v in w[:-1]]
        return LogisticRegressionModel(self, coef, float(w[-1]))


class LogisticRegressionModel(Model):
    def __init__(self, lr: LogisticRegression, coefficients, intercept):
        self.lr = lr
        self.coefficients = coefficients
        self.intercept = intercept

    def transform(self, df):
        z: E.Expression = E.Literal(self.intercept)
        for c, w in zip(self.lr.features_cols, self.coefficients):
            z = z + F.col(c) * float(w)
        prob = E.Literal(1.0) / (E.Literal(1.0)
                                 + E.UnaryMath("exp", E.Neg(z)))
        df = df.withColumn(self.lr.probability_col, prob)
        pred = E.Case(((E.Cmp(">", E.Col(self.lr.probability_col),
                              E.Literal(0.5)), E.Literal(1.0)),),
                      E.Literal(0.0))
        return df.withColumn(self.lr.prediction_col, pred)

"""Evaluators (reference: ml/evaluation/RegressionEvaluator.scala,
MulticlassClassificationEvaluator.scala) — metrics computed by the
ENGINE as one aggregate query, not a host loop."""

from __future__ import annotations

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E


class RegressionEvaluator:
    def __init__(self, labelCol: str = "label",
                 predictionCol: str = "prediction",
                 metricName: str = "rmse"):
        if metricName not in ("rmse", "mse", "mae"):
            raise ValueError(f"unknown metric {metricName!r}")
        self.label_col = labelCol
        self.prediction_col = predictionCol
        self.metric = metricName

    @property
    def is_larger_better(self) -> bool:
        return False

    def evaluate(self, df) -> float:
        err = E.Arith("-", E.Col(self.prediction_col),
                      E.Col(self.label_col))
        if self.metric == "mae":
            agg = F.avg(E.Abs(err))
        else:
            agg = F.avg(E.Arith("*", err, err))
        v = float(df.agg(E.Alias(agg, "m")).collect()[0]["m"])
        return v ** 0.5 if self.metric == "rmse" else v


class MulticlassClassificationEvaluator:
    def __init__(self, labelCol: str = "label",
                 predictionCol: str = "prediction",
                 metricName: str = "accuracy"):
        if metricName != "accuracy":
            raise ValueError(f"unknown metric {metricName!r}")
        self.label_col = labelCol
        self.prediction_col = predictionCol
        self.metric = metricName

    @property
    def is_larger_better(self) -> bool:
        return True

    def evaluate(self, df) -> float:
        hit = E.Case(((E.Cmp("==", E.Col(self.prediction_col),
                             E.Col(self.label_col)), E.Literal(1.0)),),
                     E.Literal(0.0))
        return float(df.agg(E.Alias(F.avg(hit), "m")).collect()[0]["m"])

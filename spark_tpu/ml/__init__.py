"""ML pipelines — the spark.ml subset (reference:
mllib/src/main/scala/org/apache/spark/ml/Pipeline.scala:41,93 —
Estimator/Transformer/PipelineModel; feature/, regression/,
classification/, clustering/, evaluation/).

TPU-first: fitting extracts feature columns into one device matrix and
runs closed-form/iterative solvers as jitted MXU programs
(normal equations, full-batch GD in `lax.fori_loop`, Lloyd iterations);
transform() emits ordinary engine expressions or jax UDFs, so model
application fuses into query stages like any other projection — there is
no separate "ML runtime" (the reference drives per-row JVM UDFs over
breeze/BLAS)."""

from spark_tpu.ml.pipeline import Estimator, Model, Pipeline, Transformer
from spark_tpu.ml.features import StandardScaler, StringIndexer
from spark_tpu.ml.regression import LinearRegression
from spark_tpu.ml.classification import LogisticRegression
from spark_tpu.ml.clustering import KMeans

__all__ = ["Estimator", "Transformer", "Model", "Pipeline",
           "StandardScaler", "StringIndexer", "LinearRegression",
           "LogisticRegression", "KMeans"]
from spark_tpu.ml.tree import (DecisionTreeClassifier,  # noqa: F401,E402
                               DecisionTreeRegressor,
                               RandomForestClassifier,
                               RandomForestRegressor)
from spark_tpu.ml.tuning import (CrossValidator,  # noqa: F401,E402
                                 ParamGridBuilder)
from spark_tpu.ml.evaluation import (  # noqa: F401,E402
    MulticlassClassificationEvaluator, RegressionEvaluator)

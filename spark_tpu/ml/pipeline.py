"""Estimator / Transformer / Pipeline core (reference:
ml/Pipeline.scala:41 Estimator.fit, :93 Pipeline.fit —
stage-by-stage fit-then-transform)."""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np


class Transformer:
    def transform(self, df):
        raise NotImplementedError


class Model(Transformer):
    pass


class Estimator:
    def fit(self, df) -> Model:
        raise NotImplementedError


class Pipeline(Estimator):
    """fit() runs stages in order: estimators fit on the running
    dataframe and their models transform it for later stages
    (reference: Pipeline.scala:93)."""

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def fit(self, df) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        for st in self.stages:
            if isinstance(st, Estimator):
                model = st.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            else:
                fitted.append(st)
                cur = st.transform(cur)
        return PipelineModel(fitted)


class PipelineModel(Model):
    def __init__(self, stages: Sequence[Transformer]):
        self.stages = list(stages)

    def transform(self, df):
        for st in self.stages:
            df = st.transform(df)
        return df


def features_matrix(df, cols: Sequence[str]):
    """Materialize feature columns as a dense device matrix (live rows
    compacted) — the input surface every fitter shares. One transfer,
    then everything is MXU work."""
    batch = df.select(*cols)._execute()
    mask = np.asarray(batch.data.row_mask)
    for name, cd in zip(cols, batch.data.columns):
        if cd.validity is not None and not np.asarray(
                cd.validity)[mask].all():
            raise ValueError(
                f"feature column {name!r} contains NULLs; drop or "
                "impute before fitting (reference: spark.ml raises on "
                "null features too)")
    arrs = [np.asarray(cd.data)[mask].astype(np.float32)
            for cd in batch.data.columns]
    return jnp.asarray(np.stack(arrs, axis=1))

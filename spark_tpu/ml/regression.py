"""Linear regression (reference: ml/regression/LinearRegression.scala —
WLS/normal-equations solver path)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E
from spark_tpu.ml.pipeline import Estimator, Model, features_matrix


class LinearRegression(Estimator):
    """Closed-form ridge-regularized normal equations on device — one
    (d+1)x(d+1) solve after an MXU gram-matrix matmul."""

    def __init__(self, featuresCols: Sequence[str], labelCol: str,
                 predictionCol: str = "prediction",
                 regParam: float = 1e-6):
        self.features_cols = list(featuresCols)
        self.label_col = labelCol
        self.prediction_col = predictionCol
        self.reg = regParam

    def fit(self, df) -> "LinearRegressionModel":
        xy = features_matrix(df, self.features_cols + [self.label_col])
        x, y = xy[:, :-1], xy[:, -1]

        @jax.jit
        def solve(x, y):
            ones = jnp.ones((x.shape[0], 1), x.dtype)
            xa = jnp.concatenate([x, ones], axis=1)
            g = xa.T @ xa + self.reg * jnp.eye(xa.shape[1], dtype=x.dtype)
            b = xa.T @ y
            return jnp.linalg.solve(g, b)

        w = solve(x, y)
        coef = [float(v) for v in w[:-1]]
        return LinearRegressionModel(self, coef, float(w[-1]))


class LinearRegressionModel(Model):
    def __init__(self, lr: LinearRegression, coefficients, intercept):
        self.lr = lr
        self.coefficients = coefficients
        self.intercept = intercept

    def transform(self, df):
        e: E.Expression = E.Literal(self.intercept)
        for c, w in zip(self.lr.features_cols, self.coefficients):
            e = e + F.col(c) * float(w)
        return df.withColumn(self.lr.prediction_col, e)

"""End-to-end caller-deadline propagation.

One absolute deadline, minted at the outermost entry point (the connect
Client's per-request timeout, or ``spark.tpu.deadline.defaultTimeoutS``
at ``DataFrame.collect``), travels the whole request path:

    client --X-SparkTpu-Deadline--> router --header--> replica
        --scheduler ticket--> worker thread --contextvar--> every
        retry/wait seam (chunk pipeline, spill retry, mview refresh,
        dispatch re-forward, single-flight follower waits)

so work STOPS the moment the caller can no longer use the result, and
the failure surfaces as the typed :class:`DeadlineExceeded` instead of
the work grinding on against an absent caller.

The wire form is the absolute epoch time in seconds (not a relative
timeout): relative values re-stamped at every hop would silently grant
each hop a fresh budget, which is exactly the bug this module removes.
Clock skew between processes shortens or lengthens the effective
deadline by the skew — acceptable for the sub-minute budgets served
here, and the same trade gRPC's deadline propagation makes.

Contextvars do not cross threads: thread-hopping code (scheduler
workers, the chunk-pipeline producer) must capture :func:`current` and
re-enter it with :func:`bind` — the exact discipline trace contexts
already follow.

Classification contract: :class:`DeadlineExceeded` is NEVER transient
(``recovery.is_transient`` carves it out by type before its marker
scan) — the caller's deadline passing is a property of the caller, not
of the environment, so no retry layer may absorb it.

This module is deliberately near the bottom of the import graph
(stdlib + the conf registry only): faults, recovery, and every serving
layer import it.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Iterator, Optional

from spark_tpu import conf as CF

#: absolute epoch-seconds deadline, forwarded verbatim hop to hop
DEADLINE_HEADER = "X-SparkTpu-Deadline"

DEADLINE_DEFAULT_TIMEOUT = CF.register(
    "spark.tpu.deadline.defaultTimeoutS", 0.0,
    "Deadline minted at DataFrame.collect()/toArrow() when no caller "
    "deadline is already bound (seconds; 0 disables). Connect clients "
    "mint their own from the per-request timeout regardless.", float)

_DEADLINE: ContextVar[Optional[float]] = ContextVar(
    "spark_tpu_deadline", default=None)


class DeadlineExceeded(RuntimeError):
    """The caller's absolute deadline passed. Typed and terminal: never
    retried (a deadline that passed once has passed for every retry),
    never absorbed by a fallback ladder."""

    def __init__(self, where: str, deadline: float,
                 now: Optional[float] = None):
        now = time.time() if now is None else now
        self.where = where
        self.deadline = float(deadline)
        self.late_s = max(0.0, now - self.deadline)
        super().__init__(
            f"DEADLINE_EXCEEDED at {where}: caller deadline passed "
            f"{self.late_s:.3f}s ago")


def current() -> Optional[float]:
    """The ambient absolute deadline (epoch s), or None when unbound."""
    return _DEADLINE.get()


def remaining(now: Optional[float] = None) -> Optional[float]:
    """Seconds until the ambient deadline (may be negative once
    passed); None when no deadline is bound."""
    dl = _DEADLINE.get()
    if dl is None:
        return None
    return dl - (time.time() if now is None else now)


def expired(now: Optional[float] = None) -> bool:
    rem = remaining(now)
    return rem is not None and rem <= 0.0


def check(where: str) -> None:
    """Cooperative deadline seam: raise the typed
    :class:`DeadlineExceeded` when the ambient deadline has passed.
    No-op when none is bound."""
    dl = _DEADLINE.get()
    if dl is not None and time.time() > dl:
        raise DeadlineExceeded(where, dl)


def cap_sleep(seconds: float) -> float:
    """Clamp a backoff/wait duration so no seam ever sleeps past the
    ambient deadline (the connect Client's past-timeout-backoff bug,
    fixed everywhere at once)."""
    s = max(0.0, float(seconds))
    rem = remaining()
    if rem is None:
        return s
    return max(0.0, min(s, rem))


def mint(timeout_s: Optional[float]) -> Optional[float]:
    """Absolute deadline ``timeout_s`` from now (None/<=0 -> None)."""
    if timeout_s is None or float(timeout_s) <= 0.0:
        return None
    return time.time() + float(timeout_s)


@contextmanager
def bind(deadline: Optional[float]) -> Iterator[Optional[float]]:
    """Enter an absolute deadline for the dynamic extent (None binds
    nothing and is a no-op, so call sites need no conditionals). When a
    TIGHTER deadline is already bound, it wins — a hop may shorten the
    caller's budget, never extend it."""
    if deadline is None:
        yield _DEADLINE.get()
        return
    prev = _DEADLINE.get()
    eff = deadline if prev is None else min(prev, deadline)
    token = _DEADLINE.set(eff)
    try:
        yield eff
    finally:
        _DEADLINE.reset(token)


@contextmanager
def bind_default(conf) -> Iterator[Optional[float]]:
    """Root-entry helper (DataFrame._execute): mint from
    ``spark.tpu.deadline.defaultTimeoutS`` only when NO deadline is
    already bound — a nested query under a served request must inherit
    the request's deadline, not restart the clock."""
    if _DEADLINE.get() is not None or conf is None:
        yield _DEADLINE.get()
        return
    try:
        timeout = float(conf.get(DEADLINE_DEFAULT_TIMEOUT))
    except Exception:
        timeout = 0.0
    with bind(mint(timeout)) as dl:
        yield dl


def header_value() -> Optional[str]:
    """Wire form of the ambient deadline for ``X-SparkTpu-Deadline``."""
    dl = _DEADLINE.get()
    return f"{dl:.6f}" if dl is not None else None


def from_header(value: Optional[str]) -> Optional[float]:
    """Decode ``X-SparkTpu-Deadline``; malformed values are dropped (a
    bad peer must not break serving — it just loses its deadline)."""
    if not value:
        return None
    try:
        return float(str(value).strip())
    except (TypeError, ValueError):
        return None

"""pandas-on-spark subset (reference: python/pyspark/pandas/ — the
pandas API executed by the SQL engine).

A thin, lazy layer: a ``PsFrame`` wraps an engine DataFrame; indexing,
arithmetic, boolean filtering, groupby aggregation and merge translate
to logical-plan builders and execute on the TPU engine (single chip or
mesh) only at materialization points (``to_pandas``, ``len``, ``head``).

    import spark_tpu.pandas as ps
    pdf = ps.read_parquet("lineitem.parquet")
    out = pdf[pdf.l_quantity > 10].groupby("l_returnflag").agg(
        {"l_extendedprice": "sum"})
    out.to_pandas()
"""

from spark_tpu.pandas.frame import (PsFrame, concat, from_pandas,
                                    read_parquet)

__all__ = ["PsFrame", "from_pandas", "read_parquet"]

"""PsFrame / PsColumn / PsGroupBy (reference: python/pyspark/pandas/
frame.py, generic.py, groupby.py — pared to the core surface)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E


def _session():
    from spark_tpu.api.session import SparkSession

    return SparkSession.builder.getOrCreate()


def from_pandas(pdf) -> "PsFrame":
    return PsFrame(_session().createDataFrame(pdf))


def read_parquet(path: str) -> "PsFrame":
    return PsFrame(_session().read.parquet(path))


class PsColumn:
    """A deferred column expression bound to a frame."""

    def __init__(self, frame: "PsFrame", expr: E.Expression):
        self._frame = frame
        self._expr = expr

    def _bin(self, other, fn):
        o = other._expr if isinstance(other, PsColumn) else other
        return PsColumn(self._frame, fn(self._expr, o))

    def __add__(self, o):
        return self._bin(o, lambda a, b: a + b)

    def __sub__(self, o):
        return self._bin(o, lambda a, b: a - b)

    def __mul__(self, o):
        return self._bin(o, lambda a, b: a * b)

    def __truediv__(self, o):
        return self._bin(o, lambda a, b: a / b)

    def __gt__(self, o):
        return self._bin(o, lambda a, b: a > b)

    def __ge__(self, o):
        return self._bin(o, lambda a, b: a >= b)

    def __lt__(self, o):
        return self._bin(o, lambda a, b: a < b)

    def __le__(self, o):
        return self._bin(o, lambda a, b: a <= b)

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(o, lambda a, b: a == b)

    def __ne__(self, o):  # type: ignore[override]
        return self._bin(o, lambda a, b: a != b)

    def __and__(self, o):
        return self._bin(o, lambda a, b: a & b)

    def __or__(self, o):
        return self._bin(o, lambda a, b: a | b)

    def __invert__(self):
        return PsColumn(self._frame, ~self._expr)

    def isin(self, values):
        return PsColumn(self._frame, self._expr.isin(list(values)))

    # reductions materialize
    def _agg(self, fn):
        row = self._frame._df.agg(fn(self._expr).alias("v")).collect()
        return row[0].v

    def sum(self):
        return self._agg(F.sum)

    def mean(self):
        return self._agg(F.avg)

    def min(self):  # noqa: A003
        return self._agg(F.min)

    def max(self):  # noqa: A003
        return self._agg(F.max)

    def count(self):
        return self._agg(F.count)

    def nunique(self):
        return self._agg(F.countDistinct)

    def to_pandas(self):
        name = getattr(self._expr, "name", "col")
        return self._frame._df.select(
            self._expr.alias(name))._execute().to_pandas()[name]


_AGG_FNS = {"sum": F.sum, "mean": F.avg, "avg": F.avg, "count": F.count,
            "min": F.min, "max": F.max, "nunique": F.countDistinct,
            "std": F.stddev}


class PsGroupBy:
    def __init__(self, frame: "PsFrame", keys: List[str]):
        self._frame = frame
        self._keys = keys

    def agg(self, spec: Dict[str, Union[str, List[str]]]) -> "PsFrame":
        aggs = []
        for col, hows in spec.items():
            for how in ([hows] if isinstance(hows, str) else hows):
                aggs.append(_AGG_FNS[how](col).alias(
                    f"{col}_{how}" if not isinstance(hows, str)
                    else col))
        return PsFrame(self._frame._df.groupBy(*self._keys).agg(*aggs))

    def _all_numeric(self, how: str) -> "PsFrame":
        from spark_tpu import types as T

        df = self._frame._df
        cols = [f.name for f in df.schema.fields
                if f.name not in self._keys
                and not isinstance(f.dtype, (T.StringType, T.DateType))]
        aggs = [_AGG_FNS[how](c).alias(c) for c in cols]
        return PsFrame(df.groupBy(*self._keys).agg(*aggs))

    def sum(self):
        return self._all_numeric("sum")

    def mean(self):
        return self._all_numeric("mean")

    def count(self):
        return PsFrame(self._frame._df.groupBy(*self._keys)
                       .agg(F.count("*").alias("count")))

    def min(self):  # noqa: A003
        return self._all_numeric("min")

    def max(self):  # noqa: A003
        return self._all_numeric("max")


class PsFrame:
    def __init__(self, df):
        self._df = df

    # -- metadata -------------------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return self._df.columns

    @property
    def dtypes(self):
        return {f.name: repr(f.dtype) for f in self._df.schema.fields}

    def __len__(self) -> int:
        return self._df.count()

    def __repr__(self):
        return f"PsFrame{self.columns}"

    # -- selection / filtering ------------------------------------------------

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._df.columns:
            return PsColumn(self, E.Col(name))
        raise AttributeError(name)

    def __getitem__(self, key):
        if isinstance(key, str):
            return PsColumn(self, E.Col(key))
        if isinstance(key, list):
            return PsFrame(self._df.select(*key))
        if isinstance(key, PsColumn):  # boolean filter
            return PsFrame(self._df.filter(key._expr))
        raise TypeError(f"cannot index with {type(key).__name__}")

    def __setitem__(self, name: str, value) -> None:
        expr = value._expr if isinstance(value, PsColumn) else E.Literal(value)
        self._df = self._df.withColumn(name, expr)

    def assign(self, **cols) -> "PsFrame":
        df = self._df
        for name, v in cols.items():
            df = df.withColumn(
                name, v._expr if isinstance(v, PsColumn) else E.Literal(v))
        return PsFrame(df)

    def drop(self, columns: Sequence[str]) -> "PsFrame":
        return PsFrame(self._df.drop(*columns))

    def rename(self, columns: Dict[str, str]) -> "PsFrame":
        df = self._df
        for old, new in columns.items():
            df = df.withColumnRenamed(old, new)
        return PsFrame(df)

    def drop_duplicates(self, subset=None) -> "PsFrame":
        return PsFrame(self._df.dropDuplicates(subset))

    # -- relational -----------------------------------------------------------

    def groupby(self, by: Union[str, List[str]]) -> PsGroupBy:
        keys = [by] if isinstance(by, str) else list(by)
        return PsGroupBy(self, keys)

    def merge(self, other: "PsFrame", on: Union[str, List[str]],
              how: str = "inner") -> "PsFrame":
        return PsFrame(self._df.join(other._df, on=on, how=how))

    def sort_values(self, by: Union[str, List[str]],
                    ascending: bool = True) -> "PsFrame":
        cols = [by] if isinstance(by, str) else list(by)
        return PsFrame(self._df.sort(*cols, ascending=ascending))

    # -- indexing -------------------------------------------------------------

    @property
    def iloc(self) -> "_ILoc":
        return _ILoc(self)

    @property
    def loc(self) -> "_Loc":
        return _Loc(self)

    # -- cleaning / ranking ---------------------------------------------------

    def fillna(self, value, subset=None) -> "PsFrame":
        return PsFrame(self._df.fillna(value, subset=subset))

    def dropna(self, subset=None) -> "PsFrame":
        return PsFrame(self._df.dropna(subset=subset))

    def value_counts(self, col: str) -> "PsFrame":
        from spark_tpu.api import functions as F

        return PsFrame(self._df.groupBy(col)
                       .agg(F.count("*").alias("count"))
                       .sort("count", ascending=False))

    def nlargest(self, n: int, col: str) -> "PsFrame":
        return PsFrame(self._df.sort(col, ascending=False).limit(n))

    def nsmallest(self, n: int, col: str) -> "PsFrame":
        return PsFrame(self._df.sort(col, ascending=True).limit(n))

    # -- materialization ------------------------------------------------------

    def head(self, n: int = 5):
        return PsFrame(self._df.limit(n)).to_pandas()

    def to_pandas(self):
        return self._df._execute().to_pandas()

    def describe(self):
        from spark_tpu import types as T

        df = self._df
        cols = [f.name for f in df.schema.fields
                if not isinstance(f.dtype, (T.StringType, T.DateType))]
        hows = ("count", "mean", "std", "min", "max")
        aggs = [_AGG_FNS[how](c).alias(f"{how}__{c}")
                for how in hows for c in cols]
        row = df.agg(*aggs).collect()[0].asDict()  # ONE execution
        stats = [dict({c: row[f"{how}__{c}"] for c in cols},
                      statistic=how) for how in hows]
        import pandas as pd

        return pd.DataFrame(stats).set_index("statistic")


class _ILoc:
    """Positional row access: slices plan as limit/offset (no full
    materialization); a bare int materializes one row (reference:
    pyspark.pandas iLocIndexer)."""

    def __init__(self, frame: "PsFrame"):
        self._frame = frame

    def __getitem__(self, key):
        df = self._frame._df
        if isinstance(key, slice):
            if (key.step or 1) != 1:
                raise NotImplementedError("iloc step slicing")
            start = key.start or 0
            if start < 0 or (key.stop is not None and key.stop < 0):
                raise NotImplementedError("negative iloc bounds")
            out = df.offset(start) if start else df
            if key.stop is not None:
                out = out.limit(max(0, key.stop - start))
            return PsFrame(out)
        if isinstance(key, int):
            pdf = PsFrame(df.offset(key).limit(1)).to_pandas()
            if not len(pdf):
                raise IndexError(key)
            return pdf.iloc[0]
        raise TypeError(f"cannot iloc-index with {type(key).__name__}")


class _Loc:
    """Label/mask access: loc[mask], loc[mask, cols], loc[:, cols]
    (reference: pyspark.pandas LocIndexer — the row-label forms that
    need a materialized index are out of scope, like ps defaults with
    distributed-sequence off)."""

    def __init__(self, frame: "PsFrame"):
        self._frame = frame

    def __getitem__(self, key):
        rows, cols = key if isinstance(key, tuple) else (key, None)
        df = self._frame._df
        if isinstance(rows, PsColumn):
            df = df.filter(rows._expr)
        elif not (isinstance(rows, slice) and rows.start is None
                  and rows.stop is None):
            raise NotImplementedError(
                "loc supports boolean-mask rows or ':' (positional "
                "label indexes are not materialized)")
        if cols is not None:
            names = [cols] if isinstance(cols, str) else list(cols)
            df = df.select(*names)
        return PsFrame(df)


def concat(frames: Sequence["PsFrame"], ignore_index: bool = True
           ) -> "PsFrame":
    """Row-wise union by COLUMN NAME; a column missing from a frame
    contributes NULLs (reference: pyspark.pandas.concat outer-align
    behavior)."""
    if not frames:
        raise ValueError("concat of no frames")
    all_cols: List[str] = []
    for f in frames:
        for c in f.columns:
            if c not in all_cols:
                all_cols.append(c)
    dtypes = {}
    for f in frames:
        for fld in f._df.schema.fields:
            dtypes.setdefault(fld.name, fld.dtype)
    aligned = []
    for f in frames:
        df = f._df
        missing = [c for c in all_cols if c not in f.columns]
        for c in missing:
            # typed NULL: the column's type comes from a frame that has it
            df = df.withColumn(c, E.Literal(None, dtypes[c]))
        aligned.append(df.select(*all_cols))
    out = aligned[0]
    for df in aligned[1:]:
        out = out.unionByName(df)
    return PsFrame(out)



"""Incremental aggregation: accumulator decomposition.

Splits an Aggregate into mergeable accumulators (count/sum/avg/min/max;
avg = sum+count) so partial results computed over CHUNKS of input can be
combined by a second ordinary aggregation over their union. Used by two
engines with the same math:

- structured streaming (micro-batch state merge,
  reference: statefulOperators.scala + AggUtils partial/final split)
- out-of-HBM batch execution (chunked scan-aggregate, the spill
  analogue — reference: ExternalSorter.scala:93 / TungstenAggregation
  falling back to sort-merge passes)
"""

from __future__ import annotations

from typing import Dict, List

from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


class AggSpec:
    """Accumulator decomposition of one Aggregate (streaming state merge,
    chunked out-of-HBM execution, mesh map-side combine)."""

    def __init__(self, groupings, aggregates):
        self._aggregates = tuple(aggregates)
        self.groupings = [E.strip_alias(g) for g in groupings]
        #: tumbling-window widths per grouping (None = not a window key);
        #: the engine executes the window as plain arithmetic, the width
        #: only matters for watermark eviction
        self.window_widths = [
            g.width if isinstance(g, E.TumblingWindow) else None
            for g in self.groupings]
        #: gap-based session window grouping (at most one): the
        #: streaming runner merges overlapping sessions in state
        self.session_idx: "int | None" = None
        self.session_gap: "int | None" = None
        for i, g in enumerate(self.groupings):
            if isinstance(g, E.SessionWindow):
                if self.session_idx is not None:
                    raise NotImplementedError(
                        "multiple session_window groupings")
                self.session_idx = i
                self.session_gap = g.gap
        self.groupings_exec = [
            g.as_arith() if isinstance(g, E.TumblingWindow)
            else (g.child if isinstance(g, E.SessionWindow) else g)
            for g in self.groupings]
        self.key_names = [f"__k{i}" for i in range(len(self.groupings))]
        self.partials: List[E.Alias] = []   # over input rows
        self.merges: List[E.Alias] = []     # over union(state, partials)
        self._final: Dict[tuple, E.Expression] = {}
        for call in {E.expr_key(a): a
                     for e in self._aggregates
                     for a in E.collect_aggregates(e)}.values():
            self._add(call)
        self.outputs: List[E.Alias] = []
        key_map = {E.expr_key(g): E.Col(n)
                   for g, n in zip(self.groupings, self.key_names)}

        def repl(x: E.Expression) -> E.Expression:
            # pre-order: an aggregate call is replaced wholesale BEFORE
            # its children could be rewritten (count(k) grouped by k)
            if isinstance(x, E.AggregateExpression):
                return self._final[E.expr_key(x)]
            k = E.expr_key(x)
            if k in key_map:
                return key_map[k]
            return x

        for e in self._aggregates:
            out = E.transform_expr_down(E.strip_alias(e), repl)
            self.outputs.append(E.Alias(out, e.name))

    def _acc(self, name: str, partial: E.Expression,
             merge: E.Expression) -> None:
        self.partials.append(E.Alias(partial, name))
        self.merges.append(E.Alias(merge, name))

    def _add(self, call: E.AggregateExpression) -> None:
        # shared legality rule set (analysis/legality.py): DISTINCT and
        # non-Count/Sum/Avg/Min/Max calls cannot decompose into
        # mergeable accumulators
        from spark_tpu.analysis import legality

        verdict = legality.accumulator_verdict(call)
        if not verdict.ok:
            raise NotImplementedError(verdict.reason)
        i = len(self.partials)
        k = E.expr_key(call)
        if isinstance(call, E.Count):
            n = f"__a{i}"
            self._acc(n, call, E.Sum(E.Col(n)))
            self._final[k] = E.Coalesce((E.Col(n), E.Literal(0)))
        elif isinstance(call, (E.Sum, E.Avg)):
            s, c = f"__a{i}s", f"__a{i}n"
            self._acc(s, E.Sum(call.child), E.Sum(E.Col(s)))
            self._acc(c, E.Count(call.child), E.Sum(E.Col(c)))
            nonzero = E.Cmp(">", E.Coalesce((E.Col(c), E.Literal(0))),
                            E.Literal(0))
            if isinstance(call, E.Sum):
                self._final[k] = E.Case(((nonzero, E.Col(s)),), None)
            else:
                self._final[k] = E.Case(
                    ((nonzero, E.Arith("/", E.Col(s), E.Col(c))),), None)
        elif isinstance(call, (E.Min, E.Max)):
            n = f"__a{i}"
            cls = E.Min if isinstance(call, E.Min) else E.Max
            self._acc(n, call, cls(E.Col(n)))
            self._final[k] = E.Col(n)
        else:
            raise NotImplementedError(
                f"aggregate {call} is not a mergeable accumulator")


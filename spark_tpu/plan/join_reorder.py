"""Cost-based join reordering.

Analogue of the reference's CostBasedJoinReorder (reference:
sql/catalyst/.../optimizer/CostBasedJoinReorder.scala:1 — a DP over join
orders driven by ANALYZE-collected statistics) and the size-estimation
side of JoinSelectionHelper. The TPU build has no persisted statistics;
instead it estimates cardinalities directly from the physical substrate
(device batch capacities, Parquet row-group metadata via
``FileSource.count_rows`` — exact and memoized for pushed filters) and
greedily builds a left-deep order that keeps intermediate results small.
Greedy-smallest-next rather than full DP: TPC-H-class plans have <=8
relations and star/snowflake shapes where greedy and DP agree, and the
estimator's error bars don't justify an exponential search.

Scope guard: only maximal clusters of INNER equi-joins are reordered,
and only when every column name in the cluster is globally unique (so
key/condition expressions keep meaning under any order; '#2' dedup
renames would otherwise shift). Residual non-equi conditions are applied
as a Filter above the reordered cluster — equivalent for inner joins.
The cluster's output column order is restored with a Project so parents
observe an identical schema.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


# ---- cardinality estimation -------------------------------------------------


def _filter_selectivity(cond: E.Expression) -> float:
    """Per-conjunct heuristic (reference: FilterEstimation.scala defaults
    collapsed to: equality selects less than a range predicate)."""
    from spark_tpu.plan.optimizer import split_conjuncts

    sel = 1.0
    for c in split_conjuncts(cond):
        if isinstance(c, E.Cmp) and c.op == "==":
            sel *= 0.1
        elif isinstance(c, (E.In, E.Like)):
            sel *= 0.2
        else:
            sel *= 0.4
    return max(sel, 1e-4)


def estimate_rows(plan: L.LogicalPlan) -> float:
    """Output cardinality estimate. Exact at leaves (batch capacities,
    file metadata + pushed-filter counts), heuristic above them
    (reference: statsEstimation/{SizeInBytesOnlyStatsPlanVisitor,
    FilterEstimation,JoinEstimation}.scala)."""
    if isinstance(plan, L.Relation):
        return float(plan.batch.capacity)
    if isinstance(plan, L.UnresolvedScan):
        try:
            # exact: Parquet metadata (+ memoized filtered count when
            # predicates were pushed into the scan)
            return float(plan.source.count_rows(plan.filters))
        except Exception:
            sel = 1.0
            for f in plan.filters:
                sel *= _filter_selectivity(f)
            return 1e6 * sel
    if isinstance(plan, L.Range):
        return float(plan.num_rows)
    if isinstance(plan, L.Filter):
        return max(1.0, estimate_rows(plan.child)
                   * _filter_selectivity(plan.condition))
    if isinstance(plan, L.Limit):
        return min(float(plan.n), estimate_rows(plan.child))
    if isinstance(plan, L.Sample):
        return estimate_rows(plan.child) * plan.fraction
    if isinstance(plan, L.Aggregate):
        child = estimate_rows(plan.child)
        if not plan.groupings:
            return 1.0
        return max(1.0, child ** 0.75)
    if isinstance(plan, L.Distinct):
        return max(1.0, estimate_rows(plan.child) ** 0.9)
    if isinstance(plan, L.Join):
        l = estimate_rows(plan.left)
        r = estimate_rows(plan.right)
        if plan.how == "cross" and not plan.left_keys:
            return l * r
        if plan.how in ("left_semi", "left_anti"):
            return max(1.0, l * 0.5)
        # PK-FK assumption for equi joins: one side's keys are ~unique
        return max(l, r)
    if isinstance(plan, L.Union):
        return sum(estimate_rows(c) for c in plan.children())
    children = plan.children()
    if len(children) == 1:
        return estimate_rows(children[0])
    return max((estimate_rows(c) for c in children), default=1.0)


# ---- NDV (distinct-count) estimation ---------------------------------------
#
# The reference CBO reads column NDVs from ANALYZE-collected stats
# (statsEstimation/JoinEstimation.scala); here they come from the data
# itself: one projected-column scan per (source, column), memoized on
# the FileSource. |T join R on k| = |T|*|R| / max(ndv_T(k), ndv_R(k)) —
# without this, a many-to-many key (e.g. TPC-H q5 joining supplier to
# customer on nationkey, 25 distinct values) looks identical to a PK-FK
# join and the greedy happily materializes the junk-pair blowup.

_REL_NDV_CAP = 1 << 22  # device relations larger than this: skip fetch


def _atom_ndv(atom: L.LogicalPlan, expr: E.Expression) -> Optional[float]:
    """Approximate distinct count of a join-key expression on an atom;
    None = unknown (callers fall back to rows, i.e. assume unique)."""
    inner = E.strip_alias(expr)
    if not isinstance(inner, E.Col):
        return None
    name = inner.col_name
    node = atom
    while True:
        if isinstance(node, (L.Filter, L.SubqueryAlias, L.Limit,
                             L.Sample, L.Distinct, L.Sort)):
            node = node.children()[0]
            continue
        if isinstance(node, L.Project):
            # follow plain renames only
            match = [e for e in node.exprs if e.name == name]
            if len(match) != 1:
                return None
            src = E.strip_alias(match[0])
            if not isinstance(src, E.Col):
                return None
            name = src.col_name
            node = node.child
            continue
        break
    if isinstance(node, L.UnresolvedScan):
        try:
            return float(_scan_ndv(node.source, name))
        except Exception:
            return None
    if isinstance(node, L.Relation):
        if node.batch.capacity > _REL_NDV_CAP \
                or name not in node.batch.schema:
            return None
        try:
            import numpy as np

            cd = node.batch.column(name)
            return float(np.unique(np.asarray(cd.data)).size)
        except Exception:
            return None
    if isinstance(node, L.Range):
        return float(node.num_rows)
    return None


def _scan_ndv(source, column: str) -> int:
    cache = getattr(source, "_ndv_cache", None)
    if cache is None:
        cache = source._ndv_cache = {}
    if column not in cache:
        import pyarrow.compute as pc

        tbl = source._open().to_table(columns=[column])
        cache[column] = int(pc.count_distinct(tbl.column(column)).as_py())
    return cache[column]


# ---- cluster flattening -----------------------------------------------------


def _flatten(node: L.LogicalPlan, atoms: List[L.LogicalPlan],
             key_pairs: List[Tuple[E.Expression, E.Expression]],
             conds: List[E.Expression]) -> bool:
    """Flatten a maximal inner-equi-join subtree. Returns False when the
    cluster shape is out of scope (a keyless theta join would otherwise
    be turned into a cartesian product)."""
    if isinstance(node, L.Join) and node.how == "inner":
        if not node.left_keys:
            return False
        if not _flatten(node.left, atoms, key_pairs, conds):
            return False
        if not _flatten(node.right, atoms, key_pairs, conds):
            return False
        key_pairs.extend(zip(node.left_keys, node.right_keys))
        if node.condition is not None:
            conds.append(node.condition)
        return True
    atoms.append(node)
    return True


def _atom_of(expr: E.Expression,
             name_to_atom: Dict[str, int]) -> Optional[int]:
    """The single atom an expression's references resolve to; None when
    it spans atoms or references nothing (a literal key)."""
    refs = expr.references()
    owners = {name_to_atom.get(n) for n in refs}
    if len(owners) != 1 or None in owners:
        return None
    return owners.pop()


def reorder_joins(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Top-down pass: reorder every maximal inner-join cluster of >= 3
    relations by greedy smallest-intermediate-first."""
    if isinstance(plan, L.Join) and plan.how == "inner":
        reordered = _reorder_cluster(plan)
        if reordered is not None:
            return reordered
    return plan.with_children(tuple(
        reorder_joins(c) for c in plan.children()))


def _reorder_cluster(root: L.Join) -> Optional[L.LogicalPlan]:
    atoms: List[L.LogicalPlan] = []
    key_pairs: List[Tuple[E.Expression, E.Expression]] = []
    conds: List[E.Expression] = []
    if not _flatten(root, atoms, key_pairs, conds) or len(atoms) < 3:
        return None

    # global name uniqueness: expressions keep meaning under any order
    name_to_atom: Dict[str, int] = {}
    for i, a in enumerate(atoms):
        for n in a.schema.names:
            if n in name_to_atom:
                return None
            name_to_atom[n] = i

    # edges: (atom_i, atom_j, key_on_i, key_on_j)
    edges: List[Tuple[int, int, E.Expression, E.Expression]] = []
    for lk, rk in key_pairs:
        i = _atom_of(lk, name_to_atom)
        j = _atom_of(rk, name_to_atom)
        if i is None or j is None or i == j:
            return None
        edges.append((i, j, lk, rk))

    # recurse into atoms first (nested clusters under Projects/aggregates)
    atoms = [reorder_joins(a) for a in atoms]
    est = [estimate_rows(a) for a in atoms]

    # per-edge NDVs (memoized scans); None -> assume unique on that atom
    edge_ndv = [(_atom_ndv(atoms[i], ki), _atom_ndv(atoms[j], kj))
                for (i, j, ki, kj) in edges]

    def join_size(t_est: float, joined: set, c: int) -> Tuple[float, int]:
        """(estimated output size, 0 if some edge is ~PK-FK else 1).
        size = t*r / max_k(max(ndv_t, ndv_c)) over the connecting keys;
        unknown NDV counts as the side's row estimate (unique)."""
        denom = 1.0
        fkish = 1
        for e, (i, j, _, _) in enumerate(edges):
            ndv_i, ndv_j = edge_ndv[e]
            if i in joined and j == c:
                nt, nc, t_atom, c_atom = ndv_i, ndv_j, i, j
            elif j in joined and i == c:
                nt, nc, t_atom, c_atom = ndv_j, ndv_i, j, i
            else:
                continue
            nt = nt if nt is not None else est[t_atom]
            nc = nc if nc is not None else est[c_atom]
            denom = max(denom, max(nt, nc))
            # PK-FK: one side's key is ~unique on its atom
            if nc >= 0.8 * est[c_atom] or nt >= 0.8 * est[t_atom]:
                fkish = 0
        return t_est * est[c] / denom, fkish

    n = len(atoms)
    start = min(range(n), key=lambda i: est[i])
    joined = {start}
    tree: L.LogicalPlan = atoms[start]
    tree_est = est[start]
    while len(joined) < n:
        connected = set()
        for (i, j, _, _) in edges:
            if i in joined and j not in joined:
                connected.add(j)
            elif j in joined and i not in joined:
                connected.add(i)
        if not connected:
            # disconnected components despite keys: out of scope
            return None
        # cost of joining candidate c next: PK-FK edges first, then the
        # smallest estimated output, then the smaller input
        def cost(x: int):
            size, non_fk = join_size(tree_est, joined, x)
            return (non_fk, size, est[x])

        c = min(connected, key=cost)
        new_est = join_size(tree_est, joined, c)[0]
        lkeys: List[E.Expression] = []
        rkeys: List[E.Expression] = []
        for (i, j, ki, kj) in edges:
            if i in joined and j == c:
                lkeys.append(ki)
                rkeys.append(kj)
            elif j in joined and i == c:
                lkeys.append(kj)
                rkeys.append(ki)
        tree = L.Join(tree, atoms[c], "inner",
                      tuple(lkeys), tuple(rkeys), None)
        tree_est = max(new_est, 1.0)
        joined.add(c)

    if conds:
        from spark_tpu.plan.optimizer import combine_conjuncts

        tree = L.Filter(combine_conjuncts(conds), tree)
    # restore the original output column order for parents
    orig = root.schema.names
    if tuple(tree.schema.names) != tuple(orig):
        tree = L.Project(tuple(E.Col(nm) for nm in orig), tree)
    return tree

from spark_tpu.plan import logical, optimizer  # noqa: F401

"""Logical plans.

The analogue of Catalyst's logical operators (reference:
sql/catalyst/src/main/scala/org/apache/spark/sql/catalyst/plans/logical/
basicLogicalOperators.scala) plus the TreeNode transform machinery
(reference: catalyst/trees/TreeNode.scala). Nodes are immutable
dataclasses; ``schema`` resolves output types bottom-up, which folds the
analyzer's resolution role (reference: analysis/Analyzer.scala:188) into
plan construction — the DataFrame API builds resolved plans directly,
and the SQL parser resolves names against child schemas as it builds.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import cached_property
from typing import Any, Callable, Optional, Tuple

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.types import Field, Schema


class LogicalPlan:
    """Base class; subclasses are frozen dataclasses."""

    def children(self) -> Tuple["LogicalPlan", ...]:
        return ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def with_children(self, children: Tuple["LogicalPlan", ...]) -> "LogicalPlan":
        """Rebuild this node with new children (positional)."""
        if not children:
            return self
        fields = {}
        it = iter(children)
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, LogicalPlan):
                fields[f.name] = next(it)
            else:
                fields[f.name] = v
        return dataclasses.replace(self, **fields)

    def transform_up(self, fn: Callable[["LogicalPlan"], "LogicalPlan"]) -> "LogicalPlan":
        new_children = tuple(c.transform_up(fn) for c in self.children())
        node = self.with_children(new_children) if new_children else self
        return fn(node)

    def transform_expressions(self, fn) -> "LogicalPlan":
        """Apply an expression transform to every expression in this node."""
        fields = {}
        changed = False
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            nv = _transform_value(v, fn)
            changed |= nv is not v
            fields[f.name] = nv
        return dataclasses.replace(self, **fields) if changed else self

    def expressions(self) -> Tuple[E.Expression, ...]:
        out = []
        for f in dataclasses.fields(self):
            _collect_exprs(getattr(self, f.name), out)
        return tuple(out)

    def references(self) -> set:
        refs = set()
        for e in self.expressions():
            refs |= e.references()
        return refs

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + self.node_string()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children()])

    def node_string(self) -> str:
        return type(self).__name__

    def structural_key(self) -> tuple:
        """Injective structural identity (node_string is a display
        string and may omit fields — never use it as a cache key)."""
        parts: list = [type(self).__name__]
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            parts.append(_field_key(v))
        return tuple(parts)

    def __repr__(self):
        return self.tree_string()


def _field_key(v):
    if isinstance(v, LogicalPlan):
        return v.structural_key()
    if isinstance(v, E.Expression):
        return E.expr_key(v)
    if isinstance(v, tuple):
        return tuple(_field_key(x) for x in v)
    if v.__class__.__module__ == "builtins" and not callable(v):
        return repr(v)
    return ("obj", id(v))  # sources/batches: identity


def _transform_value(v, fn):
    if isinstance(v, E.Expression):
        return E.transform_expr(v, fn)
    if isinstance(v, tuple):
        nv = tuple(_transform_value(x, fn) for x in v)
        return nv if any(a is not b for a, b in zip(nv, v)) else v
    return v


def _collect_exprs(v, out: list) -> None:
    if isinstance(v, E.Expression):
        out.append(v)
    elif isinstance(v, tuple):
        for x in v:
            _collect_exprs(x, out)


# ---- leaves ----------------------------------------------------------------


@dataclass(eq=False, frozen=True)
class Relation(LogicalPlan):
    """In-memory relation over an already-built device Batch (analogue of
    LocalRelation, reference: catalyst/plans/logical/LocalRelation.scala)."""

    batch: Any  # columnar.batch.Batch
    name: Optional[str] = None

    @property
    def schema(self) -> Schema:
        return self.batch.schema

    def node_string(self):
        return f"Relation{list(self.schema.names)}"


@dataclass(eq=False, frozen=True)
class Range(LogicalPlan):
    """spark.range(start, end, step) (reference: basicLogicalOperators
    Range + RangeExec basicPhysicalOperators.scala:412). Generated
    on-device as iota — no host data."""

    start: int
    end: int
    step: int = 1
    col_name: str = "id"

    @property
    def schema(self) -> Schema:
        return Schema((Field(self.col_name, T.INT64, nullable=False),))

    @property
    def num_rows(self) -> int:
        if self.step == 0:
            return 0
        n = (self.end - self.start + self.step - (1 if self.step > 0 else -1))
        return max(0, n // self.step)

    def node_string(self):
        return f"Range({self.start}, {self.end}, {self.step})"


@dataclass(eq=False, frozen=True)
class UnresolvedScan(LogicalPlan):
    """A file/table scan with pushed-down projection and predicates
    (DSv2 Scan + SupportsPushDownRequiredColumns/Filters analogue,
    reference: sql/catalyst/.../connector/read/SupportsPushDown*.java;
    physical peer FileSourceScanExec, DataSourceScanExec.scala:506).
    ``columns=None`` means all; ``filters`` are exact (the source both
    prunes files/row-groups and filters rows by them)."""

    source: Any  # io datasource object with .schema and .read()
    options: Tuple[Tuple[str, str], ...] = ()
    columns: Optional[Tuple[str, ...]] = None
    filters: Tuple[E.Expression, ...] = ()

    @property
    def schema(self) -> Schema:
        full = self.source.schema
        if self.columns is None:
            return full
        return Schema(tuple(full.field(n) for n in self.columns))

    def node_string(self):
        parts = [str(self.source)]
        if self.columns is not None:
            parts.append(f"cols={list(self.columns)}")
        if self.filters:
            parts.append(f"pushed=[{', '.join(map(str, self.filters))}]")
        return f"Scan({', '.join(parts)})"


# ---- unary -----------------------------------------------------------------


@dataclass(eq=False, frozen=True)
class Project(LogicalPlan):
    exprs: Tuple[E.Expression, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @cached_property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.exprs:
            dt = e.data_type(cs)
            if isinstance(dt, T.MapType):
                # maps decompose into '#keys'/'#vals' array components
                # (types.MapType)
                nullable = e.nullable(cs)
                fields.append(Field(T.map_keys_col(e.name),
                                    T.ArrayType(dt.key), nullable))
                fields.append(Field(T.map_vals_col(e.name),
                                    T.ArrayType(dt.value), nullable))
                continue
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            fields.append(Field(e.name, dt, e.nullable(cs), dictionary))
        return Schema(tuple(fields))

    def node_string(self):
        return f"Project[{', '.join(str(e) for e in self.exprs)}]"


@dataclass(eq=False, frozen=True)
class Filter(LogicalPlan):
    condition: E.Expression
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def node_string(self):
        return f"Filter[{self.condition}]"


@dataclass(eq=False, frozen=True)
class Aggregate(LogicalPlan):
    """GROUP BY. ``groupings`` are key expressions; ``aggregates`` are the
    output expressions (may mix keys and aggregate functions), matching
    the reference (plans/logical/basicLogicalOperators.scala Aggregate)."""

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @cached_property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.aggregates:
            dt = e.data_type(cs)
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            elif isinstance(inner, (E.Min, E.Max, E.First)):
                c = E.strip_alias(inner.child)
                if isinstance(c, E.Col) and c.col_name in cs:
                    dictionary = cs.field(c.col_name).dictionary
            fields.append(Field(e.name, dt, e.nullable(cs), dictionary))
        return Schema(tuple(fields))

    def node_string(self):
        return (f"Aggregate[keys=[{', '.join(map(str, self.groupings))}], "
                f"out=[{', '.join(str(e) for e in self.aggregates)}]]")


@dataclass(eq=False, frozen=True)
class Expand(LogicalPlan):
    """Replicate the input once per projection (reference:
    plans/logical Expand + execution/ExpandExec.scala:1 — the engine
    under ROLLUP/CUBE/GROUPING SETS). Output capacity is child capacity
    x len(projections), statically shaped, fully traceable."""

    projections: Tuple[Tuple[E.Expression, ...], ...]
    names: Tuple[str, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @cached_property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for i, name in enumerate(self.names):
            dt = self.projections[0][i].data_type(cs)
            nullable = False
            dictionary = None
            for proj in self.projections:
                e = proj[i]
                dt = T.common_type(dt, e.data_type(cs))
                nullable = nullable or e.nullable(cs)
                inner = E.strip_alias(e)
                if isinstance(inner, E.Col) and inner.col_name in cs:
                    dictionary = dictionary or cs.field(
                        inner.col_name).dictionary
            fields.append(Field(name, dt, nullable, dictionary))
        return Schema(tuple(fields))

    def node_string(self):
        return f"Expand[{len(self.projections)} sets]"


@dataclass(eq=False, frozen=True)
class Generate(LogicalPlan):
    """One output row per generated element, child columns replicated
    (reference: plans/logical Generate + execution/GenerateExec.scala:1;
    SQL surface: LATERAL VIEW explode(...) / explode in a SELECT list).
    ``generator`` is an E.Explode; output appends [pos,] value."""

    generator: E.Expression  # E.Explode
    out_name: str
    pos_name: Optional[str]  # set for posexplode
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @cached_property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = list(cs.fields)
        if self.pos_name is not None:
            fields.append(Field(self.pos_name, T.INT32, nullable=False))
        el = self.generator.data_type(cs)
        dictionary = None
        inner = E.strip_alias(self.generator.child)
        if isinstance(inner, E.Col) and inner.col_name in cs:
            dictionary = cs.field(inner.col_name).dictionary
        fields.append(Field(self.out_name, el, nullable=False,
                            dictionary=dictionary))
        return Schema(tuple(fields))

    def node_string(self):
        return f"Generate[{self.generator} AS {self.out_name}]"


@dataclass(eq=False, frozen=True)
class Window(LogicalPlan):
    """Append window-function columns to the child's output (reference:
    plans/logical/basicLogicalOperators.scala Window +
    execution/window/WindowExec.scala:87). Each entry is an
    Alias(WindowExpr, out_name); all entries here share nothing — the
    physical operator groups them by (partition, order) spec."""

    window_exprs: Tuple[E.Alias, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @cached_property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = list(cs.fields)
        for e in self.window_exprs:
            w = E.strip_alias(e)
            fields.append(Field(e.name, e.data_type(cs), e.nullable(cs),
                                E.window_dictionary(w, cs)))
        return Schema(tuple(fields))

    def node_string(self):
        return f"Window[{', '.join(str(e) for e in self.window_exprs)}]"


def collect_nodes(plan: "LogicalPlan", cls) -> list:
    """All nodes of type ``cls`` in the tree (pre-order)."""
    out: list = []

    def go(p):
        if isinstance(p, cls):
            out.append(p)
        for c in p.children():
            go(c)

    go(plan)
    return out


def project_with_windows(exprs: Tuple[E.Expression, ...],
                         child: LogicalPlan) -> LogicalPlan:
    """Build Project(exprs, child), hoisting any WindowExpr into a
    Window node below the projection (the analyzer's ExtractWindowExpressions
    rule, reference: analysis/Analyzer.scala)."""
    win: list = []
    new_exprs: list = []
    for e in exprs:
        if not E.contains_window(e):
            new_exprs.append(e)
            continue
        out_name = e.name

        def repl(x: E.Expression) -> E.Expression:
            if isinstance(x, E.WindowExpr):
                nm = f"__w{len(win)}"
                win.append(E.Alias(x, nm))
                return E.Col(nm)
            return x

        ne = E.transform_expr(E.strip_alias(e), repl)
        new_exprs.append(E.Alias(ne, out_name))
    if not win:
        return Project(tuple(exprs), child)
    return Project(tuple(new_exprs), Window(tuple(win), child))


@dataclass(eq=False, frozen=True)
class Sort(LogicalPlan):
    orders: Tuple[E.SortOrder, ...]
    child: LogicalPlan
    is_global: bool = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def node_string(self):
        return f"Sort[{', '.join(map(str, self.orders))}]"


@dataclass(eq=False, frozen=True)
class Limit(LogicalPlan):
    n: int
    child: LogicalPlan
    offset: int = 0

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def node_string(self):
        return f"Limit[{self.n}]"


@dataclass(eq=False, frozen=True)
class Distinct(LogicalPlan):
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema


@dataclass(eq=False, frozen=True)
class SubqueryAlias(LogicalPlan):
    alias: str
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def node_string(self):
        return f"SubqueryAlias[{self.alias}]"


@dataclass(eq=False, frozen=True)
class Repartition(LogicalPlan):
    """repartition(n) / repartition(cols) — an explicit exchange request
    (reference: plans/logical/basicLogicalOperators.scala Repartition +
    RepartitionByExpression)."""

    num_partitions: int
    keys: Tuple[E.Expression, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema


@dataclass(eq=False, frozen=True)
class Sample(LogicalPlan):
    fraction: float
    seed: int
    child: LogicalPlan
    with_replacement: bool = False

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema


# ---- binary ----------------------------------------------------------------

JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti", "cross")


@dataclass(eq=False, frozen=True)
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    how: str  # one of JOIN_TYPES
    # Equi-join keys (left_keys[i] == right_keys[i]); extra non-equi
    # predicates go to ``condition`` and are applied post-match.
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    condition: Optional[E.Expression] = None

    def children(self):
        return (self.left, self.right)

    @cached_property
    def schema(self) -> Schema:
        if self.how == "left_semi" or self.how == "left_anti":
            return self.left.schema
        lf = list(self.left.schema.fields)
        rf = list(self.right.schema.fields)
        if self.how in ("left", "full"):
            rf = [dataclasses.replace(f, nullable=True) for f in rf]
        if self.how in ("right", "full"):
            lf = [dataclasses.replace(f, nullable=True) for f in lf]
        names = E.dedup_pair_names([f.name for f in lf],
                                   [f.name for f in rf])
        out = [dataclasses.replace(f, name=n)
               for f, n in zip(lf + rf, names)]
        return Schema(tuple(out))

    def node_string(self):
        ks = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        return f"Join[{self.how}, keys=({ks}), cond={self.condition}]"


@dataclass(eq=False, frozen=True)
class Union(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        # Column names/types come from the left (Spark semantics).
        return self.left.schema


# ---- helpers ---------------------------------------------------------------


def resolve_star(plan: LogicalPlan) -> Tuple[E.Expression, ...]:
    """Expand `*` against a plan's schema."""
    return tuple(E.Col(n) for n in plan.schema.names)

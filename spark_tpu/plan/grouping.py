"""GROUPING SETS / ROLLUP / CUBE planning (reference:
analysis ResolveGroupingAnalytics in Analyzer.scala +
execution/ExpandExec.scala:1 + grouping.scala Grouping/GroupingID).

The input replicates once per grouping set through an Expand node; each
replica carries the set's keys (others typed-NULL via NullOf) plus a
literal grouping id, and the ordinary aggregation paths run over
(masked keys..., grouping_id). grouping()/grouping_id() calls rewrite
to arithmetic over the id column; references to grouping keys OUTSIDE
aggregate calls rewrite to the masked columns."""

from __future__ import annotations

from typing import List, Sequence, Tuple

from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L

GID = "__grouping_id"


def _bit_test(gid_ref: E.Expression, bit: int) -> E.Expression:
    """grouping() bit extraction with integer ops only (int / int is
    DOUBLE in this SQL dialect): (gid % 2^(bit+1)) >= 2^bit."""
    from spark_tpu import types as T

    return E.Cast(
        E.Cmp(">=", E.Arith("%", gid_ref, E.Literal(1 << (bit + 1))),
              E.Literal(1 << bit)),
        T.INT32)


def contains_grouping_fns(e: E.Expression) -> bool:
    if isinstance(e, (E.Grouping, E.GroupingId)):
        return True
    return any(contains_grouping_fns(c) for c in e.children())


def rewrite_grouping_fns(e: E.Expression,
                         keys: Sequence[E.Expression],
                         gid_col: str) -> E.Expression:
    """Rewrite grouping()/grouping_id() calls AGAINST THE AGGREGATE
    OUTPUT (e.g. in a HAVING predicate sitting above it): they read the
    grouping id from ``gid_col``; key references stay untouched (they
    resolve against the aggregate's output names)."""
    key_bit = {E.expr_key(k): len(keys) - 1 - i
               for i, k in enumerate(keys)}

    def fn(x: E.Expression) -> E.Expression:
        if isinstance(x, E.GroupingId):
            return E.Col(gid_col)
        if isinstance(x, E.Grouping):
            bit = key_bit.get(E.expr_key(x.child))
            if bit is None:
                raise ValueError(
                    f"grouping() argument {x.child} is not a grouping "
                    f"key")
            return _bit_test(E.Col(gid_col), bit)
        return x

    return E.transform_expr_down(e, fn)

MAX_SETS = 64  # cube(6) — capacity multiplies by the set count


def rollup_sets(k: int) -> List[Tuple[int, ...]]:
    return [tuple(range(i)) for i in range(k, -1, -1)]


def cube_sets(k: int) -> List[Tuple[int, ...]]:
    out = []
    for m in range((1 << k) - 1, -1, -1):
        out.append(tuple(i for i in range(k) if m & (1 << (k - 1 - i))))
    return out


def grouping_sets_aggregate(
    child: L.LogicalPlan,
    keys: Sequence[E.Expression],
    sets: Sequence[Tuple[int, ...]],
    outputs: Sequence[E.Expression],
) -> Tuple[L.LogicalPlan, "callable"]:
    """Build Expand + Aggregate for the given grouping sets. Returns
    (plan, rewrite) where ``rewrite`` maps any further expression over
    the ORIGINAL names (e.g. a HAVING predicate) into the grouped
    output space — it is already applied to ``outputs``."""
    if len(sets) > MAX_SETS:
        raise NotImplementedError(
            f"{len(sets)} grouping sets would replicate the input "
            f"{len(sets)}x (max {MAX_SETS})")
    k = len(keys)
    child_names = list(child.schema.names)
    gs_names = [f"__gs{i}" for i in range(k)]
    projections = []
    for s in sets:
        proj: List[E.Expression] = [E.Col(n) for n in child_names]
        gid = 0
        for i, key in enumerate(keys):
            if i in s:
                proj.append(key)
            else:
                proj.append(E.NullOf(key))
                gid |= 1 << (k - 1 - i)
        proj.append(E.Literal(gid))
        projections.append(tuple(proj))
    expand = L.Expand(tuple(projections),
                      tuple(child_names + gs_names + [GID]), child)

    key_map = {E.expr_key(key): E.Col(gs_names[i])
               for i, key in enumerate(keys)}
    key_bit = {E.expr_key(key): k - 1 - i for i, key in enumerate(keys)}

    def bit_of(child: E.Expression):
        bit = key_bit.get(E.expr_key(child))
        if bit is None:
            raise ValueError(
                f"grouping() argument {child} is not a grouping key")
        return bit

    def rewrite(expr: E.Expression) -> E.Expression:
        """Grouping-key refs -> masked columns; grouping()/grouping_id()
        -> arithmetic over the id. Aggregate call ARGUMENTS keep the
        original (unmasked) columns, like the reference's Expand."""
        import dataclasses

        def fn(e: E.Expression) -> E.Expression:
            if isinstance(e, E.AggregateExpression):
                # a fresh copy stops transform_expr_down's descent so
                # the aggregate's inputs stay unmasked
                return dataclasses.replace(e)
            if isinstance(e, E.GroupingId):
                return E.Col(GID)
            if isinstance(e, E.Grouping):
                return _bit_test(E.Col(GID), bit_of(e.child))
            hit = key_map.get(E.expr_key(e))
            if hit is not None:
                return hit
            return e

        return E.transform_expr_down(expr, fn)

    def rw_named(e: E.Expression) -> E.Expression:
        if isinstance(e, E.Alias):
            return E.Alias(rewrite(e.child), e.alias_name)
        r = rewrite(e)
        if r is not e and not isinstance(r, E.Alias):
            # keep the user-facing name (e.g. 'a' not '__gs0')
            return E.Alias(r, e.name)
        return r

    new_outputs = tuple(rw_named(e) for e in outputs)
    groupings = tuple(E.Col(n) for n in gs_names) + (E.Col(GID),)
    plan = L.Aggregate(groupings, new_outputs, expand)
    return plan, rewrite

"""Rule-based logical optimizer.

Analogue of Catalyst's optimizer (reference:
sql/catalyst/.../optimizer/Optimizer.scala:44 defaultBatches:71) with the
rules that matter for a columnar TPU backend: predicate pushdown, column
pruning, project collapsing, constant folding, filter simplification.
The rule-executor loop mirrors RuleExecutor.scala (fixed-point batches).
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Callable, List, Tuple

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


# ---- expression-level helpers ----------------------------------------------


def substitute(expr: E.Expression, mapping: dict) -> E.Expression:
    """Replace Col(name) by mapping[name] expressions (used when moving a
    predicate through a Project)."""

    def fn(e: E.Expression) -> E.Expression:
        if isinstance(e, E.Col) and e.col_name in mapping:
            return mapping[e.col_name]
        return e

    return E.transform_expr(expr, fn)


def split_conjuncts(e: E.Expression) -> List[E.Expression]:
    if isinstance(e, E.And):
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def split_disjuncts(e: E.Expression) -> List[E.Expression]:
    if isinstance(e, E.Or):
        return split_disjuncts(e.left) + split_disjuncts(e.right)
    return [e]


def combine_disjuncts(parts: List[E.Expression]) -> E.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = E.Or(out, p)
    return out


def factor_or_common(e: E.Expression) -> E.Expression:
    """(A AND X) OR (A AND Y) -> A AND (X OR Y): factor conjuncts common
    to every OR branch (distributivity holds under Kleene 3-valued logic).
    Unlocks equi-key extraction for TPC-H q19-style predicates where the
    join key equality is repeated inside each OR branch (reference:
    optimizer/expressions.scala BooleanSimplification 'common factor
    extraction' case)."""

    def fn(node: E.Expression) -> E.Expression:
        if not isinstance(node, E.Or):
            return node
        branches = split_disjuncts(node)
        conj_lists = [split_conjuncts(b) for b in branches]
        key_lists = [[E.expr_key(c) for c in cl] for cl in conj_lists]
        common = set(key_lists[0])
        for kl in key_lists[1:]:
            common &= set(kl)
        if not common:
            return node
        factored = [c for c, k in zip(conj_lists[0], key_lists[0])
                    if k in common]
        # drop duplicates of an already-factored conjunct within a branch
        rest_branches: List[E.Expression] = []
        any_true = False
        for cl, kl in zip(conj_lists, key_lists):
            remaining = [c for c, k in zip(cl, kl) if k not in common]
            if not remaining:
                any_true = True
            else:
                rest_branches.append(combine_conjuncts(remaining))
        if any_true:
            # one branch reduced to TRUE: OR-part vanishes entirely
            return combine_conjuncts(factored)
        return combine_conjuncts(factored +
                                 [combine_disjuncts(rest_branches)])

    return E.transform_expr(e, fn)


def combine_conjuncts(parts: List[E.Expression]) -> E.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = E.And(out, p)
    return out


def fold_constants(e: E.Expression) -> E.Expression:
    """Evaluate literal-only subtrees host-side (reference:
    optimizer/expressions.scala ConstantFolding)."""

    def fn(node: E.Expression) -> E.Expression:
        if isinstance(node, E.Arith) and isinstance(node.left, E.Literal) \
                and isinstance(node.right, E.Literal):
            lv, rv = node.left.value, node.right.value
            if lv is None or rv is None:
                return E.Literal(None, node.left.dtype)
            try:
                if isinstance(lv, datetime.date) and isinstance(rv, int):
                    val = (lv + datetime.timedelta(days=rv) if node.op == "+"
                           else lv - datetime.timedelta(days=rv))
                    return E.Literal(val)
                ops = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                       "*": lambda a, b: a * b,
                       "/": lambda a, b: a / b if b != 0 else None,
                       "%": lambda a, b: a % b if b != 0 else None}
                val = ops[node.op](lv, rv)
                if val is None:
                    return E.Literal(None, node.left.dtype)
                return E.Literal(val)
            except Exception:
                return node
        if isinstance(node, E.AddMonths) and isinstance(node.child, E.Literal):
            v = node.child.value
            if isinstance(v, datetime.date):
                months = v.year * 12 + (v.month - 1) + node.months
                y, m = divmod(months, 12)
                m += 1
                day = min(v.day, _days_in_month(y, m))
                return E.Literal(datetime.date(y, m, day))
        if isinstance(node, E.Not) and isinstance(node.child, E.Literal) \
                and isinstance(node.child.value, bool):
            return E.Literal(not node.child.value)
        return node

    return E.transform_expr(e, fn)


def _days_in_month(y: int, m: int) -> int:
    if m == 12:
        return 31
    return (datetime.date(y, m + 1, 1) - datetime.date(y, m, 1)).days


# ---- plan-level rules -------------------------------------------------------


def collapse_projects(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Project(Project(x)) -> Project(x) by substitution (reference:
    Optimizer.scala CollapseProject)."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if isinstance(node, L.Project) and isinstance(node.child, L.Project):
            inner = node.child
            mapping = {e.name: E.strip_alias(e) for e in inner.exprs}
            new_exprs = []
            for e in node.exprs:
                ne = substitute(E.strip_alias(e), mapping)
                if ne.name != e.name:
                    ne = E.Alias(ne, e.name)
                new_exprs.append(ne)
            return L.Project(tuple(new_exprs), inner.child)
        return node

    return plan.transform_up(fn)


def push_down_predicates(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Move Filters toward scans: through Projects (with substitution),
    into Join sides, below SubqueryAlias; merge adjacent Filters
    (reference: Optimizer.scala PushDownPredicates)."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if not isinstance(node, L.Filter):
            return node
        child = node.child
        if isinstance(child, L.Filter):
            return L.Filter(E.And(child.condition, node.condition), child.child)
        if isinstance(child, L.UnresolvedScan):
            # push translatable conjuncts into the scan (file/row-group
            # pruning + exact row filtering at the source; reference:
            # FileSourceStrategy / V2ScanRelationPushDown)
            from spark_tpu.io.datasource import translate_filters

            pushed, residual = translate_filters(
                split_conjuncts(node.condition))
            if pushed:
                new_scan = dataclasses.replace(
                    child, filters=child.filters + tuple(pushed))
                if residual:
                    return L.Filter(combine_conjuncts(residual), new_scan)
                return new_scan
        if isinstance(child, L.Project):
            has_agg = any(E.contains_aggregate(e) for e in child.exprs)
            if not has_agg:
                mapping = {e.name: E.strip_alias(e) for e in child.exprs}
                cond = substitute(node.condition, mapping)
                return L.Project(child.exprs, L.Filter(cond, child.child))
        if isinstance(child, L.SubqueryAlias):
            return L.SubqueryAlias(child.alias,
                                   L.Filter(node.condition, child.child))
        if isinstance(child, L.Join):
            left_names = set(child.left.schema.names)
            right_names = set(child.right.schema.names)
            left_parts, right_parts, keep = [], [], []
            for c in split_conjuncts(node.condition):
                refs = c.references()
                if refs and refs <= left_names and child.how in (
                        "inner", "left", "left_semi", "left_anti", "cross"):
                    left_parts.append(c)
                elif refs and refs <= right_names and child.how in (
                        "inner", "right", "cross"):
                    right_parts.append(c)
                else:
                    keep.append(c)
            if left_parts or right_parts:
                new_left = (L.Filter(combine_conjuncts(left_parts), child.left)
                            if left_parts else child.left)
                new_right = (L.Filter(combine_conjuncts(right_parts), child.right)
                             if right_parts else child.right)
                new_join = dataclasses.replace(
                    child, left=new_left, right=new_right)
                return L.Filter(combine_conjuncts(keep), new_join) if keep \
                    else new_join
        return node

    return plan.transform_up(fn)


def extract_equi_joins(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Filter(Join(cross/inner)) with cross-side equality conjuncts ->
    equi join keys (reference: planning/patterns.scala ExtractEquiJoinKeys
    + the planner turning ON-less comma joins into hash joins). Essential
    for SQL comma-style joins: FROM a, b WHERE a.k = b.k."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if not (isinstance(node, L.Filter) and isinstance(node.child, L.Join)):
            return node
        join = node.child
        if join.how not in ("cross", "inner"):
            return node
        out_names = join.schema.names
        n_l = len(join.left.schema.names)
        left_out = set(out_names[:n_l])
        right_out_map = dict(zip(out_names[n_l:], join.right.schema.names))

        def to_src(e: E.Expression) -> E.Expression:
            def sub(x):
                if isinstance(x, E.Col) and x.col_name in right_out_map:
                    return E.Col(right_out_map[x.col_name])
                return x

            return E.transform_expr(e, sub)

        lkeys = list(join.left_keys)
        rkeys = list(join.right_keys)
        keep: List[E.Expression] = []
        changed = False
        for c in split_conjuncts(node.condition):
            if isinstance(c, E.Cmp) and c.op == "==":
                lr, rr = c.left.references(), c.right.references()
                if lr and lr <= left_out and rr and rr <= set(right_out_map):
                    lkeys.append(c.left)
                    rkeys.append(to_src(c.right))
                    changed = True
                    continue
                if rr and rr <= left_out and lr and lr <= set(right_out_map):
                    lkeys.append(c.right)
                    rkeys.append(to_src(c.left))
                    changed = True
                    continue
            keep.append(c)
        if not changed:
            return node
        new_join = L.Join(join.left, join.right, "inner",
                          tuple(lkeys), tuple(rkeys), join.condition)
        return L.Filter(combine_conjuncts(keep), new_join) if keep \
            else new_join

    return plan.transform_up(fn)


def extract_condition_keys(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Move equality conjuncts of a Join's ON condition into equi-join
    keys, for EVERY join type (reference: planning/patterns.scala
    ExtractEquiJoinKeys operates on the full join condition). Without
    this, semi/anti/outer joins whose keys live only in the condition
    degrade to all-pairs nested loops. The condition is expressed in the
    join's OUTPUT name space (right-side duplicates carry '#2' suffixes);
    extracted right keys are mapped back to right-source names. Safe for
    outer joins: keys and condition are both part of the match predicate,
    and unmatched-row padding is unaffected."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if not isinstance(node, L.Join) or node.condition is None:
            return node
        if node.how == "cross":
            return node
        # the condition is evaluated over the joined PAIR, whose namespace
        # is left names + '#2'-deduped right names — NOT node.schema
        # (which is left-only for semi/anti joins)
        left_names = list(node.left.schema.names)
        right_names = list(node.right.schema.names)
        pair_names = E.dedup_pair_names(left_names, right_names)
        n_l = len(left_names)
        left_out = set(pair_names[:n_l])
        right_out_map = dict(zip(pair_names[n_l:], right_names))

        def to_right_src(e: E.Expression) -> E.Expression:
            def sub(x):
                if isinstance(x, E.Col) and x.col_name in right_out_map:
                    return E.Col(right_out_map[x.col_name])
                return x

            return E.transform_expr(e, sub)

        lkeys = list(node.left_keys)
        rkeys = list(node.right_keys)
        keep: List[E.Expression] = []
        changed = False
        for c in split_conjuncts(factor_or_common(node.condition)):
            if isinstance(c, E.Cmp) and c.op == "==":
                lr, rr = c.left.references(), c.right.references()
                if lr and lr <= left_out and rr and rr <= set(right_out_map):
                    lkeys.append(c.left)
                    rkeys.append(to_right_src(c.right))
                    changed = True
                    continue
                if rr and rr <= left_out and lr and lr <= set(right_out_map):
                    lkeys.append(c.right)
                    rkeys.append(to_right_src(c.left))
                    changed = True
                    continue
            keep.append(c)
        if not changed:
            return node
        return dataclasses.replace(
            node, left_keys=tuple(lkeys), right_keys=tuple(rkeys),
            condition=combine_conjuncts(keep) if keep else None)

    return plan.transform_up(fn)


def simplify_booleans(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Factor common conjuncts out of OR trees in every Filter so that
    predicate pushdown and equi-key extraction see them as top-level
    conjuncts (q19's `p_partkey = l_partkey` lives inside each OR
    branch). Reference: optimizer/expressions.scala BooleanSimplification."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if isinstance(node, L.Filter):
            new_cond = factor_or_common(node.condition)
            if new_cond is not node.condition:
                return L.Filter(new_cond, node.child)
        return node

    return plan.transform_up(fn)


def prune_filters(plan: L.LogicalPlan) -> L.LogicalPlan:
    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if isinstance(node, L.Filter) and isinstance(node.condition, E.Literal):
            if node.condition.value is True:
                return node.child
        return node

    return plan.transform_up(fn)


def constant_folding(plan: L.LogicalPlan) -> L.LogicalPlan:
    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        return node.transform_expressions(
            lambda e: fold_constants(e) if isinstance(
                e, (E.Arith, E.AddMonths, E.Not)) else e)

    return plan.transform_up(fn)


def prune_columns(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Top-down required-column analysis; inserts narrow Projects above
    leaves so scans read only what is needed (reference: Optimizer.scala
    ColumnPruning; drives Parquet column projection like
    FileSourceStrategy's readDataColumns)."""

    def prune(node: L.LogicalPlan, required: set) -> L.LogicalPlan:
        # map components travel as a unit: a reference to 'm#keys'
        # (the canonical map handle) must keep 'm#vals' alive and vice
        # versa — element_at/m[k] reads both (types.MapType)
        extra = set()
        for n in required:
            base = T.map_base_name(n)
            if base is not None:
                extra.add(T.map_keys_col(base))
                extra.add(T.map_vals_col(base))
                extra.add(base)  # a map EXPRESSION is named by its base
        required = required | extra
        if isinstance(node, L.UnresolvedScan):
            # column-projection pushdown: the scan reads only what the
            # query needs (pushed filters are evaluated by the source
            # independent of the projection)
            names = node.schema.names
            keep = tuple(n for n in names if n in required)
            if 0 < len(keep) < len(names):
                return dataclasses.replace(node, columns=keep)
            return node
        if isinstance(node, (L.Relation, L.Range)):
            names = node.schema.names
            keep = [n for n in names if n in required]
            if 0 < len(keep) < len(names):
                return L.Project(tuple(E.Col(n) for n in keep), node)
            return node
        if isinstance(node, L.Project):
            kept = tuple(e for e in node.exprs if e.name in required) or node.exprs[:1]
            child_req = set()
            for e in kept:
                child_req |= e.references()
            return L.Project(kept, prune(node.child, child_req))
        if isinstance(node, L.Filter):
            child_req = required | node.condition.references()
            return L.Filter(node.condition, prune(node.child, child_req))
        if isinstance(node, L.Aggregate):
            child_req = set()
            for e in node.groupings + node.aggregates:
                child_req |= e.references()
            return dataclasses.replace(
                node, child=prune(node.child, child_req))
        if isinstance(node, L.Window):
            win_names = {e.name for e in node.window_exprs}
            child_req = {n for n in required if n not in win_names}
            for e in node.window_exprs:
                child_req |= e.references()
            child_req &= set(node.child.schema.names)
            if not child_req:
                child_req = set(node.child.schema.names)
            return dataclasses.replace(
                node, child=prune(node.child, child_req))
        if isinstance(node, L.Generate):
            gen_names = {node.out_name} | (
                {node.pos_name} if node.pos_name else set())
            child_req = {n for n in required if n not in gen_names}
            child_req |= node.generator.references()
            # arrays ride with a hidden '#len' companion column
            child_req |= {T.array_len_col(n) for n in
                          node.generator.references()}
            child_req &= set(node.child.schema.names) | {
                T.array_len_col(n) for n in node.child.schema.names}
            return dataclasses.replace(
                node, child=prune(node.child, child_req))
        if isinstance(node, (L.Sort, L.Limit, L.Distinct, L.SubqueryAlias,
                             L.Repartition, L.Sample)):
            child_req = set(required)
            for e in node.expressions():
                child_req |= e.references()
            if isinstance(node, L.Distinct):
                child_req |= set(node.schema.names)
            return node.with_children((prune(node.children()[0], child_req),))
        if isinstance(node, L.Join):
            # required/condition names live in the OUTPUT name space
            # (right-side duplicates carry '#2' suffixes) — map them back
            # to source columns before pruning each side.
            refs = set(required)
            if node.condition is not None:
                refs |= node.condition.references()
            seen: set = set()
            left_req: set = set()
            right_req: set = set()
            entries = []  # (out_name, side_req_set, src_name) in dedup order
            for side_req, names in ((left_req, node.left.schema.names),
                                    (right_req, node.right.schema.names)):
                for n in names:
                    out = n
                    while out in seen:
                        out = out + "#2"
                    seen.add(out)
                    entries.append((out, side_req, n))
            lookup = {out: (side_req, src) for out, side_req, src in entries}
            needed = {out for out, _, _ in entries if out in refs}
            # '#2' suffixes are collision-dependent: keeping 'x#2' only
            # stays named 'x#2' if every dedup ancestor ('x') survives too
            for out in list(needed):
                base = out
                while base.endswith("#2"):
                    base = base[:-2]
                    if base in lookup:
                        needed.add(base)
            for out in needed:
                side_req, src = lookup[out]
                side_req.add(src)
            for k in node.left_keys:
                left_req |= k.references()
            for k in node.right_keys:
                right_req |= k.references()
            return dataclasses.replace(
                node,
                left=prune(node.left, left_req),
                right=prune(node.right, right_req))
        if isinstance(node, L.Union):
            # Union is positional: require everything for now.
            req = set(node.schema.names)
            return node.with_children(tuple(
                prune(c, set(c.schema.names)) for c in node.children()))
        return node.with_children(tuple(
            prune(c, set(c.schema.names)) for c in node.children()))

    return prune(plan, set(plan.schema.names))


# ---- rule executor ----------------------------------------------------------

Rule = Callable[[L.LogicalPlan], L.LogicalPlan]

def extract_generators(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Pull explode/posexplode out of SELECT lists into Generate nodes
    (reference: analysis ExtractGenerator + GenerateExec planning).
    ``select a, explode(b) as c`` becomes
    Project[a, c] over Generate[explode(b) AS c] over child."""

    def rule(node: L.LogicalPlan) -> L.LogicalPlan:
        if not isinstance(node, L.Project):
            return node
        gens = [e for e in node.exprs
                if isinstance(E.strip_alias(e), E.Explode)]
        if not gens:
            # generators nested inside other expressions are rejected
            for e in node.exprs:
                if E.contains_generator(e):
                    raise NotImplementedError(
                        f"generator must be a top-level SELECT item: {e}")
            return node
        if len(gens) > 1:
            raise NotImplementedError(
                "only one generator per SELECT list (the reference has "
                "the same restriction, ExtractGenerator)")
        gen_item = gens[0]
        gen = E.strip_alias(gen_item)
        out_name = gen_item.name if isinstance(gen_item, E.Alias) else "col"
        pos_name = None
        if gen.with_position:
            # posexplode yields (pos, col); an alias names the value col
            pos_name = "pos"
        g = L.Generate(gen, out_name, pos_name, node.child)
        new_exprs = []
        for e in node.exprs:
            if e is gen_item:
                if pos_name is not None:
                    new_exprs.append(E.Col(pos_name))
                new_exprs.append(E.Col(out_name))
            else:
                new_exprs.append(e)
        return L.Project(tuple(new_exprs), g)

    return plan.transform_up(rule)


_FIXED_POINT_BATCH: Tuple[Rule, ...] = (
    extract_generators,
    constant_folding,
    simplify_booleans,
    push_down_predicates,
    extract_equi_joins,
    extract_condition_keys,
    collapse_projects,
    prune_filters,
)

MAX_ITERATIONS = 20  # reference: RuleExecutor FixedPoint(100); ours converge fast


def _session_conf():
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is not None:
        return sess.conf

    class _Defaults:
        @staticmethod
        def get(entry):
            return entry.default

    return _Defaults()


# registered at IMPORT time like every other conf entry, so values set
# before the first optimize() still get value_type coercion
from spark_tpu import conf as _CF  # noqa: E402

RUNTIME_FILTER_ENABLED = _CF.register(
    "spark.tpu.runtimeFilter.semiJoinReduction", False,
    "Inject an exact semi-join filter on the BIG side of an "
    "inner equi-join when the other side is filtered (the "
    "TPU-first form of InjectRuntimeFilter.scala:36 — "
    "membership via the sorted join index is exact, no Bloom "
    "false-positive pass). DEFAULT OFF: the engine's adaptive "
    "sized-expansion + compaction replay already shrink "
    "downstream capacities to the matched-row count, so the "
    "extra semi pass measured as a net LOSS on TPC-H q3 at SF1 "
    "(283 ms vs 106 ms steady state). Enable it for workloads "
    "without stats replay (first-run-dominated, or out-of-core "
    "scans where touching fewer rows matters).", bool)
RUNTIME_FILTER_MIN_ROWS = _CF.register(
    "spark.tpu.runtimeFilter.minRows", 1 << 18,
    "Only semi-filter scan sides at least this large.", int)


def _runtime_filter_conf():
    return RUNTIME_FILTER_ENABLED, RUNTIME_FILTER_MIN_ROWS


def _side_scan(node: L.LogicalPlan):
    scans = L.collect_nodes(node, L.UnresolvedScan)
    return scans[0] if len(scans) == 1 else None


def _has_selective_filter(node: L.LogicalPlan) -> bool:
    if isinstance(node, L.Filter):
        return True
    if isinstance(node, L.UnresolvedScan):
        return bool(node.filters)
    return any(_has_selective_filter(c) for c in node.children())


def inject_runtime_filters(plan: L.LogicalPlan, conf) -> L.LogicalPlan:
    """Semi-join reduction (reference: InjectRuntimeFilter.scala:36 and
    spark.sql.optimizer.runtimeFilter.semiJoinReduction). For an inner
    equi-join where one side is filtered and the other is a large
    single-scan subtree, wrap the large side in
    ``large LEFT SEMI JOIN (keys of small)`` — rows that cannot match
    never flow downstream, and the executor's recorded compaction turns
    the row reduction into a CAPACITY reduction for every operator
    above the scan."""
    enabled_e, min_rows_e = _runtime_filter_conf()
    if not conf.get(enabled_e):
        return plan
    min_rows = conf.get(min_rows_e)

    def big_enough(node: L.LogicalPlan) -> bool:
        scan = _side_scan(node)
        if scan is None:
            return False
        try:
            return scan.source.count_rows(scan.filters) >= min_rows
        except Exception:
            return False

    def already_filtered(node, keys) -> bool:
        return (isinstance(node, L.Join) and node.how == "left_semi"
                and tuple(E.expr_key(k) for k in node.left_keys)
                == tuple(E.expr_key(k) for k in keys))

    def rule(node: L.LogicalPlan) -> L.LogicalPlan:
        if not (isinstance(node, L.Join) and node.how == "inner"
                and node.left_keys):
            return node
        left, right = node.left, node.right

        def filt(big, big_keys, small, small_keys):
            from spark_tpu import metrics

            metrics.record("runtime_filter", keys=[str(k)
                                                   for k in big_keys])
            reduced = L.Join(big, small, "left_semi",
                             tuple(big_keys), tuple(small_keys))
            return reduced

        if big_enough(right) and _has_selective_filter(left) \
                and not already_filtered(right, node.right_keys):
            right = filt(right, node.right_keys, left, node.left_keys)
        elif big_enough(left) and _has_selective_filter(right) \
                and not already_filtered(left, node.left_keys):
            left = filt(left, node.left_keys, right, node.right_keys)
        if left is node.left and right is node.right:
            return node
        return dataclasses.replace(node, left=left, right=right)

    return plan.transform_up(rule)


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Run rule batches to fixpoint, then one column-pruning pass
    (reference: RuleExecutor.execute, rules/RuleExecutor.scala)."""
    for _ in range(MAX_ITERATIONS):
        new_plan = plan
        for rule in _FIXED_POINT_BATCH:
            new_plan = rule(new_plan)
        if new_plan.tree_string() == plan.tree_string():
            plan = new_plan
            break
        plan = new_plan
    if _cbo_enabled():
        from spark_tpu.plan.join_reorder import reorder_joins

        plan = reorder_joins(plan)
    plan = inject_runtime_filters(plan, _session_conf())
    for rule in _extension_rules():
        plan = rule(plan)
    return prune_columns(plan)


def _extension_rules() -> Tuple[Rule, ...]:
    """Session-injected rules (reference:
    SparkSessionExtensions.injectOptimizerRule:268)."""
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is None:
        return ()
    return tuple(sess.extensions.optimizer_rules())


def _cbo_enabled() -> bool:
    from spark_tpu import conf
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is None:
        return bool(conf.CBO_JOIN_REORDER.default)
    return bool(sess.conf.get(conf.CBO_JOIN_REORDER))

"""Subquery rewriting: EXISTS/IN -> semi/anti joins, scalar subqueries
-> aggregate joins, with decorrelation of equality predicates.

The analogue of the reference's subquery planning + decorrelation tier
(reference: sql/catalyst/.../optimizer/subquery.scala
RewritePredicateSubquery, DecorrelateInnerQuery.scala,
RewriteCorrelatedScalarSubquery in Optimizer.scala). Correlated
references are OuterRef nodes captured at parse time; this pass removes
every SubqueryExpression from the plan, so the executors never see one.

Supported shapes (the TPC-H dialect):
- [NOT] EXISTS (SELECT ... WHERE outer_eq AND ... [non-equi corr]) —
  equality conjuncts become semi/anti join keys, other correlated
  conjuncts become the join condition.
- expr [NOT] IN (SELECT col ...), optionally correlated by equalities.
- scalar subqueries: uncorrelated (cross join of a 1-row aggregate) and
  correlated-by-equality aggregates (GROUP BY the correlation columns +
  LEFT JOIN — empty groups yield NULL, matching SQL).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L

_sq_counter = itertools.count()


def _has_outer(e: E.Expression) -> bool:
    if isinstance(e, E.OuterRef):
        return True
    return any(_has_outer(c) for c in e.children())


def _outer_to_col(e: E.Expression) -> E.Expression:
    def fn(x):
        if isinstance(x, E.OuterRef):
            return E.Col(x.col_name)
        return x

    return E.transform_expr(e, fn)


def _pure_outer(e: E.Expression) -> bool:
    """Only OuterRefs and literals below (no inner columns)."""
    if isinstance(e, E.Col):
        return False
    if isinstance(e, (E.OuterRef, E.Literal)):
        return True
    return bool(e.children()) and all(_pure_outer(c) for c in e.children()) \
        or isinstance(e, E.Literal)


def _pure_inner(e: E.Expression) -> bool:
    return not _has_outer(e)


def _split(cond: E.Expression) -> List[E.Expression]:
    from spark_tpu.plan.optimizer import split_conjuncts

    return split_conjuncts(cond)


def _combine(parts: List[E.Expression]) -> E.Expression:
    from spark_tpu.plan.optimizer import combine_conjuncts

    return combine_conjuncts(parts)


def _strip_correlated(
    plan: L.LogicalPlan,
) -> Tuple[L.LogicalPlan, List[E.Expression], bool]:
    """Remove correlated conjuncts from Filter nodes anywhere in the
    plan. Returns (stripped_plan, conjuncts, found_below_agg)."""
    collected: List[E.Expression] = []
    below_agg = False

    def go(node: L.LogicalPlan, under_agg: bool) -> L.LogicalPlan:
        nonlocal below_agg
        before = len(collected)
        child_under = under_agg or isinstance(node, L.Aggregate)
        children = tuple(go(c, child_under) for c in node.children())
        node = node.with_children(children) if children else node
        if isinstance(node, L.Filter):
            parts = _split(node.condition)
            corr = [p for p in parts if _has_outer(p)]
            rest = [p for p in parts if not _has_outer(p)]
            if corr:
                collected.extend(corr)
                if under_agg:
                    below_agg = True
                return L.Filter(_combine(rest), node.child) if rest \
                    else node.child
        if isinstance(node, L.Project):
            # correlated conjuncts collected in this subtree become join
            # keys/conditions ABOVE the subquery plan — widen the
            # projection so the inner columns they reference survive
            # (reference: DecorrelateInnerQuery threads attributes up)
            needed: set = set()
            for p in collected[before:]:
                needed |= p.references()  # OuterRefs contribute nothing
            missing = [n for n in needed
                       if n not in set(node.schema.names)
                       and n in set(node.child.schema.names)]
            if missing:
                node = L.Project(
                    node.exprs + tuple(E.Col(n) for n in sorted(missing)),
                    node.child)
        return node

    return go(plan, False), collected, below_agg


def _corr_to_keys(
    corr: List[E.Expression],
) -> Tuple[List[E.Expression], List[E.Expression], List[E.Expression]]:
    """Split correlated conjuncts into (outer_keys, inner_keys, residual).
    Equalities with one pure-outer and one pure-inner side become key
    pairs; everything else is residual (goes to the join condition)."""
    outer_keys: List[E.Expression] = []
    inner_keys: List[E.Expression] = []
    residual: List[E.Expression] = []
    for p in corr:
        if isinstance(p, E.Cmp) and p.op == "==":
            if _pure_outer(p.left) and _pure_inner(p.right):
                outer_keys.append(_outer_to_col(p.left))
                inner_keys.append(p.right)
                continue
            if _pure_outer(p.right) and _pure_inner(p.left):
                outer_keys.append(_outer_to_col(p.right))
                inner_keys.append(p.left)
                continue
        residual.append(p)
    return outer_keys, inner_keys, residual


def _join_condition(residual: List[E.Expression], left_names,
                    right_names) -> Optional[E.Expression]:
    """Residual correlated conjuncts reference outer columns as OuterRef
    and inner columns by their own names; the join condition evaluates
    on the joined pair where right-side duplicates carry '#2' suffixes
    (logical.Join.schema dedup). Rewrite both."""
    if not residual:
        return None
    pair = E.dedup_pair_names(left_names, right_names)
    rename = dict(zip(right_names, pair[len(list(left_names)):]))

    def fix(e: E.Expression) -> E.Expression:
        def fn(x):
            if isinstance(x, E.OuterRef):
                return E.Col(x.col_name)
            if isinstance(x, E.Col) and x.col_name in rename:
                return E.Col(rename[x.col_name])
            return x

        return E.transform_expr(e, fn)

    return _combine([fix(p) for p in residual])


def _apply_exists(plan: L.LogicalPlan, ex: E.Exists) -> L.LogicalPlan:
    sub = rewrite_subqueries(ex.plan)
    stripped, corr, below_agg = _strip_correlated(sub)
    if below_agg:
        raise NotImplementedError(
            "correlated predicate below an aggregate inside EXISTS")
    how = "left_anti" if ex.negated else "left_semi"
    if not corr:
        # uncorrelated EXISTS: keep all or no rows depending on whether
        # the subquery has any row — a 1-row COUNT()>0 cross join + filter
        flag = L.Aggregate(
            (), (E.Alias(E.Cmp(">", E.Count(None), E.Literal(0)),
                         "__exists__"),), stripped)
        joined = L.Join(plan, flag, "cross", (), ())
        cond = E.Col("__exists__") if not ex.negated \
            else E.Not(E.Col("__exists__"))
        return L.Project(tuple(E.Col(n) for n in plan.schema.names),
                         L.Filter(cond, joined))
    outer_keys, inner_keys, residual = _corr_to_keys(corr)
    cond = _join_condition(residual, plan.schema.names,
                           stripped.schema.names)
    return L.Join(plan, stripped, how, tuple(outer_keys),
                  tuple(inner_keys), cond)


def _apply_in(plan: L.LogicalPlan, isq: E.InSubquery) -> L.LogicalPlan:
    """[NOT] IN (subquery) as a semi/anti join on value equality (+ any
    correlated equalities). NOT IN is null-aware for the uncorrelated
    case (reference: RewritePredicateSubquery's null-aware anti join):
    a NULL anywhere in the subquery result, or a NULL probe value with a
    non-empty subquery, yields UNKNOWN — the row is dropped."""
    sub = rewrite_subqueries(isq.plan)
    stripped, corr, below_agg = _strip_correlated(sub)
    if below_agg:
        raise NotImplementedError(
            "correlated predicate below an aggregate inside IN subquery")
    outer_keys, inner_keys, residual = _corr_to_keys(corr)
    if isinstance(isq.child, E.TupleExpr):
        # (a, b) IN (select x, y ...): multi-key semi join (reference:
        # In.scala with a CreateStruct probe)
        probes = list(isq.child.items)
        if isq.negated:
            raise NotImplementedError(
                "NOT IN with a row-value probe (null-aware anti join "
                "over multiple columns)")
        if len(probes) > len(stripped.schema.names):
            raise ValueError("IN subquery arity mismatch")
        value_cols = [E.Col(n)
                      for n in stripped.schema.names[:len(probes)]]
        outer_keys = probes + outer_keys
        inner_keys = value_cols + inner_keys
    else:
        value_col = stripped.schema.names[0]
        outer_keys = [isq.child] + outer_keys
        inner_keys = [E.Col(value_col)] + inner_keys
    cond = _join_condition(residual, plan.schema.names,
                           stripped.schema.names)
    how = "left_anti" if isq.negated else "left_semi"
    joined = L.Join(plan, stripped, how, tuple(outer_keys),
                    tuple(inner_keys), cond)
    if not isq.negated:
        return joined
    if corr:
        # per-group null-awareness over a nullable inner column is not
        # implemented; with a non-nullable inner column the anti join is
        # exact except for a NULL probe vs a non-empty group (UNKNOWN ->
        # drop), handled via per-group counts when the probe is nullable
        if stripped.schema.fields[0].nullable:
            raise NotImplementedError(
                "correlated NOT IN over a nullable subquery column")
        probe_nullable = True
        try:
            probe_nullable = isq.child.nullable(plan.schema)
        except Exception:
            pass
        if not probe_nullable:
            return joined
        corr_outer = outer_keys[1:]
        corr_inner = inner_keys[1:]
        n_name = f"__nin{next(_sq_counter)}_n"
        key_aliases = [E.Alias(k, f"{n_name}_k{j}")
                      for j, k in enumerate(corr_inner)]
        counts = L.Aggregate(tuple(corr_inner),
                             tuple(key_aliases) +
                             (E.Alias(E.Count(None), n_name),), stripped)
        with_counts = L.Join(joined, counts, "left", tuple(corr_outer),
                             tuple(E.Col(a.alias_name)
                                   for a in key_aliases))
        group_empty = E.IsNull(E.Col(n_name))
        keep = E.Or(group_empty, E.Not(E.IsNull(isq.child)))
        return L.Project(tuple(E.Col(n) for n in plan.schema.names),
                         L.Filter(keep, with_counts))
    # uncorrelated NOT IN: attach subquery row/non-null counts and apply
    # three-valued logic: empty subquery -> keep everything; any NULL in
    # the subquery -> keep nothing; NULL probe + non-empty -> drop row
    i = next(_sq_counter)
    n_name, nn_name = f"__nin{i}_n", f"__nin{i}_nn"
    counts = L.Aggregate(
        (), (E.Alias(E.Count(None), n_name),
             E.Alias(E.Count(E.Col(value_col)), nn_name)), stripped)
    with_counts = L.Join(joined, counts, "cross", (), ())
    empty = E.Cmp("==", E.Col(n_name), E.Literal(0))
    no_nulls = E.Cmp("==", E.Col(n_name), E.Col(nn_name))
    probe_ok = E.Not(E.IsNull(isq.child))
    keep = E.Or(empty, E.And(no_nulls, probe_ok))
    return L.Project(tuple(E.Col(n) for n in plan.schema.names),
                     L.Filter(keep, with_counts))


def _apply_scalar(
    plan: L.LogicalPlan, sq: E.ScalarSubquery,
) -> Tuple[L.LogicalPlan, E.Expression]:
    """Returns (new_plan, replacement column expr)."""
    i = next(_sq_counter)
    out_name = f"__sq{i}"
    sub = rewrite_subqueries(sq.plan)
    stripped, corr, _ = _strip_correlated(sub)
    if not corr:
        first = stripped.schema.names[0]
        if isinstance(stripped, L.Aggregate) and not stripped.groupings:
            # already exactly one row — a straight cross join is safe
            renamed = L.Project((E.Alias(E.Col(first), out_name),), stripped)
            return L.Join(plan, renamed, "cross", (), ()), E.Col(out_name)
        # general relation: reduce to one row so an empty result yields
        # NULL instead of dropping all outer rows (SQL scalar-subquery
        # semantics; reference: RewriteCorrelatedScalarSubquery notes).
        # Deviation: >1 row takes the first instead of raising.
        one_row = L.Aggregate(
            (), (E.Alias(E.First(E.Col(first)), out_name),),
            L.Limit(1, stripped))
        return L.Join(plan, one_row, "cross", (), ()), E.Col(out_name)
    # correlated: the top of the subquery must be a global aggregate;
    # group it by the correlation columns and LEFT JOIN on them
    # (reference: RewriteCorrelatedScalarSubquery + constructLeftJoins)
    if not (isinstance(stripped, L.Aggregate) and not stripped.groupings
            and len(stripped.aggregates) == 1):
        raise NotImplementedError(
            "correlated scalar subquery must be a single global aggregate")
    outer_keys, inner_keys, residual = _corr_to_keys(corr)
    if residual:
        raise NotImplementedError(
            "non-equality correlation in scalar subquery")
    key_aliases = [E.Alias(k, f"__sqk{i}_{j}")
                   for j, k in enumerate(inner_keys)]
    agg_expr = E.strip_alias(stripped.aggregates[0])
    agg_out = E.Alias(agg_expr, out_name)
    grouped = L.Aggregate(tuple(inner_keys),
                          tuple(key_aliases) + (agg_out,),
                          stripped.child)
    joined = L.Join(plan, grouped, "left", tuple(outer_keys),
                    tuple(E.Col(a.alias_name) for a in key_aliases))
    result: E.Expression = E.Col(out_name)
    if isinstance(agg_expr, E.Count):
        # COUNT over an empty correlated group is 0, but the grouped LEFT
        # JOIN produces NULL for groups with no rows (reference:
        # RewriteCorrelatedScalarSubquery's COUNT bug handling)
        result = E.Coalesce((result, E.Literal(0)))
    return joined, result


def _rewrite_filter(node: L.Filter) -> L.LogicalPlan:
    base_names = node.child.schema.names
    plan = node.child
    kept: List[E.Expression] = []
    for c in _split(node.condition):
        if isinstance(c, E.Exists):
            plan = _apply_exists(plan, c)
        elif isinstance(c, E.Not) and isinstance(c.child, E.Exists):
            inner = c.child
            plan = _apply_exists(plan, E.Exists(inner.plan,
                                                not inner.negated))
        elif isinstance(c, E.InSubquery):
            plan = _apply_in(plan, c)
        elif isinstance(c, E.Not) and isinstance(c.child, E.InSubquery):
            inner = c.child
            plan = _apply_in(plan, E.InSubquery(inner.child, inner.plan,
                                                not inner.negated))
        elif E.contains_subquery(c):
            # scalar subqueries inside a comparison/expression
            def replace(e: E.Expression) -> E.Expression:
                nonlocal plan
                if isinstance(e, E.ScalarSubquery):
                    plan, col = _apply_scalar(plan, e)
                    return col
                if isinstance(e, (E.Exists, E.InSubquery)):
                    raise NotImplementedError(
                        "EXISTS/IN under OR or non-conjunct position")
                return e

            kept.append(E.transform_expr(c, replace))
        else:
            kept.append(c)
    if kept:
        plan = L.Filter(_combine(kept), plan)
    if tuple(plan.schema.names) != tuple(base_names):
        plan = L.Project(tuple(E.Col(n) for n in base_names), plan)
    return plan


def _rewrite_project(node: L.Project) -> L.LogicalPlan:
    """Scalar subqueries in SELECT position (reference:
    RewriteCorrelatedScalarSubquery handles Project as well as Filter)."""
    plan = node.child
    new_exprs: List[E.Expression] = []
    for e in node.exprs:
        if not E.contains_subquery(e):
            new_exprs.append(e)
            continue
        out_name = e.name

        def replace(x: E.Expression) -> E.Expression:
            nonlocal plan
            if isinstance(x, E.ScalarSubquery):
                plan, col = _apply_scalar(plan, x)
                return col
            if isinstance(x, (E.Exists, E.InSubquery)):
                raise NotImplementedError(
                    "EXISTS/IN subquery in SELECT position")
            return x

        ne = E.transform_expr(E.strip_alias(e), replace)
        new_exprs.append(E.Alias(ne, out_name))
    return L.Project(tuple(new_exprs), plan)


def rewrite_subqueries(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Remove every SubqueryExpression (bottom-up; nested subqueries are
    rewritten when their enclosing Filter/Project is processed)."""

    def fn(node: L.LogicalPlan) -> L.LogicalPlan:
        if isinstance(node, L.Filter) and E.contains_subquery(node.condition):
            return _rewrite_filter(node)
        if isinstance(node, L.Project) and any(
                E.contains_subquery(e) for e in node.exprs):
            return _rewrite_project(node)
        for e in node.expressions():
            if E.contains_subquery(e):
                raise NotImplementedError(
                    f"subquery expression outside WHERE/HAVING/SELECT: {e}")
        return node

    return plan.transform_up(fn)

"""Recompilation-hazard detection.

The compile store (spark_tpu/compile/store.py) keys executables by a
structural fingerprint that embeds every literal value (expr_key's
``("lit", value, dtype)``) and every scalar plan field (Range bounds,
Limit.n, Repartition.num_partitions). A plan built from a template with
data-dependent constants therefore gets a FRESH fingerprint per value —
the store can never hit, the jit stage caches never hit, and warmup is
paid on every query. This detector proves, statically, which plans are
fingerprint-stable and names the offending node when one is not.

Hazard classes (by consequence, worst first):

- **shape-bearing scalars** (PLAN-RECOMPILE-SHAPE, warn): values that
  flow into traced array shapes — Range start/end/step (capacity =
  bucket-rounded row count), Repartition.num_partitions (exchange
  buffer layout), Expand arity. Varying one re-traces AND recompiles.
  The detector additionally runs a perturbation probe: re-deriving the
  capacity with the value nudged by one says whether the capacity
  bucket (spark.tpu.batch.capacityMultiple) absorbs small variations
  (adjacent values land in one bucket and share an executable) or
  whether EVERY distinct value is a distinct program.

- **value-only literals** (PLAN-RECOMPILE-LITERAL, info): constants
  baked into the fingerprint whose variation keeps shapes stable
  (filter predicates, projection arithmetic, Limit.n — the engine
  limits by masking, not reshaping). Each distinct value still misses
  the compile store, but the re-trace lands on cached shapes.

A plan with neither class is **fingerprint-stable**: the compile store
hits for every future submission of the same query text.
"""

from __future__ import annotations

from typing import List, Tuple

from spark_tpu import conf as CF
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L

from spark_tpu.analysis.diagnostics import Diagnostic
from spark_tpu.analysis.oracle import _bucket


def _literals(expr: E.Expression, out: List[E.Literal]) -> None:
    if isinstance(expr, E.Literal):
        out.append(expr)
    for k in expr.children():
        _literals(k, out)


def _range_bucket_absorbs(node: L.Range, multiple: int) -> bool:
    """Perturbation probe: does nudging the range bound by one step
    keep the bucket-rounded capacity (and hence every traced shape
    downstream) unchanged?"""
    cap = _bucket(node.num_rows, multiple)
    import dataclasses

    nudged = dataclasses.replace(node, end=node.end + node.step)
    return _bucket(nudged.num_rows, multiple) == cap


def detect(plan: L.LogicalPlan, conf) \
        -> Tuple[List[Diagnostic], bool]:
    """Returns (diagnostics, fingerprint_stable)."""
    multiple = max(1, int(conf.get(CF.BATCH_CAPACITY_MULTIPLE)))
    diags: List[Diagnostic] = []
    value_literal_count = 0
    first_value_node = ""

    def go(node: L.LogicalPlan) -> None:
        nonlocal value_literal_count, first_value_node
        if isinstance(node, L.Range):
            absorbed = _range_bucket_absorbs(node, multiple)
            diags.append(Diagnostic(
                code="PLAN-RECOMPILE-SHAPE", level="warn",
                node=node.node_string(),
                message=(
                    f"Range bounds ({node.start}, {node.end}, "
                    f"{node.step}) are baked into the plan "
                    "fingerprint AND size the traced arrays: a "
                    "data-dependent bound re-traces and recompiles "
                    "per distinct value"
                    + ("; the capacity bucket absorbs +-1-step "
                       "variation (adjacent values share an "
                       "executable)" if absorbed else
                       "; the value sits on a capacity-bucket edge — "
                       "even +-1-step variation is a new "
                       "executable")),
                hint=("pass data-dependent row counts through a "
                      "Relation/scan instead of range bounds, or "
                      "round bounds to multiples of "
                      "spark.tpu.batch.capacityMultiple")))
        elif isinstance(node, L.Repartition) \
                and node.num_partitions > 0:
            diags.append(Diagnostic(
                code="PLAN-RECOMPILE-SHAPE", level="warn",
                node=node.node_string(),
                message=(
                    f"repartition({node.num_partitions}) bakes the "
                    "partition count into exchange buffer shapes: a "
                    "data-dependent count re-traces and recompiles "
                    "per distinct value"),
                hint=("leave num_partitions at the mesh default "
                      "(spark.sql.shuffle.partitions=0) unless the "
                      "count is a fixed constant")))
        # value-only literals: everything expr_key embeds
        lits: List[E.Literal] = []
        for e in node.expressions():
            _literals(e, lits)
        n_here = len(lits)
        if isinstance(node, L.Limit):
            n_here += 1  # Limit.n is a plan field, masked not reshaped
        if isinstance(node, L.Sample):
            n_here += 1
        if n_here:
            value_literal_count += n_here
            if not first_value_node:
                first_value_node = node.node_string()
        for c in node.children():
            go(c)

    go(plan)

    if value_literal_count:
        diags.append(Diagnostic(
            code="PLAN-RECOMPILE-LITERAL", level="info",
            node=first_value_node,
            message=(
                f"{value_literal_count} literal value(s) are baked "
                "into the structural fingerprint (first at "
                f"{first_value_node}); each distinct value is a "
                "compile-store miss, though traced shapes stay "
                "stable"),
            hint=("stable for fixed query text; parameterized "
                  "dashboards that vary constants per request will "
                  "never hit the executable store")))

    stable = not diags
    return diags, stable

"""Transform-legality rules: one shared decision procedure for every
place the engine asks "may I re-apply / decompose this aggregate
without changing bytes?".

Before this module the answer lived in three ad-hoc spots with subtly
different phrasing: the AQE skew fan
(parallel/executor._exactly_remergeable), the accumulator decomposition
(plan/incremental.AggSpec._add), and the chunked tier's
try-AggSpec-except gate (physical/chunked._find_agg). They now all
call here, and the static analyzer reports the same verdicts — with
diagnostic codes — before anything executes.

Two distinct legality questions:

- **exact re-merge** (``remerge_verdict*``): can the aggregate list be
  re-applied to its OWN output byte-identically? Required by the AQE
  skew split (a pre-merge replica runs the consumer aggregate twice)
  and by incremental materialized-view merges. Group keys pass
  through; only Sum/Min/Max over a single column qualify; Sum must be
  integral (int wraparound is associative, float rounding is not);
  Min/Max must be non-float (-0.0/NaN selection is order-dependent).

- **mergeable accumulators** (``accumulator_verdict``): can the
  aggregate be decomposed into partial accumulators that a second
  ordinary aggregation merges (count/sum/avg/min/max, no DISTINCT)?
  Required by the chunked out-of-HBM tier and streaming state merge.
  This is purely structural — merging partials happens exactly once,
  so float Sum is fine here (same additions, same order class).

- **strategy flexibility** (``strategy_verdict``): may the adaptive
  aggregation engine switch this aggregate between the partial→final,
  partial-bypass, and hash-partial strategies byte-identically?
  Switching changes WHICH rows each accumulator sees before the merge
  (bypass merges raw rows instead of per-device partials; hash groups
  in packed-code order instead of sort order), so every partial
  accumulator must be partition- and order-invariant: Count always is;
  Sum/Avg need an integral partial sum (int64 wraparound is
  associative, and decimals are scaled int64 — float rounding is not);
  Min/Max must be non-float (-0.0/NaN selection). An aggregate that
  fails is pinned to the static partial→final strategy — execution
  stays correct, just not adaptive. The analyzer reports this as
  PLAN-AGG-STRATEGY.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_tpu.expr import expressions as E


@dataclass(frozen=True)
class Verdict:
    ok: bool
    code: str = ""        # diagnostic code when not ok
    reason: str = ""
    offending: str = ""   # offending expression, printable

    def __bool__(self) -> bool:
        return self.ok


OK = Verdict(True)


def _np_dtype(dtype) -> "np.dtype":
    """numpy dtype of an engine DataType as the executor sees it
    (StringType = int32 dictionary codes, DecimalType = scaled int64,
    so both are exactly re-mergeable)."""
    from spark_tpu.expr.compiler import _jnp_dtype

    return np.dtype(_jnp_dtype(dtype))


def _merge_dtype_verdict(call: E.Expression, dt: "np.dtype") -> Verdict:
    """Numeric half of the exact-re-merge rule for one Sum/Min/Max call
    whose merged accumulator has numpy dtype ``dt``."""
    if isinstance(call, E.Sum):
        if not (np.issubdtype(dt, np.integer) or dt == np.bool_):
            return Verdict(
                False, "PLAN-MERGE-FLOATSUM",
                "float Sum re-merge changes rounding (float addition "
                "is not associative); results would not be "
                "byte-identical", str(call))
        return OK
    if np.issubdtype(dt, np.floating):
        return Verdict(
            False, "PLAN-MERGE-NONMERGEABLE",
            "float Min/Max re-merge is order-dependent (-0.0 vs 0.0 "
            "and NaN selection)", str(call))
    return OK


def remerge_verdict_cols(aggregates, schema) -> Verdict:
    """Exact re-merge legality over an ALREADY-PARTIAL output schema:
    every aggregate must be a group key (plain Col) or Sum/Min/Max over
    a single column of ``schema`` with a re-mergeable dtype. This is
    the AQE skew fan's precondition (the pre-merge replica re-applies
    the consumer's aggregate list to its own output)."""
    by_name = {f.name: f for f in schema.fields}
    for a in aggregates:
        e = E.strip_alias(a)
        if isinstance(e, E.Col):  # group key carried through
            continue
        if not isinstance(e, (E.Sum, E.Min, E.Max)):
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                f"{type(e).__name__} is not exactly re-mergeable "
                "(only integral Sum and non-float Min/Max re-apply "
                "byte-identically)", str(e))
        kids = e.children()
        if len(kids) != 1 or not isinstance(kids[0], E.Col):
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                "re-merge argument must be a single plain column "
                "(computed arguments would be re-evaluated over "
                "already-aggregated rows)", str(e))
        f = by_name.get(kids[0].name)
        if f is None:
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                f"column {kids[0].name!r} absent from the merge "
                "schema", str(e))
        try:
            dt = _np_dtype(f.dtype)
        except Exception:
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                f"no numeric device dtype for {f.dtype}", str(e))
        v = _merge_dtype_verdict(e, dt)
        if not v.ok:
            return v
    return OK


def remerge_verdict(agg) -> Verdict:
    """Static (logical-plan) variant of the exact re-merge rule: the
    same dtype discipline applied to a logical Aggregate before any
    partial output exists — each aggregate call's MERGED accumulator
    dtype (its own output dtype over the child schema) must satisfy
    the Sum/Min/Max rules. Group keys and plain column pass-throughs
    are fine; anything else is not exactly re-mergeable."""
    schema = agg.child.schema
    for a in agg.aggregates:
        e = E.strip_alias(a)
        if isinstance(e, E.Col):
            continue
        calls = E.collect_aggregates(e)
        if not calls or E.expr_key(e) != E.expr_key(calls[0]) \
                or len(calls) != 1:
            # composite output expression (avg = sum/count, arithmetic
            # over aggregates): re-applying it to its own output is
            # not the identity merge
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                "composite aggregate output is not exactly "
                "re-mergeable", str(e))
        call = calls[0]
        if not isinstance(call, (E.Sum, E.Min, E.Max)):
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                f"{type(call).__name__} is not exactly re-mergeable",
                str(call))
        try:
            dt = _np_dtype(call.data_type(schema))
        except Exception:
            return Verdict(
                False, "PLAN-MERGE-NONMERGEABLE",
                "cannot resolve the merged accumulator dtype",
                str(call))
        v = _merge_dtype_verdict(call, dt)
        if not v.ok:
            return v
    return OK


def accumulator_verdict(call: E.Expression) -> Verdict:
    """Mergeable-accumulator legality for ONE aggregate call (the
    AggSpec decomposition gate): count/sum/avg/min/max without
    DISTINCT. Structural only — partials merge exactly once, so float
    Sum is legal here."""
    if getattr(call, "distinct", False):
        return Verdict(
            False, "PLAN-ACC-NONMERGEABLE",
            "DISTINCT aggregates are not mergeable accumulators",
            str(call))
    if not isinstance(call, (E.Count, E.Sum, E.Avg, E.Min, E.Max)):
        return Verdict(
            False, "PLAN-ACC-NONMERGEABLE",
            f"aggregate {call} is not a mergeable accumulator",
            str(call))
    return OK


def accumulators_verdict(aggregates) -> Verdict:
    """Mergeable-accumulator legality over a whole aggregate list."""
    for e in aggregates:
        for call in E.collect_aggregates(e):
            v = accumulator_verdict(call)
            if not v.ok:
                return v
    return OK


def strategy_call_verdict(call: E.Expression, schema) -> Verdict:
    """Strategy flexibility for ONE aggregate call over ``schema``
    (the pre-aggregation input rows). OK means the runtime may compute
    this call's partial accumulators under ANY partitioning/grouping
    order (bypass, hash, sort) and merge to byte-identical results."""
    v = accumulator_verdict(call)
    if not v.ok:
        return v
    if isinstance(call, E.Count):
        return OK  # int64 counting is exact under any row order
    if isinstance(call, (E.Sum, E.Avg)):
        # the decomposition's partial is Sum(child) (AggSpec): its
        # accumulator dtype decides exactness, so decimal Avg (scaled
        # int64 sum + int64 count -> deterministic finalize) passes
        try:
            dt = _np_dtype(E.Sum(call.child).data_type(schema))
        except Exception:
            return Verdict(
                False, "PLAN-AGG-STRATEGY",
                "cannot resolve the partial Sum accumulator dtype",
                str(call))
        if not (np.issubdtype(dt, np.integer) or dt == np.bool_):
            return Verdict(
                False, "PLAN-AGG-STRATEGY",
                "float Sum partials are order-dependent (float "
                "addition is not associative); strategy switching "
                "would change rounding", str(call))
        return OK
    # Min/Max: same dtype discipline as the exact re-merge rule
    try:
        dt = _np_dtype(call.data_type(schema))
    except Exception:
        return Verdict(
            False, "PLAN-AGG-STRATEGY",
            "cannot resolve the Min/Max accumulator dtype", str(call))
    if np.issubdtype(dt, np.floating):
        return Verdict(
            False, "PLAN-AGG-STRATEGY",
            "float Min/Max selection is order-dependent (-0.0 vs 0.0 "
            "and NaN)", str(call))
    return OK


def strategy_verdict(aggregates, schema) -> Verdict:
    """Strategy flexibility over a whole aggregate list: every
    aggregate call must individually qualify. Works on both logical
    output expressions (the analyzer) and already-decomposed physical
    partial aliases (the distributed executor) — both reduce to the
    same set of Count/Sum/Avg/Min/Max calls over the input schema."""
    for e in aggregates:
        for call in E.collect_aggregates(e):
            v = strategy_call_verdict(call, schema)
            if not v.ok:
                return v
    return OK


def strategy_crossover(ndv_ratio: float, domain_width: int,
                       bypass_ndv_ratio: float, hash_domain_limit: int,
                       sort_domain_width: int) -> str:
    """The sort/hash crossover of the adaptive-aggregation matrix: map
    the two measured axes — estimated-NDV-to-row ratio and packed key
    domain width (``-1`` = unbounded/unpackable, e.g. string keys or a
    key range that overflows int64 packing) — to the cheapest legal
    non-static strategy. One pure function so the runtime switch, its
    EXPLAIN diagnostic, and the boundary-cell tests all share the same
    rule (code PLAN-AGG-STRATEGY surfaces this matrix when a strategy
    is pinned instead):

    - low NDV ratio, small domain  -> ``"hash"``  (dense per-device
      table, no sort; beats sorting when partials shrink the data)
    - low NDV ratio, wide domain   -> ``"partial"`` (partial->final:
      partials still shrink rows, but no dense table fits)
    - high NDV ratio, small-enough domain -> ``"bypass"`` (partials
      would not shrink; one exchange of raw rows, single final agg)
    - high NDV ratio, huge/unbounded domain -> ``"sort"`` (the sort
      rung: range exchange + segmented merge; near-distinct keys over
      a huge domain make hashing's random access and bypass's single
      unsorted final both worse than one routing sort that also yields
      key-ordered output for free)
    """
    high_ndv = ndv_ratio >= bypass_ndv_ratio
    small_domain = 0 <= domain_width <= hash_domain_limit
    if high_ndv:
        if domain_width < 0 or domain_width > sort_domain_width:
            return "sort"
        return "bypass"
    if small_domain:
        return "hash"
    return "partial"

"""Typed diagnostics for the pre-execution plan analyzer.

The reference surfaces plan problems as free-text warnings scattered
across the optimizer and AQE logs; here every finding is a typed
``Diagnostic`` with a stable code, so the submit gate, ``/api/v1/lint``
and tests can match on identity instead of message text.

Diagnostic codes (stable API — tests and deployments key on these):

- ``PLAN-DTYPE-F64``        silent float64 widening: a float64 literal
                            mixed into integral arithmetic/comparison
                            promotes the whole expression to f64
- ``PLAN-CAP-BLOWUP``       a plan node's static device footprint
                            (capacity x row width) exceeds the HBM
                            admission budget — cross joins, expands
- ``PLAN-EST-DIVERGE``      static byte estimate vs AQE's measured
                            bytes differ by more than
                            spark.tpu.analysis.divergenceFactor
- ``PLAN-AVAL-MISMATCH``    the shape/dtype oracle disagrees with the
                            physical planner's schema (engine
                            inconsistency — always error level)
- ``PLAN-RECOMPILE-SHAPE``  a shape-bearing scalar (Range bounds,
                            repartition count, expand arity) is baked
                            into the plan fingerprint: varying it
                            re-traces AND recompiles; the compile
                            store can never hit across values
- ``PLAN-RECOMPILE-LITERAL``value-only literals baked into the
                            structural fingerprint (filter constants,
                            limit counts): shapes stay stable but each
                            distinct value is a compile-store miss
- ``PLAN-MERGE-FLOATSUM``   skew split / incremental re-merge is
                            illegal: float Sum re-merge changes
                            rounding, breaking byte-identity
- ``PLAN-MERGE-NONMERGEABLE`` re-merge illegal for any other reason
                            (non-Sum/Min/Max aggregate, float Min/Max
                            -0.0/NaN ordering, computed argument)
- ``PLAN-ACC-NONMERGEABLE`` the aggregate cannot be decomposed into
                            mergeable accumulators (DISTINCT,
                            unsupported call): chunked/streaming
                            tiers execute it directly
- ``PLAN-AGG-STRATEGY``     the runtime-adaptive aggregation engine
                            cannot switch strategies for this
                            aggregate (float Sum/Min/Max partials are
                            order-dependent): it stays pinned to the
                            static partial->final path
- ``PLAN-ANALYZE-FAIL``     the analyzer itself failed on this plan
                            (reported, never raised)

Materialized-view candidacy (root aggregates only; mirrors
``mview/view.inspect_plan`` so the linter and the view manager can
never disagree):

- ``PLAN-MVIEW-OK``         cache() of this plan registers an
                            INCREMENTALLY maintainable view: appended
                            files merge into the cached batch without
                            a full recompute
- ``PLAN-MVIEW-RECOMPUTE``  registrable, but every refresh pays a
                            full device recompute (aggregate not
                            exactly re-mergeable)
- ``PLAN-MVIEW-KEYS``       a grouping key is not carried through to
                            the output as a plain column, so delta
                            partials cannot be re-grouped
- ``PLAN-MVIEW-SOURCE``     not registrable: zero/many scans, mixed
                            stream+file sources, or a source without
                            a file fingerprint
- ``PLAN-MVIEW-SHAPE``      not registrable: the aggregate is not at
                            the plan root

Tree-wide concurrency analysis (``analysis/concurrency.py`` via
``tools/lint_concurrency.py``; ``node`` is ``path:line`` instead of a
plan node for these):

- ``CONC-ORDER-CYCLE``      the static lock-acquisition graph contains
                            an edge that inverts the registered lock
                            hierarchy (spark_tpu/locks.py ranks) or a
                            cycle among unranked locks — a lock-order
                            deadlock waiting for the right interleaving
- ``CONC-UNLOCKED-MUT``     module-level or ``self._``-prefixed state
                            that is mutated under a lock somewhere is
                            mutated with no lock held here (exempt
                            table: ``[tool.lint-concurrency]``)
- ``CONC-BLOCKING-HELD``    a blocking operation (queue put/get, HTTP,
                            file IO, subprocess, sleep, device sync,
                            thread join) runs while a lock is held
- ``CONC-WAIT-NOLOOP``      ``Condition.wait`` outside a predicate
                            loop: wakeups are permitted to be spurious,
                            so every wait must re-check its predicate
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

LEVELS = ("info", "warn", "error")


@dataclass(frozen=True)
class Diagnostic:
    code: str
    level: str           # "info" | "warn" | "error"
    node: str            # node_string() of the offending plan node
    message: str
    hint: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "level": self.level,
                "node": self.node, "message": self.message,
                "hint": self.hint}

    def format(self) -> str:
        loc = f" at {self.node}" if self.node else ""
        hint = f"\n    hint: {self.hint}" if self.hint else ""
        return f"[{self.level.upper()}] {self.code}{loc}: " \
               f"{self.message}{hint}"


@dataclass(frozen=True)
class AnalysisReport:
    """One analyzer run over one plan: diagnostics + the oracle's
    byte accounting + the recompilation-hazard verdict."""

    diagnostics: Tuple[Diagnostic, ...]
    peak_bytes: int = 0            # oracle: max node capacity x width
    admission_bytes: int = 0       # admission.estimate_plan_bytes
    measured_bytes: int = 0        # AQE measured table (0 = none)
    fingerprint_stable: bool = True
    node_count: int = 0
    elapsed_ms: float = 0.0
    plan: str = ""                 # root node_string of analyzed plan
    extra: Dict[str, Any] = field(default_factory=dict)

    def errors(self) -> Tuple[Diagnostic, ...]:
        return tuple(d for d in self.diagnostics if d.level == "error")

    def warnings(self) -> Tuple[Diagnostic, ...]:
        return tuple(d for d in self.diagnostics if d.level == "warn")

    def codes(self) -> Tuple[str, ...]:
        return tuple(d.code for d in self.diagnostics)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "plan": self.plan,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "peak_bytes": self.peak_bytes,
            "admission_bytes": self.admission_bytes,
            "measured_bytes": self.measured_bytes,
            "fingerprint_stable": self.fingerprint_stable,
            "node_count": self.node_count,
            "elapsed_ms": self.elapsed_ms,
            "errors": len(self.errors()),
            "warnings": len(self.warnings()),
        }

    def format(self) -> str:
        head = [
            "== Plan Analysis ==",
            f"nodes={self.node_count} "
            f"peak_bytes={self.peak_bytes} "
            f"admission_bytes={self.admission_bytes} "
            f"measured_bytes={self.measured_bytes or '-'} "
            f"fingerprint_stable={self.fingerprint_stable} "
            f"({self.elapsed_ms:.1f} ms)",
        ]
        if not self.diagnostics:
            head.append("no diagnostics")
        return "\n".join(head + [d.format() for d in self.diagnostics])


class PlanAnalysisError(Exception):
    """Raised by the submit-time gate at spark.tpu.analysis.level=error
    when a plan carries error-level diagnostics. Carries the report so
    callers can render every finding, not just the first."""

    def __init__(self, errors: Tuple[Diagnostic, ...],
                 report: AnalysisReport):
        self.errors = tuple(errors)
        self.report = report
        lines = "; ".join(d.format() for d in self.errors)
        super().__init__(
            f"plan rejected by static analysis ({len(self.errors)} "
            f"error-level diagnostic(s)): {lines}")

"""Shape/dtype/capacity propagation oracle.

Infers, per logical operator and WITHOUT executing or tracing, the
output aval the engine will materialize for it: schema (dtypes from the
expression layer), row-count estimate (plan/join_reorder.estimate_rows
— the same cost model admission control trusts), static device row
capacity (the padded SPMD batch size after
spark.tpu.batch.capacityMultiple rounding, mirroring the physical
planner), and the resulting device bytes (capacity x true per-row
width from each dtype's numpy itemsize plus validity planes — NOT the
flat 8-bytes-a-column guess admission uses).

The per-node accounting feeds three analyzer checks:

- capacity blowups: any node whose static footprint exceeds the HBM
  admission budget (PLAN-CAP-BLOWUP),
- estimate divergence: static peak vs AQE's measured-bytes table
  (PLAN-EST-DIVERGE),
- silent float64 widening: a float64 literal promoted into integral
  arithmetic (PLAN-DTYPE-F64) — under x64 every such leak doubles the
  column's HBM footprint and silently changes comparison semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from spark_tpu import conf as CF
from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L

from spark_tpu.analysis.diagnostics import Diagnostic


@dataclass(frozen=True)
class NodeEstimate:
    """Static aval of one plan node's output."""

    node: str            # node_string()
    depth: int
    names: Tuple[str, ...]
    dtypes: Tuple[str, ...]
    rows: float          # cost-model row estimate
    capacity: int        # padded static device row capacity
    row_bytes: int       # true per-row width (itemsize + validity)
    device_bytes: int    # capacity x row_bytes

    def to_dict(self) -> dict:
        return {"node": self.node, "depth": self.depth,
                "rows": round(self.rows, 1), "capacity": self.capacity,
                "row_bytes": self.row_bytes,
                "device_bytes": self.device_bytes}


def _bucket(n: int, multiple: int) -> int:
    m = max(1, int(multiple))
    return max(m, ((max(0, int(n)) + m - 1) // m) * m)


def row_width_bytes(schema) -> int:
    """True materialized per-row width: each column's numpy itemsize
    (StringType int32 codes, DecimalType scaled int64, ...) plus one
    validity byte per nullable column."""
    total = 0
    for f in schema.fields:
        try:
            total += np.dtype(f.dtype.np_dtype or np.int64).itemsize
        except Exception:
            total += 8
        if getattr(f, "nullable", False):
            total += 1
    return max(1, total)


def _capacity(plan: L.LogicalPlan, child_caps: List[int],
              rows: float, multiple: int) -> int:
    """Static device row capacity of a node's output, mirroring the
    physical layer: leaves pad their row count up to the capacity
    multiple; most unary operators keep their child's capacity (masks,
    not reshapes); Expand stacks one block per projection; joins carry
    the PK-FK pair estimate; Union concatenates."""
    if isinstance(plan, L.Relation):
        return int(plan.batch.capacity)
    if isinstance(plan, (L.UnresolvedScan, L.Range)):
        return _bucket(int(np.ceil(rows)), multiple)
    if isinstance(plan, L.Expand):
        return child_caps[0] * max(1, len(plan.projections))
    if isinstance(plan, (L.Aggregate, L.Distinct)):
        # blocking boundary: the planner compacts aggregate output to
        # bucket(live) before anything downstream consumes it
        return _bucket(int(np.ceil(rows)), multiple)
    if isinstance(plan, L.Join):
        if plan.how == "cross" and not plan.left_keys:
            return max(1, child_caps[0]) * max(1, child_caps[1])
        if plan.how in ("left_semi", "left_anti"):
            return child_caps[0]
        return max(child_caps)
    if isinstance(plan, L.Union):
        return sum(child_caps)
    if child_caps:
        return max(child_caps)
    return _bucket(int(np.ceil(rows)), multiple)


def infer(plan: L.LogicalPlan, conf) -> List[NodeEstimate]:
    """Bottom-up per-node avals, post-order (children precede
    parents; the last entry is the root)."""
    from spark_tpu.plan.join_reorder import estimate_rows

    multiple = max(1, int(conf.get(CF.BATCH_CAPACITY_MULTIPLE)))
    out: List[NodeEstimate] = []

    def go(node: L.LogicalPlan, depth: int) -> NodeEstimate:
        child_ests = [go(c, depth + 1) for c in node.children()]
        try:
            rows = float(estimate_rows(node))
        except Exception:
            rows = max((e.rows for e in child_ests), default=1.0)
        try:
            schema = node.schema
            names = tuple(schema.names)
            dtypes = tuple(repr(f.dtype) for f in schema.fields)
            width = row_width_bytes(schema)
        except Exception:
            names, dtypes, width = (), (), 8
        cap = _capacity(node, [e.capacity for e in child_ests],
                        rows, multiple)
        est = NodeEstimate(
            node=node.node_string(), depth=depth, names=names,
            dtypes=dtypes, rows=rows, capacity=int(cap),
            row_bytes=int(width),
            device_bytes=int(cap) * int(width))
        out.append(est)
        return est

    go(plan, 0)
    return out


def peak_bytes(estimates: List[NodeEstimate]) -> int:
    return max((e.device_bytes for e in estimates), default=0)


# ---- dtype discipline -------------------------------------------------------


def _is_integral(dt) -> bool:
    return isinstance(dt, (T.IntegralType, T.BooleanType))


def _f64_literal_leaks(expr: E.Expression, schema,
                       out: List[Tuple[E.Expression, E.Expression]]) \
        -> None:
    """Collect (container, literal) pairs where a float64 Literal sits
    beside an integral operand inside arithmetic/comparison — the
    silent widening common_type applies there promotes the whole
    expression (and, downstream, the materialized column) to f64."""
    kids = expr.children()
    if isinstance(expr, (E.Arith, E.Cmp)) and len(kids) >= 2:
        def dt_of(e):
            try:
                return e.data_type(schema)
            except Exception:
                return None

        dts = [dt_of(k) for k in kids]
        has_integral = any(d is not None and _is_integral(d)
                           for d in dts)
        if has_integral:
            for k, d in zip(kids, dts):
                if isinstance(E.strip_alias(k), E.Literal) \
                        and isinstance(d, T.Float64Type):
                    out.append((expr, E.strip_alias(k)))
    for k in kids:
        _f64_literal_leaks(k, schema, out)


def dtype_diagnostics(plan: L.LogicalPlan) -> List[Diagnostic]:
    """Walk every single-child node's expressions against its input
    schema, flagging float64-literal widenings (PLAN-DTYPE-F64)."""
    diags: List[Diagnostic] = []

    def go(node: L.LogicalPlan) -> None:
        kids = node.children()
        if len(kids) == 1:
            try:
                schema = kids[0].schema
            except Exception:
                schema = None
            if schema is not None:
                found: List[Tuple[E.Expression, E.Expression]] = []
                for e in node.expressions():
                    _f64_literal_leaks(e, schema, found)
                for container, lit in found:
                    diags.append(Diagnostic(
                        code="PLAN-DTYPE-F64", level="warn",
                        node=node.node_string(),
                        message=(
                            f"float64 literal {lit.value!r} widens "
                            f"integral arithmetic in {container} to "
                            "float64 (silent x2 HBM per element, "
                            "inexact compare semantics)"),
                        hint=("cast the literal to the column's "
                              "integral dtype, or cast the column "
                              "explicitly if float math is "
                              "intended")))
        for k in kids:
            go(k)

    go(plan)
    return diags


def capacity_diagnostics(estimates: List[NodeEstimate],
                         conf) -> List[Diagnostic]:
    """PLAN-CAP-BLOWUP for nodes whose static footprint alone exceeds
    the shared HBM admission budget."""
    budget = int(conf.get(CF.SCHEDULER_HBM_BUDGET))
    diags: List[Diagnostic] = []
    for e in estimates:
        if e.device_bytes > budget:
            if e.node.startswith("Join"):
                # joins no longer ride the replan ladder: the hybrid
                # hash join stages to its memory grant and spills the
                # rest as a planned single pass
                hint = ("an over-budget join executes as a planned "
                        "single pass via the grant-driven hybrid hash "
                        "join (spark.tpu.join.hybrid.*), spilling "
                        "partitions beyond its memory grant; add join "
                        "keys or filters, or raise "
                        "spark.tpu.scheduler.hbmBudgetBytes, to avoid "
                        "the spill traffic")
            else:
                hint = ("this plan will rely on the chunked/OOM-"
                        "degradation ladder; add join keys or filters, "
                        "or raise spark.tpu.scheduler.hbmBudgetBytes")
            diags.append(Diagnostic(
                code="PLAN-CAP-BLOWUP", level="warn",
                node=e.node,
                message=(
                    f"static footprint {e.device_bytes} bytes "
                    f"(capacity {e.capacity} x {e.row_bytes} B/row) "
                    f"exceeds the HBM admission budget {budget}"),
                hint=hint))
    return diags

"""Tree-wide static concurrency analysis.

AST pass over the whole package that cross-checks the code against the
lock-hierarchy registry (``spark_tpu/locks.py`` — the same table the
runtime validator behind ``spark.tpu.debug.lockOrder`` checks):

- **lock-acquisition graph** — which locks each function acquires,
  directly (``with self._lock:``) and transitively through calls it
  makes while holding one.  Edges that invert the registered ranks, or
  cycles among unranked locks, are ``CONC-ORDER-CYCLE``.
- **shared-state discipline** — module-level ``_NAME`` and
  ``self._attr`` state that is mutated under a lock anywhere must be
  mutated under a lock everywhere (``CONC-UNLOCKED-MUT``); ``__init__``
  and ``*_locked``-suffixed functions are locked-by-convention.
- **blocking under a lock** — queue put/get, HTTP, file IO,
  subprocess, ``time.sleep``, ``block_until_ready``, ``Thread.join``,
  ``Event.wait`` while any lock is held is ``CONC-BLOCKING-HELD``.
- **condition discipline** — ``Condition.wait`` not wrapped in a
  predicate loop is ``CONC-WAIT-NOLOOP`` (wakeups may be spurious).

Interprocedural resolution is name-based and deliberately
conservative: a call resolves only when exactly one function of that
name exists in the analyzed tree; ambiguous names (``get``, ``stop``,
…) contribute no edges.  Nested functions and lambdas are analyzed as
separate entry points (they run later, not at their definition site).

Findings are typed :class:`Diagnostic` s with ``node = "path:line"``;
``tools/lint_concurrency.py`` is the CLI with the exemption tables
(``[tool.lint-concurrency]`` in pyproject.toml).
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_tpu.analysis.diagnostics import Diagnostic
from spark_tpu.locks import LOCK_RANKS

#: constructor call suffixes -> lock kind
_LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}
_NAMED_FACTORIES = {
    "named_lock": "lock",
    "named_rlock": "rlock",
    "named_condition": "condition",
}
#: attribute-chain roots whose calls block on IO
_BLOCKING_ROOTS = {"subprocess", "requests", "urllib", "socket",
                   "shutil"}
#: os.<fn> calls that hit the filesystem
_BLOCKING_OS = {"makedirs", "replace", "rename", "remove", "unlink",
                "rmdir"}
#: dict/list/deque/set mutator method names (mirrors
#: tools/lint_invariants rule 4)
_MUTATORS = ("append", "appendleft", "pop", "popleft", "clear",
             "update", "extend", "setdefault", "insert", "remove",
             "add", "discard")
#: callee names never resolved interprocedurally: builtin-shadowing
#: names are ubiquitous on foreign objects (``all(...)``,
#: ``mask.all()`` on an ndarray), so a tree method that happens to be
#: uniquely named ``all`` would be misresolved at every such call
#: site. Cost: edges through legitimately-named methods (e.g.
#: ``pools.all()``) are not seen statically — the runtime validator
#: still observes them.
_PY_BUILTINS = frozenset(dir(builtins))


def _dotted(expr: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _ctor_of(value: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, registry_name) when ``value`` constructs a lock:
    ``locks.named_*("name")`` / ``threading.Lock()`` / bare
    ``Lock()``.  registry_name is None for anonymous constructions."""
    if not isinstance(value, ast.Call):
        return None
    fn = _dotted(value.func)
    if fn is None:
        return None
    tail = fn.rsplit(".", 1)[-1]
    if tail in _NAMED_FACTORIES:
        name = None
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            name = value.args[0].value
        return (_NAMED_FACTORIES[tail], name)
    if tail in _LOCK_CTORS and (fn == tail
                                or fn == f"threading.{tail}"):
        return (_LOCK_CTORS[tail], None)
    return None


def _looks_like_lock(name: str) -> bool:
    low = name.lower()
    return ("lock" in low or "mutex" in low
            or low.endswith("_cond") or low == "cond")


class _Binding:
    """One lock the analyzer knows about."""

    __slots__ = ("name", "kind", "anonymous")

    def __init__(self, name: str, kind: str, anonymous: bool):
        self.name = name        # registry name, or "<rel>::<var>"
        self.kind = kind        # lock | rlock | condition | unknown
        self.anonymous = anonymous


class _Call:
    __slots__ = ("held", "callee", "line")

    def __init__(self, held: Tuple[str, ...], callee: str, line: int):
        self.held = held
        self.callee = callee
        self.line = line


class _Mutation:
    __slots__ = ("held", "line", "func", "in_init", "by_convention")

    def __init__(self, held: Tuple[str, ...], line: int, func: str,
                 in_init: bool, by_convention: bool):
        self.held = held
        self.line = line
        self.func = func
        self.in_init = in_init
        self.by_convention = by_convention


class _Blocking:
    __slots__ = ("held", "line", "func", "what")

    def __init__(self, held: Tuple[str, ...], line: int, func: str,
                 what: str):
        self.held = held
        self.line = line
        self.func = func
        self.what = what


class _FuncInfo:
    """Per-function summary used by the interprocedural pass."""

    def __init__(self, rel: str, qualname: str):
        self.rel = rel
        self.qualname = qualname
        self.acquires: Set[str] = set()        # directly acquired
        self.acquire_lines: Dict[str, int] = {}
        self.calls: List[_Call] = []
        self.effective: Set[str] = set()       # filled by fixpoint


class _ModuleScan(ast.NodeVisitor):
    """First pass over one module: lock bindings (module vars, class
    attrs, function locals), condition/queue/thread/event typed names,
    and `_`-prefixed module state."""

    def __init__(self, rel: str, aliases: Dict[str, str]):
        self.rel = rel
        self.aliases = aliases
        #: var or Class.attr -> _Binding
        self.locks: Dict[str, _Binding] = {}
        self.queues: Set[str] = set()
        self.threads: Set[str] = set()
        self.events: Set[str] = set()
        self.module_state: Set[str] = set()
        self._class: Optional[str] = None
        self._fdepth = 0

    def _bind(self, key: str, kind: str, reg_name: Optional[str]):
        alias = self.aliases.get(f"{self.rel}::{key}")
        if alias is not None:
            self.locks[key] = _Binding(alias, kind, False)
        elif reg_name is not None:
            self.locks[key] = _Binding(reg_name, kind, False)
        else:
            self.locks[key] = _Binding(f"{self.rel}::{key}", kind, True)

    def _scan_assign(self, target: ast.AST, value: ast.AST) -> None:
        key: Optional[str] = None
        if isinstance(target, ast.Name):
            if self._fdepth > 0:
                return  # function locals are _FunctionWalk's business
            key = target.id
            if self._class is not None:
                key = f"{self._class}.{key}"
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self" and self._class is not None):
            key = f"{self._class}.{target.attr}"
        if key is None:
            return
        ctor = _ctor_of(value)
        if ctor is not None:
            self._bind(key, ctor[0], ctor[1])
            return
        if isinstance(value, ast.Call):
            fn = _dotted(value.func) or ""
            tail = fn.rsplit(".", 1)[-1]
            if tail == "Queue":
                self.queues.add(key)
            elif tail == "Thread":
                self.threads.add(key)
            elif tail == "Event":
                self.events.add(key)
        # aliasing through config even without a recognized ctor
        # (e.g. MemoryStore._lock = manager.lock)
        if key not in self.locks \
                and f"{self.rel}::{key}" in self.aliases:
            self._bind(key, "unknown", None)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def visit_FunctionDef(self, node) -> None:
        self._fdepth += 1
        self.generic_visit(node)
        self._fdepth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._scan_assign(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._scan_assign(node.target, node.value)
        self.generic_visit(node)

    def scan_module_state(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
            for t in targets:
                if not (isinstance(t, ast.Name)
                        and t.id.startswith("_")
                        and not t.id.startswith("__")):
                    continue
                if t.id in self.locks or _looks_like_lock(t.id):
                    continue
                self.module_state.add(t.id)


class _FunctionWalk(ast.NodeVisitor):
    """Second pass: walk one function with a held-lock stack."""

    def __init__(self, analyzer: "_TreeAnalyzer", scan: _ModuleScan,
                 qualname: str, in_class: Optional[str]):
        self.a = analyzer
        self.scan = scan
        self.qualname = qualname
        self.in_class = in_class
        self.rel = scan.rel
        self.held: List[str] = []
        self.while_depth = 0
        self.local_locks: Dict[str, _Binding] = {}
        self.info = _FuncInfo(scan.rel, qualname)
        fname = qualname.rsplit(".", 1)[-1]
        self.in_init = fname == "__init__"
        self.by_convention = fname.endswith("_locked")

    # -- resolution ----------------------------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> Optional[_Binding]:
        if isinstance(expr, ast.Name):
            b = self.local_locks.get(expr.id)
            if b is not None:
                return b
            b = self.scan.locks.get(expr.id)
            if b is not None:
                return b
            if self.in_class is not None:
                b = self.scan.locks.get(f"{self.in_class}.{expr.id}")
                if b is not None:
                    return b
            if _looks_like_lock(expr.id):
                alias = self.scan.aliases.get(f"{self.rel}::{expr.id}")
                if alias is not None:
                    return _Binding(alias, "unknown", False)
                return _Binding(f"{self.rel}::{expr.id}", "unknown",
                                True)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" \
                    and self.in_class is not None:
                b = self.scan.locks.get(f"{self.in_class}.{expr.attr}")
                if b is not None:
                    return b
            dotted = _dotted(expr)
            if dotted is not None and _looks_like_lock(expr.attr):
                alias = self.scan.aliases.get(f"{self.rel}::{dotted}")
                if alias is not None:
                    return _Binding(alias, "unknown", False)
                return _Binding(f"{self.rel}::{dotted}", "unknown",
                                True)
        return None

    def _receiver_is(self, expr: ast.AST, names: Set[str]) -> bool:
        """Does the call receiver resolve to one of the typed names
        collected by the module scan (queues/threads/events)?"""
        if isinstance(expr, ast.Name):
            if expr.id in names:
                return True
            return self.in_class is not None \
                and f"{self.in_class}.{expr.id}" in names
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and self.in_class is not None:
            return f"{self.in_class}.{expr.attr}" in names
        return False

    def _is_condition(self, expr: ast.AST) -> bool:
        b = self._resolve_lock(expr)
        if b is not None and b.kind == "condition":
            return True
        tail = expr.attr if isinstance(expr, ast.Attribute) else (
            expr.id if isinstance(expr, ast.Name) else "")
        return "cond" in tail.lower()

    # -- state mutation ------------------------------------------------------

    def _note_mutation(self, key: Optional[str], line: int) -> None:
        if key is None:
            return
        self.a.mutations.setdefault((self.rel, key), []).append(
            _Mutation(tuple(self.held), line, self.qualname,
                      self.in_init, self.by_convention))

    def _state_key(self, target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Name):
            if target.id in self.scan.module_state \
                    and (target.id in self.declared_global
                         or target.id not in self.local_names):
                return target.id
            return None
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" \
                and self.in_class is not None \
                and target.attr.startswith("_") \
                and not target.attr.startswith("__"):
            key = f"{self.in_class}.{target.attr}"
            if key in self.scan.locks:
                return None
            return key
        return None

    # -- visitor -------------------------------------------------------------

    def run(self, node: ast.AST) -> _FuncInfo:
        self.local_names: Set[str] = set()
        self.declared_global: Set[str] = set()
        body = getattr(node, "body", [])
        args = getattr(node, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                self.local_names.add(a.arg)
        for stmt in body if isinstance(body, list) else [body]:
            self.visit(stmt)
        return self.info

    def visit_FunctionDef(self, node) -> None:
        self.a.walk_function(self.scan, node,
                             f"{self.qualname}.{node.name}",
                             self.in_class)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # runs later, not at definition site

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # nested classes: out of scope

    def visit_While(self, node: ast.While) -> None:
        self.while_depth += 1
        self.generic_visit(node)
        self.while_depth -= 1

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name):
                # rebinding a module _NAME requires `global` (plain
                # assignment makes a local, which is not a mutation of
                # the module state)
                if t.id in self.declared_global:
                    self._note_mutation(self._state_key(t), node.lineno)
                else:
                    self.local_names.add(t.id)
                # local lock constructions (with state_lock: ... later)
                ctor = _ctor_of(node.value)
                if ctor is not None:
                    kind, reg = ctor
                    name = reg if reg is not None \
                        else f"{self.rel}::{self.qualname}.{t.id}"
                    self.local_locks[t.id] = _Binding(
                        name, kind, reg is None)
            elif isinstance(t, ast.Subscript):
                self._note_mutation(self._state_key(t.value),
                                    node.lineno)
            else:
                self._note_mutation(self._state_key(t), node.lineno)
        self.generic_visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        t = node.target
        if isinstance(t, ast.Name) and t.id not in self.declared_global:
            pass  # augments a local (or is a SyntaxError anyway)
        elif isinstance(t, ast.Subscript):
            self._note_mutation(self._state_key(t.value), node.lineno)
        else:
            self._note_mutation(self._state_key(t), node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            inner = t.value if isinstance(t, ast.Subscript) else t
            self._note_mutation(self._state_key(inner), node.lineno)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        for n in node.names:
            self.declared_global.add(n)
            self.local_names.discard(n)

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            b = self._resolve_lock(item.context_expr)
            if b is None:
                continue
            acquired.append(b.name)
            for h in self.held:
                self.a.note_edge(h, b.name, self.rel, node.lineno,
                                 f"{self.qualname}")
            if b.name not in self.info.acquires:
                self.info.acquires.add(b.name)
                self.info.acquire_lines[b.name] = node.lineno
            # evaluate the context expressions themselves
            self.visit(item.context_expr)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # _TABLE[key] = v / del _TABLE[key] are handled by Assign/
        # Delete; loads need no tracking
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        line = node.lineno
        held = tuple(self.held)
        fn = node.func
        dotted = _dotted(fn) or ""
        tail = dotted.rsplit(".", 1)[-1]
        root = dotted.split(".", 1)[0] if dotted else ""

        # ---- blocking-call rule -------------------------------------
        if held:
            what = None
            if isinstance(fn, ast.Name) and fn.id == "open":
                what = "open()"
            elif root in _BLOCKING_ROOTS:
                what = f"{dotted}()"
            elif dotted == "time.sleep":
                what = "time.sleep()"
            elif tail == "block_until_ready":
                what = ".block_until_ready()"
            elif isinstance(fn, ast.Attribute):
                recv = fn.value
                if root == "os" and tail in _BLOCKING_OS:
                    what = f"os.{tail}()"
                elif tail in ("put", "get") \
                        and self._receiver_is(recv, self.scan.queues):
                    what = f"Queue.{tail}()"
                elif tail == "join" \
                        and self._receiver_is(recv, self.scan.threads):
                    what = "Thread.join()"
                elif tail == "wait" \
                        and self._receiver_is(recv, self.scan.events):
                    what = "Event.wait()"
            if what is not None:
                self.a.blocking.append(_Blocking(
                    held, line, f"{self.rel}::{self.qualname}", what))

        # ---- condition-wait rule ------------------------------------
        if tail == "wait" and isinstance(fn, ast.Attribute) \
                and self._is_condition(fn.value) \
                and self.while_depth == 0:
            self.a.bare_waits.append((self.rel, line, self.qualname))

        # ---- mutator-method state mutations -------------------------
        if tail in _MUTATORS and isinstance(fn, ast.Attribute):
            self._note_mutation(self._state_key(fn.value), line)

        # ---- interprocedural call edge ------------------------------
        if tail and tail not in _MUTATORS \
                and tail not in _PY_BUILTINS:
            self.info.calls.append(_Call(held, tail, line))

        # acquire()/release() style usage of known locks is out of
        # scope for edges (the tree uses `with`); still record calls
        self.generic_visit(node)


class _TreeAnalyzer:
    """Whole-tree analysis over {relpath: source}."""

    def __init__(self, sources: Dict[str, str],
                 aliases: Optional[Dict[str, str]] = None):
        self.sources = sources
        self.aliases = dict(aliases or {})
        #: (outer, inner) -> (rel, line, func) of first sighting
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.mutations: Dict[Tuple[str, str], List[_Mutation]] = {}
        self.blocking: List[_Blocking] = []
        self.bare_waits: List[Tuple[str, int, str]] = []
        self.functions: List[_FuncInfo] = []
        #: lock name -> kind (named locks keep the registry kind)
        self.kinds: Dict[str, str] = {}

    # -- collection ----------------------------------------------------------

    def note_edge(self, outer: str, inner: str, rel: str, line: int,
                  func: str) -> None:
        if outer == inner:
            return  # same-name re-entry is legal (RLock sharing)
        self.edges.setdefault((outer, inner), (rel, line, func))

    def walk_function(self, scan: _ModuleScan, node, qualname: str,
                      in_class: Optional[str]) -> None:
        w = _FunctionWalk(self, scan, qualname, in_class)
        self.functions.append(w.run(node))

    def _walk_module(self, rel: str, tree: ast.Module) -> None:
        scan = _ModuleScan(rel, self.aliases)
        scan.visit(tree)
        scan.scan_module_state(tree)
        for key, b in scan.locks.items():
            self.kinds.setdefault(b.name, b.kind)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.walk_function(scan, stmt, stmt.name, None)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.walk_function(
                            scan, sub, f"{stmt.name}.{sub.name}",
                            stmt.name)

    # -- interprocedural fixpoint -------------------------------------------

    def _propagate(self) -> None:
        by_name: Dict[str, List[_FuncInfo]] = {}
        for f in self.functions:
            by_name.setdefault(f.qualname.rsplit(".", 1)[-1],
                               []).append(f)
        for f in self.functions:
            f.effective = set(f.acquires)
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for f in self.functions:
                for call in f.calls:
                    targets = by_name.get(call.callee, ())
                    if len(targets) != 1:
                        continue  # ambiguous/unknown: no edges
                    extra = targets[0].effective - f.effective
                    if extra:
                        f.effective |= extra
                        changed = True
        # now materialize edges: call under held H reaches everything
        # the (unambiguous) callee effectively acquires
        for f in self.functions:
            for call in f.calls:
                if not call.held:
                    continue
                targets = by_name.get(call.callee, ())
                if len(targets) != 1:
                    continue
                for inner in targets[0].effective:
                    for outer in call.held:
                        self.note_edge(outer, inner, f.rel, call.line,
                                       f.qualname)

    # -- reporting -----------------------------------------------------------

    def _cycles(self) -> List[List[str]]:
        """Strongly connected components with >1 node in the edge
        graph (Tarjan is overkill at this size: iterative DFS over
        <100 nodes)."""
        nodes = sorted({n for e in self.edges for n in e})
        index = {n: i for i, n in enumerate(nodes)}
        out: Dict[str, List[str]] = {n: [] for n in nodes}
        for (a, b) in self.edges:
            out[a].append(b)
        sccs: List[List[str]] = []
        visited: Set[str] = set()
        for start in nodes:
            if start in visited:
                continue
            # nodes reachable from start that can also reach start
            reach: Set[str] = set()
            stack = [start]
            while stack:
                n = stack.pop()
                if n in reach:
                    continue
                reach.add(n)
                stack.extend(out[n])
            back = {n for n in reach
                    if self._reaches(n, start, out)}
            comp = sorted(back & reach)
            if len(comp) > 1 and not any(
                    set(comp) <= set(s) for s in sccs):
                sccs.append(comp)
            visited |= set(comp) or {start}
        return sccs

    @staticmethod
    def _reaches(src: str, dst: str, out: Dict[str, List[str]]) -> bool:
        if src == dst:
            return True
        seen = {src}
        stack = [src]
        while stack:
            n = stack.pop()
            for m in out.get(n, ()):
                if m == dst:
                    return True
                if m not in seen:
                    seen.add(m)
                    stack.append(m)
        return False

    def diagnostics(self,
                    exempt_unlocked: Optional[Dict[str, str]] = None,
                    exempt_blocking: Optional[Dict[str, str]] = None
                    ) -> List[Diagnostic]:
        exempt_unlocked = exempt_unlocked or {}
        exempt_blocking = exempt_blocking or {}
        out: List[Diagnostic] = []

        # ---- CONC-ORDER-CYCLE: rank inversions ----------------------
        for (a, b), (rel, line, func) in sorted(self.edges.items()):
            ra, rb = LOCK_RANKS.get(a), LOCK_RANKS.get(b)
            if ra is None or rb is None:
                continue
            if rb <= ra:
                out.append(Diagnostic(
                    code="CONC-ORDER-CYCLE", level="error",
                    node=f"{rel}:{line}",
                    message=(
                        f"{func} acquires {b!r} (rank {rb}) while "
                        f"holding {a!r} (rank {ra}): inverts the "
                        f"registered lock hierarchy"),
                    hint="acquire in ascending locks.LOCK_RANKS order "
                         "or release the outer lock first"))
        # ---- CONC-ORDER-CYCLE: cycles (covers unranked locks) -------
        for comp in self._cycles():
            ranked = [n for n in comp if n in LOCK_RANKS]
            if len(ranked) == len(comp):
                continue  # fully ranked cycles already reported above
            sites = [self.edges[e] for e in self.edges
                     if e[0] in comp and e[1] in comp]
            rel, line, func = sorted(sites)[0]
            out.append(Diagnostic(
                code="CONC-ORDER-CYCLE", level="error",
                node=f"{rel}:{line}",
                message=(
                    "lock-acquisition cycle: "
                    + " -> ".join(comp + [comp[0]])),
                hint="break the cycle by ordering these locks in "
                     "locks.LOCK_RANKS and acquiring in rank order"))

        # ---- CONC-UNLOCKED-MUT --------------------------------------
        for (rel, key), sites in sorted(self.mutations.items()):
            locked = [s for s in sites if s.held]
            if not locked:
                continue
            for s in sites:
                if s.held or s.in_init or s.by_convention:
                    continue
                ekey = f"{rel}::{key}"
                if ekey in exempt_unlocked:
                    continue
                lock_names = sorted({h for ls in locked
                                     for h in ls.held})
                out.append(Diagnostic(
                    code="CONC-UNLOCKED-MUT", level="error",
                    node=f"{rel}:{s.line}",
                    message=(
                        f"{key} is mutated under "
                        f"{'/'.join(lock_names)} elsewhere but with "
                        f"no lock held in {s.func}"),
                    hint=f"hold the lock here, or exempt "
                         f"'{ekey}' with a justification in "
                         f"[tool.lint-concurrency.exempt-unlocked]"))

        # ---- CONC-BLOCKING-HELD -------------------------------------
        for blk in self.blocking:
            if blk.func in exempt_blocking:
                continue
            out.append(Diagnostic(
                code="CONC-BLOCKING-HELD", level="error",
                node=f"{blk.func.split('::')[0]}:{blk.line}",
                message=(
                    f"{blk.what} while holding "
                    f"{'/'.join(blk.held)} in "
                    f"{blk.func.split('::')[-1]}"),
                hint=f"move the blocking call outside the lock, or "
                     f"exempt '{blk.func}' with a justification in "
                     f"[tool.lint-concurrency.exempt-blocking]"))

        # ---- CONC-WAIT-NOLOOP ---------------------------------------
        for (rel, line, func) in self.bare_waits:
            out.append(Diagnostic(
                code="CONC-WAIT-NOLOOP", level="error",
                node=f"{rel}:{line}",
                message=(
                    f"Condition.wait in {func} is not wrapped in a "
                    f"predicate loop; wakeups may be spurious"),
                hint="use `while not predicate: cond.wait(...)` or "
                     "cond.wait_for(predicate)"))
        return out


def analyze_sources(sources: Dict[str, str],
                    aliases: Optional[Dict[str, str]] = None,
                    exempt_unlocked: Optional[Dict[str, str]] = None,
                    exempt_blocking: Optional[Dict[str, str]] = None
                    ) -> List[Diagnostic]:
    """Run the full analysis over ``{relpath: python_source}`` and
    return the findings (the importable core of run_lint; tests feed
    seeded sources here)."""
    t = _TreeAnalyzer(sources, aliases=aliases)
    for rel, src in sorted(sources.items()):
        t._walk_module(rel, ast.parse(src, filename=rel))
    t._propagate()
    return t.diagnostics(exempt_unlocked=exempt_unlocked,
                         exempt_blocking=exempt_blocking)


def lock_graph(sources: Dict[str, str],
               aliases: Optional[Dict[str, str]] = None
               ) -> Dict[str, object]:
    """The raw acquisition graph (edges + per-function acquires), for
    debugging and for the runtime cross-check test to compare observed
    edges against."""
    t = _TreeAnalyzer(sources, aliases=aliases)
    for rel, src in sorted(sources.items()):
        t._walk_module(rel, ast.parse(src, filename=rel))
    t._propagate()
    return {
        "edges": {f"{a} -> {b}": f"{rel}:{line} ({func})"
                  for (a, b), (rel, line, func)
                  in sorted(t.edges.items())},
        "acquires": {f.qualname: sorted(f.effective)
                     for f in t.functions if f.effective},
    }

"""Pre-execution plan analyzer: orchestration + submit-time gate.

``analyze(plan, conf)`` walks a LOGICAL plan without executing it and
returns an :class:`AnalysisReport` combining the three sub-analyses:

1. the shape/dtype/capacity oracle (analysis/oracle.py) — per-node
   avals, peak device bytes, float64-literal widenings, capacity
   blowups against the HBM admission budget, and a divergence check of
   the static byte estimate against admission control's AQE-measured
   table,
2. the recompilation-hazard detector (analysis/hazards.py) — is the
   structural fingerprint stable under data-dependent values,
3. the transform-legality rules (analysis/legality.py) — shared with
   the AQE skew fan, accumulator decomposition, and the chunked tier.

The analyzer itself NEVER raises: an internal failure becomes a single
``PLAN-ANALYZE-FAIL`` diagnostic. The submit-time gate
(``maybe_gate``) raises :class:`PlanAnalysisError` only at
``spark.tpu.analysis.level=error`` and only for error-level findings.

Level policy: defect rules default to warn/info because a finding like
float Sum is only FATAL relative to an intent — q1's sum(l_quantity)
is fine to execute, illegal to skew-split. Passing
``intent="skew_split"`` escalates the PLAN-MERGE-* codes to error;
``spark.tpu.analysis.errorCodes`` (comma-separated) escalates any
chosen codes at the gate; PLAN-AVAL-MISMATCH is intrinsically error
(the oracle and the physical planner disagree — an engine bug, not a
user plan problem).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu.plan import logical as L

from spark_tpu.analysis import hazards, legality, oracle
from spark_tpu.analysis.diagnostics import (AnalysisReport, Diagnostic,
                                            PlanAnalysisError)

#: codes whose severity is intent-relative: error only when the caller
#: declares it will actually attempt the transform
_MERGE_CODES = ("PLAN-MERGE-FLOATSUM", "PLAN-MERGE-NONMERGEABLE")

_RECENT_LOCK = locks.named_lock("analysis.recent")
_RECENT_MAX = 64
_RECENT: List[AnalysisReport] = []


def _escalations(conf) -> Tuple[str, ...]:
    raw = str(conf.get(CF.ANALYSIS_ERROR_CODES) or "")
    return tuple(c.strip() for c in raw.split(",") if c.strip())


def _legality_diags(plan: L.LogicalPlan,
                    intent: Optional[str]) -> List[Diagnostic]:
    level = "error" if intent == "skew_split" else "info"
    diags: List[Diagnostic] = []

    def go(node: L.LogicalPlan) -> None:
        if isinstance(node, L.Aggregate):
            v = legality.remerge_verdict(node)
            if not v.ok:
                diags.append(Diagnostic(
                    code=v.code, level=level,
                    node=node.node_string(),
                    message=f"not exactly re-mergeable: {v.reason}",
                    hint=("the AQE skew fan and incremental merges "
                          "will fall back to single-shard execution "
                          f"for this aggregate ({v.offending})")))
            va = legality.accumulators_verdict(node.aggregates)
            if not va.ok:
                diags.append(Diagnostic(
                    code=va.code, level="info",
                    node=node.node_string(),
                    message=("no mergeable accumulator decomposition: "
                             f"{va.reason}"),
                    hint=("the chunked out-of-HBM tier will execute "
                          f"this aggregate directly ({va.offending})")))
            try:
                vs = legality.strategy_verdict(node.aggregates,
                                               node.child.schema)
            except Exception:
                vs = legality.OK
            if not vs.ok:
                diags.append(Diagnostic(
                    code="PLAN-AGG-STRATEGY", level="info",
                    node=node.node_string(),
                    message=("adaptive aggregation pinned to the "
                             f"partial->final strategy: {vs.reason}"),
                    hint=("the runtime strategy switch (partial-bypass "
                          "/ hash-partial) is skipped for this "
                          f"aggregate ({vs.offending})")))
        for c in node.children():
            go(c)

    go(plan)
    return diags


def _mview_diags(plan: L.LogicalPlan) -> List[Diagnostic]:
    """Materialized-view candidacy for root aggregates (the only plans
    mview registration accepts): surfaces whether a cache() of this
    exact plan would refresh incrementally, by full recompute, or not
    register at all — the PLAN-MVIEW-* family mirrors
    mview/view.inspect_plan so explain(mode="lint") and the manager
    can never disagree."""
    if not isinstance(plan, L.Aggregate):
        return []
    try:
        from spark_tpu.mview import inspect_plan
    except Exception:
        return []
    try:
        insp = inspect_plan(plan)
    except Exception:
        return []
    return [Diagnostic(code=code, level="info",
                       node=plan.node_string(), message=message,
                       hint=hint)
            for code, message, hint in insp.diagnostics]


def _aval_cross_check(optimized: L.LogicalPlan,
                      estimates) -> List[Diagnostic]:
    """The oracle's root aval must agree with the physical planner's
    traced schema — a mismatch means the static model and the engine
    disagree about what this plan materializes (always an error)."""
    from spark_tpu.columnar.batch import empty_batch
    from spark_tpu.physical.planner import plan_physical

    def stub_scans(node: L.LogicalPlan) -> L.LogicalPlan:
        # plan_physical materializes UnresolvedScan leaves
        # (source.read() = parquet decode + host->device transfer);
        # the analyzer must stay static, so file scans are planned
        # against empty same-schema relations instead
        if isinstance(node, L.UnresolvedScan):
            return L.Relation(empty_batch(node.schema))
        return node

    try:
        stubbed = optimized.transform_up(stub_scans)
        phys_schema = plan_physical(stubbed).schema
    except Exception as exc:
        return [Diagnostic(
            code="PLAN-ANALYZE-FAIL", level="warn",
            node=optimized.node_string(),
            message=f"physical planning failed during analysis: {exc!r}",
            hint="the aval cross-check was skipped for this plan")]
    root = estimates[-1]
    phys_names = tuple(phys_schema.names)
    phys_dtypes = tuple(repr(f.dtype) for f in phys_schema.fields)
    if root.names and (phys_names != root.names
                       or phys_dtypes != root.dtypes):
        return [Diagnostic(
            code="PLAN-AVAL-MISMATCH", level="error",
            node=optimized.node_string(),
            message=(
                "static oracle aval "
                f"{list(zip(root.names, root.dtypes))} disagrees with "
                "the physical planner's schema "
                f"{list(zip(phys_names, phys_dtypes))}"),
            hint=("engine inconsistency between the logical schema "
                  "and physical planning — report this plan"))]
    return []


def analyze(plan: L.LogicalPlan, conf=None,
            intent: Optional[str] = None,
            optimize: bool = True) -> AnalysisReport:
    """Statically analyze a logical plan. Never raises; internal
    failures surface as a PLAN-ANALYZE-FAIL diagnostic."""
    from spark_tpu import metrics
    from spark_tpu.scheduler import admission

    if conf is None:
        conf = CF.RuntimeConf()

    t0 = time.perf_counter()
    diags: List[Diagnostic] = []
    peak = adm = measured = node_count = 0
    stable = True
    root_str = ""
    try:
        root_str = plan.node_string()
        optimized = plan
        if optimize:
            from spark_tpu.plan.optimizer import optimize as _opt

            optimized = _opt(plan)
        estimates = oracle.infer(optimized, conf)
        node_count = len(estimates)
        peak = oracle.peak_bytes(estimates)
        diags.extend(oracle.dtype_diagnostics(optimized))
        diags.extend(oracle.capacity_diagnostics(estimates, conf))
        diags.extend(_aval_cross_check(optimized, estimates))

        hz, stable = hazards.detect(optimized, conf)
        diags.extend(hz)
        diags.extend(_legality_diags(optimized, intent))
        diags.extend(_mview_diags(optimized))

        # estimate-divergence: the static oracle vs what admission
        # control will actually believe (AQE-measured bytes preferred)
        adm = int(admission.estimate_plan_bytes(plan, conf))
        measured = int(admission.measured_plan_bytes(plan) or 0)
        if measured:
            factor = float(conf.get(CF.ANALYSIS_DIVERGENCE_FACTOR))
            lo, hi = sorted((max(1, peak), max(1, measured)))
            if factor > 0 and hi / lo > factor:
                diags.append(Diagnostic(
                    code="PLAN-EST-DIVERGE", level="warn",
                    node=root_str,
                    message=(
                        f"static estimate {peak} B vs AQE-measured "
                        f"{measured} B diverge by more than "
                        f"{factor:g}x"),
                    hint=("the cost model is unreliable for this plan "
                          "shape; admission and join ordering run on "
                          "measured bytes, but cold-start decisions "
                          "do not — tune "
                          "spark.tpu.analysis.divergenceFactor to "
                          "silence")))
    except Exception as exc:  # analyzer must never break submission
        diags.append(Diagnostic(
            code="PLAN-ANALYZE-FAIL", level="warn",
            node=root_str,
            message=f"static analysis failed: {exc!r}",
            hint="execution proceeds unanalyzed"))

    # conf-driven escalation of chosen codes to error (the gate's
    # deployment knob; also how tests exercise the error path)
    esc = _escalations(conf)
    if esc:
        diags = [
            Diagnostic(code=d.code, level="error", node=d.node,
                       message=d.message, hint=d.hint)
            if d.code in esc and d.level != "error" else d
            for d in diags]

    elapsed = (time.perf_counter() - t0) * 1e3
    report = AnalysisReport(
        diagnostics=tuple(diags), peak_bytes=int(peak),
        admission_bytes=int(adm), measured_bytes=int(measured),
        fingerprint_stable=bool(stable), node_count=node_count,
        elapsed_ms=elapsed, plan=root_str)

    with _RECENT_LOCK:
        _RECENT.append(report)
        del _RECENT[:-_RECENT_MAX]
    try:
        metrics.note_analysis(report)
    except Exception:
        pass
    return report


def recent_reports(n: int = 16) -> List[AnalysisReport]:
    with _RECENT_LOCK:
        return list(_RECENT[-max(0, int(n)):])


def maybe_gate(plan: L.LogicalPlan, conf) -> Optional[AnalysisReport]:
    """Submit-time gate, keyed on spark.tpu.analysis.level:

    - ``off``   (default): no analysis, returns None
    - ``warn``: analyze, log warn+ diagnostics through metrics, admit
    - ``error``: analyze and raise PlanAnalysisError if any
      diagnostic is error-level (including errorCodes escalations)
    """
    level = str(conf.get(CF.ANALYSIS_LEVEL) or "off").lower()
    if level not in ("warn", "error"):
        return None
    report = analyze(plan, conf)
    if level == "error":
        errs = report.errors()
        if errs:
            from spark_tpu import metrics

            try:
                metrics.note_analysis_gated()
            except Exception:
                pass
            raise PlanAnalysisError(errs, report)
    return report

"""Pre-execution static plan analysis (the "plan sanitizer").

Public surface::

    from spark_tpu import analysis

    report = analysis.analyze(df._plan, spark.conf)   # never raises
    print(report.format())

    analysis.maybe_gate(plan, conf)   # spark.tpu.analysis.level gate

Diagnostic codes are documented in analysis/diagnostics.py; the shared
transform-legality rules (also used by the AQE skew fan, incremental
merges, and the chunked tier) live in analysis/legality.py.
"""

from spark_tpu.analysis.analyzer import (analyze, maybe_gate,
                                         recent_reports)
from spark_tpu.analysis.diagnostics import (AnalysisReport, Diagnostic,
                                            PlanAnalysisError)
from spark_tpu.analysis import legality, oracle, hazards  # noqa: F401

__all__ = [
    "analyze", "maybe_gate", "recent_reports",
    "AnalysisReport", "Diagnostic", "PlanAnalysisError",
    "legality", "oracle", "hazards",
]

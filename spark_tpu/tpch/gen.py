"""Vectorized TPC-H data generator (spec-shaped dbgen).

Produces the eight TPC-H tables as Arrow tables with the spec's schema,
key structure, value domains and the text patterns the 22 queries
predicate on (Brand#MN, container/type vocabularies, p_name words,
comment injections, phone country codes, date windows). Row counts and
distributions follow the TPC-H specification section 4.2; text is
simplified (random word sequences rather than the spec's grammar) except
where queries match on it. Reference peer: the dbgen tool invoked by
TPCHQuerySuite (reference: sql/core/.../TPCHQuerySuite.scala:26).
"""

from __future__ import annotations

import datetime
from typing import Dict, Optional

import numpy as np
import pyarrow as pa

EPOCH = datetime.date(1970, 1, 1)
START = (datetime.date(1992, 1, 1) - EPOCH).days      # o_orderdate low
END = (datetime.date(1998, 8, 2) - EPOCH).days        # o_orderdate high

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

# (nation, region index) — spec Table 4.2.3
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]

TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
    "green", "grey", "honeydew", "hot", "indian", "ivory", "khaki",
    "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
    "magenta", "maroon", "medium", "metallic", "midnight", "mint", "misty",
    "moccasin", "navajo", "navy", "olive", "orange", "orchid", "pale",
    "papaya", "peach", "peru", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan",
    "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]

_COMMENT_WORDS = np.array([
    "carefully", "quickly", "furiously", "slyly", "blithely", "deposits",
    "requests", "packages", "accounts", "instructions", "foxes", "ideas",
    "theodolites", "pinto", "beans", "asymptotes", "dependencies", "somas",
    "platelets", "sleep", "haggle", "nag", "wake", "cajole", "detect",
    "integrate", "boost", "among", "final", "ironic", "express", "regular",
    "bold", "even", "silent", "pending", "special", "unusual",
])


MONEY = pa.decimal128(12, 2)


def _decimal_col(unscaled: np.ndarray, typ=MONEY) -> pa.Array:
    from spark_tpu.columnar.arrow import decimal_from_unscaled

    return decimal_from_unscaled(unscaled, typ)


def _money(rng, n, lo, hi) -> pa.Array:
    """Money columns are DECIMAL(12,2) per the TPC-H spec (the engine
    executes them as exact scaled int64; reference: Decimal.scala)."""
    cents = rng.integers(round(lo * 100), round(hi * 100) + 1, n)
    return _decimal_col(cents)


def _words(rng, n: int, k: int) -> np.ndarray:
    """k-word random comment strings."""
    idx = rng.integers(0, len(_COMMENT_WORDS), (n, k))
    parts = _COMMENT_WORDS[idx]
    out = parts[:, 0]
    for j in range(1, k):
        out = np.char.add(np.char.add(out, " "), parts[:, j])
    return out


def _pick(rng, n, values) -> np.ndarray:
    return np.array(values)[rng.integers(0, len(values), n)]


# ---- dictionary-encoded column builders -------------------------------------
#
# Emitting pa.DictionaryArray (int32 indices + a small vocabulary)
# instead of materialized string arrays is the whole speedup: the old
# path built millions of numpy strings and then `list()`-converted them
# for pyarrow (~160 s at SF1). The engine dictionary-encodes strings on
# ingest anyway, so this also skips a conversion on the read side.


def _dict_col(indices: np.ndarray, vocab) -> pa.DictionaryArray:
    return pa.DictionaryArray.from_arrays(
        pa.array(indices.astype(np.int32), pa.int32()),
        pa.array(list(vocab), pa.string()))


def _pick_dict(rng, n, values) -> pa.DictionaryArray:
    return _dict_col(rng.integers(0, len(values), n), values)


def _words_dict(rng, n: int, k: int, pool: int = 4096,
                inject=None) -> pa.DictionaryArray:
    """Comment column as a dictionary over ``pool`` pre-built k-word
    strings. ``inject`` = (row_indices, strings) appends extra vocab
    entries and points those rows at them (q13/q16 pattern rows)."""
    pool = min(pool, max(64, n))
    vocab = list(_words(rng, pool, k))
    idx = rng.integers(0, pool, n)
    if inject is not None:
        rows, strings = inject
        strings = list(dict.fromkeys(strings))  # vocab must be unique
        if len(rows) and strings:
            base = len(vocab)
            vocab.extend(strings)
            idx[rows] = base + np.arange(len(rows)) % len(strings)
    return _dict_col(idx, vocab)


def _numbered(prefix: str, keys: np.ndarray) -> np.ndarray:
    """'Prefix#%09d' strings, vectorized (no Python format loop)."""
    return np.char.add(
        prefix, np.char.zfill(keys.astype(np.int64).astype(str), 9))


def _numbered_names(prefix: str, keys: np.ndarray) -> pa.Array:
    return pa.array(_numbered(prefix, keys))


def generate_tables(sf: float = 0.01,
                    seed: int = 20260729) -> Dict[str, pa.Table]:
    """All eight tables at scale factor ``sf`` (sf=1 is ~6M lineitems).
    In-RAM path for sf <= ~10; above that use write_parquet_streamed
    (SF100 lineitem alone would need ~80 GB of host arrays)."""
    rng = np.random.default_rng(seed)
    tables, ctx = _gen_static(sf, rng)
    n_ord = max(1, int(1_500_000 * sf))
    orders, lineitem = _gen_orders_slice(rng, 1, n_ord + 1, ctx)
    tables["orders"] = orders
    tables["lineitem"] = lineitem
    return tables


def _gen_static(sf: float, rng) -> tuple:
    """The six non-order tables plus the context the orders/lineitem
    generator needs (part retail prices, key cardinalities)."""
    tables: Dict[str, pa.Table] = {}

    # region / nation --------------------------------------------------------
    tables["region"] = pa.table({
        "r_regionkey": pa.array(np.arange(5), pa.int64()),
        "r_name": pa.array(REGIONS),
        "r_comment": pa.array(list(_words(rng, 5, 6))),
    })
    tables["nation"] = pa.table({
        "n_nationkey": pa.array(np.arange(25), pa.int64()),
        "n_name": pa.array([n for n, _ in NATIONS]),
        "n_regionkey": pa.array(np.array([r for _, r in NATIONS]),
                                pa.int64()),
        "n_comment": pa.array(list(_words(rng, 25, 8))),
    })

    # part --------------------------------------------------------------------
    n_part = max(1, int(200_000 * sf))
    pk = np.arange(1, n_part + 1)
    # p_name: 5-word strings from a pooled vocabulary (q9 predicates on
    # '%green%' — the pool keeps every color word's hit rate intact)
    name_pool = min(8192, max(64, n_part))
    wl = np.array(P_NAME_WORDS)
    nm = wl[rng.integers(0, len(wl), (name_pool, 5))]
    name_vocab = nm[:, 0]
    for j in range(1, 5):
        name_vocab = np.char.add(np.char.add(name_vocab, " "), nm[:, j])
    brand_m = rng.integers(1, 6, n_part)
    brand_n = rng.integers(1, 6, n_part)
    brand_vocab = [f"Brand#{m}{n}" for m in range(1, 6)
                   for n in range(1, 6)]
    type_vocab = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2
                  for c in TYPE_S3]
    cont_vocab = [f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2]
    # spec: (90000 + ((partkey/10) mod 20001) + 100*(partkey mod 1000))/100
    retail_cents = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
    tables["part"] = pa.table({
        "p_partkey": pa.array(pk, pa.int64()),
        "p_name": _dict_col(rng.integers(0, name_pool, n_part),
                            name_vocab),
        "p_mfgr": _dict_col(brand_m - 1,
                            [f"Manufacturer#{m}" for m in range(1, 6)]),
        "p_brand": _dict_col((brand_m - 1) * 5 + (brand_n - 1),
                             brand_vocab),
        "p_type": _pick_dict(rng, n_part, type_vocab),
        "p_size": pa.array(rng.integers(1, 51, n_part), pa.int32()),
        "p_container": _pick_dict(rng, n_part, cont_vocab),
        "p_retailprice": _decimal_col(retail_cents),
        "p_comment": _words_dict(rng, n_part, 3),
    })

    # supplier ----------------------------------------------------------------
    n_supp = max(1, int(10_000 * sf))
    sk = np.arange(1, n_supp + 1)
    s_nation = rng.integers(0, 25, n_supp)
    # q16: ~5 per 10k suppliers carry 'Customer...Complaints'
    bad = rng.choice(n_supp, size=max(1, n_supp // 2000), replace=False)
    bad_strings = [f"Customer {w} Complaints"
                   for w in _words(rng, len(bad), 2)]
    tables["supplier"] = pa.table({
        "s_suppkey": pa.array(sk, pa.int64()),
        "s_name": _numbered_names("Supplier#", sk),
        "s_address": _words_dict(rng, n_supp, 3),
        "s_nationkey": pa.array(s_nation, pa.int64()),
        "s_phone": pa.array(_phones(rng, s_nation)),
        "s_acctbal": pa.array(_money(rng, n_supp, -999.99, 9999.99)),
        "s_comment": _words_dict(rng, n_supp, 8,
                                 inject=(bad, bad_strings)),
    })

    # partsupp ----------------------------------------------------------------
    ps_part = np.repeat(pk, 4)
    ps_supp = np.empty(len(ps_part), dtype=np.int64)
    for j in range(4):
        # spec: supplier = (partkey + j*(S/4 + (partkey-1)//S)) % S + 1
        ps_supp[j::4] = (pk + j * (n_supp // 4 + (pk - 1) // n_supp)) \
            % n_supp + 1
    tables["partsupp"] = pa.table({
        "ps_partkey": pa.array(ps_part, pa.int64()),
        "ps_suppkey": pa.array(ps_supp, pa.int64()),
        "ps_availqty": pa.array(rng.integers(1, 10_000, len(ps_part)),
                                pa.int32()),
        "ps_supplycost": pa.array(_money(rng, len(ps_part), 1.0, 1000.0)),
        "ps_comment": _words_dict(rng, len(ps_part), 5),
    })

    # customer ----------------------------------------------------------------
    n_cust = max(1, int(150_000 * sf))
    ck = np.arange(1, n_cust + 1)
    c_nation = rng.integers(0, 25, n_cust)
    # q13: some customers' orders carry 'special ... requests' comments —
    # handled on orders below
    tables["customer"] = pa.table({
        "c_custkey": pa.array(ck, pa.int64()),
        "c_name": _numbered_names("Customer#", ck),
        "c_address": _words_dict(rng, n_cust, 3),
        "c_nationkey": pa.array(c_nation, pa.int64()),
        "c_phone": pa.array(_phones(rng, c_nation)),
        "c_acctbal": pa.array(_money(rng, n_cust, -999.99, 9999.99)),
        "c_mktsegment": _pick_dict(rng, n_cust, SEGMENTS),
        "c_comment": _words_dict(rng, n_cust, 6),
    })

    ctx = {"n_part": n_part, "n_supp": n_supp, "n_cust": n_cust,
           "ck": ck, "retail_cents": retail_cents}
    return tables, ctx


def _gen_orders_slice(rng, ok_lo: int, ok_hi: int,
                      ctx: Dict) -> tuple:
    """orders + their lineitems for order keys [ok_lo, ok_hi) — the unit
    of streamed generation (SF100 cannot hold all 600M lineitems as host
    arrays at once)."""
    n_part, n_supp, n_cust = ctx["n_part"], ctx["n_supp"], ctx["n_cust"]
    ck, retail_cents = ctx["ck"], ctx["retail_cents"]
    n_ord = ok_hi - ok_lo
    ok = np.arange(ok_lo, ok_hi)
    # spec: only 2/3 of customers have orders
    cust_with_orders = ck[ck % 3 != 0] if n_cust >= 3 else ck
    o_cust = cust_with_orders[rng.integers(0, len(cust_with_orders), n_ord)]
    o_date = rng.integers(START, END - 150, n_ord)
    special = np.nonzero(rng.random(n_ord) < 0.02)[0]
    special_strings = [f"special {w} requests"
                       for w in _words(rng, min(max(len(special), 1),
                                                512), 2)]
    n_clerks = max(2, n_ord // 1000)
    clerk_vocab = _numbered("Clerk#", np.arange(1, n_clerks))
    orders = pa.table({
        "o_orderkey": pa.array(ok, pa.int64()),
        "o_custkey": pa.array(o_cust, pa.int64()),
        "o_orderstatus": _pick_dict(rng, n_ord, ["O", "F", "P"]),
        "o_totalprice": pa.array(_money(rng, n_ord, 900.0, 450_000.0)),
        "o_orderdate": pa.array(o_date.astype("int32"), pa.int32()).cast(
            pa.date32()),
        "o_orderpriority": _pick_dict(rng, n_ord, PRIORITIES),
        "o_clerk": _dict_col(rng.integers(0, len(clerk_vocab), n_ord),
                             clerk_vocab),
        "o_shippriority": pa.array(np.zeros(n_ord, dtype=np.int32),
                                   pa.int32()),
        "o_comment": _words_dict(rng, n_ord, 5,
                                 inject=(special, special_strings)),
    })

    # lineitem ----------------------------------------------------------------
    lines_per = rng.integers(1, 8, n_ord)
    l_order = np.repeat(ok, lines_per)
    l_odate = np.repeat(o_date, lines_per)
    n_li = len(l_order)
    # per-order line numbers without a Python loop: global position
    # minus the order's starting offset
    starts = np.cumsum(lines_per) - lines_per
    l_line = (np.arange(n_li) - np.repeat(starts, lines_per) + 1) \
        .astype(np.int64)
    l_part = rng.integers(1, n_part + 1, n_li)
    # supplier must be one of the part's 4 partsupp suppliers (q9 join)
    which = rng.integers(0, 4, n_li)
    l_supp = (l_part + which * (n_supp // 4 + (l_part - 1) // n_supp)) \
        % n_supp + 1
    l_qty = rng.integers(1, 51, n_li)
    l_price_cents = l_qty * retail_cents[l_part - 1]
    ship = l_odate + rng.integers(1, 122, n_li)
    commit = l_odate + rng.integers(30, 91, n_li)
    receipt = ship + rng.integers(1, 31, n_li)
    today = (datetime.date(1995, 6, 17) - EPOCH).days
    # returnflag vocab [R, A, N]; linestatus vocab [O, F]
    rf_idx = np.where(receipt <= today, rng.integers(0, 2, n_li), 2)
    ls_idx = np.where(ship > today, 0, 1)
    lineitem = pa.table({
        "l_orderkey": pa.array(l_order, pa.int64()),
        "l_partkey": pa.array(l_part, pa.int64()),
        "l_suppkey": pa.array(l_supp, pa.int64()),
        "l_linenumber": pa.array(l_line, pa.int32()),
        "l_quantity": _decimal_col(l_qty * 100),
        "l_extendedprice": _decimal_col(l_price_cents),
        "l_discount": _decimal_col(rng.integers(0, 11, n_li)),
        "l_tax": _decimal_col(rng.integers(0, 9, n_li)),
        "l_returnflag": _dict_col(rf_idx, ["R", "A", "N"]),
        "l_linestatus": _dict_col(ls_idx, ["O", "F"]),
        "l_shipdate": pa.array(ship.astype("int32"), pa.int32()).cast(
            pa.date32()),
        "l_commitdate": pa.array(commit.astype("int32"), pa.int32()).cast(
            pa.date32()),
        "l_receiptdate": pa.array(receipt.astype("int32"), pa.int32()).cast(
            pa.date32()),
        "l_shipinstruct": _pick_dict(rng, n_li, INSTRUCTIONS),
        "l_shipmode": _pick_dict(rng, n_li, SHIPMODES),
        "l_comment": _words_dict(rng, n_li, 4),
    })
    return orders, lineitem


def _phones(rng, nationkeys: np.ndarray):
    """Spec phone format: 'CC-xxx-xxx-xxxx' with CC = nationkey + 10
    (q22 matches on the country-code prefix)."""
    cc = (nationkeys + 10).astype(str)
    parts = [rng.integers(100, 1000, len(nationkeys)).astype(str),
             rng.integers(100, 1000, len(nationkeys)).astype(str),
             rng.integers(1000, 10_000, len(nationkeys)).astype(str)]
    out = cc
    for p in parts:
        out = np.char.add(np.char.add(out, "-"), p)
    return out


def write_parquet(tables: Dict[str, pa.Table], path: str) -> None:
    import os

    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    for name, tbl in tables.items():
        pq.write_table(tbl, os.path.join(path, f"{name}.parquet"))


def write_parquet_streamed(sf: float, path: str, seed: int = 20260729,
                           orders_per_slice: int = 4_000_000) -> None:
    """SF100-capable generation: the six static tables write whole;
    orders/lineitem generate and write in bounded slices of
    ``orders_per_slice`` orders (~4x lineitems), so peak host RAM is one
    slice (~4 GB) instead of the full ~100 GB. orders.parquet /
    lineitem.parquet become multi-file directories (the multi-part
    dataset layout every dbgen -S chunk run produces)."""
    import os

    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    statics, ctx = _gen_static(sf, rng)
    for name, tbl in statics.items():
        pq.write_table(tbl, os.path.join(path, f"{name}.parquet"))
    statics.clear()
    odir = os.path.join(path, "orders.parquet")
    ldir = os.path.join(path, "lineitem.parquet")
    os.makedirs(odir, exist_ok=True)
    os.makedirs(ldir, exist_ok=True)
    n_ord = max(1, int(1_500_000 * sf))
    lo, i = 1, 0
    while lo <= n_ord:
        hi = min(lo + orders_per_slice, n_ord + 1)
        srng = np.random.default_rng([seed, i])
        orders, lineitem = _gen_orders_slice(srng, lo, hi, ctx)
        pq.write_table(orders, os.path.join(odir, f"part-{i:05d}.parquet"),
                       row_group_size=1 << 20)
        pq.write_table(lineitem,
                       os.path.join(ldir, f"part-{i:05d}.parquet"),
                       row_group_size=1 << 20)
        del orders, lineitem
        lo, i = hi, i + 1


def ensure_dataset(sf: float, base: str = "/tmp",
                   seed: int = 20260729) -> str:
    """Generate-once disk cache (SF100 generation is ~15 min of rng on
    one core; benches must not pay it per run). Returns the dataset
    directory; a _DONE marker guards against half-written caches."""
    import os
    import shutil

    tag = f"{sf:g}".replace(".", "p")
    path = os.path.join(base, f"tpch_sf{tag}")
    marker = os.path.join(path, "_DONE")
    if os.path.exists(marker):
        return path
    if os.path.exists(path):
        shutil.rmtree(path)
    if sf <= 10:
        write_parquet(generate_tables(sf, seed), path)
    else:
        write_parquet_streamed(sf, path, seed)
    with open(marker, "w") as f:
        f.write("ok")
    return path


def register_views(spark, tables: Optional[Dict[str, pa.Table]] = None,
                   path: Optional[str] = None) -> None:
    """Register the eight tables as temp views, from memory or a
    write_parquet directory (the latter exercises the scan layer)."""
    names = ["region", "nation", "part", "supplier", "partsupp",
             "customer", "orders", "lineitem"]
    for name in names:
        if path is not None:
            import os

            df = spark.read.parquet(os.path.join(path, f"{name}.parquet"))
        else:
            df = spark.createDataFrame(tables[name])
        df.createOrReplaceTempView(name)

"""sqlite3-backed external result oracle.

SURVEY §4's lesson is golden results from an INDEPENDENT engine (the
reference checks TPC-H/DS results against checked-in goldens,
TPCDSQueryTestSuite); round-1's tests compared the mesh engine against
this project's own single-device mode, which shares the compiler and
therefore its bugs. sqlite3 (stdlib) shares nothing. Queries are
translated to sqlite dialect: date literals become ISO strings (which
order correctly as text), interval arithmetic folds to literal dates,
extract(year)/substring map to strftime/substr.
"""

from __future__ import annotations

import datetime
import decimal
import re
import sqlite3
from typing import Dict, List, Tuple

import pyarrow as pa

_INTERVAL_RE = re.compile(
    r"date\s*'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*interval\s*'(\d+)'"
    r"\s*(day|month|year|week)s?", re.IGNORECASE)
_DATE_RE = re.compile(r"date\s*'(\d{4}-\d{2}-\d{2})'", re.IGNORECASE)
_EXTRACT_RE = re.compile(
    r"extract\s*\(\s*year\s+from\s+([A-Za-z_0-9.]+)\s*\)", re.IGNORECASE)


def _shift(date_s: str, sign: str, qty: int, unit: str) -> str:
    d = datetime.date.fromisoformat(date_s)
    q = qty if sign == "+" else -qty
    unit = unit.lower()
    if unit == "day":
        d = d + datetime.timedelta(days=q)
    elif unit == "week":
        d = d + datetime.timedelta(days=7 * q)
    else:
        months = d.year * 12 + (d.month - 1) + (q if unit == "month"
                                                else 12 * q)
        y, m = divmod(months, 12)
        day = min(d.day, [31, 29 if y % 4 == 0 and (y % 100 != 0
                                                    or y % 400 == 0) else 28,
                          31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m])
        d = datetime.date(y, m + 1, day)
    return d.isoformat()


def to_sqlite_sql(query: str) -> str:
    q = _INTERVAL_RE.sub(
        lambda m: "'" + _shift(m.group(1), m.group(2), int(m.group(3)),
                               m.group(4)) + "'", query)
    q = _DATE_RE.sub(lambda m: "'" + m.group(1) + "'", q)
    q = _EXTRACT_RE.sub(
        lambda m: f"CAST(strftime('%Y', {m.group(1)}) AS INTEGER)", q)
    q = re.sub(r"\bsubstring\s*\(", "substr(", q, flags=re.IGNORECASE)
    return q


def load_sqlite(tables: Dict[str, pa.Table]) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    for name, tbl in tables.items():
        cols = []
        for f in tbl.schema:
            if pa.types.is_integer(f.type):
                t = "INTEGER"
            elif pa.types.is_floating(f.type) or pa.types.is_decimal(f.type):
                t = "REAL"
            else:
                t = "TEXT"  # strings and ISO dates
            cols.append(f'"{f.name}" {t}')
        conn.execute(f'CREATE TABLE {name} ({", ".join(cols)})')
        pydata = []
        for col, f in zip(tbl.columns, tbl.schema):
            vals = col.to_pylist()
            if pa.types.is_date(f.type):
                vals = [None if v is None else v.isoformat() for v in vals]
            elif pa.types.is_decimal(f.type):
                # sqlite has no decimal type; its REAL arithmetic is the
                # tolerance oracle, exactness is asserted separately
                vals = [None if v is None else float(v) for v in vals]
            pydata.append(vals)
        rows = list(zip(*pydata)) if pydata else []
        ph = ", ".join("?" * len(tbl.schema))
        conn.executemany(f"INSERT INTO {name} VALUES ({ph})", rows)
    conn.commit()
    return conn


def run_oracle(conn: sqlite3.Connection, query: str) -> List[Tuple]:
    cur = conn.execute(to_sqlite_sql(query))
    return [tuple(r) for r in cur.fetchall()]


# ---- result comparison ------------------------------------------------------


def normalize_rows(rows: List[Tuple], ndigits: int = 2) -> List[Tuple]:
    """Round floats, stringify dates, so engine and oracle rows are
    comparable; sort to neutralize tie order under ORDER BY."""
    out = []
    for r in rows:
        vals = []
        for v in r:
            if isinstance(v, bool):
                vals.append(int(v))
            elif isinstance(v, decimal.Decimal):
                vals.append(round(float(v), ndigits))
            elif isinstance(v, float):
                vals.append(round(v, ndigits))
            elif isinstance(v, (datetime.date, datetime.datetime)):
                vals.append(v.isoformat()[:10])
            else:
                vals.append(v)
        out.append(tuple(vals))
    return sorted(out, key=lambda t: tuple(
        (x is None, str(x)) for x in t))


def assert_rows_match(got: List[Tuple], want: List[Tuple],
                      rel: float = 1e-6, label: str = "") -> None:
    g = normalize_rows(got)
    w = normalize_rows(want)
    assert len(g) == len(w), (
        f"{label}: row count {len(g)} != oracle {len(w)}\n"
        f"got[:5]={g[:5]}\nwant[:5]={w[:5]}")
    for i, (gr, wr) in enumerate(zip(g, w)):
        assert len(gr) == len(wr), f"{label} row {i}: arity"
        for j, (a, b) in enumerate(zip(gr, wr)):
            if isinstance(a, float) or isinstance(b, float):
                if a is None or b is None:
                    assert a is None and b is None, \
                        f"{label} row {i} col {j}: {a!r} != {b!r}"
                    continue
                denom = max(abs(float(a)), abs(float(b)), 1.0)
                assert abs(float(a) - float(b)) / denom <= rel, (
                    f"{label} row {i} col {j}: {a!r} != {b!r}")
            else:
                assert a == b, f"{label} row {i} col {j}: {a!r} != {b!r}"

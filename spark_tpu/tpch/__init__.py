"""TPC-H: data generator, query texts, external-oracle harness.

Mirrors the reference's TPC-H assets (reference:
sql/core/src/test/resources/tpch/q1.sql..q22.sql and
sql/core/src/test/scala/org/apache/spark/sql/TPCHQuerySuite.scala:26):
the queries are written from the TPC-H specification, the generator is a
spec-shaped vectorized numpy dbgen, and result parity is checked against
sqlite3 (an independent SQL engine in the stdlib) instead of the
project's own single-device mode.
"""

from spark_tpu.tpch.gen import generate_tables, write_parquet, register_views
from spark_tpu.tpch.queries import QUERIES

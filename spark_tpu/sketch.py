"""Probabilistic sketches (reference:
common/sketch/src/main/java/org/apache/spark/util/sketch/
CountMinSketch.java:54, BloomFilter.java:42 — used by
DataFrameStatFunctions and runtime join filters).

Device-native re-expression: both sketches are dense integer arrays
updated with vectorized hashing over whole columns at once (the
reference updates row-by-row in JVM loops). Merging is elementwise
add/or, so sketches built per-device combine with a psum/any over the
mesh — the exact pattern the reference uses to merge per-partition
sketches on the driver."""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu.physical import kernels as K


def _column_hashes(values, seeds: jnp.ndarray) -> jnp.ndarray:
    """(n_seeds, n) uint64 hashes of an int64 column."""
    x = jnp.asarray(values).astype(jnp.uint64)
    return jax.vmap(lambda s: K.hash64(x ^ s))(seeds)


class CountMinSketch:
    """Conservative frequency estimation: depth x width counters;
    estimate = min over rows (never under-counts)."""

    def __init__(self, depth: int = 5, width: int = 2048,
                 table: Optional[jnp.ndarray] = None, seed: int = 42):
        self.depth = depth
        self.width = width
        self.seeds = jnp.asarray(
            np.random.default_rng(seed).integers(1, 1 << 62, depth),
            dtype=jnp.uint64)
        self.table = (jnp.zeros((depth, width), dtype=jnp.int64)
                      if table is None else table)

    @classmethod
    def for_rsd(cls, eps: float = 0.01, confidence: float = 0.99,
                seed: int = 42) -> "CountMinSketch":
        """Size from error bounds (reference: CountMinSketch.create)."""
        width = int(math.ceil(2.0 / eps))
        depth = int(math.ceil(-math.log(1 - confidence) / math.log(2)))
        return cls(depth, width, seed=seed)

    def add(self, values, mask=None) -> "CountMinSketch":
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.width)
        ones = (jnp.ones(h.shape[1], jnp.int64) if mask is None
                else jnp.asarray(mask).astype(jnp.int64))

        def upd(row, idx):
            return row.at[idx].add(ones)

        table = jax.vmap(upd)(self.table, h.astype(jnp.int64))
        return CountMinSketch(self.depth, self.width, table)

    def estimate(self, value: int) -> int:
        h = _column_hashes(jnp.asarray([value]), self.seeds) \
            % jnp.uint64(self.width)
        rows = self.table[jnp.arange(self.depth), h[:, 0].astype(jnp.int64)]
        return int(rows.min())

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        assert (self.depth, self.width) == (other.depth, other.width)
        return CountMinSketch(self.depth, self.width,
                              self.table + other.table)


class BloomFilter:
    """Membership filter; mergeable by OR (reference: BloomFilter.java:42
    putLong/mightContainLong). False positives possible, negatives not."""

    def __init__(self, num_bits: int = 1 << 16, num_hashes: int = 5,
                 bits: Optional[jnp.ndarray] = None, seed: int = 7):
        self.num_bits = num_bits
        self.num_hashes = num_hashes
        self.seeds = jnp.asarray(
            np.random.default_rng(seed).integers(1, 1 << 62, num_hashes),
            dtype=jnp.uint64)
        self.bits = (jnp.zeros((num_bits,), dtype=jnp.bool_)
                     if bits is None else bits)

    @classmethod
    def for_items(cls, expected: int, fpp: float = 0.03,
                  seed: int = 7) -> "BloomFilter":
        n_bits = int(-expected * math.log(fpp) / (math.log(2) ** 2))
        n_bits = max(64, 1 << (n_bits - 1).bit_length())  # power of two
        k = max(1, round(n_bits / expected * math.log(2)))
        return cls(n_bits, k, seed=seed)

    def add(self, values, mask=None) -> "BloomFilter":
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.num_bits)
        on = (jnp.ones(h.shape[1], jnp.bool_) if mask is None
              else jnp.asarray(mask))
        bits = self.bits
        for d in range(self.num_hashes):
            bits = bits.at[h[d].astype(jnp.int64)].max(on)
        return BloomFilter(self.num_bits, self.num_hashes, bits)

    def might_contain(self, values) -> jnp.ndarray:
        """Vectorized membership test for a whole column — this is the
        runtime-join-filter shape (reference: InjectRuntimeFilter)."""
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.num_bits)
        out = jnp.ones(h.shape[1], jnp.bool_)
        for d in range(self.num_hashes):
            out = out & self.bits[h[d].astype(jnp.int64)]
        return out

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        assert (self.num_bits, self.num_hashes) == \
            (other.num_bits, other.num_hashes)
        return BloomFilter(self.num_bits, self.num_hashes,
                           self.bits | other.bits)

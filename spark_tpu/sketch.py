"""Probabilistic sketches (reference:
common/sketch/src/main/java/org/apache/spark/util/sketch/
CountMinSketch.java:54, BloomFilter.java:42 — used by
DataFrameStatFunctions and runtime join filters).

Device-native re-expression: both sketches are dense integer arrays
updated with vectorized hashing over whole columns at once (the
reference updates row-by-row in JVM loops). Merging is elementwise
add/or, so sketches built per-device combine with a psum/any over the
mesh — the exact pattern the reference uses to merge per-partition
sketches on the driver."""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu.physical import kernels as K


def _column_hashes(values, seeds: jnp.ndarray) -> jnp.ndarray:
    """(n_seeds, n) uint64 hashes of an int64 column."""
    x = jnp.asarray(values).astype(jnp.uint64)
    return jax.vmap(lambda s: K.hash64(x ^ s))(seeds)


def hll_estimate(registers: np.ndarray) -> float:
    """HyperLogLog distinct estimate from register maxima: harmonic
    mean alpha_m * m^2 / sum(2^-M_j), with the standard linear-counting
    correction (m * ln(m / V), V = zero registers) in the small range
    where raw HLL biases high (Flajolet et al. 2007, the same
    corrections the reference's HyperLogLogPlusPlusHelper applies).

    The ONE estimator every HLL in the engine shares: the device-side
    group-key sketch traced by the adaptive-aggregation stats stage
    (parallel/operators.ExchangeStatsExec), the hybrid hash join's
    host-side partition oracle (physical/chunked.py), and the host
    ``HyperLogLog`` below all produce register maxima in this shape."""
    m = int(registers.size)
    if m == 0:
        return 0.0
    alpha = 0.7213 / (1.0 + 1.079 / m)
    est = alpha * m * m / float(
        np.sum(np.power(2.0, -registers.astype(np.float64))))
    zeros = int((registers == 0).sum())
    if est <= 2.5 * m and zeros:
        est = m * math.log(m / zeros)
    return float(est)


class HyperLogLog:
    """Host-side HLL over int64 columns, parameterized by register
    count (power of two). Register index comes from the hash's low p
    bits, rank from the leading-zero count of the remaining 64-p bits
    (via float log2 — a +/-1 rank error near powers of two is noise
    for a sketch). The same construction the device sketch traces with
    jnp (ExchangeStatsExec), so one oracle test covers both shapes.
    Merging is elementwise max, like the reference's
    HyperLogLogPlusPlusHelper partial merge."""

    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def __init__(self, registers: int = 256):
        assert registers >= 2 and registers & (registers - 1) == 0, \
            registers
        self.m = int(registers)
        self.p = self.m.bit_length() - 1
        self.registers = np.zeros(self.m, dtype=np.int64)

    def update(self, vals: np.ndarray) -> None:
        """Fold one chunk of int64 values into the registers."""
        h = np.asarray(vals).astype(np.uint64) * self._MIX
        idx = (h & np.uint64(self.m - 1)).astype(np.int64)
        rest = (h >> np.uint64(self.p)).astype(np.float64)
        nbits = 64 - self.p
        msb = np.floor(np.log2(np.maximum(rest, 1.0)))
        rank = np.where(rest > 0, nbits - msb, nbits + 1).astype(np.int64)
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        assert self.m == other.m
        out = HyperLogLog(self.m)
        out.registers = np.maximum(self.registers, other.registers)
        return out

    def estimate(self) -> float:
        return hll_estimate(self.registers)


class CountMinSketch:
    """Conservative frequency estimation: depth x width counters;
    estimate = min over rows (never under-counts)."""

    def __init__(self, depth: int = 5, width: int = 2048,
                 table: Optional[jnp.ndarray] = None, seed: int = 42):
        self.depth = depth
        self.width = width
        self.seeds = jnp.asarray(
            np.random.default_rng(seed).integers(1, 1 << 62, depth),
            dtype=jnp.uint64)
        self.table = (jnp.zeros((depth, width), dtype=jnp.int64)
                      if table is None else table)

    @classmethod
    def for_rsd(cls, eps: float = 0.01, confidence: float = 0.99,
                seed: int = 42) -> "CountMinSketch":
        """Size from error bounds (reference: CountMinSketch.create)."""
        width = int(math.ceil(2.0 / eps))
        depth = int(math.ceil(-math.log(1 - confidence) / math.log(2)))
        return cls(depth, width, seed=seed)

    def add(self, values, mask=None) -> "CountMinSketch":
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.width)
        ones = (jnp.ones(h.shape[1], jnp.int64) if mask is None
                else jnp.asarray(mask).astype(jnp.int64))

        def upd(row, idx):
            return row.at[idx].add(ones)

        table = jax.vmap(upd)(self.table, h.astype(jnp.int64))
        return CountMinSketch(self.depth, self.width, table)

    def estimate(self, value: int) -> int:
        h = _column_hashes(jnp.asarray([value]), self.seeds) \
            % jnp.uint64(self.width)
        rows = self.table[jnp.arange(self.depth), h[:, 0].astype(jnp.int64)]
        return int(rows.min())

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        assert (self.depth, self.width) == (other.depth, other.width)
        return CountMinSketch(self.depth, self.width,
                              self.table + other.table)


class BloomFilter:
    """Membership filter; mergeable by OR (reference: BloomFilter.java:42
    putLong/mightContainLong). False positives possible, negatives not."""

    def __init__(self, num_bits: int = 1 << 16, num_hashes: int = 5,
                 bits: Optional[jnp.ndarray] = None, seed: int = 7):
        self.num_bits = num_bits
        self.num_hashes = num_hashes
        self.seeds = jnp.asarray(
            np.random.default_rng(seed).integers(1, 1 << 62, num_hashes),
            dtype=jnp.uint64)
        self.bits = (jnp.zeros((num_bits,), dtype=jnp.bool_)
                     if bits is None else bits)

    @classmethod
    def for_items(cls, expected: int, fpp: float = 0.03,
                  seed: int = 7) -> "BloomFilter":
        n_bits = int(-expected * math.log(fpp) / (math.log(2) ** 2))
        n_bits = max(64, 1 << (n_bits - 1).bit_length())  # power of two
        k = max(1, round(n_bits / expected * math.log(2)))
        return cls(n_bits, k, seed=seed)

    def add(self, values, mask=None) -> "BloomFilter":
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.num_bits)
        on = (jnp.ones(h.shape[1], jnp.bool_) if mask is None
              else jnp.asarray(mask))
        bits = self.bits
        for d in range(self.num_hashes):
            bits = bits.at[h[d].astype(jnp.int64)].max(on)
        return BloomFilter(self.num_bits, self.num_hashes, bits)

    def might_contain(self, values) -> jnp.ndarray:
        """Vectorized membership test for a whole column — this is the
        runtime-join-filter shape (reference: InjectRuntimeFilter)."""
        h = _column_hashes(values, self.seeds) % jnp.uint64(self.num_bits)
        out = jnp.ones(h.shape[1], jnp.bool_)
        for d in range(self.num_hashes):
            out = out & self.bits[h[d].astype(jnp.int64)]
        return out

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        assert (self.num_bits, self.num_hashes) == \
            (other.num_bits, other.num_hashes)
        return BloomFilter(self.num_bits, self.num_hashes,
                           self.bits | other.bits)

"""Session-facing compilation service.

Three policies live here, all keeping XLA off the query critical path:

* **Stage-cache integration** (``build_stage_callable``): when the
  fused/distributed stage caches take a fresh entry, the callable they
  store consults the cross-session executable store first — a hit
  skips trace AND compile; a miss AOT-compiles on first call and
  persists the executable for the next session.
* **Background compile + hot-swap** (``CompileService.execute_plan``):
  with spark.tpu.compile.background on, a plan whose executables are
  not yet ready is served through the chunked tier (small per-chunk
  programs, sub-second compiles) while the fused executable compiles
  on a daemon thread; once ready the next execution atomically swaps
  to the fused path — byte-identical either way. A background failure
  pins the plan to the chunked tier permanently (no swap, no crash).
* **Plan-history pre-warm** (``CompileService.prewarm``): served SQL
  is journaled (plan_history.jsonl); at server start the history is
  replayed most-frequent-first on a bounded worker pool so the plan
  space is traced + compiled before the first client query arrives.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import faults, metrics
from spark_tpu.compile.store import (ExecutableStore,
                                     compiled_call_signature,
                                     stable_plan_fingerprint)


def active_service() -> Optional["CompileService"]:
    """The active session's compile service, or None when disabled —
    callers (planner/executor stage caches) treat None as 'behave
    exactly as before'."""
    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        if sess is None:
            return None
        return sess.compile_service
    except Exception:
        return None


def maybe_service(session) -> Optional["CompileService"]:
    """Build (or reuse) the session's CompileService when any
    ``spark.tpu.compile.*`` feature is enabled; None otherwise. Reused
    across calls unless the store dir changed (tests point one session
    at several tmpdirs)."""
    conf = session.conf
    try:
        root = str(conf.get(CF.COMPILE_STORE_DIR) or "")
        background = bool(conf.get(CF.COMPILE_BACKGROUND))
        hist = str(conf.get(CF.COMPILE_HISTORY_PATH) or "")
    except Exception:
        return None
    if not root and not background and not hist:
        session.__dict__.pop("_compile_service", None)
        return None
    cur = session.__dict__.get("_compile_service")
    if cur is not None and cur.root == root \
            and cur._history_path_cfg == hist:
        return cur
    svc = CompileService(session)
    session.__dict__["_compile_service"] = svc
    return svc


def build_stage_callable(tier: str, plan, trace_fn: Callable, example_args,
                         schema_box: dict, *, mesh_size: int = 1,
                         platform: Optional[str] = None,
                         extra: Any = None) -> Callable:
    """The callable a stage cache stores for a fresh entry.

    Without an active service (or with the store disabled) this is
    exactly the legacy ``jax.jit(trace_fn)`` — zero behavior change.
    With a store it becomes a hybrid: serve a persisted AOT executable
    when one matches, else AOT-compile on first call and persist."""
    jitted = jax.jit(trace_fn)
    svc = active_service()
    if svc is None or svc.store is None:
        return jitted
    try:
        from spark_tpu import trace

        with trace.span("compile.probe", tier=tier):
            return svc.stage_callable(tier, plan, jitted, example_args,
                                      schema_box, mesh_size=mesh_size,
                                      platform=platform, extra=extra)
    except Exception as e:
        metrics.record("compile", phase="stage_callable_error",
                       error=repr(e))
        return jitted


class PlanHistory:
    """Append-only JSONL journal of served plans (fingerprint + SQL when
    the plan came from SQL text), aggregated in memory for
    most-frequent-first replay. Compacted once the file grows past
    ~2x maxEntries lines."""

    def __init__(self, path: str, max_entries: int = 512):
        self.path = path
        self.max_entries = max(1, int(max_entries))
        self._lock = locks.named_lock("compile.history")
        #: fp -> [count, sql-or-None]
        self._counts: Dict[str, List] = {}
        self._lines = 0
        self._load()

    def _load(self) -> None:
        # read outside the lock, apply under it: the counters are
        # lock-guarded state everywhere else, and holding the lock
        # across file IO is exactly what the concurrency linter bans
        try:
            with open(self.path) as f:
                raw = f.readlines()
        except OSError:
            return
        with self._lock:
            for line in raw:
                self._lines += 1
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                fp = rec.get("fp")
                if not fp:
                    continue
                ent = self._counts.setdefault(fp, [0, None])
                ent[0] += int(rec.get("n", 1))
                if rec.get("sql"):
                    ent[1] = rec["sql"]

    def note(self, fp: str, sql: Optional[str] = None) -> None:
        with self._lock:
            ent = self._counts.setdefault(fp, [0, None])
            ent[0] += 1
            if sql:
                ent[1] = sql
            rec = {"fp": fp, "ts": round(time.time(), 2)}
            if sql:
                rec["sql"] = sql
            try:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                self._lines += 1
            except OSError:
                return
            if self._lines > 2 * self.max_entries:
                self._compact_locked()

    def _compact_locked(self) -> None:
        top = sorted(self._counts.items(), key=lambda kv: -kv[1][0])
        top = top[:self.max_entries]
        tmp = f"{self.path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                for fp, (n, sql) in top:
                    rec = {"fp": fp, "n": n}
                    if sql:
                        rec["sql"] = sql
                    f.write(json.dumps(rec) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._counts = {fp: [n, sql] for fp, (n, sql) in top}
        self._lines = len(top)

    def top(self, limit: int) -> List[Tuple[str, Optional[str], int]]:
        """[(fp, sql-or-None, count)] most-frequent-first."""
        with self._lock:
            items = sorted(self._counts.items(), key=lambda kv: -kv[1][0])
        return [(fp, sql, n) for fp, (n, sql) in items[:max(0, limit)]]

    def size(self) -> int:
        with self._lock:
            return len(self._counts)


def _replayable_sql(sql: Optional[str]) -> Optional[str]:
    """Only SELECT-shaped statements are safe to replay at pre-warm
    (CREATE/DROP VIEW would mutate the catalog; INSERT-style side
    effects don't exist here but the allowlist is the right shape)."""
    if not sql:
        return None
    head = sql.lstrip().upper()
    if head.startswith("SELECT") or head.startswith("WITH"):
        return sql
    return None


class CompileService:
    """Per-session compilation policy: executable store, background
    compile + hot-swap routing, served-plan history, pre-warm."""

    def __init__(self, session):
        self._session_ref = weakref.ref(session)
        conf = session.conf
        self.root = str(conf.get(CF.COMPILE_STORE_DIR) or "")
        self._history_path_cfg = str(
            conf.get(CF.COMPILE_HISTORY_PATH) or "")
        self.store: Optional[ExecutableStore] = None
        if self.root:
            self.store = ExecutableStore(
                self.root, int(conf.get(CF.COMPILE_STORE_MAX_BYTES)))
            self._route_jax_cache()
        hist_path = self._history_path_cfg or (
            os.path.join(self.root, "plan_history.jsonl")
            if self.root else "")
        self.history: Optional[PlanHistory] = None
        if hist_path:
            self.history = PlanHistory(
                hist_path, int(conf.get(CF.COMPILE_HISTORY_MAX_ENTRIES)))
        #: routing-key -> {"status": new|compiling|ready|failed,
        #:                 "chunk_serves": int, "swapped": bool, ...}
        self._plans: Dict[Any, dict] = {}
        self._plans_lock = locks.named_lock("compile.plans")
        self._jobs: List[threading.Thread] = []
        self._jobs_lock = locks.named_lock("compile.jobs")
        self._prewarm_report: Optional[dict] = None
        self._stopped = False

    # -- conf plumbing

    def _conf(self):
        sess = self._session_ref()
        return sess.conf if sess is not None else CF.RuntimeConf()

    def _route_jax_cache(self) -> None:
        """Point jax's persistent XLA cache inside the store root so
        the two halves of cross-session persistence (our AOT entries +
        jax's per-computation cache) share one directory and one byte
        bound. SPARK_TPU_JAX_CACHE=0 keeps the tier-1 suite's 'no
        global cache writes' guarantee."""
        if os.environ.get("SPARK_TPU_JAX_CACHE", "").lower() in ("0", "off"):
            return
        try:
            xla_dir = os.path.join(self.root, "xla")
            os.makedirs(xla_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xla_dir)
        except Exception:
            pass

    # -- stage-cache integration ---------------------------------------------

    def stage_callable(self, tier: str, plan, jitted, example_args,
                       schema_box: dict, *, mesh_size: int = 1,
                       platform: Optional[str] = None,
                       extra: Any = None) -> Callable:
        store = self.store
        digest = stable_plan_fingerprint(
            tier, plan, example_args, mesh_size=mesh_size,
            platform=platform, extra=extra)
        entry = store.load(digest, example_args)
        if entry is not None:
            metrics.note_exec_store("hits")
            metrics.record("compile", phase="store_hit", tier=tier,
                           digest=digest)
            schema_box["schema"] = entry["schema"]
            compiled, sig = entry["compiled"], entry["sig"]

            def hit_call(args):
                if compiled_call_signature(args) == sig:
                    return compiled(args)
                return jitted(args)  # shape drift: fall back to jit

            return hit_call

        metrics.note_exec_store("misses")
        state: dict = {}
        state_lock = locks.named_lock("compile.stage")
        serialize = bool(self._conf().get(CF.COMPILE_STORE_SERIALIZE))

        def miss_call(args):
            with state_lock:
                compiled = state.get("compiled")
                failed = state.get("failed")
            if compiled is not None:
                if compiled_call_signature(args) == state["sig"]:
                    return compiled(args)
                return jitted(args)
            if failed:
                return jitted(args)
            with state_lock:
                # re-check under the lock; first thread in compiles
                compiled = state.get("compiled")
                if compiled is None and not state.get("failed"):
                    t0 = time.perf_counter()
                    try:
                        # explicit AOT lower+compile (vs calling the
                        # jit) so the Compiled object is ours to
                        # serialize; tracing fills schema_box
                        compiled = jitted.lower(args).compile()
                        state["sig"] = compiled_call_signature(args)
                        state["compiled"] = compiled
                    except Exception as e:
                        state["failed"] = True
                        metrics.record("compile", phase="aot_failed",
                                       tier=tier, digest=digest,
                                       error=repr(e))
                        return jitted(args)
                    metrics.record(
                        "compile", phase="aot_compile", tier=tier,
                        digest=digest,
                        ms=round((time.perf_counter() - t0) * 1e3, 2))
                    if serialize:
                        store.put(digest, compiled,
                                  schema_box.get("schema"), args)
            if compiled_call_signature(args) == state.get("sig"):
                return state["compiled"](args)
            return jitted(args)

        return miss_call

    # -- background compile + hot-swap ---------------------------------------

    def _routing_key(self, lp) -> Any:
        try:
            return lp.structural_key()
        except Exception:
            return id(lp)

    def execute_plan(self, lp, conf, run_fn):
        """DataFrame._execute's entry point: route one plan execution
        through the background-compile state machine (or straight down
        the recovery ladder when backgrounding is off)."""
        from spark_tpu import recovery

        if not bool(conf.get(CF.COMPILE_BACKGROUND)):
            return recovery.run_plan_with_oom_degradation(lp, conf, run_fn)

        key = self._routing_key(lp)
        with self._plans_lock:
            info = self._plans.setdefault(
                key, {"status": "new", "chunk_serves": 0,
                      "swapped": False, "error": None})
            status = info["status"]

        if status == "ready":
            swap = False
            with self._plans_lock:
                if info["chunk_serves"] and not info["swapped"]:
                    info["swapped"] = True
                    swap = True
            if swap:
                metrics.note_exec_store("swaps")
                metrics.record("compile", phase="swap",
                               chunk_serves=info["chunk_serves"])
            return recovery.run_plan_with_oom_degradation(lp, conf, run_fn)

        # compiling / failed / new: serve through the chunked tier so
        # this request never blocks on the fused XLA compile
        found, shadow = recovery.plan_chunk_first(
            lp, conf, int(conf.get(CF.COMPILE_CHUNK_FIRST_BUDGET)))
        if found is None:
            # plan has no chunkable shape (e.g. in-memory relation):
            # nothing to hide the compile behind — run in the
            # foreground and mark ready so we don't re-probe
            out = recovery.run_plan_with_oom_degradation(lp, conf, run_fn)
            with self._plans_lock:
                if info["status"] not in ("failed",):
                    info["status"] = "ready"
            metrics.record("compile", phase="unchunkable_foreground")
            return out

        spawn = False
        with self._plans_lock:
            if info["status"] == "new":
                info["status"] = "compiling"
                spawn = True
        if spawn:
            # start the fused compile BEFORE serving, so it overlaps
            # the chunked execution below
            self._spawn_background(key, lp, conf, run_fn)
        with self._plans_lock:
            info["chunk_serves"] += 1
            serves = info["chunk_serves"]
        metrics.note_exec_store("background")
        metrics.record("compile", phase="chunk_first_serve",
                       status=info["status"], serve=serves)
        from spark_tpu.physical.chunked import execute_chunked

        try:
            return execute_chunked(found, shadow, run_fn)
        except Exception:
            # the chunked serve itself failed (not a compile problem):
            # fall through to the full recovery ladder
            return recovery.run_plan_with_oom_degradation(lp, conf, run_fn)

    def _spawn_background(self, key, lp, conf, run_fn) -> None:
        def job():
            t0 = time.perf_counter()
            metrics.record("compile", phase="background_start")
            try:
                from spark_tpu import recovery

                faults.inject("compile.background", conf)
                # executing the plan once through the normal path is
                # the compile: it populates the stage caches AND the
                # executable store for this and future sessions
                recovery.run_plan_with_oom_degradation(lp, conf, run_fn)
            except Exception as e:
                with self._plans_lock:
                    self._plans[key]["status"] = "failed"
                    self._plans[key]["error"] = repr(e)
                metrics.note_exec_store("fallbacks")
                metrics.record("compile", phase="background_failed",
                               error=repr(e))
                return
            with self._plans_lock:
                self._plans[key]["status"] = "ready"
            metrics.record(
                "compile", phase="background_done",
                ms=round((time.perf_counter() - t0) * 1e3, 2))

        t = threading.Thread(target=job, name="spark-tpu-bg-compile",
                             daemon=True)
        with self._jobs_lock:
            self._jobs = [j for j in self._jobs if j.is_alive()]
            self._jobs.append(t)
        t.start()

    def wait_background(self, timeout: float = 30.0) -> bool:
        """Join live background-compile jobs (tests + graceful stop);
        True when none remain alive."""
        deadline = time.monotonic() + timeout
        with self._jobs_lock:
            jobs = list(self._jobs)
        for t in jobs:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return not any(t.is_alive() for t in jobs)

    # -- served-plan history + pre-warm --------------------------------------

    def note_served(self, plan, sql: Optional[str] = None) -> None:
        """Journal one served plan (DataFrame._execute calls this for
        every execution; the scheduler passes SQL text through)."""
        if self.history is None:
            return
        sql = _replayable_sql(sql)
        if sql is not None:
            fp = "sql:" + hashlib.sha1(
                " ".join(sql.split()).encode()).hexdigest()[:24]
        else:
            try:
                fp = "plan:" + hashlib.sha1(
                    repr(type(plan).__name__).encode()).hexdigest()[:24]
            except Exception:
                return
        try:
            self.history.note(fp, sql)
        except Exception:
            pass

    def prewarm(self, session=None, block: bool = True,
                budget_s: Optional[float] = None,
                max_queries: Optional[int] = None) -> Optional[dict]:
        """Replay the served-plan history most-frequent-first, bounded
        by time/count budgets, populating the stage caches and the
        executable store. ``block=False`` runs on a daemon thread
        (connect-server start) and returns immediately."""
        session = session or self._session_ref()
        if session is None or self.history is None:
            return None
        if not block:
            t = threading.Thread(
                target=lambda: self.prewarm(session, block=True,
                                            budget_s=budget_s,
                                            max_queries=max_queries),
                name="spark-tpu-prewarm", daemon=True)
            with self._jobs_lock:
                self._jobs.append(t)
            t.start()
            return None
        if metrics.brownout_level() > 0:
            # fleet brownout: pre-warm is exactly the analysis-heavy
            # optional work the fleet sheds FIRST under pressure —
            # skipping it costs warmth, never correctness
            metrics.record("compile", phase="prewarm_brownout_skip",
                           level=metrics.brownout_level())
            return {"replayed": [], "skipped": [], "errors": [],
                    "brownout": True}
        conf = session.conf
        if budget_s is None:
            budget_s = float(conf.get(CF.COMPILE_PREWARM_BUDGET_S))
        if max_queries is None:
            max_queries = int(conf.get(CF.COMPILE_PREWARM_MAX_QUERIES))
        workers = max(1, int(conf.get(CF.COMPILE_PREWARM_WORKERS)))
        entries = self.history.top(max_queries)
        t0 = time.monotonic()
        report: dict = {"replayed": [], "skipped": [], "errors": [],
                        "budget_s": budget_s}
        report_lock = locks.named_lock("compile.prewarm")
        metrics.record("compile", phase="prewarm_start",
                       candidates=len(entries), workers=workers)

        def replay_one(fp: str, sql: str, count: int) -> None:
            q0 = time.perf_counter()
            try:
                session.sql(sql).collect()
            except Exception as e:
                with report_lock:
                    report["errors"].append(
                        {"fp": fp, "sql": sql[:120], "error": repr(e)})
                return
            metrics.note_exec_store("prewarmed")
            with report_lock:
                report["replayed"].append(
                    {"fp": fp, "sql": sql[:120], "count": count,
                     "ms": round((time.perf_counter() - q0) * 1e3, 1)})

        pending = []
        for fp, sql, count in entries:
            sql = _replayable_sql(sql)
            if sql is None:
                report["skipped"].append({"fp": fp, "reason": "no sql"})
                continue
            pending.append((fp, sql, count))

        if workers == 1:
            for fp, sql, count in pending:
                if time.monotonic() - t0 > budget_s:
                    report["skipped"].append(
                        {"fp": fp, "reason": "time budget"})
                    continue
                replay_one(fp, sql, count)
        else:
            idx = [0]
            idx_lock = locks.named_lock("compile.prewarm")

            def worker():
                while True:
                    with idx_lock:
                        if idx[0] >= len(pending):
                            return
                        i = idx[0]
                        idx[0] += 1
                    fp, sql, count = pending[i]
                    if time.monotonic() - t0 > budget_s:
                        with report_lock:
                            report["skipped"].append(
                                {"fp": fp, "reason": "time budget"})
                        continue
                    replay_one(fp, sql, count)

            threads = [threading.Thread(target=worker, daemon=True,
                                        name=f"spark-tpu-prewarm-{i}")
                       for i in range(min(workers, max(1, len(pending))))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        report["wall_s"] = round(time.monotonic() - t0, 2)
        metrics.record("compile", phase="prewarm_done",
                       replayed=len(report["replayed"]),
                       errors=len(report["errors"]),
                       skipped=len(report["skipped"]),
                       wall_s=report["wall_s"])
        self._prewarm_report = report
        return report

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        with self._plans_lock:
            by_status: Dict[str, int] = {}
            for info in self._plans.values():
                by_status[info["status"]] = \
                    by_status.get(info["status"], 0) + 1
            plans = len(self._plans)
        with self._jobs_lock:
            alive = sum(1 for t in self._jobs if t.is_alive())
        try:
            from spark_tpu.scheduler import admission

            measured = admission.measured_snapshot()
        except Exception:
            measured = None
        return {
            "admission_measured": measured,
            "store": self.store.stats() if self.store else None,
            "exec_store": metrics.exec_store_stats(),
            "background": {"plans": plans, "by_status": by_status,
                           "jobs_alive": alive},
            "history": {"path": self.history.path,
                        "entries": self.history.size()}
            if self.history else None,
            "prewarm": self._prewarm_report,
        }

    def stop(self, timeout: float = 5.0) -> None:
        self._stopped = True
        self.wait_background(timeout=timeout)

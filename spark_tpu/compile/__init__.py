"""AOT compilation service: cross-session executable cache, plan-history
pre-warm, and background compile with hot-swap (ROADMAP item 1).

Flare (PAPERS.md, arxiv 1703.08219) is the design reference: compiled
whole-query executables are the *product* — persisted, keyed, and
served — not a side-effect of the jit cache. ``store`` owns the
on-disk executable store (stable plan fingerprints + serialized XLA
executables); ``service`` owns the session-facing policy (stage-cache
integration, background compile + hot-swap routing, served-plan
history, pre-warm).
"""

from spark_tpu.compile.service import (CompileService, active_service,
                                       build_stage_callable, maybe_service)
from spark_tpu.compile.store import ExecutableStore, stable_plan_fingerprint

__all__ = [
    "CompileService",
    "ExecutableStore",
    "active_service",
    "build_stage_callable",
    "maybe_service",
    "stable_plan_fingerprint",
]

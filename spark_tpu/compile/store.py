"""Cross-session executable store.

The jit stage caches (physical/planner._STAGE_CACHE,
parallel/executor._DIST_STAGE_CACHE) die with the process; every fresh
session pays the full XLA compile again — 12-55 s of warmup against a
~100 ms steady state. This store persists the compiled stage
executables themselves, keyed by a *cross-process-stable* plan
fingerprint plus a capacity/mesh/device-kind environment fingerprint,
so a worker restart loads AOT artifacts instead of compiling
(jax.experimental.serialize_executable round-trips a
``jax.stages.Compiled``; the reference analogue is reusing
Janino-compiled classes, CodeGenerator.scala:1442 — taken across
processes, the Flare move of treating the executable as the product).

Why not reuse ``plan_key()`` directly: it embeds ``hash(dicts)`` for
dictionary-encoded string columns, and Python string hashes are salted
per process — fine for the in-process LRU, useless on disk. The walker
here mirrors plan_key's structure but digests dictionary *contents*
(memoized per schema — the digest is only computed on the store path,
never on the per-query hot path).

Corruption policy: any failure to read/unpickle/deserialize an entry is
a cache miss AND evicts the file — a poisoned entry must not wedge
every future session (the jax persistent cache had exactly this bug;
see api/session._harden_cache_writes).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import threading
import weakref
from typing import Any, Optional, Tuple

import jax

from spark_tpu import locks
from spark_tpu import metrics

_ENTRY_SUFFIX = ".exe"

#: process-global map (store_root, digest) -> loaded entry dict, so a
#: second Session over the same store dir in one process skips even the
#: disk read/deserialize. Tests clear it to force the disk path.
_LOADED: dict = {}
_LOADED_LOCK = locks.named_lock("compile.loaded")


# ---- stable plan fingerprint ------------------------------------------------

#: schema -> dictionary-contents digest, memoized per schema object:
#: TPC-H comment columns carry multi-million-entry dictionaries and the
#: digest must not be recomputed per lookup
_DICT_FP: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_DICT_FP_LOCK = locks.named_lock("compile.dict_fp")


def _dict_digest(schema) -> str:
    with _DICT_FP_LOCK:
        fp = _DICT_FP.get(schema)
    if fp is not None:
        return fp
    h = hashlib.sha1()
    for f in schema.fields:
        h.update(b"\x00")
        d = getattr(f, "dictionary", None)
        if d:
            for s in d:
                h.update(str(s).encode("utf-8", "replace"))
                h.update(b"\x01")
    fp = h.hexdigest()[:16]
    try:
        with _DICT_FP_LOCK:
            _DICT_FP[schema] = fp
    except TypeError:
        pass  # unweakrefable schema type: recompute next time
    return fp


def _leaf_key(plan) -> Optional[tuple]:
    """Stable identity for the two leaf scan node types (the unstable
    ``hash(dicts)`` component of their plan_key is replaced by a
    content digest)."""
    batch = getattr(plan, "batch", None)
    if batch is not None and hasattr(batch, "schema") \
            and hasattr(batch, "capacity"):
        sch = batch.schema
        return ("BatchScan", int(batch.capacity),
                tuple((f.name, repr(f.dtype)) for f in sch.fields),
                _dict_digest(sch))
    sharded = getattr(plan, "sharded", None)
    if sharded is not None:
        sch = sharded.schema
        return ("ShardScan", int(sharded.per_device_capacity),
                tuple((f.name, repr(f.dtype)) for f in sch.fields),
                _dict_digest(sch))
    return None


def _canon(v) -> Any:
    """Deterministic, repr-able canonical form of a plan-key component.
    Unknown objects collapse to their type name — that can only *widen*
    a key into a false miss, never alias two different plans that the
    structural components distinguish."""
    from spark_tpu.expr import expressions as E
    from spark_tpu.physical import operators as P

    if isinstance(v, P.PhysicalPlan):
        return stable_plan_key(v)
    if isinstance(v, E.Expression):
        return _canon(E.expr_key(v))
    if isinstance(v, (tuple, list)):
        return tuple(_canon(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((repr(k), _canon(x)) for k, x in v.items()))
    if v is None or isinstance(v, (str, bytes, bool, int, float)):
        return repr(v)
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return repr(item())  # numpy scalars
        except Exception:
            pass
    return f"<{type(v).__name__}>"


def stable_plan_key(plan) -> tuple:
    """Cross-process-stable structural key of a physical plan: mirrors
    ``plan_key()`` (type + field values + children) with content
    digests at data leaves."""
    lk = _leaf_key(plan)
    if lk is not None:
        return lk
    parts: list = [type(plan).__name__]
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            parts.append(_canon(getattr(plan, f.name)))
    else:
        parts.append(_canon(getattr(plan, "plan_key", lambda: repr(plan))()))
    return tuple(parts)


def _args_signature(args) -> tuple:
    """Treedef + leaf avals of the stage arguments — part of the store
    key (a deserialized executable is shape- and structure-specialized;
    same plan with different validity layout must be a different
    entry)."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (repr(treedef),
            tuple((tuple(getattr(leaf, "shape", ())),
                   str(getattr(leaf, "dtype", type(leaf).__name__)))
                  for leaf in leaves))


def environment_fingerprint(mesh_size: int = 1,
                            platform: Optional[str] = None) -> tuple:
    """Capacity lives in the plan key (leaf capacities); this adds the
    mesh/device-kind half: device kind + count, backend platform, jax
    version, and x64 mode (an AOT executable is specialized to all of
    them)."""
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "unknown")
        plat = platform or dev.platform
    except Exception:
        kind, plat = "unknown", platform or "unknown"
    return (plat, kind, int(mesh_size), jax.__version__,
            bool(jax.config.jax_enable_x64))


def stable_plan_fingerprint(tier: str, plan, args, *, mesh_size: int = 1,
                            platform: Optional[str] = None,
                            extra: Any = None) -> str:
    """Hex digest identifying one stage executable across sessions and
    processes: stable plan structure + argument avals + environment.

    ``extra`` carries tier-specific compilation parameters that live
    outside the plan tree: the ``fused_span`` tier (whole-query
    fusion) passes one ``("ladder", bucket, variants)`` tuple per
    fused span, so executables whose lax.switch branch set differs —
    a changed ``spark.tpu.adaptive.capacityBucket`` or
    ``spark.tpu.fusion.maxBucketVariants`` — never replay each
    other's binaries, while prewarm replays exact matches."""
    payload = (tier, stable_plan_key(plan), _args_signature(args),
               environment_fingerprint(mesh_size, platform),
               _canon(extra))
    return hashlib.sha256(repr(payload).encode()).hexdigest()[:32]


# ---- the store --------------------------------------------------------------


class ExecutableStore:
    """Disk-backed executable cache with a byte bound and LRU eviction.

    Layout under ``root``::

        entries/<digest>.exe   pickled {payload, in_tree, out_tree,
                               schema, sig} — payload is the serialized
                               XLA executable
        xla/                   jax's persistent compilation cache when
                               the session routes it here (managed by
                               jax; counted against the same byte bound)
        plan_history.jsonl     served-plan history (service owns it)

    Writes are atomic (temp + rename); loads treat ANY failure as a
    miss and evict the entry. Eviction order is file mtime — hits touch
    their entry, so mtime is last-use."""

    def __init__(self, root: str, max_bytes: int = 1 << 30):
        self.root = os.path.abspath(root)
        self.entries_dir = os.path.join(self.root, "entries")
        self.max_bytes = int(max_bytes)
        os.makedirs(self.entries_dir, exist_ok=True)
        self._lock = locks.named_lock("compile.store")

    # -- paths

    def _entry_path(self, digest: str) -> str:
        return os.path.join(self.entries_dir, digest + _ENTRY_SUFFIX)

    # -- read side

    def load(self, digest: str, args) -> Optional[dict]:
        """Return {"compiled", "schema", "sig"} for a stored executable
        whose argument signature matches ``args``, or None. Corrupt or
        mismatched-structure entries are evicted as misses."""
        with _LOADED_LOCK:
            cached = _LOADED.get((self.root, digest))
        if cached is not None:
            return cached
        path = self._entry_path(digest)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                entry = pickle.loads(f.read())
            if entry.get("sig") != _args_signature(args):
                raise ValueError("argument signature mismatch")
            from jax.experimental import serialize_executable as _se

            compiled = _se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception as e:
            # treat as a miss AND evict: a poisoned entry must not
            # wedge every future session
            metrics.note_exec_store("corrupt")
            metrics.record("compile", phase="corrupt_entry",
                           digest=digest, error=repr(e))
            self._remove(path)
            return None
        out = {"compiled": compiled, "schema": entry["schema"],
               "sig": entry["sig"]}
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        with _LOADED_LOCK:
            _LOADED[(self.root, digest)] = out
        return out

    # -- write side

    def put(self, digest: str, compiled, schema, args) -> bool:
        """Serialize ``compiled`` to disk (atomic); False when the
        platform refuses to serialize (entry stays process-local)."""
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps({
                "payload": payload, "in_tree": in_tree,
                "out_tree": out_tree, "schema": schema,
                "sig": _args_signature(args),
            }, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            metrics.record("compile", phase="serialize_failed",
                           digest=digest, error=repr(e))
            return False
        path = self._entry_path(digest)
        tmp = f"{path}.tmp{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError as e:
            metrics.record("compile", phase="put_failed",
                           digest=digest, error=repr(e))
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        with _LOADED_LOCK:
            _LOADED[(self.root, digest)] = {
                "compiled": compiled, "schema": schema,
                "sig": _args_signature(args)}
        metrics.note_exec_store("puts")
        self.enforce_budget()
        return True

    def _remove(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- size bound

    def _walk_files(self):
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                if name.endswith(".jsonl") or ".tmp" in name:
                    continue  # history + in-flight writes are exempt
                p = os.path.join(dirpath, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                yield p, st.st_size, st.st_mtime

    def total_bytes(self) -> int:
        return sum(size for _p, size, _m in self._walk_files())

    def entry_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.entries_dir)
                       if n.endswith(_ENTRY_SUFFIX))
        except OSError:
            return 0

    def enforce_budget(self) -> int:
        """Evict least-recently-used files (ours AND the managed jax
        cache subdir) until the store fits max_bytes; returns evicted
        count. Serialized under a lock — concurrent enforcement would
        double-delete."""
        with self._lock:
            files = sorted(self._walk_files(), key=lambda t: t[2])
            total = sum(size for _p, size, _m in files)
            evicted = 0
            while total > self.max_bytes and files:
                path, size, _mtime = files.pop(0)
                self._remove(path)
                total -= size
                evicted += 1
                digest = os.path.basename(path)[:-len(_ENTRY_SUFFIX)] \
                    if path.endswith(_ENTRY_SUFFIX) else None
                if digest is not None:
                    with _LOADED_LOCK:
                        _LOADED.pop((self.root, digest), None)
        if evicted:
            metrics.note_exec_store("evictions", evicted)
            metrics.record("compile", phase="evict", count=evicted,
                           bytes_after=total)
        metrics.set_gauge("compile.store.bytes", total)
        metrics.set_gauge("compile.store.entries", self.entry_count())
        return evicted

    def stats(self) -> dict:
        return {
            "root": self.root,
            "entries": self.entry_count(),
            "bytes": self.total_bytes(),
            "max_bytes": self.max_bytes,
            "loaded_in_process": sum(
                1 for (root, _d) in _LOADED if root == self.root),
        }


def clear_process_cache() -> None:
    """Drop the in-process loaded-executable registry (tests use this
    to force the disk deserialize path, simulating a fresh process)."""
    with _LOADED_LOCK:
        _LOADED.clear()


def compiled_call_signature(args) -> Tuple[Any, ...]:
    """Public alias used by the service's hybrid callable to cheaply
    check per-call argument compatibility with a Compiled."""
    return _args_signature(args)

"""Typed configuration registry.

Analogue of the reference's ConfigEntry system (reference:
core/src/main/scala/org/apache/spark/internal/config/ConfigEntry.scala:74
and sql/catalyst/.../internal/SQLConf.scala:56) — typed entries with
defaults, docs, and session-local overrides — minus the JVM machinery.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass(frozen=True)
class ConfigEntry:
    key: str
    default: Any
    doc: str
    value_type: Callable[[Any], Any] = lambda x: x


_REGISTRY: Dict[str, ConfigEntry] = {}

#: registered free-form key prefixes (per-pool scheduler keys etc.):
#: prefix -> doc. A key matching a registered prefix is considered
#: declared even though each concrete suffix is user-chosen.
_PREFIXES: Dict[str, str] = {}


def register(key: str, default: Any, doc: str,
             value_type: Callable[[Any], Any] = lambda x: x) -> ConfigEntry:
    entry = ConfigEntry(key, default, doc, value_type)
    _REGISTRY[key] = entry
    return entry


def register_prefix(prefix: str, doc: str) -> str:
    """Declare a free-form key family (e.g. per-pool scheduler keys,
    scanned by prefix). Returns the prefix so callers can keep using it
    as a plain string constant."""
    _PREFIXES[prefix] = doc
    return prefix


def is_registered(key: str) -> bool:
    """True when ``key`` is a declared ConfigEntry or matches a
    registered free-form prefix (the invariant tools/lint_invariants.py
    enforces for every literal conf key in the tree)."""
    return key in _REGISTRY or any(key.startswith(p) for p in _PREFIXES)


# ---- core entries ----------------------------------------------------------

SHUFFLE_PARTITIONS = register(
    "spark.sql.shuffle.partitions", 0,
    "Number of partitions for exchanges; 0 = one per mesh device "
    "(reference default 200: SQLConf.scala:614).", int)

BATCH_CAPACITY_MULTIPLE = register(
    "spark.tpu.batch.capacityMultiple", 1024,
    "Row capacities are rounded up to a multiple of this so jit caches "
    "hit across similar-sized inputs.", int)

BROADCAST_THRESHOLD = register(
    "spark.sql.autoBroadcastJoinThreshold", 8 * 1024 * 1024,
    "Max estimated build-side bytes for broadcast hash join "
    "(reference: SQLConf AUTO_BROADCASTJOIN_THRESHOLD).", int)

SKEW_FACTOR = register(
    "spark.tpu.skewJoin.factor", 5,
    "A distributed join whose hottest device counts more than this "
    "many times the median device's pairs after the hash exchange is "
    "re-planned as a broadcast join over the balanced pre-exchange "
    "distribution (reference: adaptive/OptimizeSkewedJoin.scala:37 "
    "SKEW_JOIN_SKEWED_PARTITION_FACTOR; under SPMD static shapes one "
    "hot device would size EVERY device's pair capacity).", int)

SKEW_MIN_PAIRS = register(
    "spark.tpu.skewJoin.minPairs", 1 << 16,
    "Absolute floor for skew demotion: the hottest device must exceed "
    "this many pairs (the factor alone misfires when most devices have "
    "ZERO pairs, e.g. fewer distinct keys than devices — reference "
    "pairs its factor with SKEW_JOIN_SKEWED_PARTITION_THRESHOLD for "
    "the same reason).", int)

SKEW_MAX_BROADCAST_BYTES = register(
    "spark.tpu.skewJoin.maxBroadcastBytes", 256 * 1024 * 1024,
    "Skew demotion replicates the build side onto every device; skip "
    "it when the build side exceeds this (skew stays slow rather than "
    "risking HBM exhaustion).", int)

CASE_SENSITIVE = register(
    "spark.sql.caseSensitive", False,
    "Whether identifiers are case sensitive (reference: SQLConf.scala).", bool)

REPARTITION_SLACK = register(
    "spark.tpu.exchange.slackFactor", 4,
    "Per-destination capacity slack factor for hash repartition "
    "(all_to_all requires static per-pair sizes).", int)

WAREHOUSE_DIR = register(
    "spark.sql.warehouse.dir", "spark-warehouse",
    "Directory for persistent (saveAsTable) tables (reference: "
    "StaticSQLConf WAREHOUSE_PATH).", str)

CBO_JOIN_REORDER = register(
    "spark.sql.cbo.joinReorder.enabled", True,
    "Reorder maximal inner equi-join clusters greedily by estimated "
    "cardinality (reference: CostBasedJoinReorder.scala:1; here driven "
    "by batch capacities and Parquet metadata, not ANALYZE stats).", bool)

EVENT_LOG_DIR = register(
    "spark.eventLog.dir", "",
    "When set, per-stage execution events are appended as JSONL under "
    "this directory (reference: EventLoggingListener.scala:48).", str)

PIPELINE_DEPTH = register(
    "spark.tpu.pipelineDepth", 2,
    "Out-of-HBM chunk pipeline depth: how many prepared chunks the "
    "background producer (parquet decode + host key filter + "
    "host->device transfer) may run ahead of device compute. 0 runs "
    "the fully serial decode->filter->ship->compute loop; >=1 "
    "overlaps the stages (the ShuffleBlockFetcherIterator in-flight "
    "window, applied to the host->device tunnel). Results are "
    "byte-identical at every depth: chunks are consumed in source "
    "order, so the device merge order never changes.", int)

PREFETCH_BYTES_MAX = register(
    "spark.tpu.prefetchBytesMax", 1 << 30,
    "Byte cap on prepared-but-unconsumed pipeline chunks (device bytes "
    "of in-flight prefetch). The producer stalls once in-flight bytes "
    "reach this, whatever the pipeline depth, so prefetch can never "
    "blow host RAM or HBM. At least one chunk is always admitted "
    "(no deadlock on a budget smaller than a single chunk).", int)

# ---- multi-tenant query scheduler (spark_tpu/scheduler/) -------------------

SCHEDULER_MODE = register(
    "spark.scheduler.mode", "FIFO",
    "Query scheduling policy across pools: FIFO (global submit order) "
    "or FAIR (weighted-fair device time across pools; reference: "
    "TaskSchedulerImpl.scala + Pool.scala spark.scheduler.mode).", str)

SCHEDULER_MAX_CONCURRENCY = register(
    "spark.tpu.scheduler.maxConcurrency", 4,
    "Scheduler worker threads: how many queries may run their "
    "host-side stages (parse, optimize, parquet decode) concurrently. "
    "Device execution is additionally gated by HBM admission control.",
    int)

SCHEDULER_QUEUE_DEPTH = register(
    "spark.tpu.scheduler.queueDepth", 64,
    "Bound on queued (not yet dequeued) queries across all pools; a "
    "submit at full queue is rejected immediately (the connect server "
    "answers 429 with Retry-After) instead of growing an unbounded "
    "backlog.", int)

SCHEDULER_HBM_BUDGET = register(
    "spark.tpu.scheduler.hbmBudgetBytes", 2 << 30,
    "Shared device-bytes budget for HBM admission control: a query is "
    "admitted to device execution only while the sum of admitted "
    "queries' estimated footprints fits. A single over-budget query "
    "still admits alone (charged the full budget) and relies on the "
    "chunked/OOM-degradation ladder.", int)

SCHEDULER_RETRY_AFTER = register(
    "spark.tpu.scheduler.retryAfterSeconds", 1.0,
    "Retry-After hint (seconds) returned with a 429 rejection when "
    "the scheduler queue is full.", float)

SCHEDULER_DEFAULT_POOL = register(
    "spark.tpu.scheduler.defaultPool", "default",
    "Pool a query lands in when the submit carries no pool name "
    "(reference: spark.scheduler.pool defaulting).", str)

#: free-form per-pool keys (scanned by prefix):
#:   spark.tpu.scheduler.pool.<name>.weight    (int, default 1)
#:   spark.tpu.scheduler.pool.<name>.minShare  (int, default 0)
SCHEDULER_POOL_PREFIX = register_prefix(
    "spark.tpu.scheduler.pool.",
    "Per-pool FAIR scheduling keys: "
    "spark.tpu.scheduler.pool.<name>.{weight,minShare}.")

# ---- HBM-resident columnar storage (spark_tpu/storage/) --------------------

STORAGE_MAX_BYTES = register(
    "spark.tpu.storage.maxBytes", 1 << 30,
    "Cap on the storage region of the unified HBM budget: total device "
    "bytes the MemoryStore may hold in cached columnar batches. The "
    "effective cap is min(this, hbmBudgetBytes - execution grants) — "
    "storage and execution share spark.tpu.scheduler.hbmBudgetBytes "
    "(reference: spark.memory.fraction / UnifiedMemoryManager).", int)

STORAGE_MIN_BYTES = register(
    "spark.tpu.storage.minBytes", 64 * 1024 * 1024,
    "Protected storage region: execution admission may evict unpinned "
    "cached batches to make room, but never below this many bytes "
    "(reference: spark.memory.storageFraction — the floor storage is "
    "guaranteed against eviction by execution).", int)

STORAGE_AUTOCACHE_THRESHOLD = register(
    "spark.tpu.storage.autoCacheThreshold", 2,
    "Auto-cache hot scans: a (source, columns, filters) scan that has "
    "been materialized this many times in the session is promoted into "
    "the HBM-resident MemoryStore (byte-accounted, LRU-evictable), so "
    "repeat queries skip parquet decode + dictionary encode + "
    "host->device transfer entirely. 0 disables auto-caching; explicit "
    "df.cache() is unaffected.", int)

JIT_STAGE_CACHE_ENTRIES = register(
    "spark.tpu.jit.stageCacheEntries", 512,
    "Entry cap for the fused-stage jit caches (single-device "
    "physical/planner._STAGE_CACHE and distributed "
    "parallel/executor._DIST_STAGE_CACHE). Compiled stage programs "
    "beyond the cap are dropped LRU — an evicted plan recompiles on "
    "next use. Live sizes are published as metrics gauges "
    "jit_cache.<fused|dist>.entries.", int)

# ---- adaptive query execution over the mesh (AQE) --------------------------

ADAPTIVE_ENABLED = register(
    "spark.tpu.adaptive.enabled", False,
    "Adaptive query execution over the ICI mesh (reference: "
    "spark.sql.adaptive.enabled / AdaptiveSparkPlanExec.scala:98): split "
    "the fused SPMD program at exchange boundaries, measure per-device "
    "live counts with one psum/pmax stats stage, then re-trace the "
    "consumer at a compacted bucket-rounded capacity, switch measured-"
    "small join builds to broadcast, and fan skewed destinations over "
    "the partial->final aggregate merge. Results are byte-identical on "
    "or off; the OOM-degradation ladder also retries a failed run with "
    "this forced on before falling back to chunking.", bool)

ADAPTIVE_BROADCAST_THRESHOLD = register(
    "spark.tpu.adaptive.autoBroadcastJoinThreshold", 8 * 1024 * 1024,
    "Max MEASURED build-side bytes (live rows x row width, counted on "
    "device, not the static capacity estimate) for runtime broadcast-"
    "join switching when adaptive execution is on (reference: "
    "DynamicJoinSelection.scala:40 over MapOutputStatistics).", int)

ADAPTIVE_CAPACITY_BUCKET = register(
    "spark.tpu.adaptive.capacityBucket", 1024,
    "Post-exchange capacities are the measured pmax live count rounded "
    "UP to a multiple of this, so adaptive re-traces of the consumer "
    "stage land on a small set of capacities and hit the jit stage "
    "cache instead of recompiling per exact row count (reference "
    "analogue: spark.sql.adaptive.coalescePartitions.*).", int)

ADAPTIVE_SKEW_FACTOR = register(
    "spark.tpu.adaptive.skewedPartitionFactor", 4,
    "A hash-exchange destination whose measured incoming live count "
    "exceeds this many times the median destination's is skewed: its "
    "rows stay on their source device (a local-shuffle-reader fan), get "
    "pre-merged by the partial aggregate, and only the merged groups "
    "re-exchange (reference: OptimizeSkewedJoin.scala "
    "SKEW_JOIN_SKEWED_PARTITION_FACTOR). Only taken when every "
    "aggregate merge is exactly re-applicable (int sum/count/min/max), "
    "so results stay byte-identical.", int)

ADAPTIVE_SKEW_MIN_ROWS = register(
    "spark.tpu.adaptive.skewMinRows", 4096,
    "Absolute floor for the skew fan: the hottest destination must "
    "expect at least this many incoming rows (the factor alone "
    "misfires on tiny exchanges where one extra row looks like 'skew' "
    "— same reason the reference pairs its factor with "
    "SKEW_JOIN_SKEWED_PARTITION_THRESHOLD).", int)

ADAPTIVE_AGG_ENABLED = register(
    "spark.tpu.adaptive.agg.enabled", True,
    "Runtime-adaptive aggregation strategy switching (only active when "
    "spark.tpu.adaptive.enabled is also on): the exchange stats stage "
    "additionally sketches the distinct group-key count (HLL-style "
    "register maxima, one extra pmax fetch) and the executor picks "
    "between the static partial->final path, partial-bypass (NDV ~ "
    "rows: skip the useless pre-aggregation, exchange raw rows by "
    "key), and a hash-partial over runtime-measured packed key codes. "
    "Results are byte-identical across strategies; aggregates whose "
    "partials are order-dependent (float Sum/Min/Max) are pinned to "
    "partial->final (see analysis PLAN-AGG-STRATEGY).", bool)

ADAPTIVE_AGG_STRATEGY = register(
    "spark.tpu.adaptive.agg.strategy", "auto",
    "Aggregation strategy override: 'auto' decides from the runtime "
    "sketch; 'partial', 'bypass', 'hash', 'sort', or 'presplit' force "
    "one strategy (an illegal or unexecutable forced choice falls "
    "back to 'partial' so results stay byte-identical). Test/debug "
    "knob.", str)

ADAPTIVE_AGG_BYPASS_NDV_RATIO = register(
    "spark.tpu.adaptive.agg.bypassNdvRatio", 0.5,
    "Partial-bypass threshold: when the sketched distinct-key estimate "
    "is at least this fraction of the live row count, pre-aggregation "
    "cannot shrink the exchange enough to pay for itself (the "
    "all-distinct pathology of 'Partial Partial Aggregates'), so raw "
    "rows exchange straight to the final aggregate.", float)

ADAPTIVE_AGG_HASH_DOMAIN_LIMIT = register(
    "spark.tpu.adaptive.agg.hashDomainLimit", 1024,
    "Max packed key-code domain (product of measured per-key value "
    "ranges, nulls included) for the hash-partial strategy: the dense "
    "segment accumulator must fit the measured selection table (<= 64 "
    "XLA fused, 64 < K <= 1024 Pallas one-pass; see ops/pallas_agg.py)."
    " Beyond it the sort-based partial wins.", int)

ADAPTIVE_AGG_SKETCH_REGISTERS = register(
    "spark.tpu.adaptive.agg.sketchRegisters", 512,
    "HyperLogLog-style register count for the group-key distinct "
    "sketch in the exchange stats stage (power of two). 512 registers "
    "give ~5% relative error — plenty to separate 'NDV ~ rows' from "
    "'NDV << rows' — and ride the existing stats fetch as one extra "
    "O(registers) int vector.", int)

ADAPTIVE_AGG_SORT_DOMAIN_WIDTH = register(
    "spark.tpu.adaptive.agg.sortDomainWidth", 1 << 20,
    "Sort/hash crossover: a high-NDV grouping (NDV ratio past "
    "bypassNdvRatio) whose measured packed key-code domain exceeds "
    "this width takes the SORT rung — raw rows range-partition by the "
    "leading group key (the stable routing sort inside the tiled "
    "all_to_all doubles as the coarse key sort) and the final "
    "segmented-scan merge emits key-ordered output, which a matching "
    "downstream global sort then skips entirely. Below it the "
    "hash-exchange bypass keeps cheaper routing ('Hash-Based vs. "
    "Sort-Based Group-By-Aggregate', arXiv 2411.13245: sort-merge "
    "grouping wins at high NDV x large key domains, and ordered "
    "output is free).", int)

ADAPTIVE_AGG_PRESPLIT_FACTOR = register(
    "spark.tpu.adaptive.agg.presplitFactor", 4,
    "Hot-KEY pre-split threshold: a group key whose Count-Min "
    "estimated row count exceeds this multiple of the fair per-device "
    "share (rows / D) is salted across ALL devices BEFORE the "
    "exchange — the partial accumulators re-merge exactly through the "
    "ordinary partial->final path — instead of letting one "
    "destination absorb the whole key and fanning it afterwards "
    "(contrast: spark.tpu.adaptive.skewedPartitionFactor reacts to hot "
    "DESTINATIONS after routing).", int)

ADAPTIVE_AGG_PRESPLIT_MIN_ROWS = register(
    "spark.tpu.adaptive.agg.presplitMinRows", 4096,
    "Absolute floor for the hot-key pre-split: the hottest key's "
    "Count-Min estimate must reach this many rows (the factor alone "
    "misfires on tiny inputs — same pairing the skew fan and the "
    "reference's SKEW_JOIN_SKEWED_PARTITION_THRESHOLD use).", int)

ADAPTIVE_AGG_CM_DEPTH = register(
    "spark.tpu.adaptive.agg.cmDepth", 4,
    "Count-Min sketch depth (independent hash rows) for the heavy-"
    "hitter estimate in the exchange stats stage. The estimate is the "
    "min over rows, so it never under-counts; depth d bounds the "
    "over-count tail at ~(1/2)^d confidence per the standard CM "
    "analysis (reference shape: common/sketch CountMinSketch.java).",
    int)

ADAPTIVE_AGG_CM_WIDTH = register(
    "spark.tpu.adaptive.agg.cmWidth", 1024,
    "Count-Min sketch width (counters per row, power of two). "
    "Over-count per estimate is bounded by rows/width in expectation; "
    "1024 counters resolve a >=4096-row hot key in a 120k-row "
    "exchange with slack. Rides the existing stats fetch as depth "
    "extra O(width) int vectors, psum-merged across the mesh.", int)

# ---- whole-query native fusion ---------------------------------------------

FUSION_ENABLED = register(
    "spark.tpu.fusion.enabled", False,
    "Whole-query native fusion (only active when "
    "spark.tpu.adaptive.enabled is also on): adaptive exchange + "
    "consumer pairs whose ONLY host dependency is the stats fetch "
    "(capacity compaction) compile into ONE XLA program — the psum/"
    "pmax stats stay on device and a lax.switch over a precompiled "
    "capacity-bucket ladder replaces the host round-trip, so a multi-"
    "exchange plan runs end-to-end with zero inter-stage host sync "
    "(the Flare thesis, arXiv 1703.08219, XLA-native). Decisions that "
    "genuinely need the host — broadcast-join switching on measured "
    "bytes, skew fan/pre-split, the agg strategy crossover, sort "
    "elision, the OOM ladder — bail out to staged execution with a "
    "typed fusion_bailout event. Results are byte-identical on or "
    "off.", bool)

FUSION_MAX_BUCKET_VARIANTS = register(
    "spark.tpu.fusion.maxBucketVariants", 4,
    "Number of capacity-ladder branches baked into one fused program: "
    "consumer capacities start at spark.tpu.adaptive.capacityBucket "
    "and grow geometrically (x4) up to the static worst case, at most "
    "this many rungs (the last rung is always the worst case, so any "
    "measured count is covered). More variants track the staged "
    "path's measured capacity tighter; fewer keep the fused program "
    "small. Part of the compile-store fingerprint — changing it "
    "recompiles fused spans.", int)

SEARCHSORTED_SORT_THRESHOLD = register(
    "spark.tpu.kernels.searchsortedSortThreshold", 50,
    "physical/kernels.searchsorted picks XLA's O((n+m)log(n+m)) "
    "method='sort' over the O(n*log m) per-row scan when the queries "
    "are large (>= 4096) AND queries*THIS > haystack size; raise it to "
    "prefer sort (wide all-to-all style lookups), lower it toward 0 to "
    "prefer scan (few queries against huge sorted runs).", int)

# ---- AOT compilation service (spark_tpu/compile/) --------------------------

COMPILE_STORE_DIR = register(
    "spark.tpu.compile.store.dir", "",
    "Root directory of the cross-session executable store: serialized "
    "AOT stage executables (entries/) plus jax's persistent XLA cache "
    "(xla/) live here, keyed by a stable plan fingerprint + "
    "capacity/mesh/device-kind, so a fresh session or worker restart "
    "skips XLA entirely. Empty disables cross-session persistence "
    "(the in-process jit stage caches still apply).", str)

COMPILE_STORE_MAX_BYTES = register(
    "spark.tpu.compile.store.maxBytes", 1 << 30,
    "Size bound for the executable store directory (AOT entries + the "
    "managed jax persistent-cache subdir); beyond it the least-"
    "recently-used entry files are evicted.", int)

COMPILE_STORE_SERIALIZE = register(
    "spark.tpu.compile.store.serialize", True,
    "Persist freshly compiled stage executables to the store via "
    "jax.experimental.serialize_executable. Off = lookups only (useful "
    "on hosts where XLA executable serialization is unreliable).", bool)

COMPILE_BACKGROUND = register(
    "spark.tpu.compile.background", False,
    "On an executable-cache miss, admit the query anyway: serve the "
    "first request(s) through the chunked tier while the fused "
    "executable compiles on a background thread, then atomically swap "
    "it in for subsequent execution — byte-identical either way. A "
    "background-compile failure pins the plan to the chunked tier "
    "permanently (no swap, no crash).", bool)

COMPILE_CHUNK_FIRST_BUDGET = register(
    "spark.tpu.compile.chunkFirst.budgetBytes", 32 << 20,
    "Shadow spark.tpu.maxDeviceBatchBytes used to force the chunked "
    "tier while the fused executable compiles in the background (the "
    "chunked tier's small per-chunk programs compile in a fraction of "
    "the fused program's time).", int)

COMPILE_HISTORY_PATH = register(
    "spark.tpu.compile.history.path", "",
    "Served-plan history file (JSONL of executed SQL + plan "
    "fingerprints) replayed by the pre-warm pass. Empty defaults to "
    "<store.dir>/plan_history.jsonl when the store is enabled.", str)

COMPILE_HISTORY_MAX_ENTRIES = register(
    "spark.tpu.compile.history.maxEntries", 512,
    "Distinct plans kept in the served-plan history (the file is "
    "compacted beyond roughly twice this many lines).", int)

COMPILE_PREWARM_ENABLED = register(
    "spark.tpu.compile.prewarm.enabled", True,
    "Replay the served-plan history at connect-server start on a "
    "background worker, most-frequent-first, pre-tracing and "
    "pre-compiling stage executables before the first client query "
    "arrives.", bool)

COMPILE_PREWARM_BUDGET_S = register(
    "spark.tpu.compile.prewarm.budgetSeconds", 120.0,
    "Wall-clock budget for the pre-warm replay; remaining history "
    "entries are skipped (marked in the pre-warm report) once it is "
    "spent.", float)

COMPILE_PREWARM_MAX_QUERIES = register(
    "spark.tpu.compile.prewarm.maxQueries", 32,
    "Most-frequent-first cap on how many distinct history plans the "
    "pre-warm pass replays.", int)

COMPILE_PREWARM_WORKERS = register(
    "spark.tpu.compile.prewarm.workers", 1,
    "Worker threads replaying the served-plan history concurrently "
    "during pre-warm. 1 = sequential (deterministic replay order); "
    "more overlaps XLA compiles of independent plans.", int)


# ---- static plan analysis (spark_tpu/analysis/) ----------------------------

ANALYSIS_LEVEL = register(
    "spark.tpu.analysis.level", "off",
    "Pre-execution static plan analysis gate: off (default, no "
    "analysis on the submit path), warn (analyze every submitted plan "
    "and record diagnostics as events/metrics), or error (additionally "
    "raise PlanAnalysisError when an error-level diagnostic fires "
    "before anything touches the device). The same level also governs "
    "conf.set of undeclared keys: warn emits a warning, error raises.",
    str)

ANALYSIS_DIVERGENCE_FACTOR = register(
    "spark.tpu.analysis.divergenceFactor", 16.0,
    "The analyzer's static byte estimate is cross-checked against "
    "AQE's measured-bytes table (scheduler/admission); when the two "
    "disagree by more than this factor in either direction, the plan "
    "gets a PLAN-EST-DIVERGE diagnostic — the cost model is lying to "
    "admission control for this plan shape.", float)

DEBUG_LOCK_ORDER = register(
    "spark.tpu.debug.lockOrder", False,
    "Runtime cross-check of the static lock hierarchy "
    "(spark_tpu/locks.py): when true, every named lock records the "
    "per-thread held-stack on acquire and locks.order_report() exposes "
    "the observed acquisition edges plus any rank inversions or cycles "
    "— the empirical validation of tools/lint_concurrency.py's graph. "
    "Off by default (a global-flag check per acquire either way).",
    bool)

ANALYSIS_ERROR_CODES = register(
    "spark.tpu.analysis.errorCodes", "",
    "Comma-separated diagnostic codes escalated to error level at the "
    "submit-time gate (e.g. 'PLAN-DTYPE-F64,PLAN-RECOMPILE-SHAPE'): a "
    "deployment that must never bake data-dependent shapes into plans "
    "can fail such queries at submit instead of discovering the "
    "recompile storm in production.", str)

MESH_DEVICES = register(
    "spark_tpu.mesh.devices", None,
    "SPMD mesh size requested via SparkSession.builder.master"
    "('mesh[N]'); -1 = all visible devices, None/unset = single-device "
    "execution.", lambda v: v if v is None else int(v))


# ---- scale-out serving tier (spark_tpu/serve/) ----------------------------

SERVE_POLICY = register(
    "spark.tpu.serve.policy", "least_queued",
    "Federation-router replica selection: 'round_robin' cycles "
    "replicas, 'least_queued' picks the replica whose scheduler "
    "reports the fewest queued+running queries at the last health "
    "probe (reference analogue: spark.scheduler.mode for in-process "
    "pools; this is its cross-replica sibling).", str)

SERVE_RESULT_CACHE_ENABLED = register(
    "spark.tpu.serve.resultCache.enabled", False,
    "Serve repeated identical queries from the plan-keyed Arrow "
    "result cache (serve/result_cache.py): keyed by the structural "
    "plan key + scan-source mtime/size fingerprints, single-flight "
    "per key, byte-identical to uncached execution.", bool)

SERVE_RESULT_CACHE_MAX_BYTES = register(
    "spark.tpu.serve.resultCache.maxBytes", 256 * 1024 * 1024,
    "Byte bound for the serve-tier result cache; least-recently-used "
    "entries are evicted past it and a single result larger than the "
    "bound is served but never cached.", int)

SERVE_DISPATCH_RETRIES = register(
    "spark.tpu.serve.dispatchRetries", 3,
    "How many times the federation router re-dispatches one request "
    "to a different replica after a replica connection failure or an "
    "injected serve.dispatch fault before surfacing the error.", int)

SERVE_HEALTH_PROBE_SECONDS = register(
    "spark.tpu.serve.healthProbeSeconds", 0.5,
    "Minimum age of a replica's cached /health snapshot before the "
    "router re-probes it; 0 probes on every dispatch (tests).", float)

SERVE_REPLICAS = register(
    "spark.tpu.serve.replicas", 2,
    "Default replica count for serve_fleet() when the caller does not "
    "pass one explicitly.", int)


# ---- SLO-driven serving (spark_tpu/slo/) ----------------------------------

SLO_ENABLED = register(
    "spark.tpu.slo.enabled", False,
    "Master switch for the SLO subsystem: per-plan latency prediction "
    "(slo/model.py), earliest-feasible-deadline-first scheduling with "
    "reject-at-admission (slo/edf.py), and predictive brownout / "
    "concurrency auto-sizing (slo/controller.py). Off is byte-identical "
    "to the plain FIFO/FAIR scheduler path.", bool)

SLO_TARGET_P99_MS = register(
    "spark.tpu.slo.targetP99Ms", 0.0,
    "Configured p99 latency SLO in milliseconds. When > 0 the "
    "predictive brownout controller enters brownout as soon as the "
    "PREDICTED p99 over the recent window crosses it (before failures "
    "accumulate), and exits once predictions drop back under "
    "exitRatio x target. 0 disables predictive brownout.", float)

SLO_REJECT_ENABLED = register(
    "spark.tpu.slo.rejectEnabled", True,
    "Reject-at-admission (only active under spark.tpu.slo.enabled): a "
    "submit whose predicted completion (queue backlog estimate + "
    "predicted run time) exceeds its deadline raises the typed "
    "InfeasibleDeadline immediately instead of burning queue slots and "
    "device time on a query that is doomed to miss.", bool)

SLO_REJECT_MARGIN = register(
    "spark.tpu.slo.rejectMargin", 1.0,
    "Safety factor on the predicted completion time before the "
    "infeasibility comparison (>1 rejects earlier, <1 gives doubtful "
    "queries the benefit of the doubt).", float)

SLO_MODEL_ALPHA = register(
    "spark.tpu.slo.model.alpha", 0.3,
    "EWMA smoothing factor for the per-plan-fingerprint latency model "
    "components (host/device/queue/transfer ms and input rows); higher "
    "adapts faster, lower is steadier.", float)

SLO_MODEL_PATH = register(
    "spark.tpu.slo.model.path", "",
    "Persistence file (JSONL) for the latency model. Empty defaults to "
    "<compile store root>/slo_model.jsonl beside the plan-history "
    "journal when the store is enabled, so a restarted replica "
    "predicts from its first query; otherwise the model is "
    "in-memory only.", str)

SLO_MODEL_MAX_ENTRIES = register(
    "spark.tpu.slo.model.maxEntries", 512,
    "Distinct plan fingerprints kept by the latency model (LRU beyond "
    "it; the journal is compacted past roughly twice this many lines).",
    int)

SLO_WINDOW_SECONDS = register(
    "spark.tpu.slo.controller.windowSeconds", 30.0,
    "Sliding window over which the controller aggregates predicted "
    "per-query latencies for the predictive-p99 brownout decision.",
    float)

SLO_MIN_PREDICTIONS = register(
    "spark.tpu.slo.controller.minPredictions", 8,
    "Minimum predictions inside the window before the predictive "
    "brownout level may change (a single slow cold query is not a "
    "p99).", int)

SLO_EXIT_RATIO = register(
    "spark.tpu.slo.controller.exitRatio", 0.8,
    "Hysteresis for predictive brownout exit: the level drops back to "
    "0 only once predicted p99 <= exitRatio x targetP99Ms.", float)

SLO_AUTOSIZE_ENABLED = register(
    "spark.tpu.slo.autoConcurrency.enabled", True,
    "Auto-size the scheduler's EFFECTIVE concurrency (only under "
    "spark.tpu.slo.enabled) from observed queue/device-time ratios: "
    "queue-dominated load shrinks the effective worker count toward "
    "autoConcurrency.min (less churn at the device gate), "
    "compute-headroom grows it back toward the configured "
    "maxConcurrency.", bool)

SLO_AUTOSIZE_MIN = register(
    "spark.tpu.slo.autoConcurrency.min", 1,
    "Floor for the auto-sized effective concurrency.", int)


# ---- materialized views (spark_tpu/mview/) --------------------------------

MVIEW_ENABLED = register(
    "spark.tpu.mview.enabled", False,
    "Treat df.cache() of an aggregate over a fingerprinted file source "
    "as a materialized view (spark_tpu/mview/): the cached device "
    "batch is refreshed when the source files change instead of being "
    "served stale, incrementally when the aggregate is exactly "
    "re-mergeable.", bool)

MVIEW_INCREMENTAL = register(
    "spark.tpu.mview.incremental", True,
    "Refresh appended-to views by executing the aggregate over the new "
    "files only and re-merging the partials into the cached batch "
    "(legal only for integer Sum / non-float Min/Max — everything "
    "else full-recomputes). Off = always full recompute; both paths "
    "are byte-identical, this is the A/B switch the on/off sweep "
    "tests flip.", bool)

MVIEW_REFRESH_RETRIES = register(
    "spark.tpu.mview.refreshRetries", 2,
    "Bounded retries of one incremental view refresh after a "
    "transient failure (including injected mview.refresh faults) "
    "before falling back to a full recompute.", int)

MVIEW_SERVE_REPOPULATE = register(
    "spark.tpu.mview.serveRepopulate", True,
    "After a view refresh, proactively re-insert the refreshed "
    "Arrow result into the serve-tier result cache under the NEW "
    "fingerprint key, so federated readers keep hitting cache across "
    "updates instead of cold-missing.", bool)


class RuntimeConf:
    """Session-scoped mutable view over the registry."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._overrides: Dict[str, Any] = dict(overrides or {})

    def get(self, entry_or_key) -> Any:
        key = entry_or_key.key if isinstance(entry_or_key, ConfigEntry) else entry_or_key
        if key in self._overrides:
            return self._overrides[key]
        if key in _REGISTRY:
            return _REGISTRY[key].default
        raise KeyError(f"unknown config key: {key}")

    def set(self, key: str, value: Any) -> None:
        if key in _REGISTRY:
            value = _REGISTRY[key].value_type(value)
        elif not is_registered(key):
            # an undeclared key silently no-ops every read path (get()
            # raises on it) — surface the typo at the level the session
            # asked for (satellite of the static-analysis gate)
            level = str(self._overrides.get(
                ANALYSIS_LEVEL.key, ANALYSIS_LEVEL.default)).lower()
            if level == "error":
                raise KeyError(
                    f"unknown config key: {key} (not a registered "
                    "ConfigEntry or prefix; set "
                    "spark.tpu.analysis.level=warn to tolerate)")
            if level == "warn":
                import warnings

                warnings.warn(
                    f"conf.set of undeclared key {key!r}: not a "
                    "registered ConfigEntry or prefix — reads of it "
                    "will raise KeyError", stacklevel=2)
        self._overrides[key] = value

    def unset(self, key: str) -> None:
        self._overrides.pop(key, None)

    def entries(self) -> Dict[str, Any]:
        out = {k: e.default for k, e in _REGISTRY.items()}
        out.update(self._overrides)
        return out

"""Micro-batch incremental execution (reference:
sql/core/.../execution/streaming/MicroBatchExecution.scala:41
runActivatedStream:234 constructNextBatch:475 runBatch:579, plus
IncrementalExecution.scala:43 and WatermarkTracker.scala).

Each trigger: log new source offsets to the WAL, splice the new rows
into the logical plan, run ORDINARY batch executions to (a) compute the
new rows' partial aggregates and (b) merge them with the previous state
version over a union — both of which run on whatever engine the session
uses, including the TPU mesh — then commit state + offsets. Aggregates
are incrementalized by accumulator decomposition (sum/count/min/max are
mergeable; avg = sum+count), the same partial/final split the batch
planner uses for distributed aggregation."""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from spark_tpu import faults, metrics
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.plan.incremental import AggSpec
from spark_tpu.streaming.state import OffsetLog, StateStore

_qids = itertools.count()


@dataclass(eq=False, frozen=True)
class StreamingSource(L.LogicalPlan):
    """Leaf marker for a streaming source; replaced per micro-batch by a
    Relation over the new rows (reference: StreamingExecutionRelation)."""

    source: object  # MemoryStream / RateStreamSource
    watermark_col: Optional[str] = None
    watermark_delay: int = 0  # same units as the event-time column

    @property
    def schema(self):
        return self.source.schema

    def node_string(self):
        return f"StreamingSource[{getattr(self.source, 'name', '?')}]"


def _find_source(plan: L.LogicalPlan) -> StreamingSource:
    found = L.collect_nodes(plan, StreamingSource)
    if len(found) != 1:
        raise NotImplementedError(
            f"exactly one streaming source supported, got {len(found)}")
    return found[0]


def _splice(plan: L.LogicalPlan, replacement: L.LogicalPlan):
    def fn(p):
        if isinstance(p, StreamingSource):
            return replacement
        return p

    return plan.transform_up(fn)


class StreamingQuery:
    """One running (manually or loop-triggered) streaming query
    (reference: StreamExecution + StreamingQuery)."""

    def __init__(self, session, plan: L.LogicalPlan, sink_name: str,
                 output_mode: str = "complete",
                 checkpoint_dir: Optional[str] = None):
        self._session = session
        self._plan = plan
        self.name = sink_name or f"stream{next(_qids)}"
        self.output_mode = output_mode
        self._src_node = _find_source(plan)
        self._source = self._src_node.source
        self._log = OffsetLog(checkpoint_dir)
        self._store = StateStore(checkpoint_dir)
        self._batch_id = self._log.last_committed
        self._appended: List[pa.Table] = []
        #: restored from the commit log so the watermark survives restart
        self._max_event_time: Optional[int] = self._log.last_watermark()
        self._agg, self._above, self._below = self._split_plan()
        if self._agg is not None and output_mode == "update":
            raise NotImplementedError(
                "outputMode('update') with aggregation: use 'complete' "
                "or 'append' (with a watermark)")
        if self._agg is not None and output_mode == "append":
            wm_col = self._src_node.watermark_col
            has_time_key = wm_col is not None and any(
                wm_col in g.references() for g in self._agg.groupings)
            if not has_time_key:
                raise NotImplementedError(
                    "append-mode streaming aggregation requires a "
                    "watermark and an event-time grouping key "
                    "(reference: UnsupportedOperationChecker)")
        self._register_sink()
        self.is_active = True

    # -- plan surgery ---------------------------------------------------------

    def _split_plan(self):
        """Locate the (single) streaming Aggregate: returns
        (spec_or_None, nodes-above builder, child-subtree-below)."""
        aggs = L.collect_nodes(self._plan, L.Aggregate)
        if not aggs:
            return None, None, None
        if len(aggs) > 1:
            raise NotImplementedError(
                "multiple aggregations in one streaming query")
        agg = aggs[0]
        if agg is not self._plan:
            # operators above the aggregate (filter/select/sort on the
            # result) are not incrementalized yet; refusing beats
            # silently dropping them
            raise NotImplementedError(
                "operators above a streaming aggregation are not "
                "supported; aggregate must be the query root")
        return AggSpec(agg.groupings, agg.aggregates), agg, agg.child

    # -- execution ------------------------------------------------------------

    def _run(self, plan: L.LogicalPlan):
        ex = getattr(self._session, "mesh_executor", None)
        if ex is not None:
            return ex.execute_logical(plan)
        from spark_tpu.physical.planner import execute_logical

        return execute_logical(plan)

    def _to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        from spark_tpu.columnar.arrow import to_arrow

        return to_arrow(self._run(plan))

    def process_all_available(self) -> None:
        """Drain the source (Trigger.AvailableNow analogue)."""
        while True:
            latest = self._source.latest_offset()
            batch_id = self._batch_id + 1
            logged = self._log.offsets_for(batch_id)
            if logged is not None:
                # offsets were WAL'd but the batch never committed — a
                # crash between log_offsets and commit: replay the exact
                # same range (exactly-once restart)
                start, end = logged["start"], logged["end"]
                metrics.record("fault_recovered", point="streaming.commit",
                               how="wal_replay", batch=batch_id)
            else:
                prev = self._log.offsets_for(self._batch_id)
                start = prev["end"] if prev else 0
                end = latest
                if end <= start:
                    return
                self._log.log_offsets(batch_id, {"start": start,
                                                 "end": end})
            self._run_batch(batch_id, start, end)

    processAllAvailable = process_all_available

    def _publish_delta(self, batch_id: int, new_rows) -> None:
        """Hand the micro-batch's (late-filtered) rows to the
        materialized-view manager BEFORE the WAL commit: a crash
        between publish and commit replays the same batch id, which
        the manager's batch-id watermark drops — subscribed views
        never double-merge and never miss a committed batch. A view
        merge that fails past its retries propagates from here, so
        the batch stays uncommitted and replay redelivers it."""
        mgr = getattr(self._session, "mview_manager", None)
        if mgr is not None:
            mgr.on_micro_batch(self.name, batch_id, new_rows)

    def _run_batch(self, batch_id: int, start: int, end: int) -> None:
        from spark_tpu.columnar.arrow import from_arrow

        new_rows = self._source.get_batch(start, end)
        wm_col = self._src_node.watermark_col
        wm_before = self._watermark()
        if wm_col is not None and wm_before is not None \
                and new_rows.num_rows > 0 \
                and wm_col in new_rows.column_names:
            # rows older than the watermark are LATE and dropped before
            # any state update (reference: EventTimeWatermark filter) —
            # otherwise an already-emitted window could re-open
            import pyarrow.compute as pc

            new_rows = new_rows.filter(
                pc.greater_equal(new_rows.column(wm_col),
                                 pa.scalar(wm_before)))
        rel = L.Relation(from_arrow(new_rows))

        if self._agg is None:
            out = self._to_arrow(_splice(self._plan, rel))
            self._publish_delta(batch_id, new_rows)
            faults.inject("streaming.commit", self._session.conf)
            self._store.commit(batch_id, pa.table({}))
            self._log.commit(batch_id)
            # output is appended only AFTER the commit so a commit
            # crash + WAL replay cannot duplicate sink rows
            self._appended.append(out)
            self._batch_id = batch_id
            self._register_sink()
            return

        spec = self._agg
        batch_child = _splice(self._below, rel)
        key_aliases = tuple(E.Alias(g, n) for g, n
                            in zip(spec.groupings_exec, spec.key_names))
        partial_outs = key_aliases + tuple(spec.partials)
        if spec.session_idx is not None:
            # provisional session end = max(event) + gap per provisional
            # session key (which IS the event time, so end = key + gap)
            ev = spec.groupings[spec.session_idx].child
            partial_outs = partial_outs + (E.Alias(
                E.Max(E.Arith("+", ev, E.Literal(spec.session_gap))),
                "__send"),)
        partial = L.Aggregate(
            tuple(spec.groupings_exec), partial_outs, batch_child)
        partial_tbl = self._to_arrow(partial)

        prev = self._store.get(self._batch_id)
        if prev is not None and prev.num_rows > 0:
            merged_in = pa.concat_tables(
                [prev, partial_tbl.select(prev.column_names)])
        else:
            merged_in = partial_tbl
        mrel = L.Relation(from_arrow(merged_in))
        keys = tuple(E.Col(n) for n in spec.key_names)
        merge_outs = tuple(E.Alias(E.Col(n), n)
                           for n in spec.key_names) + tuple(spec.merges)
        if spec.session_idx is not None:
            merge_outs = merge_outs + (E.Alias(
                E.Max(E.Col("__send")), "__send"),)
        merged = L.Aggregate(keys, merge_outs, mrel)
        state_tbl = self._to_arrow(merged)
        if spec.session_idx is not None and state_tbl.num_rows > 0:
            state_tbl = self._merge_sessions(state_tbl)

        # watermark: track max event time from the new rows
        emitted: Optional[pa.Table] = None
        if wm_col is not None and new_rows.num_rows > 0 \
                and wm_col in new_rows.column_names:
            mx = pa.compute.max(new_rows.column(wm_col)).as_py()
            mx = int(mx) if mx is not None else None
            if mx is not None:
                if self._max_event_time is None \
                        or mx > self._max_event_time:
                    self._max_event_time = mx
        if self.output_mode == "append":
            state_tbl, emitted = self._evict_closed(state_tbl)

        self._publish_delta(batch_id, new_rows)
        faults.inject("streaming.commit", self._session.conf)
        self._store.commit(batch_id, state_tbl)
        self._log.commit(batch_id, watermark=self._max_event_time)
        self._batch_id = batch_id
        if emitted is not None and emitted.num_rows > 0:
            self._appended.append(self._finalize(emitted))
        self._register_sink()

    def _merge_sessions(self, state_tbl: pa.Table) -> pa.Table:
        """Merge overlapping/adjacent provisional sessions per key
        (reference: MergingSessionsExec): sort by (keys, start), a
        session chains onto the previous while start <= running max end,
        then the chained groups re-aggregate through the SAME merge
        accumulators with start=min(start), end=max(end)."""
        from spark_tpu.columnar.arrow import from_arrow

        spec = self._agg
        skey = spec.key_names[spec.session_idx]
        other = [n for i, n in enumerate(spec.key_names)
                 if i != spec.session_idx]
        df = state_tbl.to_pandas()
        df = df.sort_values(other + [skey], kind="mergesort",
                            na_position="first").reset_index(drop=True)
        if other:
            grp = df.groupby(other, dropna=False, sort=False)
            prev_end = grp["__send"].cummax().shift(1)
            new_key = grp.cumcount() == 0
        else:
            prev_end = df["__send"].cummax().shift(1)
            new_key = df.index == 0
        head = new_key | (df[skey] > prev_end)
        df["__sid"] = head.cumsum()
        rel = L.Relation(from_arrow(pa.Table.from_pandas(
            df, preserve_index=False)))
        keys2 = tuple(E.Col(n) for n in other) + (E.Col("__sid"),)
        outs = (tuple(E.Alias(E.Col(n), n) for n in other)
                + (E.Alias(E.Min(E.Col(skey)), skey),)
                + tuple(spec.merges)
                + (E.Alias(E.Max(E.Col("__send")), "__send"),))
        merged = L.Aggregate(keys2, outs, rel)
        out = self._to_arrow(merged)
        # restore the state column order (concat in the next batch
        # selects by prev.column_names)
        return out.select(state_tbl.column_names)

    def _watermark(self) -> Optional[int]:
        if self._max_event_time is None:
            return None
        return self._max_event_time - self._src_node.watermark_delay

    def _evict_closed(self, state: pa.Table):
        """Append mode: groups whose event-time key is entirely below the
        watermark can never change — emit and drop them (reference:
        statefulOperators.scala StateStoreSaveExec append mode)."""
        wm = self._watermark()
        if wm is None or state.num_rows == 0:
            return state, None
        spec = self._agg
        # the event-time grouping is the key referencing the wm column
        idx = None
        for i, g in enumerate(spec.groupings):
            if self._src_node.watermark_col in g.references():
                idx = i
                break
        if idx is None:
            return state, None
        import pyarrow.compute as pc

        if spec.session_idx is not None:
            # a session closes when the watermark passes its END
            closed = pc.less_equal(state.column("__send"),
                                   pa.scalar(wm))
            return state.filter(pc.invert(closed)), state.filter(closed)
        key = state.column(spec.key_names[idx])
        width = spec.window_widths[idx]
        if width is not None:
            # a window [start, start+width) closes when the watermark
            # passes its END
            closed = pc.less_equal(pc.add(key, pa.scalar(width)),
                                   pa.scalar(wm))
        else:
            closed = pc.less(key, pa.scalar(wm))
        return state.filter(pc.invert(closed)), state.filter(closed)

    def _finalize(self, state_tbl: pa.Table) -> pa.Table:
        from spark_tpu.columnar.arrow import from_arrow

        spec = self._agg
        out = L.Project(tuple(spec.outputs), L.Relation(
            from_arrow(state_tbl)))
        return self._to_arrow(out)

    # -- sink -----------------------------------------------------------------

    def _current_result(self) -> pa.Table:
        if self._agg is None or self.output_mode == "append":
            if self._appended:
                return pa.concat_tables(self._appended)
            # empty table with the right schema
            state = self._store.get(self._batch_id)
            if self._agg is not None and state is not None:
                return self._finalize(state.slice(0, 0))
            return pa.table({})
        state = self._store.get(self._batch_id)
        if state is None or state.num_rows == 0:
            return pa.table({})
        return self._finalize(state)

    def _register_sink(self) -> None:
        """Memory sink: results queryable as a temp view (reference:
        memory.scala MemorySink + CreateViewCommand)."""
        from spark_tpu.columnar.arrow import from_arrow

        tbl = self._current_result()
        if tbl.num_columns == 0:
            return
        self._session.catalog._register_view(
            self.name, L.Relation(from_arrow(tbl)))

    def stop(self) -> None:
        self.is_active = False

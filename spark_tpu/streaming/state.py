"""Versioned streaming state store (reference:
sql/core/.../execution/streaming/state/StateStore.scala,
HDFSBackedStateStoreProvider.scala — versioned per-partition KV with
snapshot checkpoints to durable storage).

Collapsed for the mesh architecture: state is ONE arrow table per
committed version (group keys + accumulator columns), kept in memory and
— when a checkpoint location is configured — snapshotted to parquet per
version. Restore = read the latest committed snapshot. Exactly-once
comes from the offset WAL committing only after the state snapshot is
durable (execution.py)."""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.parquet as pq


class StateStore:
    def __init__(self, checkpoint_dir: Optional[str] = None):
        self._versions: Dict[int, pa.Table] = {}
        self._dir = checkpoint_dir
        if self._dir:
            os.makedirs(os.path.join(self._dir, "state"), exist_ok=True)

    def get(self, version: int) -> Optional[pa.Table]:
        if version in self._versions:
            return self._versions[version]
        if self._dir:
            path = os.path.join(self._dir, "state", f"{version}.parquet")
            if os.path.exists(path):
                tbl = pq.read_table(path)
                self._versions[version] = tbl
                return tbl
        return None

    def commit(self, version: int, table: pa.Table) -> None:
        self._versions[version] = table
        if self._dir:
            path = os.path.join(self._dir, "state", f"{version}.parquet")
            tmp = path + ".tmp"
            pq.write_table(table, tmp)
            os.replace(tmp, path)  # atomic rename (CheckpointFileManager)
        # retain a small window of versions in memory
        for v in sorted(self._versions):
            if v < version - 2:
                del self._versions[v]


class OffsetLog:
    """Write-ahead offset log + commit log (reference: OffsetSeqLog /
    CommitLog + HDFSMetadataLog): batch N's offsets are logged BEFORE
    processing, committed after state is durable; restart replays the
    last uncommitted batch with the same offsets — exactly-once with a
    deterministic source."""

    def __init__(self, checkpoint_dir: Optional[str] = None):
        self._dir = checkpoint_dir
        self._offsets: Dict[int, dict] = {}
        self._commits: set = set()
        self._commit_meta: Dict[int, dict] = {}
        if self._dir:
            for sub in ("offsets", "commits"):
                os.makedirs(os.path.join(self._dir, sub), exist_ok=True)
            for fn in os.listdir(os.path.join(self._dir, "offsets")):
                if not fn.endswith(".json"):
                    continue  # leftover .tmp from a crash mid-write
                b = int(fn.split(".")[0])
                with open(os.path.join(self._dir, "offsets", fn)) as f:
                    self._offsets[b] = json.load(f)
            for fn in os.listdir(os.path.join(self._dir, "commits")):
                if not fn.endswith(".json"):
                    continue
                b = int(fn.split(".")[0])
                self._commits.add(b)
                with open(os.path.join(self._dir, "commits", fn)) as f:
                    self._commit_meta[b] = json.load(f)

    @property
    def last_committed(self) -> int:
        return max(self._commits) if self._commits else -1

    @property
    def last_logged(self) -> int:
        return max(self._offsets) if self._offsets else -1

    def offsets_for(self, batch_id: int) -> Optional[dict]:
        return self._offsets.get(batch_id)

    def log_offsets(self, batch_id: int, offsets: dict) -> None:
        self._offsets[batch_id] = offsets
        if self._dir:
            path = os.path.join(self._dir, "offsets", f"{batch_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(offsets, f)
            os.replace(tmp, path)

    def commit(self, batch_id: int,
               watermark: Optional[int] = None) -> None:
        self._commits.add(batch_id)
        self._commit_meta[batch_id] = {"batch": batch_id,
                                       "watermark": watermark}
        if self._dir:
            path = os.path.join(self._dir, "commits", f"{batch_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._commit_meta[batch_id], f)
            os.replace(tmp, path)

    def last_watermark(self) -> Optional[int]:
        if not self._commits:
            return None
        meta = self._commit_meta.get(max(self._commits))
        return None if meta is None else meta.get("watermark")

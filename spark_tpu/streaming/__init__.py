"""Structured streaming: micro-batch incremental execution.

The reference's streaming engine (reference:
sql/core/.../execution/streaming/MicroBatchExecution.scala:41,
StreamExecution.scala, IncrementalExecution.scala:43) incrementalizes a
DataFrame query: each trigger reads new source offsets, splices the new
rows into the logical plan, and runs an ordinary batch execution whose
stateful operators read/write a versioned state store checkpointed with
a write-ahead offset log.

This package keeps that exact architecture — streaming rides entirely on
the batch engine (and therefore on the TPU mesh): per micro-batch the
new rows' PARTIAL aggregates are computed by the normal engine, merged
with the persisted state by a second normal aggregation over their
union, and committed as the next state version. Sources, sinks, state
store, watermark and checkpoint live here; no operator code is
duplicated.
"""

from spark_tpu.streaming.sources import MemoryStream, RateStreamSource
from spark_tpu.streaming.state import StateStore
from spark_tpu.streaming.execution import StreamingQuery, StreamingSource

__all__ = ["MemoryStream", "RateStreamSource", "StateStore",
           "StreamingQuery", "StreamingSource"]

"""DataStreamReader / DataStreamWriter — the pyspark streaming API
surface (reference: sql/streaming/DataStreamReader.scala,
DataStreamWriter.scala:226)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from spark_tpu.plan import logical as L
from spark_tpu.streaming.execution import StreamingQuery, StreamingSource


class DataStreamReader:
    def __init__(self, session):
        self._session = session
        self._format = "memory"
        self._options: Dict[str, Any] = {}

    def format(self, fmt: str) -> "DataStreamReader":
        self._format = fmt
        return self

    def option(self, key: str, value: Any) -> "DataStreamReader":
        self._options[key] = value
        return self

    def load(self, source=None):
        from spark_tpu.api.dataframe import DataFrame

        if source is not None:  # pre-built MemoryStream etc.
            return DataFrame(self._session, StreamingSource(source))
        if self._format == "rate":
            from spark_tpu.streaming.sources import RateStreamSource

            rps = int(self._options.get("rowsPerSecond", 10))
            return DataFrame(self._session,
                             StreamingSource(RateStreamSource(rps)))
        raise NotImplementedError(
            f"streaming format {self._format!r}; use "
            "spark.readStream.load(MemoryStream(...)) or format('rate')")


class DataStreamWriter:
    def __init__(self, df):
        self._df = df
        self._output_mode = "complete"
        self._format = "memory"
        self._name: Optional[str] = None
        self._checkpoint: Optional[str] = None

    def outputMode(self, mode: str) -> "DataStreamWriter":
        if mode not in ("complete", "update", "append"):
            raise ValueError(f"unknown output mode {mode!r}")
        self._output_mode = mode
        return self

    def format(self, fmt: str) -> "DataStreamWriter":
        self._format = fmt
        return self

    def queryName(self, name: str) -> "DataStreamWriter":
        self._name = name
        return self

    def option(self, key: str, value) -> "DataStreamWriter":
        if key == "checkpointLocation":
            self._checkpoint = str(value)
        return self

    def start(self):
        if self._format != "memory":
            raise NotImplementedError(
                f"streaming sink {self._format!r} (memory only)")
        from spark_tpu.streaming.groups import (FlatMapGroupsWithState,
                                                GroupStateQuery)
        from spark_tpu.streaming.join import (StreamStreamJoinQuery,
                                              find_streaming_join)

        if isinstance(self._df._plan, FlatMapGroupsWithState):
            return GroupStateQuery(
                self._df._session, self._df._plan, self._name,
                self._output_mode, self._checkpoint)
        join = find_streaming_join(self._df._plan)
        if join is not None:
            return StreamStreamJoinQuery(
                self._df._session, self._df._plan, join, self._name,
                self._output_mode, self._checkpoint)
        return StreamingQuery(self._df._session, self._df._plan,
                              self._name, self._output_mode,
                              self._checkpoint)


def with_watermark(df, col_name: str, delay: int):
    """df.withWatermark analogue: marks the event-time column + lateness
    bound on the streaming source (reference: EventTimeWatermark)."""
    import dataclasses

    def fn(p):
        if isinstance(p, StreamingSource):
            return dataclasses.replace(p, watermark_col=col_name,
                                       watermark_delay=int(delay))
        return p

    from spark_tpu.api.dataframe import DataFrame

    return DataFrame(df._session, df._plan.transform_up(fn))

"""Stream-stream joins (reference:
sql/core/.../streaming/StreamingSymmetricHashJoinExec.scala — symmetric
hash join with per-side watermark-bounded state;
UnsupportedOperationChecker for the mode/type matrix).

Micro-batch formulation over the batch engine: keep every row seen so
far per side (watermark-trimmed), and per trigger emit

    new_left  JOIN (right_state UNION new_right)
    UNION  left_state JOIN new_right

which covers old x new, new x old and new x new exactly once. The joins
themselves are ordinary batch L.Join executions, so they run fused on
whatever engine the session uses (single chip or mesh). State is one
arrow table per side per committed version, snapshotted like streaming
aggregation state (state.py); the global watermark is the MIN of the
per-side watermarks (matching the reference's WatermarkTracker policy
for multi-source queries), and rows below it leave the state — bounding
memory exactly as the reference's state eviction does.

Supported: INNER, LEFT OUTER, RIGHT OUTER and FULL OUTER equi-joins in
append mode, with an optional extra condition (preserved sides track
matched bits and emit null-padded rows when their state evicts past the
watermark — tests/test_stream_join.py; full outer tracks BOTH sides
symmetrically and requires watermarks on both)."""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

import pyarrow as pa

from spark_tpu.plan import logical as L
from spark_tpu.streaming.execution import StreamingSource, _splice
from spark_tpu.streaming.state import OffsetLog, StateStore

_qids = itertools.count()


def find_streaming_join(plan: L.LogicalPlan) -> Optional[L.Join]:
    """The Join of a two-source streaming query, or None. Stateless
    operators (Project/Filter/alias — e.g. the column-ordering Project
    the USING-join API inserts) may sit above the join; they re-run per
    emitted micro-batch."""
    sources = L.collect_nodes(plan, StreamingSource)
    if len(sources) != 2:
        return None
    node = plan
    while isinstance(node, (L.Project, L.Filter, L.SubqueryAlias)):
        node = node.children()[0]
    if not isinstance(node, L.Join):
        raise NotImplementedError(
            "stream-stream join supports only stateless operators "
            "(project/filter) above the join")
    left_srcs = L.collect_nodes(node.left, StreamingSource)
    right_srcs = L.collect_nodes(node.right, StreamingSource)
    if len(left_srcs) != 1 or len(right_srcs) != 1:
        raise NotImplementedError(
            "each join side must read exactly one streaming source")
    return node


class StreamStreamJoinQuery:
    """Runner for a two-source streaming join (API-compatible subset of
    StreamingQuery: processAllAvailable / stop / is_active / name)."""

    def __init__(self, session, root: L.LogicalPlan, plan: L.Join,
                 sink_name: Optional[str],
                 output_mode: str = "append",
                 checkpoint_dir: Optional[str] = None):
        self._root = root
        if plan.how == "right":
            # right outer = sides swapped left outer (the operators
            # above — always a Project for USING joins — are reapplied
            # per batch and restore column order/selection)
            from spark_tpu.expr import expressions as E

            lnames = set(plan.left.schema.names)
            rnames = set(plan.right.schema.names)
            if lnames & rnames and (root is plan
                                    or plan.condition is not None):
                raise NotImplementedError(
                    "right outer stream join with colliding column "
                    "names and no projection above: '#2' dedup names "
                    "shift under the side swap")
            orig = plan
            orig_names = plan.schema.names
            plan = L.Join(plan.right, plan.left, "left",
                          plan.right_keys, plan.left_keys,
                          plan.condition)
            if root is orig:
                # bare-root: restore the right-join column order
                self._root = L.Project(
                    tuple(E.Col(n) for n in orig_names), plan)
        if plan.how not in ("inner", "left", "full"):
            raise NotImplementedError(
                f"stream-stream {plan.how} join: inner, left, right and "
                "full outer are supported")
        if plan.how in ("left", "full"):
            left_src = L.collect_nodes(plan.left, StreamingSource)[0]
            if left_src.watermark_col is None:
                raise NotImplementedError(
                    "outer stream-stream joins require a watermark "
                    "on the preserved side: null-padded results emit "
                    "when the watermark proves no match can arrive "
                    "(reference: StreamingSymmetricHashJoinExec "
                    "outer-join condition)")
        if plan.how == "full":
            right_src = L.collect_nodes(plan.right, StreamingSource)[0]
            if right_src.watermark_col is None:
                raise NotImplementedError(
                    "full outer stream-stream join requires watermarks "
                    "on BOTH sides (symmetric matched-bit eviction)")
        if output_mode not in ("append", "update"):
            raise NotImplementedError(
                "stream-stream joins support append mode only "
                "(reference: UnsupportedOperationChecker)")
        if not plan.left_keys:
            raise NotImplementedError(
                "stream-stream join requires equi-join keys (unbounded "
                "cross state otherwise)")
        self._session = session
        self._join = plan
        self.name = sink_name or f"stream{next(_qids)}"
        self._sides = (L.collect_nodes(plan.left, StreamingSource)[0],
                       L.collect_nodes(plan.right, StreamingSource)[0])
        self._subtrees = (plan.left, plan.right)
        preserved = {0: plan.how in ("left", "full"),
                     1: plan.how == "full"}
        for i in (0, 1):
            wc = self._sides[i].watermark_col
            if preserved[i] and wc is not None \
                    and wc not in self._subtrees[i].schema.names:
                raise NotImplementedError(
                    "outer stream-stream join: the preserved side's "
                    f"watermark column {wc!r} must survive to the join "
                    "(state eviction reads it — drop it above the join "
                    "instead)")
        self._log = OffsetLog(checkpoint_dir)
        self._store = StateStore(checkpoint_dir)
        self._batch_id = self._log.last_committed
        self._appended: List[pa.Table] = []
        wm = self._log.last_watermark()
        # per-side max event time persisted as a pair in the commit log
        self._max_event: List[Optional[int]] = list(wm) if \
            isinstance(wm, (list, tuple)) else [None, None]
        self.is_active = True
        self._register_sink()

    # -- engine plumbing ------------------------------------------------------

    def _to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        from spark_tpu.columnar.arrow import to_arrow
        from spark_tpu.physical.planner import execute_logical

        ex = getattr(self._session, "mesh_executor", None)
        batch = ex.execute_logical(plan) if ex is not None \
            else execute_logical(plan)
        return to_arrow(batch)

    def _side_rows(self, side: int, start: int, end: int) -> pa.Table:
        """New source rows pushed through the side's subtree
        (projections/filters between source and join). Event-time maxima
        are tracked on the RAW rows — a projection may drop the
        watermark column before the join, but the watermark still
        advances (reference: EventTimeWatermarkExec sits at the
        source, not at the join)."""
        from spark_tpu.columnar.arrow import from_arrow

        src = self._sides[side]
        raw = src.source.get_batch(start, end)
        wm_col = src.watermark_col
        if wm_col and raw.num_rows > 0 and wm_col in raw.column_names:
            import pyarrow.compute as pc

            mx = pc.max(raw.column(wm_col)).as_py()
            if mx is not None:
                mx = int(mx)
                if self._max_event[side] is None \
                        or mx > self._max_event[side]:
                    self._max_event[side] = mx
        subtree = self._subtrees[side]
        if isinstance(subtree, StreamingSource):
            return raw
        return self._to_arrow(_splice(subtree, L.Relation(from_arrow(raw))))

    # -- trigger loop ---------------------------------------------------------

    def process_all_available(self) -> None:
        while True:
            batch_id = self._batch_id + 1
            logged = self._log.offsets_for(batch_id)
            if logged is not None:
                starts, ends = logged["start"], logged["end"]
            else:
                prev = self._log.offsets_for(self._batch_id)
                starts = prev["end"] if prev else [0, 0]
                ends = [self._sides[0].source.latest_offset(),
                        self._sides[1].source.latest_offset()]
                if ends[0] <= starts[0] and ends[1] <= starts[1]:
                    return
                self._log.log_offsets(batch_id,
                                      {"start": starts, "end": ends})
            self._run_batch(batch_id, starts, ends)

    processAllAvailable = process_all_available

    def _run_batch(self, batch_id: int, starts, ends) -> None:
        import pyarrow.compute as pc

        new = [self._side_rows(i, starts[i], ends[i]) for i in (0, 1)]
        state = self._load_state(self._batch_id)
        # which sides are PRESERVED (emit null-padded when unmatched):
        # left outer tracks side 0; full outer tracks both (reference:
        # SymmetricHashJoinStateManager KeyWithIndexToValue bookkeeping)
        track = {0: self._join.how in ("left", "full"),
                 1: self._join.how == "full"}
        tag = {0: "__lid", 1: "__rid"}
        flag = {0: "__matched", 1: "__matched_r"}
        for i in (0, 1):
            if track[i]:
                n = new[i].num_rows
                tagged = new[i].append_column(tag[i], pa.array(
                    [(batch_id << 32) + j for j in range(n)], pa.int64()))
                new[i] = tagged.append_column(
                    flag[i], pa.array([False] * n, pa.bool_()))

        out_parts = []
        matched: dict = {0: set(), 1: set()}
        right_all = pa.concat_tables([state[1], new[1]]) \
            if state[1].num_rows else new[1]
        joinables = []
        if new[0].num_rows and right_all.num_rows:
            joinables.append((new[0], right_all))
        if state[0].num_rows and new[1].num_rows:
            joinables.append((state[0], new[1]))
        for lt, rt in joinables:
            if track[0]:
                lt = lt.drop_columns([flag[0]])
            if track[1]:
                rt = rt.drop_columns([flag[1]])
            joined = self._join_tables(lt, rt)
            for i in (0, 1):
                if track[i]:
                    matched[i] |= set(
                        joined.column(tag[i]).to_pylist())
                    joined = joined.drop_columns([tag[i]])
            out_parts.append(joined)
        out_parts = [self._apply_above(t) for t in out_parts]

        # grow state; flip matched bits
        new_state = [
            pa.concat_tables([state[i], new[i]])
            if state[i].num_rows else new[i]
            for i in (0, 1)
        ]
        for i in (0, 1):
            if track[i] and matched[i] and new_state[i].num_rows:
                ids = new_state[i].column(tag[i]).to_pylist()
                flags = new_state[i].column(flag[i]).to_pylist()
                flags = [f or (x in matched[i])
                         for f, x in zip(flags, ids)]
                idx = new_state[i].schema.get_field_index(flag[i])
                new_state[i] = new_state[i].set_column(
                    idx, flag[i], pa.array(flags, pa.bool_()))

        # watermark-trim state; evicted unmatched preserved-side rows
        # emit null-padded (this is WHEN outer results appear — the
        # watermark proves no future row can match them)
        wm = self._watermark()
        if wm is not None:
            for i in (0, 1):
                wm_col = self._sides[i].watermark_col
                if wm_col and new_state[i].num_rows > 0 \
                        and wm_col in new_state[i].column_names:
                    keep = pc.greater_equal(
                        new_state[i].column(wm_col), pa.scalar(wm))
                    if track[i]:
                        evicted = new_state[i].filter(pc.invert(keep))
                        unmatched = evicted.filter(
                            pc.invert(evicted.column(flag[i])))
                        if unmatched.num_rows:
                            out_parts.append(self._apply_above(
                                self._null_padded(unmatched, side=i)))
                    new_state[i] = new_state[i].filter(keep)

        self._commit_state(batch_id, new_state)
        self._log.commit(batch_id, watermark=self._max_event)
        self._batch_id = batch_id
        for t in out_parts:
            if t.num_rows:
                self._appended.append(t)
        self._register_sink()

    def _null_padded(self, rows: pa.Table, side: int = 0) -> pa.Table:
        """Unmatched preserved-side rows shaped like the join output:
        that side's columns + all-null columns for the other side."""
        from spark_tpu.io.datasource import _pa_schema_from_schema

        clean = rows.drop_columns(
            [c for c in ("__lid", "__matched", "__rid", "__matched_r")
             if c in rows.column_names])
        n = clean.num_rows
        out_schema = _pa_schema_from_schema(self._join.schema)
        # join output = left fields then right fields (dedup-renamed);
        # map this side's columns positionally into its region
        ln = len(self._subtrees[0].schema.names)
        arrays = []
        for pos, f in enumerate(out_schema):
            src = None
            if side == 0 and pos < ln:
                src = self._subtrees[0].schema.names[pos]
            elif side == 1 and pos >= ln:
                src = self._subtrees[1].schema.names[pos - ln]
            if src is not None and src in clean.column_names:
                arrays.append(clean.column(src).cast(f.type))
            else:
                arrays.append(pa.nulls(n, f.type))
        return pa.Table.from_arrays(arrays, schema=out_schema)

    def _watermark(self) -> Optional[int]:
        """MIN of per-side watermarks (a row may still find matches from
        the slower side, so the faster side cannot evict past it)."""
        wms = []
        for i in (0, 1):
            if self._sides[i].watermark_col is not None:
                if self._max_event[i] is None:
                    return None
                wms.append(self._max_event[i]
                           - self._sides[i].watermark_delay)
        return min(wms) if wms else None

    def _join_tables(self, left: pa.Table, right: pa.Table) -> pa.Table:
        from spark_tpu.columnar.arrow import from_arrow

        j = L.Join(L.Relation(from_arrow(left)),
                   L.Relation(from_arrow(right)),
                   "inner", self._join.left_keys, self._join.right_keys,
                   self._join.condition)
        return self._to_arrow(j)

    def _apply_above(self, joined: pa.Table) -> pa.Table:
        """Re-run the stateless operators above the join (the USING
        Project, post-join filters) on one emitted batch."""
        if self._root is self._join:
            return joined
        from spark_tpu.columnar.arrow import from_arrow

        rel = L.Relation(from_arrow(joined))

        # transform_up rebuilds ancestors, so identity match fails; the
        # tree contains exactly ONE Join (find_streaming_join contract)
        def fn(p):
            return rel if isinstance(p, L.Join) else p

        return self._to_arrow(self._root.transform_up(fn))

    # -- state layout: one table per side, tagged columns -----------------------

    def _load_state(self, version: int) -> Tuple[pa.Table, pa.Table]:
        empty = (self._empty_side(0), self._empty_side(1))
        tbl = self._store.get(version)
        if tbl is None or tbl.num_rows == 0 or "__side" not in \
                tbl.column_names:
            return empty
        import pyarrow.compute as pc

        out = []
        for i in (0, 1):
            part = tbl.filter(pc.equal(tbl.column("__side"), i))
            names = [n for n in part.column_names
                     if n.startswith(f"s{i}_")]
            side = pa.table({n[3:]: part.column(n) for n in names})
            out.append(side)
        return tuple(out)  # type: ignore[return-value]

    def _empty_side(self, i: int) -> pa.Table:
        from spark_tpu.io.datasource import _pa_schema_from_schema

        schema = _pa_schema_from_schema(self._subtrees[i].schema)
        return pa.Table.from_arrays(
            [pa.array([], f.type) for f in schema], schema=schema)

    def _commit_state(self, version: int,
                      sides: List[pa.Table]) -> None:
        """Pack both sides into one table (prefixed columns + __side
        tag) so the existing versioned snapshot machinery applies."""
        parts = []
        for i, side in enumerate(sides):
            n = side.num_rows
            cols = {"__side": pa.array([i] * n, pa.int8())}
            for j, name in enumerate(side.column_names):
                cols[f"s{i}_{name}"] = side.column(name)
            parts.append(cols)
        # union of columns with nulls on the other side
        all_names: List[str] = ["__side"]
        for i, side in enumerate(sides):
            all_names += [f"s{i}_{n}" for n in side.column_names]
        arrays = {}
        for name in all_names:
            chunks = []
            for i, cols in enumerate(parts):
                n = sides[i].num_rows
                if name in cols:
                    chunks.append(cols[name])
                else:
                    typ = None
                    for c2 in parts:
                        if name in c2:
                            a = c2[name]
                            typ = a.type if isinstance(a, pa.Array) \
                                else a.chunk(0).type if a.num_chunks \
                                else pa.null()
                            break
                    chunks.append(pa.nulls(n, typ or pa.null()))
            arrays[name] = pa.concat_arrays(
                [c.combine_chunks() if isinstance(c, pa.ChunkedArray)
                 else c for c in chunks])
        self._store.commit(version, pa.table(arrays))

    # -- sink -----------------------------------------------------------------

    def _current_result(self) -> pa.Table:
        if self._appended:
            return pa.concat_tables(self._appended)
        return pa.Table.from_arrays(
            [pa.array([], f.type) for f in self._result_schema()],
            schema=self._result_schema())

    def _result_schema(self) -> pa.Schema:
        from spark_tpu.io.datasource import _pa_schema_from_schema

        return _pa_schema_from_schema(self._root.schema)

    def _register_sink(self) -> None:
        from spark_tpu.columnar.arrow import from_arrow

        tbl = self._current_result()
        if tbl.num_columns == 0:
            return
        self._session.catalog._register_view(
            self.name, L.Relation(from_arrow(tbl)))

    def stop(self) -> None:
        self.is_active = False

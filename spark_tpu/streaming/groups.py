"""Arbitrary stateful per-group streaming: applyInPandasWithState /
flatMapGroupsWithState (reference:
sql/core/.../streaming/FlatMapGroupsWithStateExec.scala and the PySpark
surface python/pyspark/sql/pandas/group_ops.py applyInPandasWithState).

Host-side by nature — the user function is arbitrary Python over pandas
frames, exactly like the reference's Python worker path — so the engine
treats it as a stateful sink-side operator: per micro-batch the new
rows are grouped host-side, each group's persisted state object is
rehydrated, the user function runs, and updated states checkpoint with
the same versioned snapshot/commit protocol as streaming aggregation
(state.py). TPU work stays in the plan BELOW this operator (filters,
projections, joins still fuse on device)."""

from __future__ import annotations

import itertools
import pickle
import time as _time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import pyarrow as pa

from spark_tpu.plan import logical as L
from spark_tpu.streaming.execution import StreamingSource, _splice
from spark_tpu.streaming.state import OffsetLog, StateStore

_qids = itertools.count()


class GroupState:
    """Per-key mutable state handle (reference: GroupState.scala).
    ``setTimeoutDuration(ms)`` arms a PROCESSING-TIME timeout: if no new
    rows arrive for the key before the deadline, the user function is
    invoked once with an empty frame and ``hasTimedOut=True``
    (reference: FlatMapGroupsWithStateExec.scala:373)."""

    def __init__(self, value=None, exists: bool = False,
                 deadline_ms: Optional[int] = None,
                 has_timed_out: bool = False):
        self._value = value
        self._exists = exists
        self._removed = False
        self._updated = False
        self._deadline_ms = deadline_ms
        self._has_timed_out = has_timed_out
        self._now_ms: Optional[int] = None  # set by the runner

    @property
    def hasTimedOut(self) -> bool:  # noqa: N802 (pyspark surface)
        return self._has_timed_out

    def setTimeoutDuration(self, duration_ms: int) -> None:  # noqa: N802
        if self._now_ms is None:
            raise ValueError(
                "timeouts require timeoutConf='ProcessingTimeTimeout'")
        self._deadline_ms = self._now_ms + int(duration_ms)

    @property
    def exists(self) -> bool:
        return self._exists and not self._removed

    def get(self):
        if not self.exists:
            raise ValueError("state does not exist; check state.exists")
        return self._value

    def getOption(self):
        return self._value if self.exists else None

    def update(self, value) -> None:
        self._value = value
        self._exists = True
        self._removed = False
        self._updated = True

    def remove(self) -> None:
        self._removed = True
        self._updated = True


@dataclass(eq=False, frozen=True)
class FlatMapGroupsWithState(L.LogicalPlan):
    """Logical marker; executable only by the streaming runner."""

    keys: Tuple[str, ...]
    func: Callable  # func(key_tuple, pandas.DataFrame, GroupState) -> pdf
    out_schema: "L.Schema"
    child: L.LogicalPlan
    timeout_conf: str = "NoTimeout"

    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.out_schema

    def node_string(self):
        return f"FlatMapGroupsWithState[keys={list(self.keys)}]"


class GroupStateQuery:
    """Streaming runner for a FlatMapGroupsWithState root (subset of
    the StreamingQuery interface)."""

    def __init__(self, session, plan: FlatMapGroupsWithState,
                 sink_name: Optional[str], output_mode: str = "append",
                 checkpoint_dir: Optional[str] = None):
        if output_mode not in ("append", "update"):
            raise NotImplementedError(
                "flatMapGroupsWithState supports append/update output")
        self._session = session
        self._node = plan
        self.name = sink_name or f"stream{next(_qids)}"
        srcs = L.collect_nodes(plan, StreamingSource)
        if len(srcs) != 1:
            raise NotImplementedError(
                "exactly one streaming source per stateful-group query")
        self._src = srcs[0]
        self._log = OffsetLog(checkpoint_dir)
        self._store = StateStore(checkpoint_dir)
        self._batch_id = self._log.last_committed
        self._appended: List[pa.Table] = []
        self.is_active = True
        self._register_sink()

    # -- engine plumbing ------------------------------------------------------

    def _to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        from spark_tpu.columnar.arrow import to_arrow
        from spark_tpu.physical.planner import execute_logical

        ex = getattr(self._session, "mesh_executor", None)
        batch = ex.execute_logical(plan) if ex is not None \
            else execute_logical(plan)
        return to_arrow(batch)

    def process_all_available(self) -> None:
        while True:
            batch_id = self._batch_id + 1
            logged = self._log.offsets_for(batch_id)
            if logged is not None:
                start, end = logged["start"], logged["end"]
            else:
                prev = self._log.offsets_for(self._batch_id)
                start = prev["end"] if prev else 0
                end = self._src.source.latest_offset()
                if end <= start:
                    return
                self._log.log_offsets(batch_id,
                                      {"start": start, "end": end})
            self._run_batch(batch_id, start, end)

    processAllAvailable = process_all_available

    def _run_batch(self, batch_id: int, start: int, end: int) -> None:
        from spark_tpu.columnar.arrow import from_arrow

        raw = self._src.source.get_batch(start, end)
        below = self._node.child
        if isinstance(below, StreamingSource):
            tbl = raw
        else:
            tbl = self._to_arrow(
                _splice(below, L.Relation(from_arrow(raw))))
        pdf = tbl.to_pandas()

        states = self._load_states(self._batch_id)
        timeouts_on = self._node.timeout_conf == "ProcessingTimeTimeout"
        now_ms = int(_time.time() * 1000)
        out_frames = []
        keys = list(self._node.keys)
        seen: set = set()
        if len(pdf):
            for key_vals, group in pdf.groupby(keys, dropna=False):
                kt = key_vals if isinstance(key_vals, tuple) \
                    else (key_vals,)
                st = states.get(kt, GroupState())
                if timeouts_on:
                    st._now_ms = now_ms
                    st._deadline_ms = None  # re-arm explicitly per call
                st._has_timed_out = False
                result = self._node.func(kt, group, st)
                states[kt] = st
                seen.add(kt)
                if result is not None and len(result):
                    out_frames.append(result)
        if timeouts_on:
            # expired groups with no new data fire ONCE with an empty
            # frame and hasTimedOut=True (reference:
            # FlatMapGroupsWithStateExec.scala:373)
            import pandas as _pd

            empty_pdf = (pdf.iloc[0:0] if len(pdf.columns)
                         else _pd.DataFrame())
            for kt, st in list(states.items()):
                if kt in seen or not st.exists:
                    continue
                if st._deadline_ms is not None \
                        and st._deadline_ms <= now_ms:
                    st._now_ms = now_ms
                    st._has_timed_out = True
                    st._deadline_ms = None
                    result = self._node.func(kt, empty_pdf, st)
                    st._has_timed_out = False
                    if result is not None and len(result):
                        out_frames.append(result)
        # drop removed states
        states = {k: s for k, s in states.items()
                  if s.exists}
        self._commit_states(batch_id, states)
        self._log.commit(batch_id)
        self._batch_id = batch_id
        for f in out_frames:
            self._appended.append(pa.Table.from_pandas(
                f, preserve_index=False))
        self._register_sink()

    # -- state layout: key tuple + pickled, versioned state payload -----------
    #
    # The payload is a tagged dict ({_STATE_TAG: <format version>, ...}),
    # NOT a bare (value, deadline) tuple: shape-sniffing breaks the
    # moment a user's state value is itself a 2-tuple, and leaves no
    # room for new fields. Legacy layouts (untagged 2-tuple from the
    # timeout era, bare value before that) are still read.

    _STATE_TAG = "__group_state__"
    _STATE_VERSION = 1

    def _load_states(self, version: int) -> dict:
        tbl = self._store.get(version)
        out: dict = {}
        if tbl is None or tbl.num_rows == 0:
            return out
        key_bin = tbl.column("__key").to_pylist()
        val_bin = tbl.column("__state").to_pylist()
        for kb, vb in zip(key_bin, val_bin):
            payload = pickle.loads(vb)
            if isinstance(payload, dict) and self._STATE_TAG in payload:
                ver = payload[self._STATE_TAG]
                if ver > self._STATE_VERSION:
                    raise ValueError(
                        f"group-state checkpoint format v{ver} is newer "
                        f"than this engine supports "
                        f"(v{self._STATE_VERSION})")
                value = payload["value"]
                deadline = payload.get("deadline_ms")
            elif isinstance(payload, tuple) and len(payload) == 2:
                value, deadline = payload  # legacy (value, deadline)
            else:  # pre-timeout checkpoint layout: bare value
                value, deadline = payload, None
            out[pickle.loads(kb)] = GroupState(value, True,
                                               deadline_ms=deadline)
        return out

    def _commit_states(self, version: int, states: dict) -> None:
        keys = [pickle.dumps(k) for k in states]
        vals = [pickle.dumps({self._STATE_TAG: self._STATE_VERSION,
                              "value": s.getOption(),
                              "deadline_ms": s._deadline_ms})
                for s in states.values()]
        self._store.commit(version, pa.table({
            "__key": pa.array(keys, pa.binary()),
            "__state": pa.array(vals, pa.binary())}))

    # -- sink -----------------------------------------------------------------

    def _register_sink(self) -> None:
        from spark_tpu.columnar.arrow import from_arrow
        from spark_tpu.io.datasource import _pa_schema_from_schema

        if self._appended:
            tbl = pa.concat_tables(self._appended)
        else:
            schema = _pa_schema_from_schema(self._node.out_schema)
            tbl = pa.Table.from_arrays(
                [pa.array([], f.type) for f in schema], schema=schema)
        if tbl.num_columns == 0:
            return
        self._session.catalog._register_view(
            self.name, L.Relation(from_arrow(tbl)))

    def stop(self) -> None:
        self.is_active = False

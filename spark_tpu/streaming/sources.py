"""Streaming sources (reference:
sql/core/.../execution/streaming/memory.scala:42 MemoryStream,
sources/RateStreamProvider.scala).

A source exposes monotonically increasing integer offsets; the engine
reads half-open offset ranges ``(start, end]`` so every row is processed
exactly once per committed batch."""

from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

import pyarrow as pa

from spark_tpu import locks
from spark_tpu.types import Schema

_ids = itertools.count()


class MemoryStream:
    """In-memory source for deterministic tests (the StreamTest pattern,
    reference: sql/core/src/test/.../streaming/StreamTest.scala:342)."""

    def __init__(self, schema_or_example):
        if isinstance(schema_or_example, pa.Table):
            self._example = schema_or_example.schema
        else:
            self._example = schema_or_example
        self._rows: List[pa.Table] = []
        self._lock = locks.named_lock("streaming.source")
        self.name = f"memory-{next(_ids)}"

    # -- producer side --------------------------------------------------------

    def add_data(self, data) -> int:
        """Append rows; returns the new latest offset."""
        import pandas as pd

        if isinstance(data, pa.Table):
            tbl = data
        elif isinstance(data, pd.DataFrame):
            tbl = pa.Table.from_pandas(data, preserve_index=False)
        else:
            rows = list(data)
            names = list(rows[0].keys())
            tbl = pa.table({n: [r[n] for r in rows] for n in names})
        with self._lock:
            self._rows.append(tbl)
            return len(self._rows)

    # -- engine side ----------------------------------------------------------

    @property
    def schema(self) -> Schema:
        from spark_tpu.columnar.arrow import schema_from_arrow

        with self._lock:
            if self._rows:
                return schema_from_arrow(self._rows[0].schema)
        if isinstance(self._example, pa.Schema):
            return schema_from_arrow(self._example)
        return self._example

    def latest_offset(self) -> int:
        with self._lock:
            return len(self._rows)

    def get_batch(self, start: int, end: int) -> pa.Table:
        with self._lock:
            parts = self._rows[start:end]
        if not parts:
            first = self._rows[0] if self._rows else None
            return (first.slice(0, 0) if first is not None
                    else pa.table({}))
        return pa.concat_tables(parts)


class RateStreamSource:
    """rows-per-second generator (reference: RateStreamProvider.scala):
    offset = seconds elapsed; each second yields ``rows_per_second`` rows
    with (timestamp, value)."""

    def __init__(self, rows_per_second: int = 10):
        self.rows_per_second = int(rows_per_second)
        self._t0 = time.time()
        self.name = f"rate-{next(_ids)}"

    @property
    def schema(self) -> Schema:
        from spark_tpu import types as T
        from spark_tpu.types import Field, Schema

        return Schema((Field("timestamp", T.INT64, nullable=False),
                       Field("value", T.INT64, nullable=False)))

    def latest_offset(self) -> int:
        return int(time.time() - self._t0)

    def get_batch(self, start: int, end: int) -> pa.Table:
        rps = self.rows_per_second
        values = list(range(start * rps, end * rps))
        ts = [int(self._t0) + v // rps for v in values]
        return pa.table({"timestamp": pa.array(ts, pa.int64()),
                         "value": pa.array(values, pa.int64())})

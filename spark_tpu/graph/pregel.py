"""Pregel on dense device arrays (reference: graphx/Pregel.scala:59
`apply` — initial msg, vprog/sendMsg/mergeMsg loop with active-set
tracking; PageRank.scala, ConnectedComponents.scala).

Messages aggregate per destination with the sorted-segment kernels
(edges are sorted by dst at construction — the one-time analogue of
GraphX's routing tables), so every superstep is gathers + cumsum-style
scans: no scatter, no host syncs, and `lax.fori_loop` keeps the entire
run inside one XLA program."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu.physical import kernels as K


class Graph:
    """Vertices are arbitrary int64 ids (densified host-side once);
    edges are (src, dst[, weight])."""

    def __init__(self, vertex_ids, edge_src, edge_dst, edge_attr=None):
        vid = np.asarray(vertex_ids, dtype=np.int64)
        order = np.argsort(vid, kind="stable")
        self.vertex_ids = vid[order]
        if self.vertex_ids.size and (
                self.vertex_ids[1:] == self.vertex_ids[:-1]).any():
            dup = self.vertex_ids[1:][self.vertex_ids[1:]
                                      == self.vertex_ids[:-1]]
            raise ValueError(
                f"duplicate vertex ids: {np.unique(dup)[:5].tolist()}")
        self.n = int(vid.shape[0])
        es = np.asarray(edge_src)
        ed = np.asarray(edge_dst)
        src = np.searchsorted(self.vertex_ids, es)
        dst = np.searchsorted(self.vertex_ids, ed)
        for idx, vals, side in ((src, es, "src"), (dst, ed, "dst")):
            bad = (idx >= self.n) | (self.vertex_ids[
                np.clip(idx, 0, self.n - 1)] != vals)
            if bad.any():
                raise ValueError(
                    f"edge {side} references unknown vertex ids: "
                    f"{np.unique(vals[bad])[:5].tolist()}")
        # sort edges by destination ONCE: message merge becomes a
        # monotone-segment reduction (kernels.seg_* sorted path)
        eorder = np.argsort(dst, kind="stable")
        self.src = jnp.asarray(src[eorder])
        self.dst = jnp.asarray(dst[eorder])
        self.edge_attr = (None if edge_attr is None
                          else jnp.asarray(np.asarray(edge_attr)[eorder]))
        self.m = int(self.src.shape[0])
        self.out_degree = jnp.zeros((self.n,), jnp.int32).at[self.src].add(1)
        self.in_degree = jnp.zeros((self.n,), jnp.int32).at[self.dst].add(1)

    # -- the core loop --------------------------------------------------------

    def pregel(self, init_state: jnp.ndarray,
               message: Callable,
               update: Callable,
               num_iters: int,
               merge: str = "sum",
               default_msg=0.0):
        """Run ``num_iters`` supersteps:

            msgs      = message(src_state, edge_attr)      # (m,)
            merged[v] = merge(msgs to v)  or default_msg if none
            state     = update(state, merged)

        merge: 'sum' | 'min' | 'max'. The whole loop compiles to one XLA
        program (reference peer: Pregel.scala:115's while loop of joins)."""
        dst = self.dst
        red = {"sum": K.seg_sum, "min": K.seg_min, "max": K.seg_max}[merge]
        has_in = self.in_degree > 0
        m_mask = jnp.ones((self.m,), jnp.bool_)

        def step(_, state):
            sstate = state[self.src]
            msgs = message(sstate, self.edge_attr)
            merged = red(msgs, dst.astype(jnp.int32), m_mask, self.n,
                         sorted_seg=True)
            merged = jnp.where(
                has_in, merged,
                jnp.asarray(default_msg, dtype=merged.dtype))
            return update(state, merged)

        return jax.lax.fori_loop(0, num_iters, step, init_state)

    # -- library algorithms (reference: graphx/lib/) --------------------------

    def pagerank(self, num_iters: int = 20,
                 reset_prob: float = 0.15) -> jnp.ndarray:
        """reference: graphx/lib/PageRank.scala `run` — contribution =
        rank/outDegree along each edge, rank = reset + (1-reset)*sum."""
        deg = jnp.maximum(self.out_degree, 1).astype(jnp.float32)

        def message(src_rank, _):
            return src_rank / deg[self.src]

        def update(rank, contrib):
            return reset_prob + (1.0 - reset_prob) * contrib

        init = jnp.full((self.n,), 1.0, jnp.float32)
        return self.pregel(init, message, update, num_iters,
                           merge="sum", default_msg=0.0)

    def connected_components(self,
                             num_iters: Optional[int] = None) -> np.ndarray:
        """Min-label propagation over the UNDIRECTED graph (reference:
        graphx/lib/ConnectedComponents.scala). Returns, per vertex, the
        minimum original vertex id of its component."""
        both_src = jnp.concatenate([self.src, self.dst])
        both_dst = jnp.concatenate([self.dst, self.src])
        order = jnp.argsort(both_dst, stable=True)
        src = both_src[order]
        dst = both_dst[order].astype(jnp.int32)
        m_mask = jnp.ones((src.shape[0],), jnp.bool_)
        has_in = (jnp.zeros((self.n,), jnp.int32).at[dst].add(1)) > 0
        big = jnp.iinfo(jnp.int64).max

        def step(_, labels):
            msgs = labels[src]
            merged = K.seg_min(msgs, dst, m_mask, self.n, sorted_seg=True)
            merged = jnp.where(has_in, merged, big)
            return jnp.minimum(labels, merged)

        labels = jnp.asarray(self.vertex_ids)
        if num_iters is not None:
            return np.asarray(jax.lax.fori_loop(0, num_iters, step,
                                                labels))
        # default: blocks of supersteps until a fixpoint (the reference
        # Pregel loop stops when no messages remain) — diameter-bound
        # instead of O(n) rounds
        block = 8
        run_block = jax.jit(
            lambda l: jax.lax.fori_loop(0, block, step, l))
        for _ in range(0, max(2, self.n), block):
            new_labels = run_block(labels)
            if bool(jnp.all(new_labels == labels)):
                break
            labels = new_labels
        return np.asarray(labels)

    def triangle_count(self) -> int:
        """Total triangles via dense adjacency matmul (MXU-native for
        graphs small enough to densify; reference:
        graphx/lib/TriangleCount.scala counts via neighbor-set
        intersection). trace(A^3)/6 over the undirected simple graph."""
        a = jnp.zeros((self.n, self.n), jnp.float32)
        a = a.at[self.src, self.dst].set(1.0)
        a = a.at[self.dst, self.src].set(1.0)
        a = a * (1.0 - jnp.eye(self.n))
        a3 = a @ a @ a
        return int(jnp.trace(a3) / 6.0)

"""Graph processing — the GraphX/Pregel subset (reference:
graphx/src/main/scala/org/apache/spark/graphx/Pregel.scala:59,
impl/GraphImpl.scala).

TPU-first redesign: the reference iterates RDD joins per superstep
(vertex-program / sendMsg / mergeMsg as three shuffles per round). Here
a graph is dense device arrays (edges pre-sorted by destination once),
and a whole Pregel run is ONE jitted program: `lax.fori_loop` over
supersteps, each being gather(src state) -> edge message -> segmented
merge by destination (cumsum/scan kernels — scatter-free) -> vertex
update. No shuffles, no per-round dispatch."""

from spark_tpu.graph.pregel import Graph

__all__ = ["Graph"]

"""History reader: render JSONL event logs for a human.

The reference serves event logs through a Jetty web UI + History Server
(reference: core/src/main/scala/org/apache/spark/ui/SparkUI.scala:40,
deploy/history/FsHistoryProvider.scala:1, status/AppStatusStore.scala).
A single-process TPU driver does not need a web stack to make its
history legible — this module folds the JSONL event stream
(metrics.py, written under ``spark.eventLog.dir``) into per-query and
per-stage rollups and renders them as text (CLI) or a single static
HTML file.

Usage::

    python -m spark_tpu.history <event-log-dir-or-file> [--html out.html]

or programmatically: ``history.summarize(path)`` -> list of query
dicts; ``spark_tpu.tracing.query_profile()`` remains the live
in-process view.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def _iter_events(path: str):
    files = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith((".jsonl", ".log", ".json")):
                files.append(os.path.join(path, name))
    else:
        files = [path]
    for f in files:
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line of a live log


def summarize(path: str) -> List[Dict[str, Any]]:
    """Fold the event stream into queries: each ``query_start`` mark
    opens a bucket; stage events accumulate wall time per operator."""
    return summarize_events(_iter_events(path))


def summarize_events(events) -> List[Dict[str, Any]]:
    """Fold an event ITERABLE (JSONL file or the live in-memory ring —
    the live UI server in spark_tpu.ui reads the ring through this)."""
    queries: List[Dict[str, Any]] = []
    current: Optional[Dict[str, Any]] = None

    def close():
        nonlocal current
        if current is not None:
            queries.append(current)
            current = None

    for ev in events:
        kind = ev.get("kind", "")
        if kind == "query_start":
            close()
            current = {"label": str(ev.get("description", "?")),
                       "ts": ev.get("ts"), "stages": [],
                       "events": 0, "total_ms": 0.0}
            continue
        if current is None:
            current = {"label": "(before first query mark)", "ts": None,
                       "stages": [], "events": 0, "total_ms": 0.0}
        current["events"] += 1
        ms = float(ev.get("ms", 0.0) or 0.0)
        current["total_ms"] += ms
        if kind == "stage":
            current["stages"].append({
                "kind": str(ev.get("op", "stage")),
                "node": str(ev.get("node", ""))[:100],
                "ms": ms,
                "cap_in": ev.get("cap_in"),
                "error": ev.get("error"),
            })
        elif kind in ("stage_compile", "chunked_agg", "runtime_filter",
                      "skew_join_broadcast", "stage_retry") \
                or (kind == "heartbeat" and not ev.get("ok", True)):
            current["stages"].append({
                "kind": kind if kind != "heartbeat" else "heartbeat_fail",
                "node": json.dumps({k: v for k, v in ev.items()
                                    if k not in ("kind", "ts")})[:100],
                "ms": ms,
                "error": ev.get("error"),
            })
    close()
    return queries


def render_text(queries: List[Dict[str, Any]], top: int = 8) -> str:
    out = []
    out.append(f"{'query':<44} {'stages':>6} {'total ms':>10}")
    out.append("-" * 64)
    for q in queries:
        out.append(f"{q['label'][:44]:<44} {len(q['stages']):>6} "
                   f"{q['total_ms']:>10.1f}")
        for st in sorted(q["stages"], key=lambda s: -s["ms"])[:top]:
            err = (f"  ERROR: {st['error']}"
                   if st.get("error") else "")
            out.append(f"    {st['ms']:>9.1f} ms  {st['kind']:<19} "
                       f"{st['node']}{err}")
    return "\n".join(out)


def render_html(queries: List[Dict[str, Any]]) -> str:
    """One static page: per-query bars + stage tables (the SQL-tab
    DAG view collapsed to what matters: where the time went)."""
    from html import escape

    maxms = max((q["total_ms"] for q in queries), default=1.0) or 1.0
    rows = []
    for i, q in enumerate(queries):
        w = int(100 * q["total_ms"] / maxms)
        stage_rows = "".join(
            f"<tr><td>{st['ms']:.1f}</td><td>{escape(st['kind'])}</td>"
            f"<td><code>{escape(st['node'])}"
            + (f" <b>ERROR: {escape(str(st['error']))}</b>"
               if st.get("error") else "")
            + "</code></td></tr>"
            for st in sorted(q["stages"], key=lambda s: -s["ms"]))
        rows.append(
            f"<details><summary><b>{escape(q['label'])}</b> — "
            f"{q['total_ms']:.1f} ms, {len(q['stages'])} stages "
            f"<span style='display:inline-block;background:#4a90d9;"
            f"height:10px;width:{w}%'></span></summary>"
            f"<table border=1 cellpadding=3><tr><th>ms</th><th>kind"
            f"</th><th>stage</th></tr>{stage_rows}</table></details>")
    return ("<html><head><meta charset='utf-8'><title>spark_tpu history"
            "</title></head><body><h2>spark_tpu event-log history</h2>"
            + "".join(rows) + "</body></html>")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Render a spark_tpu JSONL event log "
                    "(spark.eventLog.dir) as text or HTML.")
    ap.add_argument("path", help="event-log file or directory")
    ap.add_argument("--html", metavar="OUT",
                    help="write a static HTML report instead of text")
    args = ap.parse_args(argv)
    queries = summarize(args.path)
    if not queries:
        print("no events found")
        return 1
    if args.html:
        with open(args.html, "w") as f:
            f.write(render_html(queries))
        print(f"wrote {args.html} ({len(queries)} queries)")
    else:
        print(render_text(queries))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""History reader: render JSONL event logs for a human.

The reference serves event logs through a Jetty web UI + History Server
(reference: core/src/main/scala/org/apache/spark/ui/SparkUI.scala:40,
deploy/history/FsHistoryProvider.scala:1, status/AppStatusStore.scala).
A single-process TPU driver does not need a web stack to make its
history legible — this module folds the JSONL event stream
(metrics.py, written under ``spark.eventLog.dir``) into per-query and
per-stage rollups and renders them as text (CLI) or a single static
HTML file.

Span events (spark_tpu/trace/) ride the same stream: ``chrome_trace``
folds one query's span tree into Chrome trace-event JSON — load the
file in Perfetto (ui.perfetto.dev) or chrome://tracing for the
waterfall the reference gets from its timeline view. The live server
serves it at ``GET /trace/<trace_id>``; offline, ``--perfetto out.json
[--trace <id>]`` renders it from a JSONL log.

Usage::

    python -m spark_tpu.history <event-log-dir-or-file> [--html out.html]
    python -m spark_tpu.history <event-log-dir> --perfetto out.json \
        [--trace <trace_id>]

or programmatically: ``history.summarize(path)`` -> list of query
dicts; ``spark_tpu.tracing.query_profile()`` remains the live
in-process view.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def _iter_events(path: str):
    files = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith((".jsonl", ".log", ".json")):
                files.append(os.path.join(path, name))
    else:
        files = [path]
    for f in files:
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line of a live log


def summarize(path: str) -> List[Dict[str, Any]]:
    """Fold the event stream into queries: each ``query_start`` mark
    opens a bucket; stage events accumulate wall time per operator."""
    return summarize_events(_iter_events(path))


def summarize_events(events) -> List[Dict[str, Any]]:
    """Fold an event ITERABLE (JSONL file or the live in-memory ring —
    the live UI server in spark_tpu.ui reads the ring through this)."""
    queries: List[Dict[str, Any]] = []
    current: Optional[Dict[str, Any]] = None

    def close():
        nonlocal current
        if current is not None:
            queries.append(current)
            current = None

    for ev in events:
        kind = ev.get("kind", "")
        if kind == "query_start":
            close()
            current = {"label": str(ev.get("description", "?")),
                       "ts": ev.get("ts"), "stages": [],
                       "trace_id": ev.get("trace_id"),
                       "events": 0, "total_ms": 0.0}
            continue
        if kind == "span":
            # spans nest (query.execute contains every stage), so their
            # ms would double-count into total_ms; the trace view
            # (chrome_trace / tracing.format_trace) is their rollup
            continue
        if current is None:
            current = {"label": "(before first query mark)", "ts": None,
                       "stages": [], "events": 0, "total_ms": 0.0}
        current["events"] += 1
        ms = float(ev.get("ms", 0.0) or 0.0)
        current["total_ms"] += ms
        if kind == "stage":
            current["stages"].append({
                "kind": str(ev.get("op", "stage")),
                "node": str(ev.get("node", ""))[:100],
                "ms": ms,
                "cap_in": ev.get("cap_in"),
                "error": ev.get("error"),
            })
        elif kind in ("stage_compile", "chunked_agg", "runtime_filter",
                      "skew_join_broadcast", "stage_retry") \
                or (kind == "heartbeat" and not ev.get("ok", True)):
            current["stages"].append({
                "kind": kind if kind != "heartbeat" else "heartbeat_fail",
                "node": json.dumps({k: v for k, v in ev.items()
                                    if k not in ("kind", "ts")})[:100],
                "ms": ms,
                "error": ev.get("error"),
            })
    close()
    return queries


def chrome_trace(events, trace_id: Optional[str] = None
                 ) -> Dict[str, Any]:
    """Fold span events into Chrome trace-event JSON (the format
    Perfetto and chrome://tracing load). ``events`` is any event
    iterable (``metrics.query_events(tid)``, a JSONL log); when
    ``trace_id`` is given only that trace is rendered.

    Mapping: each ``span`` event becomes one complete ("X") slice —
    ``ts``/``dur`` in microseconds relative to the trace's earliest
    span, ``pid`` per replica (the ``replica`` attr; 0 = driver/client
    side), ``tid`` from the recording thread — so the fleet renders as
    one process lane per replica with real thread interleaving. Flat
    traced events (fault_injected, serve shed/redispatch, stage_retry)
    become instant ("i") markers on the same lanes."""
    evs = [e for e in events
           if trace_id is None or e.get("trace_id") == trace_id]
    spans = [e for e in evs if e.get("kind") == "span"
             and "t0" in e and "ms" in e]
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(float(e["t0"]) for e in spans)
    # one Chrome "process" lane per replica; 0 is the driver/client side
    pids: Dict[str, int] = {}

    def pid_of(ev: Dict[str, Any]) -> int:
        rep = ev.get("replica")
        if rep is None:
            return 0
        return pids.setdefault(str(rep), len(pids) + 1)

    meta_keys = ("kind", "name", "ms", "t0", "ts", "tid", "n")
    out: List[Dict[str, Any]] = []
    for e in spans:
        out.append({
            "name": str(e.get("name", "span")),
            "cat": "span",
            "ph": "X",
            "ts": round((float(e["t0"]) - base) * 1e6, 1),
            "dur": round(float(e["ms"]) * 1e3, 1),
            "pid": pid_of(e),
            "tid": int(e.get("tid", 0)),
            "args": {k: v for k, v in e.items() if k not in meta_keys},
        })
    marker_kinds = ("fault_injected", "fault_recovered", "stage_retry",
                    "chunk_retry", "serve", "result_cache")
    for e in evs:
        if e.get("kind") in marker_kinds and "ts" in e:
            out.append({
                "name": str(e.get("kind")),
                "cat": "event",
                "ph": "i",
                "s": "t",
                "ts": round(max(0.0, (float(e["ts"]) - base)) * 1e6, 1),
                "pid": pid_of(e),
                "tid": int(e.get("tid", 0)),
                "args": {k: v for k, v in e.items()
                         if k not in meta_keys},
            })
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "driver"}}]
    meta += [{"name": "process_name", "ph": "M", "pid": p,
              "args": {"name": f"replica {r}"}}
             for r, p in sorted(pids.items(), key=lambda kv: kv[1])]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def render_text(queries: List[Dict[str, Any]], top: int = 8) -> str:
    out = []
    out.append(f"{'query':<44} {'stages':>6} {'total ms':>10}")
    out.append("-" * 64)
    for q in queries:
        out.append(f"{q['label'][:44]:<44} {len(q['stages']):>6} "
                   f"{q['total_ms']:>10.1f}")
        for st in sorted(q["stages"], key=lambda s: -s["ms"])[:top]:
            err = (f"  ERROR: {st['error']}"
                   if st.get("error") else "")
            out.append(f"    {st['ms']:>9.1f} ms  {st['kind']:<19} "
                       f"{st['node']}{err}")
    return "\n".join(out)


def render_html(queries: List[Dict[str, Any]]) -> str:
    """One static page: per-query bars + stage tables (the SQL-tab
    DAG view collapsed to what matters: where the time went)."""
    from html import escape

    maxms = max((q["total_ms"] for q in queries), default=1.0) or 1.0
    rows = []
    for i, q in enumerate(queries):
        w = int(100 * q["total_ms"] / maxms)
        stage_rows = "".join(
            f"<tr><td>{st['ms']:.1f}</td><td>{escape(st['kind'])}</td>"
            f"<td><code>{escape(st['node'])}"
            + (f" <b>ERROR: {escape(str(st['error']))}</b>"
               if st.get("error") else "")
            + "</code></td></tr>"
            for st in sorted(q["stages"], key=lambda s: -s["ms"]))
        rows.append(
            f"<details><summary><b>{escape(q['label'])}</b> — "
            f"{q['total_ms']:.1f} ms, {len(q['stages'])} stages "
            f"<span style='display:inline-block;background:#4a90d9;"
            f"height:10px;width:{w}%'></span></summary>"
            f"<table border=1 cellpadding=3><tr><th>ms</th><th>kind"
            f"</th><th>stage</th></tr>{stage_rows}</table></details>")
    return ("<html><head><meta charset='utf-8'><title>spark_tpu history"
            "</title></head><body><h2>spark_tpu event-log history</h2>"
            + "".join(rows) + "</body></html>")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Render a spark_tpu JSONL event log "
                    "(spark.eventLog.dir) as text or HTML.")
    ap.add_argument("path", help="event-log file or directory")
    ap.add_argument("--html", metavar="OUT",
                    help="write a static HTML report instead of text")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write Chrome trace-event JSON (load in "
                         "ui.perfetto.dev) instead of text")
    ap.add_argument("--trace", metavar="TRACE_ID",
                    help="with --perfetto: render only this trace id")
    args = ap.parse_args(argv)
    if args.perfetto:
        doc = chrome_trace(_iter_events(args.path), trace_id=args.trace)
        n = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
        if not n:
            print("no span events found")
            return 1
        with open(args.perfetto, "w") as f:
            json.dump(doc, f)
        print(f"wrote {args.perfetto} ({n} spans)")
        return 0
    queries = summarize(args.path)
    if not queries:
        print("no events found")
        return 1
    if args.html:
        with open(args.html, "w") as f:
            f.write(render_html(queries))
        print(f"wrote {args.html} ({len(queries)} queries)")
    else:
        print(render_text(queries))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Scheduling pools (reference: core/.../scheduler/Pool.scala +
SchedulableBuilder.scala's FairSchedulableBuilder, collapsed to the
query level: there is no XML file, pools are declared through ordinary
conf keys ``spark.tpu.scheduler.pool.<name>.{weight,minShare}`` and
materialize lazily on first use).

Ranking mirrors the reference's FairSchedulingAlgorithm: pools running
below their ``minShare`` come first (most starved first); the rest are
ordered by accumulated *device time* over ``weight`` — stride
scheduling, so a weight-2 pool receives twice the device time of a
weight-1 pool under contention. FIFO mode ignores pools and ranks by
global submit order.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict

from spark_tpu import locks
from spark_tpu import conf as CF


class Pool:
    """One scheduling pool: a FIFO queue of tickets plus the running
    count and accumulated device-time the fair ranking feeds on."""

    def __init__(self, name: str, weight: int = 1, min_share: int = 0):
        self.name = name
        self.weight = max(1, int(weight))
        self.min_share = max(0, int(min_share))
        #: tickets submitted but not yet dequeued by a worker
        self.queue: deque = deque()
        #: dequeued-but-unfinished queries (host or device phase)
        self.running = 0
        #: queries currently holding a device admission
        self.device_running = 0
        #: accumulated device-gate wall time (the fair-share currency)
        self.device_ms = 0.0
        self.finished = 0

    def fair_rank(self):
        """Sort key: starved pools (device_running < minShare) first,
        most starved first; then least device_ms/weight (stride)."""
        if self.device_running < self.min_share:
            return (0, self.device_running / max(1, self.min_share),
                    self.name)
        return (1, self.device_ms / self.weight, self.name)

    def snapshot(self) -> dict:
        return {"name": self.name, "weight": self.weight,
                "min_share": self.min_share, "queued": len(self.queue),
                # running counts dequeued-but-unfinished queries only;
                # self.running also includes the still-queued ones
                "running": self.running - len(self.queue),
                "device_running": self.device_running,
                "device_ms": round(self.device_ms, 2),
                "finished": self.finished}


def build_pools(conf) -> Dict[str, Pool]:
    """Materialize the pools declared in ``conf`` (prefix scan over
    ``spark.tpu.scheduler.pool.<name>.*``) plus the default pool."""
    specs: Dict[str, Dict[str, int]] = {}
    prefix = CF.SCHEDULER_POOL_PREFIX
    for key, value in conf.entries().items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):]
        if "." not in rest:
            continue
        name, attr = rest.rsplit(".", 1)
        if attr in ("weight", "minShare"):
            specs.setdefault(name, {})[attr] = int(value)
    pools = {
        name: Pool(name, weight=spec.get("weight", 1),
                   min_share=spec.get("minShare", 0))
        for name, spec in specs.items()}
    default = str(conf.get(CF.SCHEDULER_DEFAULT_POOL))
    pools.setdefault(default, Pool(default))
    return pools


class PoolRegistry:
    """Thread-safe pool lookup that materializes unknown pool names on
    demand (the reference logs a warning and creates the pool with
    default weight — same here, a client naming a new pool must not
    fail its query)."""

    def __init__(self, conf):
        self._conf = conf
        self._lock = locks.named_lock("scheduler.pools")
        self._pools = build_pools(conf)
        self.default_name = str(conf.get(CF.SCHEDULER_DEFAULT_POOL))

    def get(self, name=None) -> Pool:
        name = str(name) if name else self.default_name
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                pool = self._pools[name] = Pool(name)
            return pool

    def all(self):
        with self._lock:
            return list(self._pools.values())

"""Query-level scheduler: lifecycle, worker pool, device admission.

Replaces the connect server's global ``_exec_lock`` (one slow
aggregation used to block every other client) with a real control
plane, the query-level analogue of the reference's
TaskSchedulerImpl.scala + Pool.scala:

- queries are submitted into named pools and move through
  QUEUED -> ADMITTED -> RUNNING -> FINISHED/FAILED/CANCELLED;
- a bounded worker pool runs host-side stages (parse, optimize,
  parquet decode via the chunk pipeline) concurrently across queries;
- device execution is gated by HBM admission control (admission.py):
  a query passes the gate only when its estimated footprint fits the
  shared budget AND it is the policy-best waiter — FIFO by submit
  order, FAIR by per-pool device-time/weight stride. Grants are
  strictly in policy order (no bypass), so a large query can wait for
  the budget to drain but can never starve behind a stream of small
  ones;
- the queue is bounded: a submit at full depth raises
  SchedulerQueueFull immediately (the connect server turns that into
  429 + Retry-After) — backpressure, never an unbounded backlog;
- ``scheduler.admit`` is a fault-injection seam: transient faults
  retry the admission (bounded by spark.stage.maxConsecutiveAttempts),
  injected OOM halves the query's footprint estimate down to the
  degradation floor (the admission-side rung of the OOM ladder;
  execution keeps its own run_plan_with_oom_degradation rungs), and
  corruption surfaces typed and unretried.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import deadline as DL
from spark_tpu import faults, metrics
from spark_tpu.scheduler.admission import (AdmissionController,
                                           estimate_plan_bytes)
from spark_tpu.scheduler.pool import PoolRegistry
from spark_tpu.slo.edf import edf_key

QUEUED = "QUEUED"
ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

#: states a ticket can still leave
_LIVE = (QUEUED, ADMITTED, RUNNING)


class SchedulerQueueFull(RuntimeError):
    """Submit rejected: the bounded queue is at depth. Carries the
    Retry-After hint the connect server forwards with its 429."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"scheduler queue full ({depth} queued); retry after "
            f"{retry_after_s:g}s")
        self.retry_after_s = float(retry_after_s)


class QueryCancelled(RuntimeError):
    """The query was cancelled (explicitly or by its deadline)."""


class QueryTicket:
    """Handle for one submitted query: state, result, cancellation."""

    def __init__(self, qid: int, *, pool: str, description: str,
                 run: Callable, prepare: Optional[Callable],
                 est_bytes: int, deadline: Optional[float]):
        self.id = qid
        self.pool = pool
        self.description = description
        self.est_bytes = int(est_bytes)
        self.deadline = deadline  # absolute time.time(), or None
        self.state = QUEUED
        self.submitted_t = time.time()
        self.admitted_t: Optional[float] = None
        self.started_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.device_ms = 0.0
        self.error: Optional[BaseException] = None
        self._run = run
        self._prepare = prepare
        self._result: Any = None
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._charge = 0  # admission bytes currently held
        #: per-query unified RetryBudget, attached by the worker at
        #: execution start (None before that / when disabled)
        self.retry_budget = None
        self._granted = False  # holding an admission grant (charge may
        # legitimately be 0 when storage eviction covered the footprint)
        #: SLO stamps — all None/False unless spark.tpu.slo.enabled
        self.slo_fp: Optional[str] = None
        self.slo_rows: Optional[float] = None
        self.slo_run_pred_ms: Optional[float] = None
        self.slo_predicted_ms: Optional[float] = None
        self._slo_picked = False
        # span context of the submitting thread (connect request /
        # client): workers re-enter it so the whole execution — stages,
        # faults, retries — attributes to the submitter's trace
        self._trace_ctx = metrics.trace_context()

    # -- client surface ------------------------------------------------------

    def cancel(self) -> bool:
        """Request cancellation. Queued queries are cancelled
        immediately by the scheduler; running queries observe it at
        their next ``check_cancelled()`` seam. Returns False when the
        query already finished."""
        if self._done.is_set():
            return False
        self._cancel.set()
        return True

    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def check_cancelled(self) -> None:
        """Cooperative cancellation/deadline seam for running queries."""
        if self._cancel.is_set():
            raise QueryCancelled(f"query {self.id} cancelled")
        if self.deadline is not None and time.time() > self.deadline:
            raise QueryCancelled(
                f"DEADLINE_EXCEEDED: query {self.id} passed its "
                f"deadline")

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the query finishes; raise its error if it
        FAILED or was CANCELLED."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.id} still {self.state} after {timeout}s")
        if self.error is not None:
            raise self.error
        return self._result

    def queue_wait_ms(self) -> float:
        end = self.admitted_t or self.finished_t or time.time()
        return max(0.0, (end - self.submitted_t) * 1e3)

    def info(self) -> Dict[str, Any]:
        return {
            "id": self.id, "pool": self.pool,
            "description": self.description[:200],
            "state": self.state, "est_bytes": self.est_bytes,
            "submitted": round(self.submitted_t, 3),
            "queue_wait_ms": round(self.queue_wait_ms(), 2),
            "device_ms": round(self.device_ms, 2),
            "error": repr(self.error) if self.error is not None
            else None,
            # SLO fields appear only when the subsystem predicted this
            # query (absent = payload byte-identical to the pre-SLO one)
            **({"slo_predicted_ms": round(self.slo_predicted_ms, 2)}
               if self.slo_predicted_ms is not None else {}),
        }


class QueryScheduler:
    """The control plane. One per serving session (the connect server
    builds one and registers it on the session for the UI)."""

    def __init__(self, session=None, conf=None):
        if conf is None:
            conf = session.conf if session is not None else CF.RuntimeConf()
        self._conf = conf
        self._session = session
        self.mode = str(conf.get(CF.SCHEDULER_MODE)).upper()
        if self.mode not in ("FIFO", "FAIR"):
            raise ValueError(
                f"spark.scheduler.mode must be FIFO or FAIR, got "
                f"{self.mode!r}")
        self.max_queue_depth = max(
            0, int(conf.get(CF.SCHEDULER_QUEUE_DEPTH)))
        self.retry_after_s = float(conf.get(CF.SCHEDULER_RETRY_AFTER))
        self.pools = PoolRegistry(conf)
        # share the session's unified storage/execution memory manager
        # when there is one, so admission can reclaim unpinned cached
        # batches; a conf-only scheduler (tests) gets a private manager
        self.admission = AdmissionController(
            int(conf.get(CF.SCHEDULER_HBM_BUDGET)),
            manager=getattr(session, "memory_manager", None))
        self._cond = locks.named_condition("scheduler.cond")
        # grant releases by OTHER tenants of the shared manager (hybrid
        # join spill grants, direct manager users) must wake the gate
        # too, not just this scheduler's own _release. The manager fires
        # listeners after dropping its lock, so the callback's
        # cond-acquire creates no storage.unified -> scheduler.cond
        # hierarchy edge.
        self.admission.manager.add_release_listener(self._wake_gate)
        self._seq = 0
        self._queued = 0
        self._gate: List[QueryTicket] = []  # waiting for device admission
        self._recent: deque = deque(maxlen=256)  # finished + live tickets
        self._stopped = False
        self.rejected = 0
        n_workers = max(1, int(conf.get(CF.SCHEDULER_MAX_CONCURRENCY)))
        # SLO control plane (ROADMAP item 5): None unless
        # spark.tpu.slo.enabled — every SLO branch below is guarded on
        # ``self._slo is not None`` so the FIFO/FAIR paths are
        # byte-identical to the pre-SLO scheduler when off
        self._slo = None
        self._active_runs = 0  # tickets picked and not yet finished
        if bool(conf.get(CF.SLO_ENABLED)):
            try:
                from spark_tpu.slo.controller import SloController
                from spark_tpu.slo.model import (LatencyModel,
                                                 model_path_from_conf)

                model = LatencyModel(
                    model_path_from_conf(conf),
                    alpha=float(conf.get(CF.SLO_MODEL_ALPHA)),
                    max_entries=int(conf.get(CF.SLO_MODEL_MAX_ENTRIES)))
                self._slo = SloController(conf, model, n_workers)
            except Exception:
                self._slo = None  # SLO is advisory: never block startup
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"spark-tpu-sched-{i}")
            for i in range(n_workers)]
        for w in self._workers:
            w.start()

    # -- submission ----------------------------------------------------------

    def submit(self, run: Callable, *, prepare: Optional[Callable] = None,
               pool: Optional[str] = None, description: str = "",
               est_bytes: Optional[int] = None,
               deadline_s: Optional[float] = None,
               slo_fp: Optional[str] = None,
               slo_rows: Optional[float] = None) -> QueryTicket:
        """Queue a query. ``prepare(ticket)`` is the host-side stage
        (parse/optimize/estimate; runs concurrently on the worker pool,
        may return a refined est_bytes); ``run(ticket)`` is the
        device-side stage, entered only after HBM admission. Raises
        SchedulerQueueFull at full queue depth, and — when
        spark.tpu.slo.enabled with a deadline set and ``slo_fp`` known
        to the latency model — InfeasibleDeadline when predicted
        completion already exceeds the deadline (reject-at-admission:
        the query is shed before it costs a queue slot)."""
        p = self.pools.get(pool)
        deadline = time.time() + float(deadline_s) \
            if deadline_s is not None else None
        # the submitter's propagated absolute deadline (connect header,
        # collect-minted) rides onto the ticket; the tighter bound wins
        ambient = DL.current()
        if ambient is not None:
            deadline = ambient if deadline is None \
                else min(deadline, ambient)
        # prediction + reject-gate fault seams run OUTSIDE the condition
        # lock (a hang-kind injection must never stall the scheduler
        # while holding it); the feasibility math itself is pure and
        # runs under the lock against the live backlog
        pred_ms = None
        slo_reject = False
        if self._slo is not None:
            pred_ms = self._slo.predict_run_ms(slo_fp, slo_rows)
            if deadline is not None and pred_ms is not None:
                slo_reject = self._slo.reject_gate()
        with self._cond:
            if self._stopped:
                raise RuntimeError("scheduler is stopped")
            if self._queued >= self.max_queue_depth:
                self.rejected += 1
                metrics.record("scheduler", phase="rejected",
                               pool=p.name, queued=self._queued)
                raise SchedulerQueueFull(self._queued, self.retry_after_s)
            predicted_total = None
            if self._slo is not None and pred_ms is not None:
                # the queue-wait model must match the EDF pick: a
                # deadlined submit only waits behind queued tickets
                # whose deadline sorts at-or-before its own (it jumps
                # the rest), while a deadline-less submit sorts last
                # and waits behind everything; in-flight work can't be
                # preempted either way
                predicted_total = self._slo.admission_check_locked(
                    deadline=deadline, pred_run_ms=pred_ms,
                    pending_ms=[x.slo_run_pred_ms
                                for q in self.pools.all()
                                for x in q.queue
                                if deadline is None
                                or (x.deadline is not None
                                    and x.deadline <= deadline)],
                    inflight_ms=[x.slo_run_pred_ms
                                 for x in self._recent
                                 if x.state in (ADMITTED, RUNNING)],
                    reject=slo_reject)
            self._seq += 1
            t = QueryTicket(
                self._seq, pool=p.name, description=description,
                run=run, prepare=prepare,
                est_bytes=est_bytes if est_bytes is not None
                else self.admission.budget,
                deadline=deadline)
            t.slo_fp = slo_fp
            t.slo_rows = slo_rows
            t.slo_run_pred_ms = pred_ms
            t.slo_predicted_ms = predicted_total
            p.queue.append(t)
            p.running += 1  # dequeued-or-queued live count, see _finish
            self._queued += 1
            self._recent.append(t)
            metrics.record("scheduler", phase="submitted", id=t.id,
                           pool=p.name, est_bytes=t.est_bytes)
            self._cond.notify_all()
        return t

    def submit_query(self, build_df: Callable[[], Any], *,
                     pool: Optional[str] = None, description: str = "",
                     deadline_s: Optional[float] = None,
                     sql: Optional[str] = None) -> QueryTicket:
        """Engine-query convenience: ``build_df()`` -> DataFrame is the
        host-side parse/plan stage (its footprint is then estimated
        from the logical plan); the device stage materializes Arrow.
        ``sql`` is the raw statement when the caller has one (the
        connect server does): it rides on the DataFrame so the compile
        service's served-plan history records a replayable identity
        even for frames not built via session.sql."""
        holder: dict = {}

        def prepare(t: QueryTicket):
            df = build_df()
            if sql is not None and getattr(df, "_sql_text", None) is None:
                df._sql_text = sql
            holder["df"] = df
            conf = df._session.conf if df._session is not None \
                else self._conf
            if self._slo is not None:
                # refine the SLO identity once the plan exists: a
                # structural fingerprint for SQL-less submissions (so
                # the model still learns them) and scan-stat input
                # rows for size-scaled predictions on the next run
                from spark_tpu.slo.model import (fingerprint_plan,
                                                 plan_input_rows)

                if t.slo_fp is None:
                    t.slo_fp = fingerprint_plan(df._plan)
                if t.slo_rows is None:
                    t.slo_rows = plan_input_rows(df._plan)
            return estimate_plan_bytes(df._plan, conf)

        def run(t: QueryTicket):
            t.check_cancelled()
            return holder["df"].toArrow()

        slo_fp = None
        if self._slo is not None and sql is not None:
            from spark_tpu.slo.model import fingerprint_sql

            slo_fp = fingerprint_sql(sql)
        return self.submit(run, prepare=prepare, pool=pool,
                           description=description, deadline_s=deadline_s,
                           slo_fp=slo_fp)

    def cancel(self, qid: int) -> bool:
        """Cancel by id: a QUEUED query finishes CANCELLED right here;
        an ADMITTED/RUNNING one is flagged for its next seam."""
        with self._cond:
            t = next((x for x in self._recent if x.id == qid), None)
            if t is None or t.done():
                return False
            t._cancel.set()
            if t.state == QUEUED:
                p = self.pools.get(t.pool)
                if t in p.queue:
                    p.queue.remove(t)
                    self._queued -= 1
                    self._finish_locked(
                        t, CANCELLED,
                        error=QueryCancelled(
                            f"query {t.id} cancelled while queued"))
            self._cond.notify_all()
            return True

    # -- introspection -------------------------------------------------------

    def queue_depth(self) -> int:
        """Live queued-query count (NOT the configured bound) under the
        scheduler lock — the federation router's load signal."""
        with self._cond:
            return self._queued

    def running_count(self) -> int:
        """Queries past the queue (ADMITTED at the device gate or
        RUNNING on a worker) right now, under the scheduler lock."""
        with self._cond:
            return sum(1 for t in self._recent
                       if t.state in (ADMITTED, RUNNING))

    def status(self) -> Dict[str, Any]:
        with self._cond:
            st = {
                "mode": self.mode,
                "queue_depth": self.max_queue_depth,
                "queued": self._queued,
                "gate_waiters": len(self._gate),
                "rejected": self.rejected,
                "admission": self.admission.snapshot(),
                "pools": [p.snapshot() for p in self.pools.all()],
            }
            if self._slo is not None:
                st["slo"] = self._slo.snapshot()
            return st

    def describe(self, n: int = 64) -> List[Dict[str, Any]]:
        """Recent + live tickets, newest first (the /queries payload)."""
        with self._cond:
            return [t.info() for t in list(self._recent)[-n:]][::-1]

    # -- worker side ---------------------------------------------------------

    def _pick_locked(self) -> Optional[QueryTicket]:
        """Next ticket to dequeue, per policy; purges cancelled and
        deadline-expired queue heads. Caller holds the lock."""
        now = time.time()
        if self._slo is not None:
            return self._pick_slo_locked(now)
        for p in self.pools.all():
            while p.queue:
                head = p.queue[0]
                if head.cancelled() or (head.deadline is not None
                                        and now > head.deadline):
                    p.queue.popleft()
                    self._queued -= 1
                    why = "cancelled while queued" if head.cancelled() \
                        else "DEADLINE_EXCEEDED while queued"
                    self._finish_locked(
                        head, CANCELLED,
                        error=QueryCancelled(f"query {head.id} {why}"))
                    continue
                break
        candidates = [p for p in self.pools.all() if p.queue]
        if not candidates:
            return None
        if self.mode == "FAIR":
            best = min(candidates, key=lambda p: p.fair_rank())
        else:
            best = min(candidates, key=lambda p: p.queue[0].id)
        t = best.queue.popleft()
        self._queued -= 1
        return t

    def _pick_slo_locked(self, now: float) -> Optional[QueryTicket]:
        """SLO pick: earliest-deadline-first across ALL pool queues
        (not just heads — EDF may owe the next slot to a mid-queue
        ticket), bounded by the controller's auto-sized effective
        concurrency. Purges cancelled/expired tickets anywhere in the
        queues: under EDF an expired ticket is never "in the way" at
        the head, so head-only purging would leak it. Caller holds
        the lock."""
        for p in self.pools.all():
            for x in list(p.queue):
                if x.cancelled() or (x.deadline is not None
                                     and now > x.deadline):
                    p.queue.remove(x)
                    self._queued -= 1
                    why = "cancelled while queued" if x.cancelled() \
                        else "DEADLINE_EXCEEDED while queued"
                    self._finish_locked(
                        x, CANCELLED,
                        error=QueryCancelled(f"query {x.id} {why}"))
        if self._active_runs >= self._slo.effective_concurrency():
            return None  # auto-sized below the worker count: idle some
        best: Optional[QueryTicket] = None
        best_pool = None
        for p in self.pools.all():
            for x in p.queue:
                if best is None or edf_key(x) < edf_key(best):
                    best, best_pool = x, p
        if best is None:
            return None
        best_pool.queue.remove(best)
        self._queued -= 1
        best._slo_picked = True
        self._active_runs += 1
        return best

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                t = None
                while not self._stopped:
                    t = self._pick_locked()
                    if t is not None:
                        break
                    # notify-driven: submit/cancel/stop/finish all
                    # notify; the timeout is only a liveness backstop
                    self._cond.wait(0.5)
                if self._stopped:
                    return
            self._execute(t)

    def _execute(self, t: QueryTicket) -> None:
        from spark_tpu import recovery, trace

        # worker threads don't inherit the submitter's contextvars;
        # re-enter the captured span context so the run attributes to
        # the submitting request's trace (root here for tickets
        # submitted outside any trace). The ticket's absolute deadline
        # and a fresh per-query RetryBudget enter scope the same way:
        # every retry/wait seam under this worker draws from ONE pool
        # and stops when the caller's deadline passes.
        t.retry_budget = recovery.budget_from_conf(self._conf)
        with trace.attach(t._trace_ctx), DL.bind(t.deadline), \
                recovery.bind_budget(t.retry_budget):
            with trace.span("scheduler.run", id=t.id, pool=t.pool):
                self._execute_traced(t)

    def _execute_traced(self, t: QueryTicket) -> None:
        from spark_tpu import trace

        try:
            t.check_cancelled()
            with trace.span("scheduler.queue", id=t.id, pool=t.pool):
                if t._prepare is not None:
                    # host-side stage: runs concurrently across workers
                    est = t._prepare(t)
                    if est:
                        t.est_bytes = int(est)
                self._admit(t)
            t.state = RUNNING
            t.started_t = time.time()
            t.check_cancelled()
            out = t._run(t)
            self._finish(t, FINISHED, result=out)
            if self._slo is not None:
                # fold the completed run back into the latency model
                # (no scheduler lock held here; never raises)
                self._slo.note_finished(t)
        except (QueryCancelled, DL.DeadlineExceeded) as e:
            self._finish(t, CANCELLED, error=e)
        except Exception as e:  # noqa: BLE001 — typed via ticket.error
            self._finish(t, FAILED, error=e)
        finally:
            self._release(t)
            with self._cond:
                self._cond.notify_all()

    # -- the device-admission gate -------------------------------------------

    def _gate_best_locked(self) -> Optional[QueryTicket]:
        if not self._gate:
            return None
        if self._slo is not None:
            return min(self._gate, key=edf_key)
        if self.mode == "FAIR":
            return min(self._gate, key=lambda x:
                       self.pools.get(x.pool).fair_rank() + (x.id,))
        return min(self._gate, key=lambda x: x.id)

    def _admit(self, t: QueryTicket) -> None:
        """Pass the HBM admission gate, then the ``scheduler.admit``
        fault seam: transient faults re-admit (bounded attempts),
        injected OOM halves the footprint estimate down to the
        degradation floor, corruption surfaces typed."""
        from spark_tpu import recovery

        attempts = max(1, int(self._conf.get(recovery.STAGE_MAX_ATTEMPTS)))
        floor = max(1, int(self._conf.get(recovery.OOM_DEGRADE_FLOOR)))
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            self._gate_wait(t)
            try:
                faults.inject("scheduler.admit", self._conf)
                if attempt:
                    metrics.record("fault_recovered",
                                   point="scheduler.admit",
                                   how="admit_retry", attempts=attempt)
                t.state = ADMITTED
                t.admitted_t = time.time()
                metrics.record("scheduler", phase="admitted", id=t.id,
                               pool=t.pool, est_bytes=t.est_bytes,
                               queue_wait_ms=round(t.queue_wait_ms(), 2))
                return
            except Exception as e:
                self._release(t)
                with self._cond:
                    self._cond.notify_all()
                last = e
                if recovery.is_oom(e):
                    # admission-side degradation rung: shrink the
                    # claimed footprint; execution's own OOM ladder
                    # (run_plan_with_oom_degradation) guards the rest
                    if t.est_bytes // 2 < floor:
                        raise
                    t.est_bytes //= 2
                    metrics.record("scheduler", phase="admit_degraded",
                                   id=t.id, pool=t.pool,
                                   est_bytes=t.est_bytes)
                    continue
                if recovery.is_transient(e):
                    metrics.record("stage_retry",
                                   label="scheduler.admit",
                                   attempt=attempt, error=repr(e))
                    # a re-admission is a re-attempt like any other:
                    # it draws from the query's unified budget so
                    # admit retries and execution retries share one
                    # per-query pool instead of stacking
                    if not recovery.retry_allowed("scheduler.admit"):
                        raise recovery.RetryBudgetExhausted(
                            "scheduler.admit",
                            recovery.current_budget()) from e
                    continue
                raise
        raise RuntimeError(
            f"scheduler.admit failed {attempts} consecutive attempts "
            f"(last: {last!r})") from last

    def _gate_wait(self, t: QueryTicket) -> None:
        """Block until this ticket is the policy-best gate waiter AND
        its estimate fits the budget; acquires the admission charge."""
        with self._cond:
            self._gate.append(t)
            try:
                while True:
                    t.check_cancelled()
                    if self._stopped:
                        raise QueryCancelled(
                            f"query {t.id} cancelled: scheduler stopped")
                    if (self._gate_best_locked() is t
                            and self.admission.fits(t.est_bytes)):
                        t._charge = self.admission.acquire(t.est_bytes)
                        t._granted = True
                        self.pools.get(t.pool).device_running += 1
                        t._gate_t0 = time.perf_counter()
                        return
                    # notify-driven: grant releases (scheduler's own
                    # _release and, via the manager's release listener,
                    # any other tenant's), cancel and stop all notify.
                    # The timeout is a deadline/liveness backstop only.
                    timeout = 0.5
                    if t.deadline is not None:
                        timeout = min(
                            timeout, max(0.01, t.deadline - time.time()))
                    self._cond.wait(timeout)
            finally:
                self._gate.remove(t)
                # the policy-best waiter changed: wake the others so
                # the new best re-checks its fit without polling
                self._cond.notify_all()

    def _wake_gate(self) -> None:
        """Release-listener target: an execution grant somewhere on the
        shared memory manager was released, so a gate waiter may fit."""
        with self._cond:
            self._cond.notify_all()

    def _release(self, t: QueryTicket) -> None:
        if t._granted:
            self.admission.release(t._charge)
            t._charge = 0
            t._granted = False
            elapsed_ms = (time.perf_counter() - t._gate_t0) * 1e3
            t.device_ms += elapsed_ms
            with self._cond:
                p = self.pools.get(t.pool)
                p.device_ms += elapsed_ms
                p.device_running -= 1

    # -- lifecycle end -------------------------------------------------------

    def _finish(self, t: QueryTicket, state: str, result=None,
                error: Optional[BaseException] = None) -> None:
        self._release(t)
        with self._cond:
            self._finish_locked(t, state, result=result, error=error)

    def _finish_locked(self, t: QueryTicket, state: str, result=None,
                       error: Optional[BaseException] = None) -> None:
        if t.done():
            return
        if t._slo_picked:
            t._slo_picked = False
            self._active_runs -= 1
        t.state = state
        t._result = result
        t.error = error
        t.finished_t = time.time()
        p = self.pools.get(t.pool)
        p.running -= 1
        if state == FINISHED:
            p.finished += 1
        metrics.record("scheduler", phase=state.lower(), id=t.id,
                       pool=t.pool,
                       queue_wait_ms=round(t.queue_wait_ms(), 2),
                       device_ms=round(t.device_ms, 2),
                       error=repr(error) if error is not None else None)
        t._done.set()

    # -- shutdown ------------------------------------------------------------

    def stop(self, timeout: float = 5.0) -> None:
        """Stop workers; queued queries finish CANCELLED, running ones
        are flagged and joined briefly (daemon threads — a wedged query
        cannot wedge interpreter exit)."""
        with self._cond:
            self._stopped = True
            for p in self.pools.all():
                while p.queue:
                    t = p.queue.popleft()
                    self._queued -= 1
                    self._finish_locked(
                        t, CANCELLED,
                        error=QueryCancelled(
                            f"query {t.id} cancelled: scheduler stopped"))
            for t in self._recent:
                if not t.done():
                    t._cancel.set()
            self._cond.notify_all()
        deadline = time.time() + timeout
        for w in self._workers:
            w.join(max(0.0, deadline - time.time()))

"""HBM admission control: a shared device-bytes budget that decides
*when* a query may touch the device and under *what* memory budget.

The footprint estimate reuses the cost model the join reorderer already
trusts (plan/join_reorder.estimate_rows — exact at Parquet/batch
leaves, heuristic above) times the schema row width, taken as the MAX
over plan nodes: the widest intermediate a plan materializes is what
actually presses HBM, not its (often tiny, post-aggregate) output.

Admission is deliberately optimistic at the edges, mirroring the
chunk pipeline's prefetch cap (conf.PREFETCH_BYTES_MAX): a query larger
than the whole budget is still admitted when the device is otherwise
idle — charged the full budget so nothing else co-runs — and relies on
the existing chunked/OOM-degradation ladder
(recovery.run_plan_with_oom_degradation) to survive. Refusing it
outright would make over-budget queries unservable even on an idle
device.
"""

from __future__ import annotations

import threading

from spark_tpu import locks

from spark_tpu import conf as CF

#: floor on any footprint estimate — below this the estimate noise
#: exceeds the signal and admission decisions would thrash
MIN_ESTIMATE_BYTES = 64 * 1024

#: measured stage footprints from prior executions, keyed by the
#: logical plan's injective structural_key() (adaptive execution's
#: answer to "use measured, not static, plan bytes once stats exist":
#: DataFrame._execute notes the max stage_bytes event of each finished
#: query here; estimate_plan_bytes prefers a recorded measurement over
#: the static row-count estimate). Bounded LRU under a lock —
#: structural keys pin source objects by id, so unbounded growth would
#: also pin dead batches.
_MEASURED_LOCK = locks.named_lock("admission.measured")
_MEASURED_MAX_ENTRIES = 512
_MEASURED: "dict" = {}


def note_measured_bytes(plan, nbytes: int) -> None:
    """Record the measured peak stage footprint of an executed logical
    plan (no-op when the key cannot be computed or the value is
    non-positive)."""
    if nbytes <= 0:
        return
    try:
        key = plan.structural_key()
    except Exception:
        return
    with _MEASURED_LOCK:
        # re-insertion moves the key to the back of the dict (LRU-ish:
        # python dicts preserve insertion order)
        prev = _MEASURED.pop(key, 0)
        _MEASURED[key] = max(int(nbytes), prev)
        while len(_MEASURED) > _MEASURED_MAX_ENTRIES:
            _MEASURED.pop(next(iter(_MEASURED)))


def measured_plan_bytes(plan):
    """The recorded measurement for this plan shape, or None."""
    try:
        key = plan.structural_key()
    except Exception:
        return None
    with _MEASURED_LOCK:
        return _MEASURED.get(key)


def measured_snapshot() -> dict:
    """Size/total of the measured-footprint table — after a pre-warm
    replay (compile/service) this is populated before the first client
    query, so admission decisions start from measured bytes instead of
    static estimates; the compile service surfaces it in status()."""
    with _MEASURED_LOCK:
        return {"plans": len(_MEASURED),
                "max_bytes": max(_MEASURED.values(), default=0)}


def estimate_plan_bytes(plan, conf) -> int:
    """Estimated device footprint of executing ``plan``: a MEASURED
    peak stage footprint from a prior run of the same plan shape when
    one exists (note_measured_bytes), else max over plan nodes of
    estimated rows x 8-byte columns (x64 engine). Falls back to the
    device batch budget when estimation fails — unknown plans admit
    serially rather than stampeding HBM."""
    from spark_tpu.physical.chunked import MAX_DEVICE_BATCH_BYTES

    measured = measured_plan_bytes(plan)
    if measured is not None:
        return max(MIN_ESTIMATE_BYTES, int(measured))
    try:
        from spark_tpu.plan.join_reorder import estimate_rows

        def node_bytes(node) -> float:
            try:
                width = 8 * max(1, len(node.schema.names))
            except Exception:
                width = 8
            own = estimate_rows(node) * width
            return max([own] + [node_bytes(c) for c in node.children()])

        est = int(node_bytes(plan))
    except Exception:
        est = int(conf.get(MAX_DEVICE_BATCH_BYTES))
    return max(MIN_ESTIMATE_BYTES, est)


def seeded_build_bytes(plan, fallback: int) -> int:
    """Grant request for the hybrid hash join's build staging: the
    MEASURED peak footprint of this plan shape when a prior run (AQE)
    recorded one, else the planner's static estimate passed as
    ``fallback``. Deliberately does NOT fall through to the device
    batch budget the way estimate_plan_bytes does — an unknown join
    should request what the planner believes, not a 5 GiB default that
    would evict the whole cache for nothing."""
    measured = measured_plan_bytes(plan)
    if measured is not None and measured > 0:
        return max(MIN_ESTIMATE_BYTES, int(measured))
    return max(MIN_ESTIMATE_BYTES, int(fallback))


class AdmissionController:
    """Byte-budget gate over the EXECUTION side of the unified
    storage/execution memory manager (storage/unified.py — the
    UnifiedMemoryManager analogue). When the serving session holds an
    HBM-resident MemoryStore, admission and cached storage share one
    budget: an admission that does not fit first evicts unpinned cached
    batches down to the protected ``spark.tpu.storage.minBytes``
    region. ``fits``/``acquire`` are lock-protected; the scheduler
    holds its own condition around them, so the controller itself never
    blocks."""

    def __init__(self, budget_bytes: int, manager=None):
        from spark_tpu.storage.unified import UnifiedMemoryManager

        self._m = manager if manager is not None \
            else UnifiedMemoryManager(budget_bytes)

    @property
    def budget(self) -> int:
        return self._m.budget

    @property
    def manager(self):
        """The shared UnifiedMemoryManager (storage attaches here)."""
        return self._m

    def charge_for(self, nbytes: int) -> int:
        """What an admission of ``nbytes`` costs: capped at the whole
        budget so an over-budget query can still admit alone."""
        return self._m.charge_for(nbytes)

    def fits(self, nbytes: int) -> bool:
        return self._m.fits_execution(nbytes)

    def acquire(self, nbytes: int) -> int:
        """Charge the budget (evicting unpinned storage if needed);
        returns the charge to pass to release(). Caller must have
        checked fits() under the scheduler lock."""
        return self._m.acquire_execution(nbytes)

    def release(self, charge: int) -> None:
        self._m.release_execution(charge)

    def snapshot(self) -> dict:
        return self._m.snapshot()

"""Multi-tenant query scheduler (reference: core/.../scheduler/
TaskSchedulerImpl.scala + Pool.scala, lifted from task level to query
level): fair scheduling pools, HBM admission control, and concurrent
serving for the connect server.

The subsystem has three parts:

- ``pool``       FIFO / weighted-fair pools configured via
                 ``spark.scheduler.mode`` and
                 ``spark.tpu.scheduler.pool.<name>.{weight,minShare}``
- ``admission``  a shared device-bytes budget; queries are admitted to
                 device execution only while their estimated HBM
                 footprints fit (over-budget queries admit alone and
                 lean on the chunked/OOM-degradation ladder)
- ``scheduler``  the query lifecycle (QUEUED -> ADMITTED -> RUNNING ->
                 FINISHED/FAILED/CANCELLED), a host-side worker pool,
                 cancellation, deadlines, and per-query metrics
"""

from spark_tpu.scheduler.admission import (AdmissionController,
                                           estimate_plan_bytes)
from spark_tpu.scheduler.pool import Pool, build_pools
from spark_tpu.scheduler.scheduler import (QueryCancelled, QueryScheduler,
                                           QueryTicket, SchedulerQueueFull)

__all__ = [
    "AdmissionController",
    "estimate_plan_bytes",
    "Pool",
    "build_pools",
    "QueryCancelled",
    "QueryScheduler",
    "QueryTicket",
    "SchedulerQueueFull",
]

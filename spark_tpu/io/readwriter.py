"""DataFrameReader / DataFrameWriter — the spark.read / df.write surface
(reference: sql/core/.../DataFrameReader.scala, DataFrameWriter.scala,
FileFormatWriter.scala:1; python python/pyspark/sql/readwriter.py).

Reads go through io.datasource.FileSource (pyarrow.dataset). Writes
materialize the query to Arrow and emit Spark-shaped output: a DIRECTORY
of part files (so outputs are re-readable by this reader and by Spark),
with Spark's save modes and hive-style partitionBy.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

import pyarrow as pa

from spark_tpu.plan import logical as L
from spark_tpu.types import Schema


class DataFrameReader:
    def __init__(self, session):
        self._session = session
        self._format = "parquet"
        self._schema: Optional[Schema] = None
        self._options: Dict[str, Any] = {}

    def format(self, fmt: str) -> "DataFrameReader":  # noqa: A003
        self._format = fmt
        return self

    def schema(self, schema: Union[Schema, str]) -> "DataFrameReader":
        if isinstance(schema, str):
            from spark_tpu.sql.ddl import parse_ddl_schema

            schema = parse_ddl_schema(schema)
        self._schema = schema
        return self

    def option(self, key: str, value: Any) -> "DataFrameReader":
        self._options[key] = value
        return self

    def options(self, **opts: Any) -> "DataFrameReader":
        self._options.update(opts)
        return self

    def load(self, path: Union[str, Sequence[str]],
             format: Optional[str] = None,  # noqa: A002
             schema: Optional[Union[Schema, str]] = None,
             **options: Any):
        from spark_tpu.api.dataframe import DataFrame
        from spark_tpu.io.datasource import FileSource

        if format is not None:
            self._format = format
        if schema is not None:
            self.schema(schema)
        self._options.update(options)
        paths = [path] if isinstance(path, str) else list(path)
        source = FileSource(self._format, paths, self._schema, self._options)
        return DataFrame(self._session, L.UnresolvedScan(source))

    def parquet(self, *paths: str):
        self._format = "parquet"
        return self.load(list(paths) if len(paths) > 1 else paths[0])

    def csv(self, path: Union[str, Sequence[str]],
            schema: Optional[Union[Schema, str]] = None,
            **options: Any):
        self._format = "csv"
        return self.load(path, schema=schema, **options)

    def orc(self, *paths: str):
        self._format = "orc"
        return self.load(list(paths) if len(paths) > 1 else paths[0])

    def json(self, path: Union[str, Sequence[str]], **options: Any):
        self._format = "json"
        return self.load(path, **options)

    def table(self, name: str):
        return self._session.table(name)


class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._format = "parquet"
        self._mode = "error"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def format(self, fmt: str) -> "DataFrameWriter":  # noqa: A003
        self._format = fmt
        return self

    def mode(self, mode: str) -> "DataFrameWriter":
        aliases = {"errorifexists": "error", "default": "error"}
        mode = aliases.get(mode.lower(), mode.lower())
        if mode not in ("error", "overwrite", "append", "ignore"):
            raise ValueError(f"unknown save mode {mode!r}")
        self._mode = mode
        return self

    def option(self, key: str, value: Any) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def options(self, **opts: Any) -> "DataFrameWriter":
        self._options.update(opts)
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = [c for group in cols
                              for c in (group if isinstance(group, (list, tuple))
                                        else [group])]
        return self

    # -- terminal actions ----------------------------------------------------

    def save(self, path: str, format: Optional[str] = None,  # noqa: A002
             mode: Optional[str] = None, **options: Any) -> None:
        if format is not None:
            self._format = format
        if mode is not None:
            self.mode(mode)
        self._options.update(options)

        exists = os.path.exists(path)
        if exists:
            if self._mode == "error":
                raise FileExistsError(
                    f"path {path} already exists (mode=error; use "
                    "mode('overwrite') or mode('append'))")
            if self._mode == "ignore":
                return
            if self._mode == "overwrite":
                shutil.rmtree(path) if os.path.isdir(path) else os.remove(path)

        table = self._df.toArrow()
        self._write_table(table, path)

    def saveAsTable(self, name: str) -> None:
        """Persistent table under spark.sql.warehouse.dir (reference:
        DataFrameWriter.saveAsTable + the session catalog's persistent
        tier, SessionCatalog.scala:61): data as parquet + a metadata
        JSON recording the format, re-registered on lookup by any later
        session pointing at the same warehouse."""
        import json

        from spark_tpu import conf as CF

        session = self._df._session
        wh = session.conf.get(CF.WAREHOUSE_DIR)
        os.makedirs(wh, exist_ok=True)
        path = os.path.join(wh, name.lower())
        self.save(os.path.join(path, "data"))
        meta = {"name": name.lower(), "format": self._format,
                "partition_by": self._partition_by,
                "options": {k: str(v) for k, v in self._options.items()}}
        with open(os.path.join(path, "_table.json"), "w") as f:
            json.dump(meta, f)
        session.catalog.refresh_persistent(name.lower())

    def _write_table(self, table: pa.Table, path: str) -> None:
        import pyarrow.dataset as pads

        part_id = uuid.uuid4().hex[:8]
        basename = "part-{{i}}-{0}.{1}".format(part_id, self._format)
        fmt: Any = self._format
        write_opts = None
        if self._format == "csv":
            import pyarrow.csv as pacsv

            header = str(self._options.get("header", "true")).lower() == "true"
            delim = self._options.get(
                "sep", self._options.get("delimiter", ","))
            fmt = pads.CsvFileFormat(
                parse_options=pacsv.ParseOptions(delimiter=delim))
            write_opts = fmt.make_write_options(
                include_header=header, delimiter=delim)
        elif self._format == "json":
            # pyarrow.dataset cannot write json; emit one ndjson part
            os.makedirs(path, exist_ok=True)
            fname = os.path.join(path, f"part-00000-{part_id}.json")
            table.to_pandas().to_json(fname, orient="records", lines=True,
                                      date_format="iso")
            return
        elif self._format == "orc":
            # pyarrow.dataset cannot WRITE orc; use the direct writer
            if self._partition_by:
                raise NotImplementedError(
                    "partitionBy with the ORC writer is not supported "
                    "(pyarrow's dataset writer has no ORC output); use "
                    "parquet for partitioned layouts")
            from pyarrow import orc as paorc

            os.makedirs(path, exist_ok=True)
            fname = os.path.join(path, f"part-00000-{part_id}.orc")
            paorc.write_table(table, fname)
            return
        pads.write_dataset(
            table, path, format=fmt,
            file_options=write_opts,
            basename_template=basename,
            partitioning=(pads.partitioning(
                pa.schema([table.schema.field(c)
                           for c in self._partition_by]), flavor="hive")
                          if self._partition_by else None),
            existing_data_behavior="overwrite_or_ignore")

    def parquet(self, path: str, mode: Optional[str] = None) -> None:
        self.save(path, format="parquet", mode=mode)

    def csv(self, path: str, mode: Optional[str] = None,
            **options: Any) -> None:
        self.save(path, format="csv", mode=mode, **options)

    def json(self, path: str, mode: Optional[str] = None) -> None:
        self.save(path, format="json", mode=mode)

    def orc(self, path: str, mode: Optional[str] = None) -> None:
        self.save(path, format="orc", mode=mode)



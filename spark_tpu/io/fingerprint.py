"""Shared scan-source freshness fingerprints.

One (path, mtime_ns, size) walk used by every layer that must decide
"are these files still the bytes I computed from?": the datasource's
own batch/auto-cache invalidation (io/datasource.FileSource), the
serve-tier result cache key (serve/result_cache.plan_result_key), and
the materialized-view delta detector (spark_tpu/mview/). Before this
module each of those carried its own copy of the stat walk, so an
invalidation bug could exist in exactly one of them; now the walk,
the per-plan collection, and the append-vs-rewrite classification are
defined once.

Fingerprints are plain tuples of ``(path, mtime_ns, size)`` triples in
a deterministic order (sorted directory walks, path order as given),
so tuple equality IS freshness equality and the tuples embed directly
into cache keys.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

#: one stat triple: (absolute path, st_mtime_ns, st_size)
StatTriple = Tuple[str, int, int]


def stat_paths(paths: Sequence[str]) -> Tuple[StatTriple, ...]:
    """Stat every file under ``paths`` (directories walk recursively,
    files sorted per directory so the order is deterministic across
    runs); unreadable entries are skipped — a vanished file simply
    changes the fingerprint, which is the invalidation we want."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    fp = os.path.join(root, f)
                    try:
                        st = os.stat(fp)
                        out.append((fp, st.st_mtime_ns, st.st_size))
                    except OSError:
                        pass
        else:
            try:
                st = os.stat(p)
                out.append((p, st.st_mtime_ns, st.st_size))
            except OSError:
                pass
    return tuple(out)


def source_fingerprint(source) -> Optional[Tuple[StatTriple, ...]]:
    """Fingerprint of one datasource object, None when the source has
    no file identity (in-memory relations, streaming sources)."""
    fpf = getattr(source, "_fingerprint", None)
    if not callable(fpf):
        return None
    try:
        return fpf()
    except Exception:
        return None


def plan_fingerprints(plan) -> Tuple[Any, ...]:
    """Freshness token over every scan source in ``plan``, in plan
    order. Sources without a file fingerprint key by object identity —
    which the structural plan key already embeds, so pairing this tuple
    with ``structural_key()`` stays injective."""
    from spark_tpu.plan import logical as L

    out = []
    for scan in L.collect_nodes(plan, L.UnresolvedScan):
        fp = source_fingerprint(scan.source)
        out.append(fp if fp is not None else ("src", id(scan.source)))
    return tuple(out)


def classify_delta(old: Tuple[StatTriple, ...],
                   new: Tuple[StatTriple, ...]):
    """Classify how a source moved from fingerprint ``old`` to ``new``:

    - ``("unchanged", ())``      identical fingerprints
    - ``("appended", added)``     every old file survives byte-identical
                                  and only new files appeared — the
                                  incremental-merge case; ``added`` is
                                  the new paths in fingerprint order
    - ``("changed", ())``        anything else (rewrite, truncation,
                                  deletion, mtime bump) — only a full
                                  recompute is sound
    """
    if old == new:
        return "unchanged", ()
    old_map = {p: (m, s) for p, m, s in old}
    new_map = {p: (m, s) for p, m, s in new}
    for p, stat in old_map.items():
        if new_map.get(p) != stat:
            return "changed", ()
    added = tuple(p for p, _, _ in new if p not in old_map)
    if not added:
        # same paths, different order (should not happen with the
        # deterministic walk, but never merge on a guess)
        return "changed", ()
    return "appended", added

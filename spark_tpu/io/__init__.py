"""File datasources: readers, writers, scan planning.

The analogue of the reference's datasource layer (reference:
sql/core/.../execution/datasources/ — DataSource.scala, FileFormat.scala,
FileSourceStrategy.scala, DataSourceScanExec.scala:506) collapsed onto
pyarrow.dataset: host-side async columnar decode feeds Arrow batches to
the device via columnar/arrow.py.
"""

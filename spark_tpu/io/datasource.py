"""File scan source + pushdown translation.

Role of the reference's FileSourceScanExec + format readers (reference:
sql/core/.../execution/DataSourceScanExec.scala:506,
datasources/parquet/VectorizedParquetRecordReader.java:1,
FileSourceStrategy.scala:1). The TPU build replaces the JVM vectorized
decoders with pyarrow.dataset (multi-file scans, hive partition
discovery, column projection, predicate-based file/row-group pruning and
exact row filtering), then ships Arrow columns to device HBM through
columnar/arrow.from_arrow.

Pushdown surface (DSv2 SupportsPushDownFilters/RequiredColumns analogue):
the optimizer calls ``translate_filters`` to split a predicate into a
pyarrow dataset expression (pushed — pruned at the file/row-group level
AND applied exactly by the scan) and a residual kept in the plan.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.dataset as pads

from spark_tpu import types as T
from spark_tpu.columnar.batch import Batch
from spark_tpu.expr import expressions as E
from spark_tpu.types import Field, Schema


def _pa_schema_from_schema(schema: Schema) -> pa.Schema:
    from spark_tpu.columnar.arrow import dtype_to_arrow_type

    return pa.schema([
        pa.field(f.name, dtype_to_arrow_type(f.dtype), nullable=f.nullable)
        for f in schema.fields
    ])


def _schema_from_pa(pa_schema: pa.Schema) -> Schema:
    from spark_tpu.columnar.arrow import arrow_type_to_dtype

    return Schema(tuple(
        Field(f.name, arrow_type_to_dtype(f.type), nullable=f.nullable)
        for f in pa_schema
    ))


# ---- predicate translation --------------------------------------------------


class _Untranslatable(Exception):
    pass


def _literal_value(e: E.Expression):
    if isinstance(e, E.Literal):
        return e.value
    raise _Untranslatable


def _coerce_literal(v, col_name: str, dtypes):
    """Adapt a python literal to the column's storage type for pyarrow:
    a float literal against a DECIMAL column must become a Decimal
    scalar (arrow refuses decimal-vs-double comparisons: 'Precision is
    not great enough'). str(float) round-trips the short literals SQL
    texts contain, so 0.05 means exactly 0.05."""
    if dtypes is None or not isinstance(v, (int, float)):
        return v
    dt = dtypes.get(col_name)
    if isinstance(dt, T.DecimalType):
        import decimal

        return decimal.Decimal(str(v))
    return v


def _translate(e: E.Expression, dtypes=None) -> "pads.Expression":
    """Our Expression -> pyarrow.dataset Expression; raises
    _Untranslatable for anything the scan layer cannot evaluate.
    ``dtypes`` ({col: DataType}, optional) enables storage-aware literal
    coercion at actual read time."""
    import pyarrow.compute as pc

    if isinstance(e, E.Cmp):
        if isinstance(e.left, E.Col):
            name, v, op = e.left.col_name, _literal_value(e.right), e.op
        elif isinstance(e.right, E.Col):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
            name, v = e.right.col_name, _literal_value(e.left)
            op = flip.get(e.op, e.op)
        else:
            raise _Untranslatable
        if v is None:
            raise _Untranslatable
        f = pc.field(name)
        v = _coerce_literal(v, name, dtypes)
        return {"==": f == v, "!=": f != v, "<": f < v,
                "<=": f <= v, ">": f > v, ">=": f >= v}[op]
    if isinstance(e, E.In) and isinstance(e.child, E.Col):
        if any(v is None for v in e.values):
            raise _Untranslatable
        vals = [_coerce_literal(v, e.child.col_name, dtypes)
                for v in e.values]
        return pc.field(e.child.col_name).isin(vals)
    if isinstance(e, E.IsNull) and isinstance(e.child, E.Col):
        return pc.field(e.child.col_name).is_null()
    if isinstance(e, E.Not):
        inner = e.child
        if isinstance(inner, E.IsNull) and isinstance(inner.child, E.Col):
            return ~pc.field(inner.child.col_name).is_null()
        return ~_translate(inner, dtypes)
    if isinstance(e, E.And):
        return _translate(e.left, dtypes) & _translate(e.right, dtypes)
    if isinstance(e, E.Or):
        return _translate(e.left, dtypes) | _translate(e.right, dtypes)
    raise _Untranslatable


def translate_filters(
    conjuncts: Sequence[E.Expression],
) -> Tuple[List[E.Expression], List[E.Expression]]:
    """Split conjuncts into (pushable, residual). A conjunct is pushable
    when ``_translate`` fully understands it."""
    pushed: List[E.Expression] = []
    residual: List[E.Expression] = []
    for c in conjuncts:
        try:
            _translate(c)
            pushed.append(c)
        except _Untranslatable:
            residual.append(c)
    return pushed, residual


def _filters_to_pads(
    filters: Tuple[E.Expression, ...],
    dtypes=None,
) -> Optional["pads.Expression"]:
    if not filters:
        return None
    out = _translate(filters[0], dtypes)
    for c in filters[1:]:
        out = out & _translate(c, dtypes)
    return out


# ---- the source -------------------------------------------------------------


class FileSource:
    """A lazily-opened multi-file scan (one table = one source).

    ``fmt`` is 'parquet' | 'csv' | 'json'. Hive-style partition
    directories are auto-discovered for parquet (partition columns become
    ordinary columns and participate in predicate pushdown = partition
    pruning, reference: PartitioningUtils.scala / PartitioningAwareFileIndex).
    """

    def __init__(self, fmt: str, paths: Sequence[str],
                 schema: Optional[Schema] = None,
                 options: Optional[Dict[str, Any]] = None):
        self.fmt = fmt
        self.paths = list(paths)
        self._schema = schema
        self.options = dict(options or {})
        self._dataset: Optional[pads.Dataset] = None
        self._cache: Dict[tuple, Batch] = {}
        self._count_cache: Dict[tuple, int] = {}
        #: per-(columns, filters) materialization counts, driving
        #: auto-cache promotion into the session MemoryStore
        self._read_counts: Dict[tuple, int] = {}

    # -- dataset / schema ----------------------------------------------------

    def _fingerprint(self) -> tuple:
        """Freshness token over the underlying files ((path, mtime_ns,
        size) tuples) so a re-read after a rewrite never serves stale
        cached batches, and the memoized pyarrow dataset (which pins its
        discovered file list) is rebuilt (round-2 advisor finding).
        The walk itself is shared with the serve result cache and the
        materialized-view delta detector (io/fingerprint.py) so all
        three invalidate identically."""
        from spark_tpu.io.fingerprint import stat_paths

        return stat_paths(self.paths)

    def _broadcast_change(self) -> None:
        """A rewrite/append was just DETECTED on this source: append a
        ``source_changed`` record to the active session's fleet
        invalidation log (if one exists) so every replica's TTL'd
        fingerprint probe and cached results for these paths drop now
        instead of waiting out the TTL. Strictly best-effort — reads
        never depend on it."""
        try:
            from spark_tpu.api.session import SparkSession

            sess = SparkSession.getActiveSession()
            log = getattr(sess, "serve_invalidation_log", None) \
                if sess is not None else None
            if log is not None:
                log.append("source_changed", self.paths)
        except Exception:
            pass

    def _open(self) -> pads.Dataset:
        fp = self._fingerprint()
        if getattr(self, "_fp", None) != fp:
            # underlying files changed: drop dataset + batch/count caches
            # (store entries key on the fingerprint, so they simply
            # stop matching and age out LRU)
            first = not hasattr(self, "_fp")
            self._dataset = None
            self._cache.clear()
            self._count_cache.clear()
            self._read_counts.clear()
            self._fp = fp
            if not first:
                self._broadcast_change()
        if self._dataset is not None:
            return self._dataset
        kwargs: Dict[str, Any] = {}
        if self.fmt == "parquet":
            kwargs["format"] = "parquet"
            kwargs["partitioning"] = "hive"
        elif self.fmt == "csv":
            import pyarrow.csv as pacsv

            header = str(self.options.get("header", "true")).lower() == "true"
            delim = self.options.get("sep", self.options.get("delimiter", ","))
            read_opts = {}
            if not header:
                if self._schema is not None:
                    # real names up front so projection/predicate pushdown
                    # and column_types see the declared schema
                    read_opts["column_names"] = list(self._schema.names)
                else:
                    read_opts["autogenerate_column_names"] = True
            parse_opts = pacsv.ParseOptions(delimiter=delim)
            convert = {}
            if self._schema is not None:
                convert["column_types"] = {
                    f.name: _pa_schema_from_schema(
                        Schema((f,)))[0].type
                    for f in self._schema.fields}
            fmt = pads.CsvFileFormat(
                parse_options=parse_opts,
                read_options=pacsv.ReadOptions(**read_opts),
                convert_options=pacsv.ConvertOptions(**convert)
                if convert else None)
            kwargs["format"] = fmt
            if str(self.options.get("partitioning", "")) == "hive":
                kwargs["partitioning"] = "hive"
        elif self.fmt == "json":
            kwargs["format"] = "json"
            if str(self.options.get("partitioning", "")) == "hive":
                kwargs["partitioning"] = "hive"
        elif self.fmt == "orc":
            # pyarrow's C++ ORC reader — the vectorized-decoder tier the
            # reference reaches via Java ORC (OrcColumnarBatchReader)
            kwargs["format"] = "orc"
            kwargs["partitioning"] = "hive"
        else:
            raise ValueError(f"unsupported format {self.fmt!r}")
        if self._schema is not None and self.fmt == "parquet":
            kwargs["schema"] = _pa_schema_from_schema(self._schema)
        # pyarrow accepts a directory only as a scalar path, not in a list
        src = self.paths[0] if len(self.paths) == 1 else self.paths
        self._dataset = pads.dataset(src, **kwargs)
        return self._dataset

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            self._schema = _schema_from_pa(self._open().schema)
        return self._schema

    def _dtypes(self) -> Dict[str, Any]:
        """{column: engine DataType} for storage-aware literal coercion
        in pushed filters (decimal columns vs float literals)."""
        return {f.name: f.dtype for f in self.schema.fields}

    # -- scanning ------------------------------------------------------------

    def _session_store(self):
        """(MemoryStore, auto-cache threshold) of the active session;
        (None, 0) outside a session or with auto-caching disabled."""
        from spark_tpu.api.session import SparkSession

        sess = SparkSession.getActiveSession()
        store = getattr(sess, "memory_store", None) if sess else None
        if store is None:
            return None, 0
        from spark_tpu import conf as CF

        try:
            thr = int(sess.conf.get(CF.STORAGE_AUTOCACHE_THRESHOLD))
        except Exception:
            thr = 0
        return (store, thr) if thr > 0 else (None, 0)

    def _store_key(self, key) -> tuple:
        # fingerprint in the key: a rewritten file misses naturally and
        # the stale entry ages out LRU
        return ("scan", self.fmt, tuple(self.paths), self._fp, key)

    def read(self, columns: Optional[Tuple[str, ...]] = None,
             filters: Tuple[E.Expression, ...] = ()) -> Batch:
        """Materialize the scan to a device Batch, reading only
        ``columns`` and pruning/filtering by ``filters`` (exact).

        Hot scans are auto-cached: once the same (columns, filters)
        projection has materialized ``spark.tpu.storage.autoCacheThreshold``
        times, its device batch is promoted into the session's
        HBM-resident MemoryStore (byte-accounted, LRU-evictable, pinned
        while the running query reads it), and repeat queries skip
        parquet decode + dictionary encode + host->device transfer."""
        import time as _time

        from spark_tpu import metrics
        from spark_tpu.columnar.arrow import from_arrow

        ds = self._open()  # first: freshness check may clear the cache
        key = (columns, tuple(E.expr_key(f) for f in filters))
        self._read_counts[key] = self._read_counts.get(key, 0) + 1
        store, threshold = self._session_store()
        skey = self._store_key(key) if store is not None else None
        if store is not None:
            hit = store.get(skey, pin=True)
            if hit is not None:
                return hit
        # auto-cache promotion is optional work: under fleet brownout
        # the scan still serves (and store hits above still hit), it
        # just stops PROMOTING new entries into HBM
        hot = (store is not None
               and self._read_counts[key] >= threshold
               and metrics.brownout_level() == 0)
        hit = self._cache.get(key)
        if hit is not None:
            self._cache[key] = self._cache.pop(key)  # LRU touch
            if hot and store.put(skey, hit, pin=True):
                self._cache.pop(key, None)  # now owned by the store
            return hit
        t0 = _time.perf_counter()
        table = ds.to_table(
            columns=list(columns) if columns is not None else None,
            filter=_filters_to_pads(filters, self._dtypes()))
        t1 = _time.perf_counter()
        batch = from_arrow(table)  # dict-encode + host->device transfer
        t2 = _time.perf_counter()
        metrics.record("scan", fmt=self.fmt, rows=table.num_rows,
                       decode_ms=round((t1 - t0) * 1e3, 2),
                       transfer_ms=round((t2 - t1) * 1e3, 2))
        if hot and store.put(skey, batch, pin=True):
            return batch
        # bounded LRU: parameterized pushed filters must not pin an
        # unbounded number of device-resident batches
        while len(self._cache) >= 4:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = batch
        return batch

    def count_rows(self, filters: Tuple[E.Expression, ...] = ()) -> int:
        """Row count without materializing (drives the out-of-HBM
        chunking decision). Memoized per filter set — the decision runs
        on every execution of an aggregate-over-scan query."""
        ds = self._open()  # freshness check may clear the count cache
        key = tuple(E.expr_key(f) for f in filters)
        hit = self._count_cache.get(key)
        if hit is None:
            hit = ds.count_rows(
                filter=_filters_to_pads(filters, self._dtypes()))
            self._count_cache[key] = hit
        return hit

    def iter_batches(self, columns: Optional[Tuple[str, ...]] = None,
                     filters: Tuple[E.Expression, ...] = (),
                     rows_per_chunk: int = 1 << 20):
        """Stream the scan as bounded arrow tables WITHOUT materializing
        the whole dataset — host RAM is the staging tier for
        larger-than-HBM execution (reference spill analogue:
        ExternalSorter.scala:93; here the data never needed to be
        device-resident in the first place)."""
        import pyarrow as pa

        ds = self._open()
        pending: list = []
        n = 0
        for rb in ds.to_batches(
                columns=list(columns) if columns is not None else None,
                filter=_filters_to_pads(filters, self._dtypes()),
                batch_size=rows_per_chunk):
            if rb.num_rows == 0:
                continue
            pending.append(rb)
            n += rb.num_rows
            while n >= rows_per_chunk:
                # emit EXACTLY rows_per_chunk rows (remainder carries
                # over): every chunk then pads to ONE static capacity,
                # so the whole stream reuses a single compiled program
                # — varying chunk sizes meant a fresh XLA compile per
                # chunk (~minutes each on TPU at SF100)
                tbl = pa.Table.from_batches(pending)
                yield tbl.slice(0, rows_per_chunk)
                rest = tbl.slice(rows_per_chunk)
                pending = rest.to_batches() if rest.num_rows else []
                n = rest.num_rows
        if pending:
            yield pa.Table.from_batches(pending)

    def __repr__(self):
        return f"{self.fmt}:{','.join(self.paths)}"

"""Expression -> JAX compiler.

This is where the reference's two evaluation paths collapse into one:
interpreted eval + Janino whole-stage codegen (reference:
expressions/codegen/CodeGenerator.scala:1345,
WholeStageCodegenExec.scala:627) are replaced by tracing expressions
into jax ops and letting XLA fuse the pipeline. Null semantics follow
SQL three-valued logic, carried as (values, validity-mask) pairs.

String expressions never touch bytes on device: predicates/transforms
are evaluated host-side over the column dictionary at *trace time* and
become int32-code lookup-table gathers on device.
"""

from __future__ import annotations

import datetime
import fnmatch
import re
from typing import Dict, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.types import DataType


class TV(NamedTuple):
    """Typed value: device data + validity + host metadata.

    Array-typed TVs carry 2D ``data`` (capacity, max_len) plus per-row
    ``lengths``; at batch boundaries the lengths ride as a hidden
    '<col>#len' companion column (types.ArrayType)."""

    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # None = all valid
    dtype: DataType
    dictionary: Optional[Tuple[str, ...]] = None
    lengths: Optional[jnp.ndarray] = None  # int32[capacity], arrays only

    def valid_or_true(self, n: int) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((n,), dtype=jnp.bool_)
        return self.validity


class Env:
    """Column environment for evaluation: name -> TV, plus row count.
    ``mask`` (optional) is the live-row mask — host UDFs use it to show
    dead rows as NULL instead of leaking garbage slot values."""

    def __init__(self, columns: Dict[str, TV], capacity: int, mask=None):
        self.columns = columns
        self.capacity = capacity
        self.mask = mask

    @classmethod
    def from_batch(cls, batch) -> "Env":
        cols = {}
        fields = list(zip(batch.schema.fields, batch.data.columns))
        by_name = {f.name: cd for f, cd in fields}
        for f, cd in fields:
            lengths = None
            if isinstance(f.dtype, T.ArrayType):
                comp = by_name.get(T.array_len_col(f.name))
                lengths = None if comp is None else comp.data
            cols[f.name] = TV(cd.data, cd.validity, f.dtype,
                              f.dictionary, lengths)
        return cls(cols, batch.capacity)


def _and_validity(a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _jnp_dtype(dt: DataType):
    return jnp.dtype(dt.np_dtype)


def _round_half_up_div(data: jnp.ndarray, factor: int) -> jnp.ndarray:
    """Exact scaled-int scale-down with HALF_UP rounding (the
    reference's Decimal.changePrecision ROUND_HALF_UP default)."""
    half = factor // 2
    mag = (jnp.abs(data) + half) // factor
    return jnp.sign(data) * mag


def _float_to_scaled(data: jnp.ndarray, scale: int) -> jnp.ndarray:
    """float -> scaled int64 with HALF_UP rounding (jnp.round would be
    banker's HALF_EVEN, which diverges from the reference on .5s)."""
    scaled = data.astype(jnp.float64) * float(10 ** scale)
    return (jnp.sign(scaled)
            * jnp.floor(jnp.abs(scaled) + 0.5)).astype(jnp.int64)


def _cast_data(data: jnp.ndarray, src: DataType, dst: DataType) -> jnp.ndarray:
    sdec = isinstance(src, T.DecimalType)
    ddec = isinstance(dst, T.DecimalType)
    if sdec and ddec:
        if src.scale == dst.scale:
            return data
        if dst.scale > src.scale:
            return data * (10 ** (dst.scale - src.scale))
        return _round_half_up_div(data, 10 ** (src.scale - dst.scale))
    if sdec:
        if isinstance(dst, (T.Float32Type, T.Float64Type)):
            return (data.astype(jnp.float64)
                    / float(10 ** src.scale)).astype(_jnp_dtype(dst))
        # decimal -> integral truncates toward zero (Decimal.toLong)
        mag = jnp.abs(data) // (10 ** src.scale)
        return (jnp.sign(data) * mag).astype(_jnp_dtype(dst))
    if ddec:
        if src.is_integral or isinstance(src, T.BooleanType):
            return data.astype(jnp.int64) * (10 ** dst.scale)
        return _float_to_scaled(data, dst.scale)
    if type(src) is type(dst):
        return data
    return data.astype(_jnp_dtype(dst))


def _dict_table(dictionary: Tuple[str, ...], fn) -> np.ndarray:
    """Evaluate a python predicate/transform over a dictionary host-side."""
    return np.array([fn(s) for s in dictionary])


_NATIVE_DICT_MIN = 2048


def _use_native(dictionary) -> bool:
    """Large dictionaries route to the C++ kernels (spark_tpu/native):
    per-entry CPython overhead dominates above a few thousand entries —
    the TPC-H q13 comment column has ~1.5M distinct values at SF1."""
    if len(dictionary) < _NATIVE_DICT_MIN:
        return False
    from spark_tpu import native

    return native.available()


def _like_to_regex(pattern: str) -> "re.Pattern":
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def string_rank_table(dictionary: Tuple[str, ...]) -> np.ndarray:
    """rank[code] = lexicographic rank of dictionary[code]; used to give
    codes an order-preserving integer proxy (sorts, min/max, </> compares)."""
    order = sorted(range(len(dictionary)), key=lambda i: dictionary[i])
    rank = np.empty(len(dictionary), dtype=np.int32)
    for r, i in enumerate(order):
        rank[i] = r
    return rank


def unify_dictionaries(
    dicts: Tuple[Tuple[str, ...], ...]
) -> Tuple[Tuple[str, ...], Tuple[np.ndarray, ...]]:
    """Merge several dictionaries into one (sorted) dictionary; returns
    (union, per-input translation tables old_code -> new_code)."""
    union = sorted(set().union(*[set(d) for d in dicts]))
    pos = {s: i for i, s in enumerate(union)}
    tables = tuple(
        np.array([pos[s] for s in d], dtype=np.int32) if d else
        np.zeros((0,), dtype=np.int32)
        for d in dicts
    )
    return tuple(union), tables


def _literal_tv(value, dtype: DataType, n: int) -> TV:
    if value is None:
        data = jnp.zeros((n,), dtype=_jnp_dtype(dtype))
        return TV(data, jnp.zeros((n,), dtype=jnp.bool_), dtype, None)
    if isinstance(dtype, T.StringType):
        # single-entry dictionary
        return TV(jnp.zeros((n,), dtype=jnp.int32), None, dtype, (value,))
    if isinstance(dtype, T.DateType):
        value = T.date_to_days(value) if isinstance(value, datetime.date) else value
    if isinstance(dtype, T.TimestampType) and isinstance(value, datetime.datetime):
        value = int(value.timestamp() * 1_000_000)
    if isinstance(dtype, T.DecimalType):
        import decimal as _dec

        q = _dec.Decimal(str(value)).scaleb(dtype.scale)
        value = int(q.to_integral_value(rounding=_dec.ROUND_HALF_UP))
    data = jnp.full((n,), value, dtype=_jnp_dtype(dtype))
    return TV(data, None, dtype, None)


def _dict_product(name: str, tvs: List[TV], n: int, null_sentinel: bool,
                  join) -> TV:
    """Shared core of CONCAT/CONCAT_WS: cartesian dictionary product with
    mixed-radix code combination, then re-sort/dedup of the output
    dictionary. ``join`` maps one tuple of per-input dictionary entries
    (None = null when ``null_sentinel``) to an output string. With
    ``null_sentinel``, each nullable input's dictionary gains a trailing
    None entry its null rows are re-coded to."""
    for tv in tvs:
        if not isinstance(tv.dtype, T.StringType):
            raise NotImplementedError(f"{name} supports strings only")
    dicts = [tuple(tv.dictionary or ("",))
             + ((None,) if null_sentinel and tv.validity is not None
                else ())
             for tv in tvs]
    total = 1
    for d in dicts:
        total *= len(d)
    if total > (1 << 20):
        raise NotImplementedError(
            f"{name} dictionary product too large ({total})")
    combo: List[tuple] = [()]
    for d in dicts:
        combo = [t + (s,) for t in combo for s in d]
    joined = [join(t) for t in combo]
    new_dict = tuple(sorted(set(joined)))
    pos = {s: i for i, s in enumerate(new_dict)}
    remap = np.array([pos[s] for s in joined], dtype=np.int32)
    codes = jnp.zeros((n,), dtype=jnp.int32)
    for tv, d in zip(tvs, dicts):
        c = (tv.data if len(tv.dictionary or ())
             else jnp.zeros((n,), jnp.int32))
        if null_sentinel and tv.validity is not None:
            c = jnp.where(tv.validity, c, len(d) - 1)
        codes = codes * len(d) + c
    return TV(jnp.asarray(remap)[codes], None, T.STRING, new_dict)


def evaluate(expr: E.Expression, env: Env) -> TV:
    """Evaluate an expression to a TV. Called inside jit traces."""
    n = env.capacity

    if isinstance(expr, E.Literal):
        return _literal_tv(expr.value, expr.dtype, n)

    if isinstance(expr, E.Col):
        try:
            tv = env.columns[expr.col_name]
        except KeyError:
            # a bare MAP reference resolves to its '#keys' component,
            # the canonical map handle (types.MapType)
            kc = T.map_keys_col(expr.col_name)
            if kc in env.columns:
                return evaluate(E.Col(kc), env)
            raise KeyError(
                f"column {expr.col_name!r} not in {sorted(env.columns)}")
        if isinstance(tv.dtype, T.ArrayType) and tv.lengths is None:
            # fold the hidden '#len' companion back into the TV: pipes
            # built from batches carry lengths as an ordinary column
            comp = env.columns.get(T.array_len_col(expr.col_name))
            if comp is not None:
                tv = tv._replace(lengths=comp.data)
        return tv

    if isinstance(expr, E.Alias):
        return evaluate(expr.child, env)

    if type(expr).__name__ == "JaxUdf":
        tvs = [evaluate(a, env) for a in expr.args]
        out = expr.fn(*[tv.data for tv in tvs])
        validity = None
        for tv in tvs:
            validity = _and_validity(validity, tv.validity)
        return TV(out, validity, expr.return_type, None)

    if type(expr).__name__ == "ArrowUdf":
        # host round trip: only legal on the eager (blocking) path —
        # np.asarray of a tracer fails loudly under jit. Dead rows show
        # as NULL so Python logic never sees garbage slot values.
        tvs = [evaluate(a, env) for a in expr.args]
        dead = (None if env.mask is None
                else ~np.asarray(env.mask))
        arrays = [_tv_to_arrow(tv, n, dead) for tv in tvs]
        out = expr.fn(*arrays)
        return _arrow_to_tv(out, expr.return_type, n)

    if isinstance(expr, E.TumblingWindow):
        # batch evaluation: window start = child - child % width
        return evaluate(expr.as_arith(), env)

    if isinstance(expr, E.Neg):
        tv = evaluate(expr.child, env)
        return TV(-tv.data, tv.validity, tv.dtype, None)

    if isinstance(expr, E.Abs):
        tv = evaluate(expr.child, env)
        return TV(jnp.abs(tv.data), tv.validity, tv.dtype, None)

    if isinstance(expr, E.Arith):
        return _eval_arith(expr, env)

    if isinstance(expr, E.Cmp):
        return _eval_cmp(expr, env)

    if isinstance(expr, E.And):
        lt = evaluate(expr.left, env)
        rt = evaluate(expr.right, env)
        lv = lt.valid_or_true(n)
        rv = rt.valid_or_true(n)
        ld = lt.data & lv  # treat null as "unknown"; track explicitly below
        rd = rt.data & rv
        vals = lt.data & rt.data
        # Kleene: valid if both valid, or either side is a valid False.
        valid = (lv & rv) | (lv & ~lt.data) | (rv & ~rt.data)
        if lt.validity is None and rt.validity is None:
            valid = None
        return TV(vals, valid, T.BOOLEAN, None)

    if isinstance(expr, E.Or):
        lt = evaluate(expr.left, env)
        rt = evaluate(expr.right, env)
        lv = lt.valid_or_true(n)
        rv = rt.valid_or_true(n)
        vals = lt.data | rt.data
        valid = (lv & rv) | (lv & lt.data) | (rv & rt.data)
        if lt.validity is None and rt.validity is None:
            valid = None
        return TV(vals, valid, T.BOOLEAN, None)

    if isinstance(expr, E.Not):
        tv = evaluate(expr.child, env)
        return TV(~tv.data, tv.validity, T.BOOLEAN, None)

    if isinstance(expr, E.IsNull):
        tv = evaluate(expr.child, env)
        if tv.validity is None:
            return TV(jnp.zeros((n,), dtype=jnp.bool_), None, T.BOOLEAN, None)
        return TV(~tv.validity, None, T.BOOLEAN, None)

    if isinstance(expr, E.MakeArray):
        tvs = [evaluate(a, env) for a in expr.args]
        # null ELEMENTS inside arrays are not representable in the
        # padded layout (types.ArrayType) — Spark's CreateArray would
        # keep [1, NULL]; here a null input nulls the WHOLE array row
        # (documented ArrayType deviation, PARITY.md): size()/
        # element_at() then see a null array, never a wrong length
        validity = None
        for t in tvs:
            validity = _and_validity(validity, t.validity)
        el = tvs[0].dtype
        for t in tvs[1:]:
            el = T.common_type(el, t.dtype)
        if isinstance(el, T.StringType):
            union, tables = unify_dictionaries(
                tuple(t.dictionary or () for t in tvs))
            cols = [(jnp.asarray(tb)[t.data] if len(t.dictionary or ())
                     else t.data) for t, tb in zip(tvs, tables)]
            dictionary: Optional[Tuple[str, ...]] = union
        else:
            cols = [_cast_data(t.data, t.dtype, el) for t in tvs]
            dictionary = None
        data = jnp.stack(cols, axis=1)
        lengths = jnp.full((n,), len(tvs), dtype=jnp.int32)
        if validity is not None:
            lengths = jnp.where(validity, lengths, 0)
        return TV(data, validity, T.ArrayType(el), dictionary, lengths)

    if isinstance(expr, E.Split):
        tv = evaluate(expr.child, env)
        if not isinstance(tv.dtype, T.StringType):
            raise NotImplementedError("split() needs a string input")
        dictionary = tv.dictionary or ()
        parts = [s.split(expr.delim) for s in dictionary]
        max_len = max((len(p) for p in parts), default=1)
        el_dict = tuple(sorted({w for p in parts for w in p}))
        pos = {s: i for i, s in enumerate(el_dict)}
        vals = np.zeros((max(1, len(parts)), max_len), dtype=np.int32)
        lens = np.zeros((max(1, len(parts)),), dtype=np.int32)
        for i, p in enumerate(parts):
            lens[i] = len(p)
            for j, w in enumerate(p):
                vals[i, j] = pos[w]
        codes = tv.data if len(dictionary) else jnp.zeros((n,), jnp.int32)
        return TV(jnp.asarray(vals)[codes], tv.validity,
                  T.ArrayType(T.STRING), el_dict,
                  jnp.asarray(lens)[codes])

    if isinstance(expr, E.Size):
        tv = evaluate(expr.child, env)
        if tv.lengths is None:
            raise NotImplementedError("size() over a non-array value")
        return TV(tv.lengths.astype(jnp.int32), tv.validity, T.INT32,
                  None)

    if isinstance(expr, E.ElementAt):
        pair = _map_pair(expr.child, env)
        if pair is not None:
            return _map_get(pair, evaluate(expr.index, env), n)
        tv = evaluate(expr.child, env)
        it = evaluate(expr.index, env)
        if tv.lengths is None or tv.data.ndim != 2:
            raise NotImplementedError("element_at over a non-array value")
        idx = it.data.astype(jnp.int32)
        lens = tv.lengths.astype(jnp.int32)
        if expr.sql_subscript:  # x[i]: 0-based (GetArrayItem)
            pos = idx
            ok = (pos >= 0) & (pos < lens)
        else:
            pos = jnp.where(idx > 0, idx - 1, lens + idx)
            ok = (pos >= 0) & (pos < lens) & (idx != 0)
        got = jnp.take_along_axis(
            tv.data, jnp.clip(pos, 0, max(tv.data.shape[1] - 1, 0))[:, None],
            axis=1)[:, 0]
        validity = tv.valid_or_true(n) & it.valid_or_true(n) & ok
        return TV(got, validity, tv.dtype.element, tv.dictionary)

    if isinstance(expr, E.ArrayContains):
        tv = evaluate(expr.child, env)
        vt = evaluate(expr.value, env)
        if tv.lengths is None or tv.data.ndim != 2:
            raise NotImplementedError(
                "array_contains over a non-array value")
        L = tv.data.shape[1]
        alive = jnp.arange(L)[None, :] < tv.lengths[:, None]
        if isinstance(tv.dtype.element, T.StringType):
            # translate the needle into the element dictionary's codes
            union, (ta, tb) = unify_dictionaries(
                (tv.dictionary or (), vt.dictionary or ()))
            adata = (jnp.asarray(ta)[tv.data]
                     if len(tv.dictionary or ()) else tv.data)
            vdata = (jnp.asarray(tb)[vt.data]
                     if len(vt.dictionary or ()) else vt.data)
            eq = adata == vdata[:, None]
        else:
            # compare in the COMMON type: casting the needle to the
            # element type would truncate 10.5 -> 10 and falsely match
            ct = T.common_type(tv.dtype.element, vt.dtype)
            eq = (_cast_data(tv.data, tv.dtype.element, ct)
                  == _cast_data(vt.data, vt.dtype, ct)[:, None])
        res = jnp.any(eq & alive, axis=1)
        validity = _and_validity(tv.validity, vt.validity)
        return TV(res, validity, T.BOOLEAN, None)

    if isinstance(expr, E.HigherOrder):
        return _eval_higher_order(expr, env, n)

    if isinstance(expr, (E.CreateMap, E.MapFromArrays)):
        raise NotImplementedError(
            "map-typed expressions are only legal at the top of a "
            "projection (the Project expands them into '#keys'/'#vals' "
            "component columns — types.MapType)")

    if isinstance(expr, E.Explode):
        raise NotImplementedError(
            "explode() is a generator: only valid in a SELECT list or "
            "LATERAL VIEW (planned as GenerateExec), not nested inside "
            "another expression")

    if isinstance(expr, E.NullOf):
        tv = evaluate(expr.like, env)
        return TV(tv.data, jnp.zeros((n,), dtype=jnp.bool_), tv.dtype,
                  tv.dictionary)

    if isinstance(expr, E.In):
        tv = evaluate(expr.child, env)
        if isinstance(tv.dtype, T.StringType):
            values = set(expr.values)
            table = _dict_table(tv.dictionary or (), lambda s: s in values)
            res = jnp.asarray(table)[tv.data] if len(table) else jnp.zeros(
                (n,), dtype=jnp.bool_)
            return TV(res, tv.validity, T.BOOLEAN, None)
        res = jnp.zeros((n,), dtype=jnp.bool_)
        for v in expr.values:
            if v is None:
                continue  # NULL list element never equals (engine-wide
                # two-valued IN; non-matching rows stay false, not null)
            if isinstance(tv.dtype, T.DateType) and isinstance(v, datetime.date):
                v = T.date_to_days(v)
            if isinstance(tv.dtype, T.DecimalType):
                # device data is the SCALED int64: scale the literal the
                # same way _literal_tv does. A literal that does not land
                # on the scale grid (0.0501 vs scale 2) can never equal a
                # stored value — skip it rather than round to a false hit.
                import decimal as _dec

                q = _dec.Decimal(str(v)).scaleb(tv.dtype.scale)
                if q != q.to_integral_value():
                    continue
                v = int(q)
            res = res | (tv.data == v)
        return TV(res, tv.validity, T.BOOLEAN, None)

    if isinstance(expr, E.Like):
        tv = evaluate(expr.child, env)
        dictionary = tv.dictionary or ()
        if _use_native(dictionary):
            from spark_tpu import native

            table = native.like_table(dictionary, expr.pattern)
        else:
            rx = _like_to_regex(expr.pattern)
            table = _dict_table(dictionary,
                                lambda s: rx.match(s) is not None)
        res = jnp.asarray(table)[tv.data] if len(table) else jnp.zeros(
            (n,), dtype=jnp.bool_)
        return TV(res, tv.validity, T.BOOLEAN, None)

    if isinstance(expr, E.StringPredicate):
        tv = evaluate(expr.child, env)
        needle = expr.needle
        dictionary = tv.dictionary or ()
        if _use_native(dictionary):
            from spark_tpu import native

            table = native.predicate_table(dictionary, expr.op, needle)
        else:
            fn = {
                "startswith": lambda s: s.startswith(needle),
                "endswith": lambda s: s.endswith(needle),
                "contains": lambda s: needle in s,
            }[expr.op]
            table = _dict_table(dictionary, fn)
        res = jnp.asarray(table)[tv.data] if len(table) else jnp.zeros(
            (n,), dtype=jnp.bool_)
        return TV(res, tv.validity, T.BOOLEAN, None)

    if isinstance(expr, E.Concat):
        # null propagates (unlike CONCAT_WS): plain cartesian product
        tvs = [evaluate(a, env) for a in expr.args]
        out = _dict_product(
            "CONCAT", tvs, n, null_sentinel=False,
            join=lambda t: "".join(t))
        validity = None
        for tv in tvs:
            validity = _and_validity(validity, tv.validity)
        return TV(out.data, validity, T.STRING, out.dictionary)

    if isinstance(expr, E.ConcatWs):
        # null inputs are SKIPPED with their separator; result non-null
        tvs = [evaluate(a, env) for a in expr.args]
        return _dict_product(
            "CONCAT_WS", tvs, n, null_sentinel=True,
            join=lambda t: expr.sep.join(p for p in t if p is not None))

    if isinstance(expr, E.Substring):
        tv = evaluate(expr.child, env)
        dictionary = tv.dictionary or ()
        transformed = [s[expr.pos - 1: expr.pos - 1 + expr.length]
                       for s in dictionary]
        new_dict = tuple(sorted(set(transformed)))
        pos = {s: i for i, s in enumerate(new_dict)}
        table = np.array([pos[t] for t in transformed], dtype=np.int32)
        codes = (jnp.asarray(table)[tv.data] if len(table)
                 else jnp.zeros((n,), dtype=jnp.int32))
        return TV(codes, tv.validity, T.STRING, new_dict)

    if isinstance(expr, E.Cast):
        return _eval_cast(expr, env)

    if isinstance(expr, E.Case):
        return _eval_case(expr, env)

    if isinstance(expr, E.Coalesce):
        tvs = [evaluate(a, env) for a in expr.args]
        out_dt = tvs[0].dtype
        out_dict = tvs[0].dictionary
        if isinstance(out_dt, T.StringType):
            # args carry DIFFERENT dictionaries (e.g. a column and a
            # fill literal) — remap every code into the union dictionary
            # before blending, as Case does
            union, tables = unify_dictionaries(tuple(
                tv.dictionary or () for tv in tvs))
            tvs = [
                TV(jnp.asarray(t)[tv.data] if len(tv.dictionary or ())
                   else tv.data, tv.validity, T.STRING, union)
                for tv, t in zip(tvs, tables)
            ]
            out_dict = union
        data = tvs[-1].data
        valid = tvs[-1].validity
        for tv in reversed(tvs[:-1]):
            v = tv.valid_or_true(n)
            data = jnp.where(v, _cast_data(tv.data, tv.dtype, out_dt), data)
            # valid where this arg is valid OR the later fallback was valid
            valid = None if valid is None else (v | valid)
        return TV(data, valid, out_dt, out_dict)

    if isinstance(expr, E.ExtractDatePart):
        tv = evaluate(expr.child, env)
        y, m, d = _civil_from_days(tv.data.astype(jnp.int64))
        part = {"year": y, "month": m, "day": d}[expr.part]
        return TV(part.astype(jnp.int32), tv.validity, T.INT32, None)

    if isinstance(expr, E.UnaryMath):
        tv = evaluate(expr.child, env)
        x = tv.data
        if expr.op in ("floor", "ceil") and tv.dtype.is_integral:
            # identity on integers — a float64 round-trip would corrupt
            # values above 2^53
            return TV(x.astype(jnp.int64), tv.validity, T.INT64, None)
        if expr.op == "floor":
            out = jnp.floor(x.astype(jnp.float64)).astype(jnp.int64)
        elif expr.op == "ceil":
            out = jnp.ceil(x.astype(jnp.float64)).astype(jnp.int64)
        elif expr.op == "sign":
            out = jnp.sign(x)
        else:
            xf = x.astype(jnp.float64)
            out = {"sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log,
                   "log10": jnp.log10}[expr.op](xf)
        dt = (T.INT64 if expr.op in ("floor", "ceil")
              else (tv.dtype if expr.op == "sign" else T.FLOAT64))
        return TV(out, tv.validity, dt, None)

    if isinstance(expr, E.Round):
        tv = evaluate(expr.child, env)
        if tv.dtype.is_integral and expr.scale >= 0:
            return tv
        x = tv.data.astype(jnp.float64)
        f = 10.0 ** expr.scale
        # HALF_UP (Spark) — numpy/jax round is half-even
        out = jnp.sign(x) * jnp.floor(jnp.abs(x) * f + 0.5) / f
        if tv.dtype.is_integral:
            # negative scale on an integral column stays integral
            # (matches Round.data_type)
            return TV(out.astype(jnp.int64), tv.validity, T.INT64, None)
        return TV(out, tv.validity, T.FLOAT64, None)

    if isinstance(expr, E.Pow):
        lt = evaluate(expr.left, env)
        rt = evaluate(expr.right, env)
        out = jnp.power(lt.data.astype(jnp.float64),
                        rt.data.astype(jnp.float64))
        validity = None
        if lt.validity is not None or rt.validity is not None:
            validity = lt.valid_or_true(n) & rt.valid_or_true(n)
        return TV(out, validity, T.FLOAT64, None)

    if isinstance(expr, E.StringTransform):
        tv = evaluate(expr.child, env)
        a = expr.args
        fn = {
            "upper": str.upper, "lower": str.lower, "trim": str.strip,
            "ltrim": str.lstrip, "rtrim": str.rstrip,
            "initcap": lambda s: s.title(),
            "reverse": lambda s: s[::-1],
            "repeat": lambda s: s * int(a[0]),
            # pad cycles from its START (reference StringLPad: lpad
            # ('abc', 6, 'xy') = 'xyxabc', not tail-aligned 'yxyabc');
            # non-positive length = '' (UTF8String.lpad substring(0, len))
            "lpad": lambda s: (s[:max(0, int(a[0]))]
                               if len(s) >= int(a[0])
                               else (str(a[1]) * int(a[0]))
                               [:int(a[0]) - len(s)] + s),
            "rpad": lambda s: (s[:max(0, int(a[0]))]
                               if len(s) >= int(a[0])
                               else (s + str(a[1]) * int(a[0]))
                               [:int(a[0])]),
            # Spark translate: extra match chars (no replacement) delete
            "translate": lambda s: s.translate(str.maketrans(
                str(a[0])[: len(str(a[1]))], str(a[1])[: len(str(a[0]))],
                str(a[0])[len(str(a[1])):])),
        }[expr.op]
        return _dict_transform(tv, fn, n)

    if isinstance(expr, E.StrLength):
        tv = evaluate(expr.child, env)
        dictionary = tv.dictionary or ()
        table = np.array([len(s) for s in dictionary] or [0],
                         dtype=np.int32)
        return TV(jnp.asarray(table)[tv.data], tv.validity, T.INT32, None)

    if isinstance(expr, E.RegexpExtract):
        import re as _re

        rx = _re.compile(expr.pattern)

        def extract(s: str) -> str:
            m = rx.search(s)
            if m is None:
                return ""
            try:
                return m.group(expr.group) or ""
            except IndexError:
                return ""

        tv = evaluate(expr.child, env)
        return _dict_transform(tv, extract, n)

    if isinstance(expr, E.RegexpReplace):
        import re as _re

        rx = _re.compile(expr.pattern)
        tv = evaluate(expr.child, env)
        return _dict_transform(tv, lambda s: rx.sub(expr.replacement, s), n)

    if isinstance(expr, E.RegexpLike):
        import re as _re

        rx = _re.compile(expr.pattern)
        tv = evaluate(expr.child, env)
        dictionary = tv.dictionary or ()
        table = np.array([bool(rx.search(s)) for s in dictionary] or [False])
        return TV(jnp.asarray(table)[tv.data], tv.validity, T.BOOLEAN, None)

    if isinstance(expr, E.DateTrunc):
        tv = evaluate(expr.child, env)
        y, m, d = _civil_from_days(tv.data.astype(jnp.int64))
        if expr.unit in ("year", "yy", "yyyy"):
            days = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        elif expr.unit in ("month", "mon", "mm"):
            days = _days_from_civil(y, m, jnp.ones_like(d))
        else:
            raise NotImplementedError(f"date_trunc unit {expr.unit!r}")
        return TV(days.astype(jnp.int32), tv.validity, T.DATE, None)

    if isinstance(expr, E.LastDay):
        tv = evaluate(expr.child, env)
        y, m, d = _civil_from_days(tv.data.astype(jnp.int64))
        days = _days_from_civil(y, m, _days_in_month(y, m))
        return TV(days.astype(jnp.int32), tv.validity, T.DATE, None)

    if isinstance(expr, E.AddMonths):
        tv = evaluate(expr.child, env)
        y, m, d = _civil_from_days(tv.data.astype(jnp.int64))
        total = (y * 12 + (m - 1)) + expr.months
        ny = total // 12
        nm = total - ny * 12 + 1
        last = _days_in_month(ny, nm)
        nd = jnp.minimum(d, last)
        days = _days_from_civil(ny, nm, nd)
        return TV(days.astype(jnp.int32), tv.validity, T.DATE, None)

    raise NotImplementedError(f"cannot compile expression: {expr!r}")


def _civil_from_days(days: jnp.ndarray):
    """Days-since-epoch -> (year, month, day), branch-free civil-calendar
    algorithm (Howard Hinnant's days_from_civil inverse)."""
    z = days + 719468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray):
    """(year, month, day) -> days-since-epoch (Hinnant's days_from_civil)."""
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(jnp.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y: jnp.ndarray, m: jnp.ndarray):
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                          dtype=jnp.int64)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    base = lengths[m - 1]
    return jnp.where((m == 2) & leap, base + 1, base)


def _tv_to_arrow(tv: TV, n: int, dead=None):
    """Concrete TV -> pyarrow array: nulls from validity AND the dead-row
    mask (host UDFs see NULL for dead slots, never garbage); dictionary
    codes decode to strings."""
    import pyarrow as pa

    data = np.asarray(tv.data)
    mask = (None if tv.validity is None
            else ~np.asarray(tv.validity))
    if dead is not None:
        mask = dead if mask is None else (mask | dead)
    if isinstance(tv.dtype, T.StringType):
        d = list(tv.dictionary or ()) + [""]
        codes = np.clip(data, 0, len(d) - 1)
        vals = np.array(d, dtype=object)[codes]
        return pa.array(vals, type=pa.string(),
                        mask=mask if mask is not None else None)
    if isinstance(tv.dtype, T.DateType):
        return pa.array(data.astype("datetime64[D]"), mask=mask)
    return pa.array(data, mask=mask)


def _arrow_to_tv(arr, dtype: DataType, n: int) -> TV:
    """pyarrow array -> TV (dictionary-encodes strings)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if len(arr) != n:
        raise ValueError(
            f"arrow UDF returned {len(arr)} rows, expected {n}")
    validity = None
    if arr.null_count:
        validity = jnp.asarray(np.asarray(pc.is_valid(arr)))
    if isinstance(dtype, T.StringType):
        enc = pc.dictionary_encode(arr.combine_chunks()
                                   if isinstance(arr, pa.ChunkedArray)
                                   else arr)
        dictionary = tuple(enc.dictionary.to_pylist())
        codes = np.asarray(enc.indices.fill_null(0))
        return TV(jnp.asarray(codes.astype(np.int32)), validity,
                  T.STRING, dictionary)
    np_arr = np.asarray(arr.fill_null(0) if arr.null_count else arr)
    return TV(jnp.asarray(np_arr.astype(_jnp_dtype(dtype))), validity,
              dtype, None)


def _dict_transform(tv: TV, fn, n: int) -> TV:
    """Apply a host string function over the dictionary; codes remap
    through a translation table on device (the pattern every string
    expression uses — strings never materialize on the TPU)."""
    dictionary = tv.dictionary or ()
    transformed = [fn(s) for s in dictionary]
    new_dict = tuple(sorted(set(transformed)))
    pos = {s: i for i, s in enumerate(new_dict)}
    table = np.array([pos[t] for t in transformed] or [0], dtype=np.int32)
    codes = (jnp.asarray(table)[tv.data] if len(dictionary)
             else jnp.zeros((n,), dtype=jnp.int32))
    return TV(codes, tv.validity, T.STRING, new_dict)


def _eval_arith(expr: E.Arith, env: Env) -> TV:
    n = env.capacity
    lt = evaluate(expr.left, env)
    rt = evaluate(expr.right, env)
    valid = _and_validity(lt.validity, rt.validity)

    # date arithmetic
    if isinstance(lt.dtype, T.DateType) and rt.dtype.is_integral:
        op = jnp.add if expr.op == "+" else jnp.subtract
        return TV(op(lt.data, rt.data.astype(jnp.int32)), valid, T.DATE, None)
    if isinstance(rt.dtype, T.DateType) and lt.dtype.is_integral and expr.op == "+":
        return TV(rt.data + lt.data.astype(jnp.int32), valid, T.DATE, None)
    if isinstance(lt.dtype, T.DateType) and isinstance(rt.dtype, T.DateType):
        return TV((lt.data - rt.data).astype(jnp.int32), valid, T.INT32, None)

    if (isinstance(lt.dtype, T.DecimalType)
            or isinstance(rt.dtype, T.DecimalType)):
        dec_dt = expr._decimal_result(lt.dtype, rt.dtype)
        if dec_dt is not None:
            return _decimal_arith(expr.op, lt, rt, dec_dt, valid)

    out_dt = T.common_type(lt.dtype, rt.dtype)
    if expr.op == "/" and out_dt.is_integral:
        out_dt = T.FLOAT64
    ld = _cast_data(lt.data, lt.dtype, out_dt)
    rd = _cast_data(rt.data, rt.dtype, out_dt)

    if expr.op == "+":
        data = ld + rd
    elif expr.op == "-":
        data = ld - rd
    elif expr.op == "*":
        data = ld * rd
    elif expr.op == "/":
        zero = rd == 0
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        data = ld / safe
        valid = _and_validity(valid, ~zero)
    elif expr.op == "%":
        zero = rd == 0
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        # SQL remainder keeps the dividend's sign (fmod), unlike jnp.mod.
        data = ld - jnp.trunc(ld / safe) * safe if not out_dt.is_integral \
            else ld - (jnp.sign(ld) * (jnp.abs(ld) // jnp.abs(safe))) * safe
        valid = _and_validity(valid, ~zero)
    else:
        raise NotImplementedError(expr.op)
    return TV(data, valid, out_dt, None)


def _decimal_arith(op: str, lt: TV, rt: TV, out_dt, valid) -> TV:
    """Exact scaled-int64 decimal arithmetic (reference:
    decimalExpressions.scala over Decimal.scala). +,-,% align scales and
    stay integral; * adds scales then rescales to the bounded result
    type; / routes through float64 and rounds HALF_UP to the result
    scale (exact for quotients below 2^53). Overflow past 18 digits is
    not detected (the reference's int128 range is wider — documented
    DecimalType deviation)."""
    def as_scaled(tv, scale):
        if isinstance(tv.dtype, T.DecimalType):
            return _cast_data(tv.data, tv.dtype,
                              T.DecimalType(T.DecimalType.MAX_PRECISION,
                                            scale))
        return tv.data.astype(jnp.int64) * (10 ** scale)

    s1 = lt.dtype.scale if isinstance(lt.dtype, T.DecimalType) else 0
    s2 = rt.dtype.scale if isinstance(rt.dtype, T.DecimalType) else 0
    if op in ("+", "-"):
        s = max(s1, s2)
        ld, rd = as_scaled(lt, s), as_scaled(rt, s)
        data = ld + rd if op == "+" else ld - rd
        data = _cast_data(data, T.DecimalType(38, s), out_dt)
        return TV(data, valid, out_dt, None)
    if op == "*":
        prod = as_scaled(lt, s1) * as_scaled(rt, s2)  # scale s1+s2
        data = _cast_data(prod, T.DecimalType(38, s1 + s2), out_dt)
        return TV(data, valid, out_dt, None)
    if op == "/":
        lf = as_scaled(lt, s1).astype(jnp.float64) / float(10 ** s1)
        rf = as_scaled(rt, s2).astype(jnp.float64) / float(10 ** s2)
        zero = rf == 0.0
        safe = jnp.where(zero, jnp.ones_like(rf), rf)
        data = _float_to_scaled(lf / safe, out_dt.scale)
        return TV(data, _and_validity(valid, ~zero), out_dt, None)
    if op == "%":
        s = max(s1, s2)
        ld, rd = as_scaled(lt, s), as_scaled(rt, s)
        zero = rd == 0
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        # remainder keeps the dividend's sign
        mag = jnp.abs(ld) - (jnp.abs(ld) // jnp.abs(safe)) * jnp.abs(safe)
        data = jnp.sign(ld) * mag
        data = _cast_data(data, T.DecimalType(38, s), out_dt)
        return TV(data, _and_validity(valid, ~zero), out_dt, None)
    raise NotImplementedError(op)


def _string_cmp_tables(lt: TV, rt: TV, op: str, n: int):
    """Comparison between two string TVs via host dictionaries."""
    import operator

    ops = {"==": operator.eq, "!=": operator.ne, "<": operator.lt,
           "<=": operator.le, ">": operator.gt, ">=": operator.ge}
    pyop = ops[op]
    ld = lt.dictionary or ()
    rd = rt.dictionary or ()
    if rt.dictionary is not None and len(rd) == 1 and rt.validity is None:
        # col OP literal: one table over the column dictionary
        needle = rd[0]
        table = _dict_table(ld, lambda s: pyop(s, needle))
        return (jnp.asarray(table)[lt.data] if len(ld)
                else jnp.zeros((n,), dtype=jnp.bool_))
    if lt.dictionary is not None and len(ld) == 1 and lt.validity is None:
        needle = ld[0]
        table = _dict_table(rd, lambda s: pyop(needle, s))
        return (jnp.asarray(table)[rt.data] if len(rd)
                else jnp.zeros((n,), dtype=jnp.bool_))
    # col OP col: translate both into a unified sorted dictionary, then
    # compare the (order-preserving) unified codes.
    union, (tl, tr) = unify_dictionaries((ld, rd))
    lcodes = jnp.asarray(tl)[lt.data] if len(ld) else lt.data
    rcodes = jnp.asarray(tr)[rt.data] if len(rd) else rt.data
    jops = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
            "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}
    return jops[op](lcodes, rcodes)


def _eval_cmp(expr: E.Cmp, env: Env) -> TV:
    n = env.capacity
    lt = evaluate(expr.left, env)
    rt = evaluate(expr.right, env)
    valid = _and_validity(lt.validity, rt.validity)

    # date/timestamp vs string: the string side coerces to the temporal
    # type via its dictionary (reference: DateTimeUtils / implicit cast
    # in BinaryComparison type coercion) — 'YYYY-MM-DD' literals and
    # columns compare as days/micros, not lexicographically
    def _temporal_coerce(tv: TV, other_dt) -> TV:
        if not isinstance(tv.dtype, T.StringType):
            return tv
        entries = tv.dictionary or ()
        if isinstance(other_dt, T.DateType):
            parsed = [_parse_date_days(s) for s in entries]
            vals = np.array([v if v is not None else 0 for v in parsed]
                            or [0], dtype=np.int32)
            ok_tab = np.array([v is not None for v in parsed] or [False])
            data = jnp.asarray(vals)[tv.data] if len(entries) \
                else tv.data.astype(jnp.int32)
            ok = jnp.asarray(ok_tab)[tv.data] if len(entries) \
                else jnp.zeros((n,), jnp.bool_)
            return TV(data, _and_validity(tv.validity, ok),
                      T.DATE, None)
        if isinstance(other_dt, T.TimestampType):
            vals, ok_tab = [], []
            for s in entries:
                try:
                    dtv = datetime.datetime.fromisoformat(s)
                    vals.append(int(dtv.timestamp() * 1_000_000))
                    ok_tab.append(True)
                except ValueError:
                    vals.append(0)
                    ok_tab.append(False)
            data = jnp.asarray(np.array(vals or [0], np.int64))[tv.data] \
                if len(entries) else tv.data.astype(jnp.int64)
            ok = jnp.asarray(np.array(ok_tab or [False]))[tv.data] \
                if len(entries) else jnp.zeros((n,), jnp.bool_)
            return TV(data, _and_validity(tv.validity, ok),
                      T.TIMESTAMP, None)
        return tv

    if isinstance(lt.dtype, (T.DateType, T.TimestampType)) \
            and isinstance(rt.dtype, T.StringType):
        rt = _temporal_coerce(rt, lt.dtype)
        valid = _and_validity(lt.validity, rt.validity)
    elif isinstance(rt.dtype, (T.DateType, T.TimestampType)) \
            and isinstance(lt.dtype, T.StringType):
        lt = _temporal_coerce(lt, rt.dtype)
        valid = _and_validity(lt.validity, rt.validity)

    if isinstance(lt.dtype, T.StringType) or isinstance(rt.dtype, T.StringType):
        data = _string_cmp_tables(lt, rt, expr.op, n)
        return TV(data, valid, T.BOOLEAN, None)

    if isinstance(lt.dtype, T.DateType) or isinstance(rt.dtype, T.DateType):
        ld, rd = lt.data, rt.data
    else:
        out_dt = T.common_type(lt.dtype, rt.dtype)
        ld = _cast_data(lt.data, lt.dtype, out_dt)
        rd = _cast_data(rt.data, rt.dtype, out_dt)
    jops = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
            "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}
    return TV(jops[expr.op](ld, rd), valid, T.BOOLEAN, None)


def _eval_cast(expr: E.Cast, env: Env) -> TV:
    n = env.capacity
    tv = evaluate(expr.child, env)
    dst = expr.dtype
    if isinstance(tv.dtype, T.DecimalType) and isinstance(
            dst, T.DecimalType):
        if tv.dtype.scale == dst.scale:
            return TV(tv.data, tv.validity, dst, None)
        return TV(_cast_data(tv.data, tv.dtype, dst), tv.validity, dst,
                  None)
    if type(tv.dtype) is type(dst):
        return tv
    if isinstance(dst, T.StringType):
        raise NotImplementedError("cast to string not yet supported")
    if isinstance(tv.dtype, T.StringType):
        # string -> numeric/date via dictionary
        if isinstance(dst, T.DateType):
            table = np.array(
                [T.date_to_days(datetime.date.fromisoformat(s))
                 for s in (tv.dictionary or ())], dtype=np.int32)
        elif isinstance(dst, T.DecimalType):
            import decimal as _dec

            table = np.array(
                [int(_dec.Decimal(s).scaleb(dst.scale).to_integral_value(
                    rounding=_dec.ROUND_HALF_UP))
                 for s in (tv.dictionary or ())], dtype=np.int64)
        else:
            table = np.array([float(s) for s in (tv.dictionary or ())],
                             dtype=dst.np_dtype)
        data = (jnp.asarray(table)[tv.data] if len(table)
                else jnp.zeros((n,), dtype=_jnp_dtype(dst)))
        return TV(data, tv.validity, dst, None)
    return TV(tv.data.astype(_jnp_dtype(dst)), tv.validity, dst, None)


def _eval_case(expr: E.Case, env: Env) -> TV:
    n = env.capacity
    conds = [evaluate(c, env) for c, _ in expr.branches]
    vals = [evaluate(v, env) for _, v in expr.branches]
    else_tv = (evaluate(expr.else_value, env)
               if expr.else_value is not None else None)

    out_is_string = any(isinstance(v.dtype, T.StringType) for v in vals)
    if out_is_string:
        dicts = [v.dictionary or () for v in vals]
        if else_tv is not None:
            dicts.append(else_tv.dictionary or ())
        union, tables = unify_dictionaries(tuple(dicts))
        vals = [
            TV(jnp.asarray(t)[v.data] if len(v.dictionary or ()) else v.data,
               v.validity, T.STRING, union)
            for v, t in zip(vals, tables[: len(vals)])
        ]
        if else_tv is not None:
            t = tables[-1]
            else_tv = TV(
                jnp.asarray(t)[else_tv.data] if len(else_tv.dictionary or ())
                else else_tv.data,
                else_tv.validity, T.STRING, union)
        out_dt: DataType = T.STRING
        out_dict: Optional[Tuple[str, ...]] = union
    else:
        out_dt = vals[0].dtype
        for v in vals[1:]:
            out_dt = T.common_type(out_dt, v.dtype)
        if else_tv is not None:
            out_dt = T.common_type(out_dt, else_tv.dtype)
        out_dict = None

    if else_tv is not None:
        data = _cast_data(else_tv.data, else_tv.dtype, out_dt)
        valid = else_tv.validity
    else:
        data = jnp.zeros((n,), dtype=_jnp_dtype(out_dt))
        valid = jnp.zeros((n,), dtype=jnp.bool_)

    matched = jnp.zeros((n,), dtype=jnp.bool_)
    for c, v in zip(conds, vals):
        fire = c.data & c.valid_or_true(n) & ~matched
        data = jnp.where(fire, _cast_data(v.data, v.dtype, out_dt), data)
        v_valid = v.valid_or_true(n)
        valid_arr = valid if valid is not None else jnp.ones((n,), jnp.bool_)
        valid = jnp.where(fire, v_valid, valid_arr)
        matched = matched | fire
    return TV(data, valid, out_dt, out_dict)


def _eval_higher_order(expr: "E.HigherOrder", env: Env, n: int) -> TV:
    """Higher-order array functions, vectorized on the padded layout
    (reference: higherOrderFunctions.scala — there an interpreted
    per-element lambda; here the lambda body traces ONCE over the
    flattened (rows x max_len) element plane, so XLA fuses it like any
    other columnar expression).

    Null semantics deviations (documented): a NULL lambda result is not
    representable as a null ELEMENT (types.ArrayType) — transform over
    a nullable body refuses loudly like array(); exists/forall treat a
    NULL predicate as false (three-valued NULL results are not
    produced)."""
    tv = evaluate(expr.child, env)
    if tv.lengths is None or tv.data.ndim != 2:
        raise NotImplementedError(f"{expr.kind}() over a non-array value")
    width = tv.data.shape[1]
    lens = tv.lengths.astype(jnp.int32)
    alive = jnp.arange(width)[None, :] < lens[:, None]
    params = expr.fn.params

    if expr.kind == "aggregate":
        return _eval_array_aggregate(expr, tv, lens, env, n)

    # element-plane environment: outer row columns repeat per element
    cols: Dict[str, TV] = {}
    for name, otv in env.columns.items():
        if otv.data.ndim != 1:
            continue  # array-typed outer columns are not in scope
        cols[name] = TV(
            jnp.repeat(otv.data, width),
            None if otv.validity is None
            else jnp.repeat(otv.validity, width),
            otv.dtype, otv.dictionary)
    cols[params[0]] = TV(tv.data.reshape(-1), None, tv.dtype.element,
                         tv.dictionary)
    if len(params) > 1:  # (x, i) -> ...: 0-based position
        cols[params[1]] = TV(
            jnp.tile(jnp.arange(width, dtype=jnp.int32), n), None,
            T.INT32, None)
    res = evaluate(expr.fn.body, Env(cols, n * width))

    if expr.kind == "transform":
        if res.validity is not None:
            raise NotImplementedError(
                "transform() lambda with a nullable result: null array "
                "elements are not representable — coalesce() inside the "
                "lambda")
        return TV(res.data.reshape(n, width), tv.validity,
                  T.ArrayType(res.dtype), res.dictionary, lens)

    pred = (res.data.astype(jnp.bool_)
            & res.valid_or_true(n * width)).reshape(n, width)
    if expr.kind == "filter":
        keep = pred & alive
        # stable per-row compaction: kept elements slide left
        perm = jnp.argsort(~keep, axis=1, stable=True)
        data = jnp.take_along_axis(tv.data, perm, axis=1)
        return TV(data, tv.validity, tv.dtype, tv.dictionary,
                  keep.sum(axis=1).astype(jnp.int32))
    if expr.kind == "exists":
        return TV(jnp.any(pred & alive, axis=1), tv.validity, T.BOOLEAN,
                  None)
    if expr.kind == "forall":
        return TV(jnp.all(pred | ~alive, axis=1), tv.validity, T.BOOLEAN,
                  None)
    raise NotImplementedError(f"higher-order kind {expr.kind!r}")


def _eval_array_aggregate(expr: "E.HigherOrder", tv: TV, lens, env: Env,
                          n: int) -> TV:
    """aggregate(arr, zero, (acc, x) -> ..., [acc -> ...]): a traced
    fold, unrolled over the (small) max_len axis; each step is a full-
    width vector op so rows fold in parallel."""
    acc = evaluate(expr.zero, env)
    if isinstance(acc.dtype, T.StringType):
        raise NotImplementedError("aggregate() with a string accumulator")
    acc_name, x_name = expr.fn.params
    width = tv.data.shape[1]
    for j in range(width):
        cols = dict(env.columns)
        cols[acc_name] = acc
        cols[x_name] = TV(tv.data[:, j], None, tv.dtype.element,
                          tv.dictionary)
        new = evaluate(expr.fn.body, Env(cols, n))
        ct = T.common_type(acc.dtype, new.dtype)
        step = j < lens
        data = jnp.where(step, _cast_data(new.data, new.dtype, ct),
                         _cast_data(acc.data, acc.dtype, ct))
        if acc.validity is None and new.validity is None:
            validity = None
        else:
            validity = jnp.where(step, new.valid_or_true(n),
                                 acc.valid_or_true(n))
        acc = TV(data, validity, ct, None)
    if expr.finish is not None:
        cols = dict(env.columns)
        cols[expr.finish.params[0]] = acc
        acc = evaluate(expr.finish.body, Env(cols, n))
    validity = _and_validity(tv.validity, acc.validity)
    return TV(acc.data, validity, acc.dtype, acc.dictionary)


def _map_pair(child: "E.Expression", env: Env):
    """(keys TV, vals TV) when ``child`` references a decomposed MAP
    column or is an inline map expression (types.MapType); None
    otherwise."""
    child = E.strip_alias(child)
    if isinstance(child, E.CreateMap):
        return (evaluate(E.MakeArray(child.args[::2]), env),
                evaluate(E.MakeArray(child.args[1::2]), env))
    if isinstance(child, E.MapFromArrays):
        return (evaluate(child.keys, env), evaluate(child.vals, env))
    if not isinstance(child, E.Col):
        return None
    nm = child.col_name
    if nm.endswith(T.MAP_KEYS_SUFFIX):
        base = nm[:-len(T.MAP_KEYS_SUFFIX)]
    elif T.map_keys_col(nm) in env.columns:
        base = nm
    else:
        return None
    kc, vc = T.map_keys_col(base), T.map_vals_col(base)
    if kc not in env.columns or vc not in env.columns:
        return None
    return evaluate(E.Col(kc), env), evaluate(E.Col(vc), env)


def _map_get(pair, needle: TV, n: int) -> TV:
    """element_at(map, key) / m[key]: vectorized key match over the
    padded keys plane + take_along_axis into the values plane
    (reference: GetMapValue, complexTypeExtractors.scala). Missing key
    -> NULL."""
    ktv, vtv = pair
    if ktv.lengths is None or ktv.data.ndim != 2:
        raise NotImplementedError("element_at over a non-map value")
    width = ktv.data.shape[1]
    alive = jnp.arange(width)[None, :] < ktv.lengths[:, None]
    if isinstance(ktv.dtype.element, T.StringType):
        union, (tk, tn) = unify_dictionaries(
            (ktv.dictionary or (), needle.dictionary or ()))
        kdata = (jnp.asarray(tk)[ktv.data]
                 if len(ktv.dictionary or ()) else ktv.data)
        ndata = (jnp.asarray(tn)[needle.data]
                 if len(needle.dictionary or ()) else needle.data)
        eq = kdata == ndata[:, None]
    else:
        ct = T.common_type(ktv.dtype.element, needle.dtype)
        eq = (_cast_data(ktv.data, ktv.dtype.element, ct)
              == _cast_data(needle.data, needle.dtype, ct)[:, None])
    eq = eq & alive
    found = jnp.any(eq, axis=1)
    pos = jnp.argmax(eq, axis=1)
    out = jnp.take_along_axis(vtv.data, pos[:, None], axis=1)[:, 0]
    validity = (ktv.valid_or_true(n) & needle.valid_or_true(n) & found)
    return TV(out, validity, vtv.dtype.element, vtv.dictionary)


def evaluate_map_pair(expr: "E.Expression", env: Env):
    """Evaluate a map-typed projection expression to its (keys TV,
    vals TV) component pair — the Project-level expansion point for
    CreateMap / MapFromArrays / map column references."""
    expr = E.strip_alias(expr)
    if isinstance(expr, E.CreateMap):
        ktv = evaluate(E.MakeArray(expr.args[::2]), env)
        vtv = evaluate(E.MakeArray(expr.args[1::2]), env)
        return ktv, vtv
    if isinstance(expr, E.MapFromArrays):
        ktv = evaluate(expr.keys, env)
        vtv = evaluate(expr.vals, env)
        if ktv.lengths is None or vtv.lengths is None:
            raise NotImplementedError("map_from_arrays needs array inputs")
        return ktv, vtv
    pair = _map_pair(expr, env)
    if pair is not None:
        return pair
    raise NotImplementedError(f"not a map-typed expression: {expr}")


def _parse_date_days(s: str):
    """ISO date string -> days since epoch; None when unparseable."""
    try:
        return T.date_to_days(datetime.date.fromisoformat(s.strip()))
    except ValueError:
        return None

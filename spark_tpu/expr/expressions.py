"""Expression tree (IR).

The analogue of Catalyst's expression nodes (reference:
sql/catalyst/.../expressions/Expression.scala and the ~600 expression
classes under expressions/). Two big simplifications relative to the
reference:

- there is no interpreted-vs-codegen duality: expressions compile to jax
  ops (expr/compiler.py) and XLA plays the role Janino played
  (reference: expressions/codegen/CodeGenerator.scala:1345),
- nulls are (values, validity-mask) pairs, not boxed values.

Nodes are immutable; ``data_type(schema)`` resolves the output type
against an input schema (the analyzer's type-resolution role,
reference: analysis/Analyzer.scala:188).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

from spark_tpu import types as T
from spark_tpu.types import DataType, Schema


class Expression:
    """Base class. Subclasses are frozen dataclasses."""

    #: True for expressions that cannot run inside a jit trace (host
    #: UDFs); operators containing one execute eagerly between stages.
    blocks_trace: bool = False

    def children(self) -> Tuple["Expression", ...]:
        return ()

    def data_type(self, schema: Schema) -> DataType:
        raise NotImplementedError

    def nullable(self, schema: Schema) -> bool:
        return True

    @property
    def name(self) -> str:
        """Output column name when this expression is projected."""
        return str(self)

    def references(self) -> set:
        refs = set()
        for c in self.children():
            refs |= c.references()
        return refs

    # -- convenience builders (mirrors the Column DSL) --------------------
    def __add__(self, other):
        return Arith("+", self, lit_or_expr(other))

    def __radd__(self, other):
        return Arith("+", lit_or_expr(other), self)

    def __sub__(self, other):
        return Arith("-", self, lit_or_expr(other))

    def __rsub__(self, other):
        return Arith("-", lit_or_expr(other), self)

    def __mul__(self, other):
        return Arith("*", self, lit_or_expr(other))

    def __rmul__(self, other):
        return Arith("*", lit_or_expr(other), self)

    def __truediv__(self, other):
        return Arith("/", self, lit_or_expr(other))

    def __mod__(self, other):
        return Arith("%", self, lit_or_expr(other))

    def __neg__(self):
        return Neg(self)

    def __eq__(self, other):  # type: ignore[override]
        return Cmp("==", self, lit_or_expr(other))

    def __ne__(self, other):  # type: ignore[override]
        return Cmp("!=", self, lit_or_expr(other))

    def __lt__(self, other):
        return Cmp("<", self, lit_or_expr(other))

    def __le__(self, other):
        return Cmp("<=", self, lit_or_expr(other))

    def __gt__(self, other):
        return Cmp(">", self, lit_or_expr(other))

    def __ge__(self, other):
        return Cmp(">=", self, lit_or_expr(other))

    def __and__(self, other):
        return And(self, lit_or_expr(other))

    def __or__(self, other):
        return Or(self, lit_or_expr(other))

    def __invert__(self):
        return Not(self)

    def __hash__(self):
        return object.__hash__(self)

    def __bool__(self):
        # __eq__ builds a Cmp node, so truthiness of an Expression is
        # always a bug (it silently made any two Case-sum aggregates
        # "equal" in dedup paths). Fail loudly instead.
        raise TypeError(
            "Expression has no truth value; use expr_key()/semantic_eq() "
            "for comparison, is_null()/is_not_null() for null tests")

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dtype: DataType) -> "Cast":
        return Cast(self, dtype)

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "Not":
        return Not(IsNull(self))

    def isin(self, *values) -> "In":
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        return In(self, tuple(values))

    def between(self, lo, hi) -> "And":
        return And(Cmp(">=", self, lit_or_expr(lo)),
                   Cmp("<=", self, lit_or_expr(hi)))

    def like(self, pattern: str) -> "Like":
        return Like(self, pattern)

    def asc(self) -> "SortOrder":
        return SortOrder(self, ascending=True)

    def desc(self) -> "SortOrder":
        return SortOrder(self, ascending=False)

    def semantic_eq(self, other: "Expression") -> bool:
        return expr_key(self) == expr_key(other)

    def over(self, window) -> "Expression":
        """Attach a window spec (pyspark Column.over)."""
        return window._attach(self)


def lit_or_expr(v: Any) -> Expression:
    return v if isinstance(v, Expression) else Literal(v)


def dedup_pair_names(left_names, right_names) -> list:
    """Joined-pair output names: left keeps its names, duplicates from
    the right gain '#2' suffixes. THE canonical copy — logical Join
    schema, physical pair envs, and optimizer/subquery condition
    rewrites must all agree on this mapping."""
    seen = set()
    out = []
    for n in list(left_names) + list(right_names):
        name = n
        while name in seen:
            name = name + "#2"
        seen.add(name)
        out.append(name)
    return out


def _key_part(v):
    """Key for one field value; recurses into arbitrarily nested tuples so
    no raw Expression (whose __eq__ is the DSL's Cmp builder) ever lands
    inside a key — e.g. Case.branches is a tuple of (cond, value) pairs."""
    if isinstance(v, Expression):
        return expr_key(v)
    if isinstance(v, tuple):
        return tuple(_key_part(x) for x in v)
    return repr(v)


def expr_key(e: Expression):
    """Structural identity key (dataclass __eq__ is hijacked by the SQL
    `==` DSL, so semantic comparison goes through this)."""
    if isinstance(e, Literal):
        return ("lit", e.value, repr(e.dtype))
    parts = [type(e).__name__]
    for f_name, f_val in vars(e).items():
        parts.append(_key_part(f_val))
    return tuple(parts)


@dataclass(eq=False, frozen=True)
class Literal(Expression):
    value: Any
    dtype: DataType = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.dtype is None:
            object.__setattr__(self, "dtype", T.infer_type(self.value))

    def data_type(self, schema: Schema) -> DataType:
        return self.dtype

    def nullable(self, schema: Schema) -> bool:
        return self.value is None

    @property
    def name(self) -> str:
        return str(self.value)

    def __str__(self):
        return repr(self.value)


@dataclass(eq=False, frozen=True)
class Col(Expression):
    col_name: str

    def data_type(self, schema: Schema) -> DataType:
        return schema.field(self.col_name).dtype

    def nullable(self, schema: Schema) -> bool:
        return schema.field(self.col_name).nullable

    def references(self) -> set:
        return {self.col_name}

    @property
    def name(self) -> str:
        return self.col_name

    def __str__(self):
        return self.col_name


@dataclass(eq=False, frozen=True)
class Alias(Expression):
    child: Expression
    alias_name: str

    def children(self):
        return (self.child,)

    def data_type(self, schema: Schema) -> DataType:
        return self.child.data_type(schema)

    def nullable(self, schema: Schema) -> bool:
        return self.child.nullable(schema)

    @property
    def name(self) -> str:
        return self.alias_name

    def __str__(self):
        return f"{self.child} AS {self.alias_name}"


@dataclass(eq=False, frozen=True)
class Arith(Expression):
    op: str  # + - * / %
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def data_type(self, schema: Schema) -> DataType:
        lt = self.left.data_type(schema)
        rt = self.right.data_type(schema)
        # date +/- days
        if isinstance(lt, T.DateType) and rt.is_integral and self.op in ("+", "-"):
            return T.DATE
        if isinstance(rt, T.DateType) and lt.is_integral and self.op == "+":
            return T.DATE
        if isinstance(lt, T.DateType) and isinstance(rt, T.DateType) and self.op == "-":
            return T.INT32
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            dec = self._decimal_result(lt, rt)
            if dec is not None:
                return dec
        out = T.common_type(lt, rt)
        if self.op == "/" and out.is_integral:
            return T.FLOAT64  # SQL: integer / -> double (non-ANSI Spark)
        return out

    def _decimal_result(self, lt, rt):
        """Spark's decimal arithmetic result types (reference:
        DecimalPrecision.scala / decimalExpressions.scala), bounded at
        the engine's 18-digit cap. None -> fall through (decimal op
        float = double)."""
        if isinstance(lt, (T.Float32Type, T.Float64Type)) \
                or isinstance(rt, (T.Float32Type, T.Float64Type)):
            return None
        p1 = lt.precision if isinstance(lt, T.DecimalType) else 19
        s1 = lt.scale if isinstance(lt, T.DecimalType) else 0
        p2 = rt.precision if isinstance(rt, T.DecimalType) else 19
        s2 = rt.scale if isinstance(rt, T.DecimalType) else 0
        if self.op in ("+", "-"):
            s = max(s1, s2)
            return T.bounded_decimal(max(p1 - s1, p2 - s2) + s + 1, s)
        if self.op == "*":
            return T.bounded_decimal(p1 + p2 + 1, s1 + s2)
        if self.op == "/":
            s = max(6, s1 + p2 + 1)
            return T.bounded_decimal(p1 - s1 + s2 + s, s)
        if self.op == "%":
            return T.bounded_decimal(min(p1 - s1, p2 - s2) + max(s1, s2),
                                     max(s1, s2))
        return None

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(eq=False, frozen=True)
class Neg(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    def __str__(self):
        return f"(- {self.child})"


@dataclass(eq=False, frozen=True)
class Cmp(Expression):
    op: str  # == != < <= > >=
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(eq=False, frozen=True)
class And(Expression):
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.left} AND {self.right})"


@dataclass(eq=False, frozen=True)
class Or(Expression):
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.left} OR {self.right})"


@dataclass(eq=False, frozen=True)
class Not(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"(NOT {self.child})"


@dataclass(eq=False, frozen=True)
class IsNull(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def nullable(self, schema):
        return False

    def __str__(self):
        return f"({self.child} IS NULL)"


@dataclass(eq=False, frozen=True)
class MakeArray(Expression):
    """array(e1, e2, ...) literal-ish constructor (reference:
    CreateArray, complexTypeCreator.scala). Fixed length = arity."""

    args: Tuple[Expression, ...]

    def children(self):
        return self.args

    def data_type(self, schema):
        dt = self.args[0].data_type(schema)
        for a in self.args[1:]:
            dt = T.common_type(dt, a.data_type(schema))
        return T.ArrayType(dt)

    def __str__(self):
        return f"array({', '.join(map(str, self.args))})"


@dataclass(eq=False, frozen=True)
class Split(Expression):
    """split(str, delim) -> array<string> (reference: StringSplit,
    regexpExpressions.scala — here delim is a LITERAL separator, not a
    regex; evaluated over the host dictionary)."""

    child: Expression
    delim: str

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.ArrayType(T.STRING)

    def __str__(self):
        return f"split({self.child}, {self.delim!r})"


@dataclass(eq=False, frozen=True)
class Size(Expression):
    """size(array) (reference: Size, collectionOperations.scala)."""

    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.INT32

    def __str__(self):
        return f"size({self.child})"


@dataclass(eq=False, frozen=True)
class ElementAt(Expression):
    """element_at(array, i): 1-based, negative from the end, NULL when
    out of range (reference: ElementAt, collectionOperations.scala).
    Over a MAP column the index is a KEY lookup (GetMapValue).
    ``sql_subscript`` marks the ``x[i]`` form, which is 0-based for
    arrays (GetArrayItem) but still a key lookup for maps."""

    child: Expression
    index: Expression
    sql_subscript: bool = False

    def children(self):
        return (self.child, self.index)

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        if isinstance(dt, T.ArrayType):
            return dt.element
        if isinstance(dt, T.MapType):
            return dt.value
        raise TypeError(f"element_at over non-array {dt!r}")

    def nullable(self, schema):
        return True

    def __str__(self):
        return f"element_at({self.child}, {self.index})"


@dataclass(eq=False, frozen=True)
class ArrayContains(Expression):
    """array_contains(array, value) (reference: ArrayContains)."""

    child: Expression
    value: Expression

    def children(self):
        return (self.child, self.value)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"array_contains({self.child}, {self.value})"


@dataclass(eq=False, frozen=True)
class TupleExpr(Expression):
    """(a, b, ...) row-value constructor — only legal as the probe of a
    multi-column IN (subquery) (reference: In.scala accepts
    CreateStruct probes; the subquery rewrite expands it to a
    multi-key semi join)."""

    items: Tuple[Expression, ...]

    def children(self):
        return self.items

    def data_type(self, schema):
        raise TypeError(
            "a row-value (a, b) is only valid as the probe of a "
            "multi-column IN (subquery)")

    def __str__(self):
        return "(" + ", ".join(str(i) for i in self.items) + ")"


@dataclass(eq=False, frozen=True)
class MapHandle(Col):
    """A BARE reference to a decomposed MAP column, resolved to its
    '#keys' component (the canonical handle, types.MapType). Evaluates
    exactly like Col; the SQL select list uses the marker to expand a
    selected map to its component pair (map_keys() returns a plain Col
    and is NOT expanded)."""


@dataclass(eq=False, frozen=True)
class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) constructor (reference: CreateMap,
    complexTypeCreator.scala). Map-typed expressions are only legal at
    the top of a projection — the physical Project expands them into
    the '#keys'/'#vals' component pair (types.MapType)."""

    args: Tuple[Expression, ...]

    def __post_init__(self):
        if len(self.args) % 2:
            raise TypeError("map() needs an even argument count")

    def children(self):
        return self.args

    def data_type(self, schema):
        kt = self.args[0].data_type(schema)
        vt = self.args[1].data_type(schema)
        for k in self.args[2::2]:
            kt = T.common_type(kt, k.data_type(schema))
        for v in self.args[3::2]:
            vt = T.common_type(vt, v.data_type(schema))
        return T.MapType(kt, vt)

    def __str__(self):
        return f"map({', '.join(str(a) for a in self.args)})"


@dataclass(eq=False, frozen=True)
class MapFromArrays(Expression):
    """map_from_arrays(keys, values) (reference: MapFromArrays)."""

    keys: Expression
    vals: Expression

    def children(self):
        return (self.keys, self.vals)

    def data_type(self, schema):
        kt = self.keys.data_type(schema)
        vt = self.vals.data_type(schema)
        if not isinstance(kt, T.ArrayType) or not isinstance(vt, T.ArrayType):
            raise TypeError("map_from_arrays needs two array inputs")
        return T.MapType(kt.element, vt.element)

    def __str__(self):
        return f"map_from_arrays({self.keys}, {self.vals})"


@dataclass(eq=False, frozen=True)
class Lambda(Expression):
    """Anonymous function for higher-order array functions: ``x ->
    body`` / ``(x, i) -> body`` (reference: LambdaFunction,
    higherOrderFunctions.scala). Params bind as column names inside the
    body, shadowing outer columns; the TPU evaluation vectorizes the
    body over the flattened (rows x max_len) element plane — no per-
    element interpretation."""

    params: Tuple[str, ...]
    body: Expression

    def children(self):
        return (self.body,)

    def data_type(self, schema):
        raise TypeError("a lambda has no standalone type")

    def __str__(self):
        ps = ", ".join(self.params)
        return f"({ps}) -> {self.body}"


def _with_fields(schema, extra_fields):
    return T.Schema(tuple(schema.fields) + tuple(extra_fields))


@dataclass(eq=False, frozen=True)
class HigherOrder(Expression):
    """transform / filter / exists / forall / aggregate over arrays
    (reference: higherOrderFunctions.scala ArrayTransform/ArrayFilter/
    ArrayExists/ArrayForAll/ArrayAggregate). ``zero``/``finish`` are for
    ``aggregate`` only."""

    kind: str  # transform | filter | exists | forall | aggregate
    child: Expression
    fn: Lambda
    zero: Optional[Expression] = None
    finish: Optional["Lambda"] = None

    def children(self):
        return (self.child, self.fn) + (
            (self.zero,) if self.zero is not None else ())

    def _element_schema(self, schema):
        dt = self.child.data_type(schema)
        if not isinstance(dt, T.ArrayType):
            raise TypeError(f"{self.kind}() over non-array {dt!r}")
        fields = [T.Field(self.fn.params[0], dt.element, False)]
        if len(self.fn.params) > 1:
            fields.append(T.Field(self.fn.params[1], T.INT32, False))
        return _with_fields(schema, fields)

    def data_type(self, schema):
        if self.kind == "transform":
            return T.ArrayType(
                self.fn.body.data_type(self._element_schema(schema)))
        if self.kind == "filter":
            return self.child.data_type(schema)
        if self.kind in ("exists", "forall"):
            return T.BOOLEAN
        if self.kind == "aggregate":
            dt = self.child.data_type(schema)
            acc_dt = self.zero.data_type(schema)
            s2 = _with_fields(schema, [
                T.Field(self.fn.params[0], acc_dt, False),
                T.Field(self.fn.params[1], dt.element, False)])
            acc_dt = T.common_type(acc_dt, self.fn.body.data_type(s2))
            if self.finish is not None:
                s3 = _with_fields(
                    schema, [T.Field(self.finish.params[0], acc_dt,
                                     False)])
                return self.finish.body.data_type(s3)
            return acc_dt
        raise TypeError(f"unknown higher-order kind {self.kind!r}")

    def __str__(self):
        parts = [str(self.child), str(self.fn)]
        if self.zero is not None:
            parts.insert(1, str(self.zero))
        return f"{self.kind}({', '.join(parts)})"


@dataclass(eq=False, frozen=True)
class Explode(Expression):
    """Generator marker: one output row per array element (reference:
    Explode/PosExplode, generators.scala). Only legal inside a
    Generate plan node (physical GenerateExec); evaluating it as an
    ordinary expression raises."""

    child: Expression
    with_position: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        if isinstance(dt, T.ArrayType):
            return dt.element
        raise TypeError(f"explode over non-array {dt!r}")

    def __str__(self):
        return ("posexplode" if self.with_position else "explode") \
            + f"({self.child})"


@dataclass(eq=False, frozen=True)
class Grouping(Expression):
    """grouping(col): 1 when the row is a subtotal that aggregated
    ``col`` away (reference: grouping.scala Grouping). A marker —
    ResolveGroupingAnalytics-style rewriting (plan/grouping.py) replaces
    it with arithmetic over the grouping id before evaluation."""

    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.INT32

    def __str__(self):
        return f"grouping({self.child})"


@dataclass(eq=False, frozen=True)
class GroupingId(Expression):
    """grouping_id() marker (reference: grouping.scala GroupingID)."""

    def data_type(self, schema):
        return T.INT64

    def __str__(self):
        return "grouping_id()"


def contains_generator(e: Expression) -> bool:
    if isinstance(e, Explode):
        return True
    return any(contains_generator(c) for c in e.children())


@dataclass(eq=False, frozen=True)
class NullOf(Expression):
    """NULL typed like ``like`` (reference: Literal(null, child.dataType)
    inside NullIf's If rewrite, nullExpressions.scala). Keeps Case's
    common-type inference working where an untyped null literal cannot."""

    like: Expression

    def children(self):
        return (self.like,)

    def data_type(self, schema):
        return self.like.data_type(schema)

    def nullable(self, schema):
        return True

    def __str__(self):
        return f"NULL_OF({self.like})"


@dataclass(eq=False, frozen=True)
class In(Expression):
    child: Expression
    values: Tuple[Any, ...]  # python literals

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.child} IN {self.values})"


@dataclass(eq=False, frozen=True)
class Like(Expression):
    """SQL LIKE with % and _ wildcards; evaluated host-side over the
    column dictionary, gathered on device by code."""

    child: Expression
    pattern: str

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.child} LIKE {self.pattern!r})"


@dataclass(eq=False, frozen=True)
class Cast(Expression):
    child: Expression
    dtype: DataType

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.dtype

    def __str__(self):
        return f"CAST({self.child} AS {self.dtype})"


@dataclass(eq=False, frozen=True)
class Case(Expression):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE e END. With no ELSE,
    unmatched rows are NULL (SQL semantics). ``when``/``otherwise``
    make this directly chainable (pyspark's F.when().when().otherwise())."""

    branches: Tuple[Tuple[Expression, Expression], ...]
    else_value: Optional[Expression]

    def when(self, condition: "Expression", value: Any) -> "Case":
        return Case(self.branches + ((condition, lit_or_expr(value)),),
                    self.else_value)

    def otherwise(self, value: Any) -> "Case":
        return Case(self.branches, lit_or_expr(value))

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.else_value is not None:
            out.append(self.else_value)
        return tuple(out)

    def data_type(self, schema):
        dt = self.branches[0][1].data_type(schema)
        for _, v in self.branches[1:]:
            dt = T.common_type(dt, v.data_type(schema))
        if self.else_value is not None:
            dt = T.common_type(dt, self.else_value.data_type(schema))
        return dt

    def __str__(self):
        return "CASE ..."


@dataclass(eq=False, frozen=True)
class Coalesce(Expression):
    args: Tuple[Expression, ...]

    def children(self):
        return self.args

    def data_type(self, schema):
        dt = self.args[0].data_type(schema)
        for a in self.args[1:]:
            dt = T.common_type(dt, a.data_type(schema))
        return dt

    def __str__(self):
        return f"COALESCE({', '.join(map(str, self.args))})"


@dataclass(eq=False, frozen=True)
class ExtractDatePart(Expression):
    """EXTRACT(YEAR|MONTH|DAY FROM date_expr)."""

    part: str  # 'year' | 'month' | 'day'
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.INT32

    def __str__(self):
        return f"EXTRACT({self.part} FROM {self.child})"


@dataclass(eq=False, frozen=True)
class AddMonths(Expression):
    child: Expression
    months: int

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.DATE

    def __str__(self):
        return f"ADD_MONTHS({self.child}, {self.months})"


@dataclass(eq=False, frozen=True)
class StringPredicate(Expression):
    """startswith / endswith / contains — host dictionary evaluation."""

    op: str  # 'startswith' | 'endswith' | 'contains'
    child: Expression
    needle: str

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"{self.op}({self.child}, {self.needle!r})"


@dataclass(eq=False, frozen=True)
class Substring(Expression):
    """SUBSTRING(str, pos, len) — 1-based, host dictionary transform."""

    child: Expression
    pos: int
    length: int

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.STRING

    def __str__(self):
        return f"SUBSTRING({self.child}, {self.pos}, {self.length})"


@dataclass(eq=False, frozen=True)
class Concat(Expression):
    """String concatenation (|| / concat()). Evaluated over host
    dictionaries: the output dictionary is the cartesian product of the
    input dictionaries (guarded), codes combine by mixed radix."""

    args: Tuple[Expression, ...]

    def children(self):
        return self.args

    def data_type(self, schema):
        return T.STRING

    def __str__(self):
        return f"CONCAT({', '.join(map(str, self.args))})"


@dataclass(eq=False, frozen=True)
class ConcatWs(Expression):
    """concat_ws(sep, ...): separator-joined concat that SKIPS null
    arguments (reference: ConcatWs, stringExpressions.scala — null
    inputs drop out with their separator; result is never null unless
    the separator is). Evaluated over host dictionaries like Concat,
    with a per-input null sentinel absorbed into the mixed radix."""

    sep: str
    args: Tuple[Expression, ...]

    def children(self):
        return self.args

    def data_type(self, schema):
        return T.STRING

    def nullable(self, schema):
        return False

    def __str__(self):
        return f"CONCAT_WS({self.sep!r}, {', '.join(map(str, self.args))})"


@dataclass(eq=False, frozen=True)
class Abs(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    def __str__(self):
        return f"ABS({self.child})"


@dataclass(eq=False, frozen=True)
class UnaryMath(Expression):
    """floor/ceil/sqrt/exp/ln/log10/sign (reference: catalyst
    expressions/mathExpressions.scala)."""

    op: str
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        if self.op in ("floor", "ceil"):
            return T.INT64
        if self.op == "sign":
            return self.child.data_type(schema)
        return T.FLOAT64

    def __str__(self):
        return f"{self.op.upper()}({self.child})"


@dataclass(eq=False, frozen=True)
class Round(Expression):
    """ROUND(x, scale) with HALF_UP ties (Spark semantics; numpy rounds
    half-even)."""

    child: Expression
    scale: int = 0

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        return dt if dt.is_integral else T.FLOAT64

    def __str__(self):
        return f"ROUND({self.child}, {self.scale})"


@dataclass(eq=False, frozen=True)
class Pow(Expression):
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def data_type(self, schema):
        return T.FLOAT64

    def __str__(self):
        return f"POWER({self.left}, {self.right})"


@dataclass(eq=False, frozen=True)
class StringTransform(Expression):
    """upper/lower/trim/ltrim/rtrim/initcap/reverse/repeat/lpad/rpad/
    translate — host dictionary transforms (reference:
    stringExpressions.scala Upper/Lower/StringTrim/StringLPad/...).
    ``args`` carries the op's scalar parameters (pad string, width...)."""

    op: str
    child: Expression
    args: Tuple = ()

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.STRING

    def __str__(self):
        return f"{self.op.upper()}({self.child})"


@dataclass(eq=False, frozen=True)
class StrLength(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.INT32

    def __str__(self):
        return f"LENGTH({self.child})"


@dataclass(eq=False, frozen=True)
class RegexpExtract(Expression):
    """regexp_extract(str, pattern, group) (reference:
    regexpExpressions.scala RegExpExtract; no match -> '')."""

    child: Expression
    pattern: str
    group: int = 1

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.STRING

    def __str__(self):
        return f"REGEXP_EXTRACT({self.child}, {self.pattern!r}, {self.group})"


@dataclass(eq=False, frozen=True)
class RegexpReplace(Expression):
    child: Expression
    pattern: str
    replacement: str

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.STRING

    def __str__(self):
        return f"REGEXP_REPLACE({self.child}, {self.pattern!r})"


@dataclass(eq=False, frozen=True)
class RegexpLike(Expression):
    """RLIKE / regexp_like — boolean regex match over the dictionary."""

    child: Expression
    pattern: str

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.BOOLEAN

    def __str__(self):
        return f"({self.child} RLIKE {self.pattern!r})"


@dataclass(eq=False, frozen=True)
class DateTrunc(Expression):
    """date_trunc('year'|'month', date) (reference:
    datetimeExpressions.scala TruncDate)."""

    unit: str
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.DATE

    def __str__(self):
        return f"DATE_TRUNC({self.unit!r}, {self.child})"


@dataclass(eq=False, frozen=True)
class LastDay(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.DATE

    def __str__(self):
        return f"LAST_DAY({self.child})"


# ---- window expressions -----------------------------------------------------


@dataclass(eq=False, frozen=True)
class RowNumber(Expression):
    """row_number() — 1-based position within the window partition
    (reference: expressions/windowExpressions.scala RowNumber)."""

    def data_type(self, schema):
        return T.INT32

    def nullable(self, schema):
        return False

    @property
    def name(self):
        return "row_number()"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Rank(Expression):
    dense: bool = False

    def data_type(self, schema):
        return T.INT32

    def nullable(self, schema):
        return False

    @property
    def name(self):
        return "dense_rank()" if self.dense else "rank()"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class LagLead(Expression):
    """lag/lead(child, offset, default) — value of a row offset rows
    before/after within the partition (reference:
    windowExpressions.scala Lag/Lead)."""

    child: Expression
    offset: int
    default: Optional[Expression]
    lead: bool  # False = lag

    def children(self):
        return (self.child,) if self.default is None \
            else (self.child, self.default)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        fn = "lead" if self.lead else "lag"
        return f"{fn}({self.child}, {self.offset})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class NTile(Expression):
    n: int

    def data_type(self, schema):
        return T.INT32

    def nullable(self, schema):
        return False

    @property
    def name(self):
        return f"ntile({self.n})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class WindowExpr(Expression):
    """fn OVER (PARTITION BY ... ORDER BY ... frame) (reference:
    expressions/windowExpressions.scala WindowExpression +
    WindowSpecDefinition). ``frame`` is (mode, start, end) with mode
    'rows'|'range', bounds None=unbounded, 0=current row, +/-n offsets;
    None frame means the SQL default (RANGE UNBOUNDED PRECEDING..CURRENT
    ROW with ORDER BY, whole partition without)."""

    func: Expression
    partition_by: Tuple[Expression, ...]
    order_by: Tuple["SortOrder", ...]
    frame: Optional[Tuple[str, Optional[int], Optional[int]]] = None

    def children(self):
        return (self.func,) + tuple(self.partition_by) + tuple(self.order_by)

    def data_type(self, schema):
        dt = self.func.data_type(schema)
        if isinstance(self.func, Count):
            return T.INT64
        return dt

    @property
    def name(self):
        return f"{self.func.name} OVER (...)"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class TumblingWindow(Expression):
    """Tumbling event-time window key: floor(child / width) * width, the
    window START (reference: expressions/TimeWindow.scala). Carrying the
    width lets streaming eviction close a window only when the watermark
    passes its END."""

    child: Expression
    width: int

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        return "window"

    def __str__(self):
        return f"window({self.child}, {self.width})"

    def as_arith(self) -> Expression:
        return Arith("-", self.child,
                     Arith("%", self.child, Literal(self.width)))


@dataclass(eq=False, frozen=True)
class SessionWindow(Expression):
    """Gap-based session window key (reference:
    expressions/SessionWindow.scala; planned by MergingSessionsExec).
    The streaming runner keys partial aggregates by the raw event time
    (each event opens a provisional [t, t+gap) session) and merges
    overlapping sessions in the state-merge step; the grouping output
    is the merged session START."""

    child: Expression
    gap: int

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        return "session_window"

    def __str__(self):
        return f"session_window({self.child}, {self.gap})"


def window_dictionary(w: "WindowExpr", schema) -> Optional[tuple]:
    """String dictionary of a window output, when the function carries
    values through from a dictionary-encoded column (lag/lead/min/max/
    first)."""
    fn = w.func
    if not isinstance(fn, (LagLead, Min, Max, First)):
        return None
    c = strip_alias(fn.child)
    if isinstance(c, Col) and c.col_name in schema:
        return schema.field(c.col_name).dictionary
    return None


def contains_blocking(e: Expression) -> bool:
    """Any host-only (untraceable) expression below — forces the
    enclosing operator onto the eager path."""
    if e.blocks_trace:
        return True
    return any(contains_blocking(c) for c in e.children())


def contains_window(e: Expression) -> bool:
    if isinstance(e, WindowExpr):
        return True
    return any(contains_window(c) for c in e.children())


# ---- subquery expressions ---------------------------------------------------


@dataclass(eq=False, frozen=True)
class OuterRef(Expression):
    """A correlated reference to a column of the OUTER query inside a
    subquery (reference: expressions/subquery.scala OuterReference).
    Resolved dtype is captured at parse time; decorrelation
    (plan/subquery.py) eliminates these before execution."""

    col_name: str
    dtype: DataType = None  # type: ignore[assignment]

    def data_type(self, schema: Schema) -> DataType:
        return self.dtype

    def references(self) -> set:
        return set()  # not a reference of the INNER plan

    def __str__(self):
        return f"outer({self.col_name})"


class SubqueryExpression(Expression):
    """Marker base (reference: expressions/subquery.scala)."""


@dataclass(eq=False, frozen=True)
class ScalarSubquery(SubqueryExpression):
    plan: Any  # LogicalPlan producing one row, one column

    def data_type(self, schema: Schema) -> DataType:
        return self.plan.schema.fields[0].dtype

    def __str__(self):
        return "scalar-subquery(...)"


@dataclass(eq=False, frozen=True)
class InSubquery(SubqueryExpression):
    child: Expression
    plan: Any  # LogicalPlan producing one column
    negated: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema: Schema) -> DataType:
        return T.BOOLEAN

    def __str__(self):
        n = "NOT " if self.negated else ""
        return f"({self.child} {n}IN subquery(...))"


@dataclass(eq=False, frozen=True)
class Exists(SubqueryExpression):
    plan: Any  # LogicalPlan
    negated: bool = False

    def data_type(self, schema: Schema) -> DataType:
        return T.BOOLEAN

    def nullable(self, schema):
        return False

    def __str__(self):
        n = "NOT " if self.negated else ""
        return f"{n}EXISTS(...)"


def contains_subquery(e: Expression) -> bool:
    if isinstance(e, SubqueryExpression):
        return True
    return any(contains_subquery(c) for c in e.children())


# ---- sort order ------------------------------------------------------------


@dataclass(eq=False, frozen=True)
class SortOrder(Expression):
    """Sort key wrapper (reference: expressions/SortOrder.scala).
    nulls_first default matches Spark: NULLS FIRST for ASC, LAST for DESC."""

    child: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def nulls_first_resolved(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return self.ascending

    def __str__(self):
        d = "ASC" if self.ascending else "DESC"
        return f"{self.child} {d}"


# ---- aggregates ------------------------------------------------------------


class AggregateExpression(Expression):
    """Marker base for aggregate functions (reference:
    expressions/aggregate/)."""

    def data_type(self, schema):
        raise NotImplementedError


@dataclass(eq=False, frozen=True)
class Sum(AggregateExpression):
    child: Expression
    distinct: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        if dt.is_integral:
            return T.INT64
        if isinstance(dt, T.DecimalType):
            # reference: Sum widens by 10 integral digits (Sum.scala)
            return T.bounded_decimal(dt.precision + 10, dt.scale)
        return dt

    @property
    def name(self):
        d = "DISTINCT " if self.distinct else ""
        return f"sum({d}{self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Avg(AggregateExpression):
    child: Expression
    distinct: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        if isinstance(dt, T.DecimalType):
            # reference: Average adds 4 fractional digits (Average.scala)
            return T.bounded_decimal(dt.precision + 4, dt.scale + 4)
        return T.FLOAT64

    @property
    def name(self):
        d = "DISTINCT " if self.distinct else ""
        return f"avg({d}{self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Count(AggregateExpression):
    """COUNT(expr); COUNT(*) is Count(None)."""

    child: Optional[Expression] = None
    distinct: bool = False

    def children(self):
        return (self.child,) if self.child is not None else ()

    def data_type(self, schema):
        return T.INT64

    def nullable(self, schema):
        return False

    @property
    def name(self):
        inner = "*" if self.child is None else str(self.child)
        d = "DISTINCT " if self.distinct else ""
        return f"count({d}{inner})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Min(AggregateExpression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        return f"min({self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Max(AggregateExpression):
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        return f"max({self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class StddevVariance(AggregateExpression):
    """stddev_samp/stddev_pop/var_samp/var_pop via Welford-free
    sum/sum-of-squares formulation (matches benchmark parity targets,
    reference: AggregateBenchmark stddev row)."""

    kind: str  # 'stddev_samp' | 'stddev_pop' | 'var_samp' | 'var_pop'
    child: Expression

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.FLOAT64

    @property
    def name(self):
        return f"{self.kind}({self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class First(AggregateExpression):
    child: Expression
    ignore_nulls: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name(self):
        return f"first({self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Collect(AggregateExpression):
    """collect_list / collect_set: gather the group's values into an
    array (reference: expressions/aggregate/collect.scala). Blocking-
    only on device — the output width is the largest group's count, a
    data-dependent shape (the sort-agg path host-syncs it alongside the
    group count)."""

    child: Expression
    unique: bool = False  # collect_set

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        return T.ArrayType(self.child.data_type(schema))

    @property
    def name(self):
        return f"collect_{'set' if self.unique else 'list'}({self.child})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class Percentile(AggregateExpression):
    """percentile_approx / median, computed EXACTLY per group by a
    (group, value) lexsort + per-group rank gather — fully vectorized
    over groups, no host sync (reference:
    aggregate/ApproximatePercentile.scala:81, aggregate/Median uses
    exact Percentile; the TPU build has no reason to approximate:
    the sort is the same device sort every blocking aggregate pays).
    ``interpolate`` (median / exact percentile) returns float64 between
    ranks; otherwise the actual element at rank ceil(q*n) is returned in
    the input's type, matching approx_percentile's contract of picking
    a REAL element."""

    child: Expression
    percentage: float
    interpolate: bool = False

    def children(self):
        return (self.child,)

    def data_type(self, schema):
        if self.interpolate:
            return T.FLOAT64
        return self.child.data_type(schema)

    @property
    def name(self):
        fn = "median" if (self.interpolate and self.percentage == 0.5) \
            else ("percentile" if self.interpolate else "percentile_approx")
        arg = "" if fn == "median" else f", {self.percentage}"
        return f"{fn}({self.child}{arg})"

    def __str__(self):
        return self.name


def strip_alias(e: Expression) -> Expression:
    while isinstance(e, Alias):
        e = e.child
    return e


def contains_aggregate(e: Expression) -> bool:
    if isinstance(e, AggregateExpression):
        return True
    if isinstance(e, WindowExpr):
        return False  # the aggregate belongs to the window, not the query
    return any(contains_aggregate(c) for c in e.children())


def collect_aggregates(e: Expression) -> list:
    if isinstance(e, AggregateExpression):
        return [e]
    if isinstance(e, WindowExpr):
        return []
    out = []
    for c in e.children():
        out.extend(collect_aggregates(c))
    return out


def transform_expr_down(e: Expression, fn) -> Expression:
    """PRE-order expression transform: ``fn`` sees each node before its
    children; when fn returns a replacement, recursion stops there
    (TreeNode.transformDown analogue)."""
    import dataclasses

    ne = fn(e)
    if ne is not e:
        return ne
    new_fields = {}
    changed = False
    for f_name, f_val in vars(e).items():
        if isinstance(f_val, Expression):
            nv = transform_expr_down(f_val, fn)
            changed |= nv is not f_val
            new_fields[f_name] = nv
        elif isinstance(f_val, tuple) and any(
                isinstance(x, Expression)
                or (isinstance(x, tuple)
                    and any(isinstance(y, Expression) for y in x))
                for x in f_val):
            # handles tuple-of-tuple fields too (Case.branches)
            nlist = []
            for x in f_val:
                if isinstance(x, Expression):
                    nlist.append(transform_expr_down(x, fn))
                elif isinstance(x, tuple):
                    nlist.append(tuple(
                        transform_expr_down(y, fn)
                        if isinstance(y, Expression) else y for y in x))
                else:
                    nlist.append(x)
            nlist = tuple(nlist)
            changed |= any(
                a is not b if not isinstance(a, tuple)
                else any(p is not q for p, q in zip(a, b))
                for a, b in zip(nlist, f_val))
            new_fields[f_name] = nlist
        else:
            new_fields[f_name] = f_val
    if changed:
        e = dataclasses.replace(e, **{
            k: v for k, v in new_fields.items()
            if k in {fl.name for fl in dataclasses.fields(e)}
        })
    return e


def transform_expr(e: Expression, fn) -> Expression:
    """Bottom-up expression transform (TreeNode.transformUp analogue,
    reference: catalyst/trees/TreeNode.scala)."""
    import dataclasses

    new_fields = {}
    changed = False
    for f_name, f_val in vars(e).items():
        if isinstance(f_val, Expression):
            nv = transform_expr(f_val, fn)
            changed |= nv is not f_val
            new_fields[f_name] = nv
        elif isinstance(f_val, tuple) and f_val and any(
            isinstance(x, Expression)
            or (isinstance(x, tuple) and any(isinstance(y, Expression) for y in x))
            for x in f_val
        ):
            nlist = []
            for x in f_val:
                if isinstance(x, Expression):
                    nx = transform_expr(x, fn)
                    changed |= nx is not x
                    nlist.append(nx)
                elif isinstance(x, tuple):
                    ny = tuple(
                        transform_expr(y, fn) if isinstance(y, Expression) else y
                        for y in x
                    )
                    # identity check: `ny != x` would route through the
                    # DSL __eq__/__bool__ on Expression elements
                    changed |= any(a is not b for a, b in zip(ny, x))
                    nlist.append(ny)
                else:
                    nlist.append(x)
            new_fields[f_name] = tuple(nlist)
        else:
            new_fields[f_name] = f_val
    if changed:
        e = dataclasses.replace(e, **{
            k: v for k, v in new_fields.items()
            if k in {fl.name for fl in dataclasses.fields(e)}
        })
    return fn(e)

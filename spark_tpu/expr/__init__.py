from spark_tpu.expr import expressions
from spark_tpu.expr.compiler import TV, Env, evaluate

__all__ = ["expressions", "TV", "Env", "evaluate"]

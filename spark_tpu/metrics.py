"""Per-stage metrics + JSON event log.

Analogue of the reference's SQLMetrics + event logging
(sql/core/.../execution/metric/SQLMetrics.scala:40,
core/.../scheduler/EventLoggingListener.scala:48), collapsed to what a
single-process driver needs: every executed stage (fused program or
blocking operator) appends an event carrying operator, capacities and
wall time. The in-memory ring is inspectable via ``recent()``/
``last_query()``; setting ``spark.eventLog.dir`` also appends JSONL to
disk so hung or slow stages are visible post-mortem (the round-2 q19/q21
hangs shipped precisely because nothing recorded per-stage timing).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()
_IO_LOCK = threading.Lock()
_EVENTS: deque = deque(maxlen=4096)
_QUERY_MARKS: deque = deque(maxlen=64)
_counter = 0


_PATH_CACHE: Dict[str, Optional[str]] = {}


def _log_path() -> Optional[str]:
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is None:
        return None
    try:
        d = sess.conf.get("spark.eventLog.dir")
    except KeyError:
        return None
    if not d:
        return None
    # resolve + mkdir once per configured directory
    if d not in _PATH_CACHE:
        os.makedirs(d, exist_ok=True)
        _PATH_CACHE[d] = os.path.join(d, "events.jsonl")
    return _PATH_CACHE[d]


def record(kind: str, **fields: Any) -> None:
    global _counter
    ev = {"n": _counter, "ts": round(time.time(), 4), "kind": kind}
    ev.update(fields)
    path = _log_path()
    with _LOCK:
        _counter += 1
        _EVENTS.append(ev)
    if path is not None:
        # separate IO lock: disk latency must not serialize stages that
        # only touch the in-memory ring
        with _IO_LOCK:
            with open(path, "a") as f:
                f.write(json.dumps(ev) + "\n")


def query_start(description: str) -> int:
    with _LOCK:
        mark = _counter
    _QUERY_MARKS.append(mark)
    record("query_start", description=description)
    return mark


def recent(n: int = 100) -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_EVENTS)[-n:]


def last_query() -> List[Dict[str, Any]]:
    """Events since the last query_start (inclusive)."""
    with _LOCK:
        evs = list(_EVENTS)
    mark = _QUERY_MARKS[-1] if _QUERY_MARKS else 0
    return [e for e in evs if e["n"] >= mark]


def reset() -> None:
    with _LOCK:
        _EVENTS.clear()
        _QUERY_MARKS.clear()


class stage_timer:
    """Context manager recording one stage execution event."""

    def __init__(self, op: str, **fields: Any):
        self.op = op
        self.fields = fields

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ms = (time.perf_counter() - self.t0) * 1e3
        record("stage", op=self.op, ms=round(ms, 2),
               error=None if exc is None else repr(exc), **self.fields)
        return False

"""Per-stage metrics + JSON event log.

Analogue of the reference's SQLMetrics + event logging
(sql/core/.../execution/metric/SQLMetrics.scala:40,
core/.../scheduler/EventLoggingListener.scala:48), collapsed to what a
single-process driver needs: every executed stage (fused program or
blocking operator) appends an event carrying operator, capacities and
wall time. The in-memory ring is inspectable via ``recent()``/
``last_query()``; setting ``spark.eventLog.dir`` also appends JSONL to
disk so hung or slow stages are visible post-mortem (the round-2 q19/q21
hangs shipped precisely because nothing recorded per-stage timing).

Trace attribution: ``record()`` stamps the active span context
(spark_tpu/trace/ keeps it in the contextvar held here) onto every
event as ``trace_id``/``span_id``/``parent_id``, and query marks are
trace-id keyed — ``last_query()`` selects by trace id when the newest
query has one, so concurrent queries no longer steal each other's
stage/fault events; positional slicing survives only as the fallback
for id-less events.

Disk writes are buffered: ``record()`` appends to an in-memory line
buffer flushed on size (``_LOG_FLUSH_EVENTS``) or age
(``_LOG_FLUSH_SECONDS``), plus ``flush_log()`` at query end (trace root
exit) and atexit — span-volume logging must not serialize hot stages
behind one open+write per event.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from spark_tpu import locks

_LOCK = locks.named_lock("metrics.registry")
_IO_LOCK = locks.named_lock("metrics.io")
_EVENTS: deque = deque(maxlen=4096)
#: (first event counter, trace_id-or-None) per started query
_QUERY_MARKS: deque = deque(maxlen=64)
_counter = 0

#: active span context — a spark_tpu.trace.SpanContext; lives here (not
#: in spark_tpu/trace/) so record() can read it without an import cycle
_TRACE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "spark_tpu_trace_ctx", default=None)


def trace_context():
    return _TRACE_CTX.get()


def set_trace_context(ctx):
    """Set the active span context; returns the token for reset."""
    return _TRACE_CTX.set(ctx)


def reset_trace_context(token) -> None:
    _TRACE_CTX.reset(token)


_PATH_CACHE: Dict[str, Optional[str]] = {}

# ---- buffered JSONL writer (all state under _IO_LOCK) ----------------------

_LOG_BUF: List[str] = []
_LOG_BUF_PATH: Optional[str] = None
_LOG_LAST_FLUSH = 0.0
_LOG_FLUSH_EVENTS = 128
_LOG_FLUSH_SECONDS = 0.5


def _log_path() -> Optional[str]:
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is None:
        return None
    try:
        d = sess.conf.get("spark.eventLog.dir")
    except KeyError:
        return None
    if not d:
        return None
    # resolve + mkdir once per configured directory (under the IO lock:
    # concurrent queries must not race the mkdir/cache fill)
    with _IO_LOCK:
        if d not in _PATH_CACHE:
            os.makedirs(d, exist_ok=True)
            _PATH_CACHE[d] = os.path.join(d, "events.jsonl")
        return _PATH_CACHE[d]


def record(kind: str, **fields: Any) -> None:
    global _counter
    ev = {"ts": round(time.time(), 4), "kind": kind}
    ev.update(fields)
    ctx = _TRACE_CTX.get()
    if ctx is not None:
        # stamp the enclosing span's identity; explicit fields (the
        # span event records its own triple) win
        ev.setdefault("trace_id", ctx[0])
        ev.setdefault("span_id", ctx[1])
        if ctx[2] is not None:
            ev.setdefault("parent_id", ctx[2])
    path = _log_path()
    with _LOCK:
        ev["n"] = _counter
        _counter += 1
        _EVENTS.append(ev)
    if path is not None:
        _buffered_write(path, json.dumps(ev) + "\n")


def _buffered_write(path: str, line: str) -> None:
    """Append one JSONL line through the buffer. Separate IO lock: disk
    latency must not serialize stages that only touch the in-memory
    ring; appends buffer and flush on size/age so span volume costs one
    write per batch, not per event."""
    global _LOG_BUF_PATH, _LOG_LAST_FLUSH
    now = time.monotonic()
    with _IO_LOCK:
        if _LOG_BUF_PATH != path:
            # eventLog.dir changed mid-run: drain to the old file
            if _LOG_BUF and _LOG_BUF_PATH is not None:
                with open(_LOG_BUF_PATH, "a") as f:
                    f.write("".join(_LOG_BUF))
            _LOG_BUF.clear()
            _LOG_BUF_PATH = path
            _LOG_LAST_FLUSH = now
        _LOG_BUF.append(line)
        if (len(_LOG_BUF) >= _LOG_FLUSH_EVENTS
                or now - _LOG_LAST_FLUSH >= _LOG_FLUSH_SECONDS):
            with open(path, "a") as f:
                f.write("".join(_LOG_BUF))
            _LOG_BUF.clear()
            _LOG_LAST_FLUSH = now


def flush_log() -> None:
    """Drain the buffered JSONL writer (query end / atexit / before a
    reader opens the file)."""
    global _LOG_LAST_FLUSH
    with _IO_LOCK:
        if _LOG_BUF and _LOG_BUF_PATH is not None:
            with open(_LOG_BUF_PATH, "a") as f:
                f.write("".join(_LOG_BUF))
        _LOG_BUF.clear()
        _LOG_LAST_FLUSH = time.monotonic()


atexit.register(flush_log)


def query_start(description: str) -> int:
    ctx = _TRACE_CTX.get()
    tid = ctx[0] if ctx is not None else None
    with _LOCK:
        mark = _counter
        # mark append stays inside the lock: with concurrent queries an
        # interleaved record() would otherwise skew which events
        # last_query() attributes to the newest query
        _QUERY_MARKS.append((mark, tid))
    record("query_start", description=description)
    return mark


def recent(n: int = 100) -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_EVENTS)[-n:]


def query_events(trace_id: str) -> List[Dict[str, Any]]:
    """Every ring event stamped with ``trace_id`` (exact attribution,
    immune to concurrent interleaving)."""
    with _LOCK:
        evs = list(_EVENTS)
    return [e for e in evs if e.get("trace_id") == trace_id]


def query_marks() -> List[Tuple[int, Optional[str]]]:
    """(first event counter, trace_id) per started query, oldest
    first — the per-query folding key for history/ui rollups."""
    with _LOCK:
        return list(_QUERY_MARKS)


def last_query() -> List[Dict[str, Any]]:
    """Events of the most recent query. Trace-id keyed when the newest
    mark has one (events of OTHER concurrent queries are excluded;
    id-less events inside the positional window are kept so legacy
    emitters still attribute); pure positional slicing otherwise."""
    with _LOCK:
        evs = list(_EVENTS)
        mark, tid = _QUERY_MARKS[-1] if _QUERY_MARKS else (0, None)
    if tid is not None:
        return [e for e in evs
                if e.get("trace_id") == tid
                or ("trace_id" not in e and e["n"] >= mark)]
    return [e for e in evs if e["n"] >= mark]


def reset() -> None:
    with _LOCK:
        _EVENTS.clear()
        _QUERY_MARKS.clear()


def record_exchange(op: str, *, mode: str, devices: int, rows: int,
                    capacity_before: int, capacity_after: int,
                    buffer_bytes: int, exchanges: int = 1,
                    slice_capacity: Optional[int] = None) -> None:
    """One exchange observation (parallel/executor records these):
    ``mode`` is "adaptive" (a cut stage that ran under measured bounds)
    or "fused" (exchanges ran inside a fused stage at the static
    worst-case capacity — capacities then describe the stage output).
    ``capacity_*`` are PER-DEVICE capacities before/after adaptive
    compaction; ``buffer_bytes`` is the (D, slice) all_to_all send
    tensor a device ships over ICI; ``rows`` is global live rows
    through the exchange. The derived live-row fraction / padding
    ratio and the raw fields also land in gauges (exchange.*) for the
    ui /api/v1/exchange endpoint."""
    slots = max(1, int(capacity_after) * int(devices))
    live_fraction = min(1.0, int(rows) / slots)
    padding_ratio = round(1.0 - live_fraction, 4)
    fields: Dict[str, Any] = dict(
        op=op, mode=mode, devices=int(devices), rows=int(rows),
        exchanges=int(exchanges),
        capacity_before=int(capacity_before),
        capacity_after=int(capacity_after),
        buffer_bytes=int(buffer_bytes),
        live_fraction=round(live_fraction, 4),
        padding_ratio=padding_ratio)
    if slice_capacity is not None:
        fields["slice_capacity"] = int(slice_capacity)
    record("exchange", **fields)
    for k in ("rows", "buffer_bytes", "padding_ratio", "live_fraction",
              "capacity_before", "capacity_after"):
        set_gauge(f"exchange.{k}", fields[k])
    set_gauge("exchange.mode", mode)


# ---- gauges -----------------------------------------------------------------

#: last-set values for point-in-time measures (cache sizes, occupancy)
#: that would flood the event ring if recorded per change
_GAUGES: Dict[str, Any] = {}


def set_gauge(name: str, value: Any) -> None:
    with _LOCK:
        _GAUGES[name] = value


def gauges() -> Dict[str, Any]:
    with _LOCK:
        return dict(_GAUGES)


# ---- persistent compile-cache counters --------------------------------------

#: hit/miss counts for jax's persistent (disk) compilation cache —
#: api/session wraps the jax lookup path to feed these; warmup_s was
#: otherwise opaque (6-55 s per query with no sign whether XLA compiled
#: fresh or loaded an AOT executable)
_COMPILE_CACHE = {"hits": 0, "misses": 0}


def note_compile_cache(hit: bool) -> None:
    with _LOCK:
        _COMPILE_CACHE["hits" if hit else "misses"] += 1


def compile_cache_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_COMPILE_CACHE)


# ---- static-analysis counters -----------------------------------------------

#: pre-execution plan analyzer (spark_tpu/analysis/) — runs, total
#: error/warning-level diagnostics produced, and plans rejected by the
#: level=error submit gate. Shown in tracing.analysis_profile and
#: /api/v1/lint.
_ANALYSIS = {"runs": 0, "errors": 0, "warnings": 0, "gated": 0}


def note_analysis(report) -> None:
    """Fold one AnalysisReport into the counters and gauges; also logs
    the run as an ``analysis`` event so it lands in the query mark."""
    errs = len(report.errors())
    warns = len(report.warnings())
    with _LOCK:
        _ANALYSIS["runs"] += 1
        _ANALYSIS["errors"] += errs
        _ANALYSIS["warnings"] += warns
        _GAUGES["analysis.peak_bytes"] = int(report.peak_bytes)
        _GAUGES["analysis.fingerprint_stable"] = \
            bool(report.fingerprint_stable)
        _GAUGES["analysis.elapsed_ms"] = round(report.elapsed_ms, 3)
    record("analysis", plan=report.plan, errors=errs, warnings=warns,
           diagnostics=len(report.diagnostics),
           peak_bytes=int(report.peak_bytes),
           fingerprint_stable=bool(report.fingerprint_stable),
           elapsed_ms=round(report.elapsed_ms, 3))


def note_analysis_gated() -> None:
    with _LOCK:
        _ANALYSIS["gated"] += 1


def analysis_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_ANALYSIS)


# ---- executable-store counters ----------------------------------------------

#: cross-session executable store (spark_tpu/compile/) — hits/misses
#: against the AOT store, serialize puts, LRU evictions, corrupt-entry
#: evictions, background-compile chunk-first serves, hot swaps,
#: permanent chunked fallbacks after background failure, and pre-warmed
#: replays. Shown in tracing.warmup_profile and /api/v1/compile.
_EXEC_STORE = {"hits": 0, "misses": 0, "puts": 0, "evictions": 0,
               "corrupt": 0, "background": 0, "swaps": 0,
               "fallbacks": 0, "prewarmed": 0}


def note_exec_store(kind: str, n: int = 1) -> None:
    with _LOCK:
        _EXEC_STORE[kind] = _EXEC_STORE.get(kind, 0) + int(n)


def exec_store_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_EXEC_STORE)


def reset_exec_store() -> None:
    with _LOCK:
        for k in list(_EXEC_STORE):
            _EXEC_STORE[k] = 0


# ---- serving-tier counters --------------------------------------------------

#: federation router + plan-keyed result cache (spark_tpu/serve/) —
#: result-cache hits/misses, single-flight waits that piggybacked on an
#: in-flight execution, router dispatches, queue-full sheds to another
#: replica, re-dispatches after a replica death, all-replicas-saturated
#: rejections (the only case a client still sees a 429), and replica
#: connection failures. Shown in tracing.serve_profile and
#: /api/v1/serve.
_SERVE = {"hits": 0, "misses": 0, "waits": 0, "wait_timeouts": 0,
          "dispatches": 0, "sheds": 0, "redispatches": 0,
          "rejected": 0, "replica_failures": 0,
          "breaker_transitions": 0, "epoch_mints": 0,
          "epoch_retries": 0, "epoch_fences": 0,
          "invalidations": 0, "rebuilds": 0}


def note_serve(kind: str, n: int = 1) -> None:
    with _LOCK:
        _SERVE[kind] = _SERVE.get(kind, 0) + int(n)


def serve_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_SERVE)


def reset_serve() -> None:
    with _LOCK:
        for k in list(_SERVE):
            _SERVE[k] = 0


# ---- SLO serving counters ---------------------------------------------------

#: the SLO subsystem (spark_tpu/slo/) — submit-time predictions made,
#: finished queries folded back into the latency model, typed
#: InfeasibleDeadline rejects at admission, predictive brownout
#: transitions (predicted p99 vs target, distinct from the serve
#: tier's failure-driven brownout), effective-concurrency resizes, and
#: model-journal entries loaded at startup. Shown in scheduler.status
#: and /health.
_SLO = {"predictions": 0, "observations": 0, "cold_observations": 0,
        "rejects": 0, "brownout_enters": 0, "brownout_exits": 0,
        "resizes": 0, "loads": 0}


def note_slo(kind: str, n: int = 1) -> None:
    with _LOCK:
        _SLO[kind] = _SLO.get(kind, 0) + int(n)


def slo_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_SLO)


def reset_slo() -> None:
    with _LOCK:
        for k in list(_SLO):
            _SLO[k] = 0


# ---- adaptive-aggregation counters ------------------------------------------

#: the runtime-adaptive aggregation engine (parallel/executor.py) —
#: per-strategy pick counts (the static partial->final path, the
#: partial-bypass raw-row exchange, the measured hash-partial),
#: strategy pins forced by legality (order-dependent float partials),
#: sketch failures absorbed by falling back to partial->final, and how
#: many decisions ran with a forced conf override. Shown in
#: tracing.aggregation_profile and /api/v1/agg.
_AGG = {"partial": 0, "bypass": 0, "hash": 0, "sort": 0, "presplit": 0,
        "pinned": 0, "sketch_failures": 0, "presplit_failures": 0,
        "forced": 0, "sort_elided": 0}


def note_agg(kind: str, n: int = 1) -> None:
    with _LOCK:
        _AGG[kind] = _AGG.get(kind, 0) + int(n)


def agg_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_AGG)


def reset_agg() -> None:
    with _LOCK:
        for k in list(_AGG):
            _AGG[k] = 0


# ---- whole-query fusion counters --------------------------------------------

#: whole-query native fusion (parallel/executor.py _try_fuse) — fused
#: programs launched (one per query that fused), exchange+consumer
#: spans folded into them, bailouts back to staged execution (see the
#: per-reason fusion_bailout events for the taxonomy), and injected
#: faults absorbed at fusion.decide. Shown in tracing.fusion_profile
#: and the bench fusion phase.
_FUSION = {"fused_programs": 0, "fused_spans": 0, "bailouts": 0,
           "fault_fallbacks": 0}


def note_fusion(kind: str, n: int = 1) -> None:
    with _LOCK:
        _FUSION[kind] = _FUSION.get(kind, 0) + int(n)


def fusion_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_FUSION)


def reset_fusion() -> None:
    with _LOCK:
        for k in list(_FUSION):
            _FUSION[k] = 0


# ---- materialized-view counters ---------------------------------------------

#: the incremental materialized-view engine (spark_tpu/mview/) —
#: view registrations, fresh-hit serves, incremental delta merges,
#: full recomputes (non-mergeable plans, rewrites, incremental=off),
#: transient refresh retries, retry-exhaustion fallbacks to full
#: recompute, stream micro-batch merges, WAL-replay dedups dropped by
#: the batch-id watermark, and serve-tier result-cache repopulations.
#: Shown in tracing.mview_profile and /api/v1/mview.
_MVIEW = {"registrations": 0, "hits": 0, "incremental_merges": 0,
          "full_recomputes": 0, "refresh_retries": 0,
          "refresh_fallbacks": 0, "stream_merges": 0,
          "stream_dedups": 0, "serve_repopulations": 0}


def note_mview(kind: str, n: int = 1) -> None:
    with _LOCK:
        _MVIEW[kind] = _MVIEW.get(kind, 0) + int(n)


def mview_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_MVIEW)


def reset_mview() -> None:
    with _LOCK:
        for k in list(_MVIEW):
            _MVIEW[k] = 0


# ---- hybrid-hash-join counters ----------------------------------------------

#: the grant-driven dynamic hybrid hash join (physical/chunked.py
#: _HybridHashJoinAgg) — grants taken from the unified memory manager
#: (and their byte total), zero-byte grants (storage pins starved the
#: join: everything spills), mid-pass resident-set grows, partitions
#: demoted to host spill files (and the bytes written), spill file
#: writes/read-backs, bounded retries at the join.spill seams, recursive
#: repartitions of overflowing buckets, and fallbacks one rung down to
#: the static grace-hash join. Shown in tracing.storage_profile and
#: /api/v1/storage (via the manager snapshot) plus the hybrid_hash_agg
#: event per join.
_JOIN = {"grants": 0, "grant_bytes": 0, "zero_grants": 0, "grows": 0,
         "spilled_partitions": 0, "spill_bytes": 0, "spill_writes": 0,
         "spill_reads": 0, "spill_retries": 0,
         "recursive_repartitions": 0, "fallbacks": 0}


def note_join(kind: str, n: int = 1) -> None:
    with _LOCK:
        _JOIN[kind] = _JOIN.get(kind, 0) + int(n)


def join_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_JOIN)


def reset_join() -> None:
    with _LOCK:
        for k in list(_JOIN):
            _JOIN[k] = 0


# ---- recovery / OOM-ladder counters -----------------------------------------

#: the reactive recovery layer (recovery.py) — ``replans`` counts every
#: OOM-ladder re-execution (rung 0 forced-adaptive retry plus each
#: halved-budget chunked attempt): the number a planned single-pass
#: hybrid join keeps at ZERO where the old halve-and-retry path pays
#: one wasted device execution per rung. ``ladder_exhausted`` counts
#: queries that fell off the floor of the ladder.
_RECOVERY = {"replans": 0, "ladder_exhausted": 0}


def note_recovery(kind: str, n: int = 1) -> None:
    with _LOCK:
        _RECOVERY[kind] = _RECOVERY.get(kind, 0) + int(n)


def recovery_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_RECOVERY)


def reset_recovery() -> None:
    with _LOCK:
        for k in list(_RECOVERY):
            _RECOVERY[k] = 0


# ---- unified retry-budget counters ------------------------------------------

#: the per-query unified retry budget (recovery.RetryBudget) — ``draws``
#: counts every granted re-attempt across ALL layers (the per-query sum
#: is bounded by the budget instead of the old multiplicative product of
#: per-layer bounds), ``floor_draws`` the subset granted by a layer's
#: floor guarantee after the shared pool emptied, ``denials`` refused
#: draws (the seam surfaces RetryBudgetExhausted), ``exhaustions`` the
#: times a pool first hit empty, and ``legacy_attempts`` re-attempts
#: taken on the budget-less fallback path (the A/B counter the chaos
#: campaign compares against the budgeted path).
_RETRY = {"draws": 0, "floor_draws": 0, "denials": 0, "exhaustions": 0,
          "legacy_attempts": 0}


def note_retry_budget(kind: str, n: int = 1) -> None:
    with _LOCK:
        _RETRY[kind] = _RETRY.get(kind, 0) + int(n)


def retry_budget_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_RETRY)


def reset_retry_budget() -> None:
    with _LOCK:
        for k in list(_RETRY):
            _RETRY[k] = 0


# ---- fleet brownout level ----------------------------------------------------

#: fleet-wide brownout (serve/federation.py BrownoutController) —
#: ``level`` is the CURRENT shedding level (0 = normal; 1 = optional
#: analysis-heavy work shed: trace sampling, compile pre-warm, scan
#: auto-cache promotion), ``entered``/``exited`` count transitions.
#: Stored here (not on the controller) so consumers at the bottom of
#: the import graph — trace sampling, the datasource — read one int
#: without importing the serve tier.
_BROWNOUT = {"level": 0, "entered": 0, "exited": 0}


def set_brownout(level: int) -> None:
    with _LOCK:
        prev = _BROWNOUT["level"]
        level = int(level)
        if level > prev:
            _BROWNOUT["entered"] = _BROWNOUT.get("entered", 0) + 1
        elif level < prev:
            _BROWNOUT["exited"] = _BROWNOUT.get("exited", 0) + 1
        _BROWNOUT["level"] = level


def brownout_level() -> int:
    with _LOCK:
        return int(_BROWNOUT["level"])


def brownout_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_BROWNOUT)


def reset_brownout() -> None:
    with _LOCK:
        _BROWNOUT["level"] = 0
        _BROWNOUT["entered"] = 0
        _BROWNOUT["exited"] = 0


class PipelineStats:
    """Wall-time accounting for the out-of-HBM chunk pipeline
    (physical/pipeline.py): per-stage totals (decode / filter /
    transfer / compute), producer/consumer stall counters, and a
    DIRECTLY MEASURED overlap — the wall time during which a producer
    stage (decode/filter/transfer) and a consumer stage
    (compute/sidecar) were simultaneously in flight. Summing per-stage
    totals and subtracting wall time would mis-report overlap when
    stages interleave with stalls; the concurrency clock counts exactly
    the seconds the pipeline actually hid behind device compute."""

    PRODUCER_STAGES = ("decode", "filter", "transfer")
    CONSUMER_STAGES = ("compute", "sidecar")

    def __init__(self):
        self._t0 = time.perf_counter()
        self._lock = locks.named_lock("metrics.pipeline_stats")
        self._ms: Dict[str, float] = {}
        self._active = {"producer": 0, "consumer": 0}
        self._both_since: Optional[float] = None
        self._overlap_s = 0.0
        self.max_inflight_bytes = 0
        self.max_inflight_chunks = 0

    def add(self, stage: str, ms: float) -> None:
        with self._lock:
            self._ms[stage] = self._ms.get(stage, 0.0) + ms

    def timed(self, stage: str):
        return _PipelineStageTimer(self, stage)

    def _enter(self, role: str) -> None:
        with self._lock:
            self._active[role] += 1
            if (self._both_since is None
                    and all(self._active.values())):
                self._both_since = time.perf_counter()

    def _exit(self, role: str) -> None:
        with self._lock:
            self._active[role] -= 1
            if self._both_since is not None \
                    and not all(self._active.values()):
                self._overlap_s += time.perf_counter() - self._both_since
                self._both_since = None

    def note_inflight(self, nbytes: int, chunks: int) -> None:
        with self._lock:
            self.max_inflight_bytes = max(self.max_inflight_bytes,
                                          int(nbytes))
            self.max_inflight_chunks = max(self.max_inflight_chunks,
                                           int(chunks))

    def overlap_ms(self) -> float:
        with self._lock:
            s = self._overlap_s
            if self._both_since is not None:
                s += time.perf_counter() - self._both_since
        return s * 1e3

    def finish(self) -> Dict[str, Any]:
        """Close the clock and return the event fields to splat into
        ``record(...)``."""
        wall_ms = (time.perf_counter() - self._t0) * 1e3
        overlap = self.overlap_ms()
        with self._lock:
            ms = dict(self._ms)
        out: Dict[str, Any] = {
            f"{s}_ms": round(ms.get(s, 0.0), 2)
            for s in ("decode", "filter", "transfer", "compute")}
        if ms.get("sidecar"):
            out["sidecar_ms"] = round(ms["sidecar"], 2)
        out["wall_ms"] = round(wall_ms, 2)
        out["overlap_ms"] = round(overlap, 2)
        out["overlap_ratio"] = round(overlap / wall_ms, 4) if wall_ms \
            else 0.0
        out["stall_producer_ms"] = round(ms.get("stall_producer", 0.0), 2)
        out["stall_consumer_ms"] = round(ms.get("stall_consumer", 0.0), 2)
        out["max_inflight_bytes"] = self.max_inflight_bytes
        out["max_inflight_chunks"] = self.max_inflight_chunks
        return out


class _PipelineStageTimer:
    """Context manager: one timed pipeline-stage region, feeding both
    the per-stage total and the producer/consumer concurrency clock."""

    def __init__(self, stats: PipelineStats, stage: str):
        self._stats = stats
        self._stage = stage
        if stage in PipelineStats.PRODUCER_STAGES:
            self._role: Optional[str] = "producer"
        elif stage in PipelineStats.CONSUMER_STAGES:
            self._role = "consumer"
        else:
            self._role = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        if self._role is not None:
            self._stats._enter(self._role)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._role is not None:
            self._stats._exit(self._role)
        self._stats.add(self._stage,
                        (time.perf_counter() - self._t0) * 1e3)
        return False


class stage_timer:
    """Context manager recording one stage execution event."""

    def __init__(self, op: str, **fields: Any):
        self.op = op
        self.fields = fields

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ms = (time.perf_counter() - self.t0) * 1e3
        record("stage", op=self.op, ms=round(ms, 2),
               error=None if exc is None else repr(exc), **self.fields)
        return False

"""Deterministic, conf-driven fault injection.

The reference proves its recovery paths with chaos-style suites
(FailureSuite.scala, DAGSchedulerSuite's MockBackend killing executors
mid-stage); none of our failure paths were testable because there was
no way to *cause* a failure deterministically at a given seam. This
module is that switchboard: named injection points wired at the real
seams of the execution stack, armed per-session through ordinary conf
keys, raising *typed* faults that the recovery layer classifies the
same way it classifies the real thing.

Injection points (key = ``spark.tpu.faultInjection.<point>``):

- ``pipeline.decode``    parquet chunk decode in the out-of-HBM chunk
                         pipeline (physical/pipeline.py producer)
- ``pipeline.transfer``  host filter + host->device transfer of one
                         prepared chunk (same producer)
- ``execute.device``     whole-batch (resident) device execution of a
                         plan (api/dataframe.py _execute)
- ``exchange.all_to_all``the all-to-all collective exchange
                         (parallel/exchange.py, fires at trace time)
- ``streaming.commit``   micro-batch state/offset commit
                         (streaming/execution.py)
- ``connect.request``    the connect server's HTTP request handling
                         (connect/server.py)
- ``scheduler.admit``    the multi-tenant scheduler's HBM admission
                         decision (scheduler/scheduler.py), fired as a
                         query passes the device-admission gate
- ``compile.background`` the background fused-compile job of the AOT
                         compilation service (compile/service.py);
                         a fired fault pins the plan to the chunked
                         tier permanently (no swap, no crash)
- ``serve.dispatch``     the federation router's forward of one request
                         to a chosen replica (serve/federation.py) —
                         a transient fault is a replica dying mid-query
                         and triggers a bounded re-dispatch to a
                         different replica; the single-flight result
                         cache guarantees the query still executes at
                         most once per structural key
- ``serve.ownership``    the fleet ownership-control seams: the
                         router's per-replica epoch broadcast after a
                         mint (serve/federation.py) and a replica's
                         eager rebuild of newly-gained shards
                         (connect/server.py). ANY kind is absorbed:
                         a replica that misses the broadcast adopts
                         the epoch lazily from the next stamped
                         request, and a failed eager rebuild degrades
                         to lazy rebuild on first query — ownership
                         control traffic is advisory, bytes never
                         depend on it
- ``serve.invalidate``   a ResultCache applying one invalidation-log
                         record (serve/result_cache.py): ANY kind
                         degrades to a FULL cache clear — the planned,
                         bounded worst case is a cold cache, never a
                         stale one
- ``mview.refresh``      one incremental materialized-view refresh
                         (mview/manager.py): transient faults retry up
                         to spark.tpu.mview.refreshRetries, anything
                         past that falls back to a full recompute
                         (file views) or re-raises so the streaming
                         WAL replay redelivers the delta (stream
                         views) — bytes stay identical either way
- ``agg.strategy``       the adaptive aggregation strategy decision
                         (parallel/executor.py), fired between the
                         sketch fetch and the strategy pick: ANY kind
                         (transient/oom/hang/corrupt) is absorbed by
                         falling back to the static partial->final
                         strategy — the sketch is advisory, its result
                         is discarded on failure, so even a 'corrupt'
                         sketch cannot change bytes
- ``agg.presplit``       the hot-key pre-split arm of the adaptive
                         aggregation switch (parallel/executor.py),
                         fired after the Count-Min heavy-hitter scan
                         elects pre-splitting but before the salted
                         exchange is built: ANY kind degrades to the
                         static partial->final strategy — like
                         ``agg.strategy``, the candidate list is pure
                         advice and is discarded whole on failure
- ``fusion.decide``      the whole-query fusion decision
                         (parallel/executor.py _try_fuse), fired after
                         the plan is judged fusible but before the
                         fused span is built: ANY kind
                         (transient/oom/hang/corrupt) degrades to
                         staged adaptive execution — the fused program
                         is pure plan rewriting, the staged path
                         computes the identical bytes, so injection
                         can only cost the host round-trips fusion
                         would have saved
- ``slo.predict``        the SLO latency-model prediction at submit
                         time (slo/controller.py, OUTSIDE the
                         scheduler's condition lock): ANY kind is
                         absorbed as "no prediction" — the query is
                         treated FIFO-equivalent (always feasible, no
                         EDF advantage), bytes never depend on the
                         model
- ``slo.reject``         the reject-at-admission decision gate
                         (slo/controller.py): ANY kind FAILS OPEN —
                         the feasibility check is skipped and the
                         query admitted, so injection can only admit
                         more than policy would, never shed spuriously
- ``join.spill``         the hybrid hash join's host-spill seams
                         (physical/chunked.py _HybridHashJoinAgg):
                         spill-file WRITE during the partition pass,
                         spill-file READ-BACK during the join pass, and
                         the recursive repartition of an overflowing
                         bucket. transient/hang retry up to
                         spark.tpu.join.hybrid.spillRetryAttempts;
                         corrupt (or retry exhaustion) falls back one
                         rung down the ladder — the static grace-hash
                         join recomputed from source, byte-identical;
                         oom surfaces to the OOM degradation ladder
                         (the LAST resort)

Spec grammar (the conf value):

- ``none``               disarmed (default)
- ``nth:K[:kind]``       fire exactly once, on the K-th arrival at the
                         point (1-based) — the deterministic workhorse
- ``prob:P:SEED[:kind]`` fire each arrival with probability P from a
                         dedicated ``random.Random(f"{SEED}:{point}")``
                         stream — deterministic across reruns,
                         independent of any other RNG use, and ISOLATED
                         per point: the stream is salted with the point
                         name, so one point's draw count never perturbs
                         another point's sequence and a multi-point
                         chaos schedule reproduces from the campaign
                         seed alone (str seeding hashes via sha512 —
                         stable across processes and PYTHONHASHSEED)

Fault kinds (default ``transient``):

- ``transient``  UNAVAILABLE-style environment failure — retryable
                 (recovery.is_transient is True)
- ``oom``        RESOURCE_EXHAUSTED device OOM — NOT retryable; routed
                 to the degradation ladder (recovery.is_oom is True)
- ``hang``       sleeps ``spark.tpu.faultInjection.hangSeconds`` then
                 raises DEADLINE_EXCEEDED — a hang that a deadline
                 caught, so suites stay bounded while still exercising
                 the timeout/retry path
- ``corrupt``    DATA_LOSS — neither transient nor OOM: recovery must
                 surface it unretried as a clean, typed error

Arming counters live on the conf object, keyed by (point, spec), so
changing the spec re-arms the point and independent sessions never
share state. Every fired fault lands in the event log as
``fault_injected``; recoveries land as ``fault_recovered`` /
``degraded_to_chunked`` from the layer that absorbed them.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import metrics

POINTS = (
    "pipeline.decode",
    "pipeline.transfer",
    "execute.device",
    "exchange.all_to_all",
    "streaming.commit",
    "connect.request",
    "scheduler.admit",
    "compile.background",
    "serve.dispatch",
    "serve.ownership",
    "serve.invalidate",
    "mview.refresh",
    "agg.strategy",
    "agg.presplit",
    "join.spill",
    "slo.predict",
    "slo.reject",
    "fusion.decide",
)

KINDS = ("transient", "oom", "hang", "corrupt")

_ENTRIES = {
    point: CF.register(
        f"spark.tpu.faultInjection.{point}", "none",
        f"Fault injection spec for the '{point}' seam: none | "
        "nth:K[:kind] | prob:P:SEED[:kind]; kind in "
        "transient|oom|hang|corrupt (default transient).", str)
    for point in POINTS
}

HANG_SECONDS = CF.register(
    "spark.tpu.faultInjection.hangSeconds", 0.2,
    "How long an injected 'hang' fault sleeps before surfacing as "
    "DEADLINE_EXCEEDED (bounded so fault suites never actually hang).",
    float)


class InjectedFault(Exception):
    """Base class for injected faults; carries the point and kind so
    tests and the event log can tell injected failures from real ones."""

    kind = "transient"

    def __init__(self, point: str, message: str):
        super().__init__(message)
        self.point = point


class InjectedTransientError(InjectedFault):
    """UNAVAILABLE-shaped environment failure (retryable)."""

    kind = "transient"


class InjectedDeadlineError(InjectedFault):
    """DEADLINE_EXCEEDED surfaced after an injected hang (retryable)."""

    kind = "hang"


class InjectedOOMError(InjectedFault):
    """RESOURCE_EXHAUSTED device OOM (degradation ladder, not retry)."""

    kind = "oom"


class InjectedCorruptionError(InjectedFault):
    """DATA_LOSS — unrecoverable by design; must surface unretried."""

    kind = "corrupt"


@dataclass(frozen=True)
class _Spec:
    mode: str  # "nth" | "prob"
    kind: str
    k: int = 0
    p: float = 0.0
    seed: int = 0


def parse_spec(spec: str) -> Optional[_Spec]:
    """Parse a spec string; None when disarmed. Raises ValueError on a
    malformed spec — a typo'd injection silently doing nothing would be
    the exact observability hole this module exists to close."""
    s = str(spec or "").strip()
    if not s or s == "none":
        return None
    parts = s.split(":")
    try:
        if parts[0] == "nth" and len(parts) in (2, 3):
            kind = parts[2] if len(parts) == 3 else "transient"
            out = _Spec("nth", kind, k=int(parts[1]))
        elif parts[0] == "prob" and len(parts) in (3, 4):
            kind = parts[3] if len(parts) == 4 else "transient"
            out = _Spec("prob", kind, p=float(parts[1]),
                        seed=int(parts[2]))
        else:
            raise ValueError(s)
    except (ValueError, IndexError):
        raise ValueError(
            f"malformed fault-injection spec {spec!r}: expected "
            "none | nth:K[:kind] | prob:P:SEED[:kind]") from None
    if out.kind not in KINDS:
        raise ValueError(
            f"unknown fault kind {out.kind!r} in spec {spec!r}: "
            f"expected one of {KINDS}")
    return out


class _PointState:
    __slots__ = ("calls", "fired", "rng")

    def __init__(self, point: str, spec: _Spec):
        self.calls = 0
        self.fired = 0
        # the stream is salted with the point name: two points armed
        # from one campaign seed draw DECORRELATED sequences, and one
        # point's arrival count cannot shift another's draws — the
        # reproducibility contract multi-point chaos schedules rely on
        self.rng = random.Random(f"{spec.seed}:{point}") \
            if spec.mode == "prob" else None


_LOCK = locks.named_lock("faults.registry")


def _resolve_conf(conf):
    if conf is not None:
        return conf
    # seams inside traced/collective code (exchange) have no conf in
    # scope: fall back to the active session's
    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        return None if sess is None else sess.conf
    except Exception:
        return None


def _state(conf, point: str, spec_str: str, spec: _Spec) -> _PointState:
    states = conf.__dict__.setdefault("_fault_injection_state", {})
    key: Tuple[str, str] = (point, spec_str)
    st = states.get(key)
    if st is None:
        st = states[key] = _PointState(point, spec)
    return st


def reset(conf) -> None:
    """Drop all arming counters on ``conf`` (tests re-arm cleanly)."""
    conf.__dict__.pop("_fault_injection_state", None)


def fire_count(conf, point: str) -> int:
    """How many times ``point`` has fired on ``conf`` (any spec)."""
    states = conf.__dict__.get("_fault_injection_state", {})
    return sum(st.fired for (p, _), st in states.items() if p == point)


def inject(point: str, conf=None) -> None:
    """Arrival at a named injection point: no-op unless the point is
    armed on the session conf AND this arrival is selected, in which
    case the typed fault is recorded and raised (or, for ``hang``,
    slept then raised as a deadline)."""
    conf = _resolve_conf(conf)
    if conf is None:
        return
    entry = _ENTRIES.get(point)
    if entry is None:
        raise ValueError(f"unknown fault-injection point {point!r}: "
                         f"expected one of {POINTS}")
    try:
        spec_str = conf.get(entry)
    except KeyError:
        return
    spec = parse_spec(spec_str)
    if spec is None:
        return
    with _LOCK:
        st = _state(conf, point, str(spec_str), spec)
        st.calls += 1
        if spec.mode == "nth":
            fire = st.calls == spec.k and st.fired == 0
        else:
            fire = st.rng.random() < spec.p
        if fire:
            st.fired += 1
        calls = st.calls
    if not fire:
        return
    metrics.record("fault_injected", point=point, fault=spec.kind,
                   call=calls)
    if spec.kind == "oom":
        raise InjectedOOMError(
            point, f"RESOURCE_EXHAUSTED: injected device OOM at "
                   f"{point} (call {calls})")
    if spec.kind == "corrupt":
        raise InjectedCorruptionError(
            point, f"DATA_LOSS: injected corruption at {point} "
                   f"(call {calls})")
    if spec.kind == "hang":
        # the sleep never outlives the caller: a bound deadline caps it
        # (the injected "hang" models exactly the wait a real deadline
        # would cut short)
        from spark_tpu import deadline as _deadline

        delay = float(conf.get(HANG_SECONDS))
        time.sleep(_deadline.cap_sleep(max(0.0, delay)))
        raise InjectedDeadlineError(
            point, f"DEADLINE_EXCEEDED: injected hang at {point} "
                   f"surfaced after {delay:g}s (call {calls})")
    raise InjectedTransientError(
        point, f"UNAVAILABLE: injected transient fault at {point} "
               f"(call {calls})")

"""Lock-hierarchy registry and runtime lock-order validator.

Every long-lived lock in the tree is created through this module's
``named_lock`` / ``named_rlock`` / ``named_condition`` factories and
carries a NAME and a RANK from ``LOCK_RANKS``.  The rank defines the
only legal acquisition order: a thread holding a lock may only acquire
locks of strictly GREATER rank (outermost locks have the smallest
rank).  Re-entry of the same name is always legal — shared RLocks
(MemoryStore aliases UnifiedMemoryManager.lock) and per-instance locks
sharing one name (LruDict, PipelineStats, cache entry locks) both rely
on it.

Two verifiers check the same table:

- the static analyzer (``spark_tpu/analysis/concurrency.py`` via
  ``tools/lint_concurrency.py``) builds the lock-acquisition graph from
  the AST and reports edges that invert the ranks or form cycles;
- the runtime validator (``spark.tpu.debug.lockOrder``) records the
  per-thread held-stack on every acquire and flags observed
  rank-inverting edges and cycles in the observed edge set
  (``order_report()``).

This module is deliberately stdlib-only: metrics.py and every other
lock-bearing module imports it, so it must sit at the bottom of the
import graph.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

#: name -> rank.  Ascending rank is the legal outer->inner acquisition
#: order; gaps leave room for future locks.  Locks that never nest with
#: anything still get a name so the runtime validator can prove it.
LOCK_RANKS: Dict[str, int] = {
    # --- session / cache tier (outermost: held around whole queries) --
    "session.cache.registry": 100,   # CacheManager._lock: name->entry map
    "mview.manager": 120,            # ViewManager._lock: view registry
    "session.cache.entry": 140,      # per-entry materialization lock
    "mview.view": 150,               # MaterializedView.lock (refresh state)
    # --- compile service ---------------------------------------------
    "compile.plans": 200,            # CompileService._plans_lock
    "compile.jobs": 210,             # CompileService._jobs_lock
    "compile.stage": 220,            # per-stage background-compile state
    "compile.store": 230,            # ExecutableStore._lock (disk index)
    "compile.loaded": 240,           # compile/store.py _LOADED cache
    "compile.dict_fp": 250,          # compile/store.py dict-fp cache
    "compile.history": 260,          # PlanHistory._lock (history file)
    "compile.prewarm": 270,          # prewarm report/index accumulators
    # --- scheduler / execution ---------------------------------------
    "scheduler.cond": 300,           # QueryScheduler._cond: queue+gate
    "scheduler.pools": 310,          # PoolRegistry._lock
    "slo.model": 320,                # LatencyModel EWMA state + journal
    "slo.controller": 325,           # SloController window/resize state
    "pipeline.cond": 350,            # ChunkPipeline._cond: inflight budget
    "serve.invalidation": 355,       # InvalidationLog ring + subscribers
    "serve.result_cache": 360,       # ResultCache._flights map
    "serve.federation": 370,         # FederationRouter round-robin state
    "serve.ownership": 372,          # shard->owner map + epoch state
    "serve.breaker": 380,            # per-replica CircuitBreaker window
    "serve.brownout": 385,           # BrownoutController pressure window
    # --- storage / memory manager (inner: leaf data structures) ------
    "storage.unified": 400,          # UnifiedMemoryManager.lock (RLock,
    #                                  shared with MemoryStore._lock)
    "storage.lru": 420,              # LruDict._lock (serve blob cache)
    "admission.measured": 440,       # measured plan-bytes table
    "streaming.source": 460,         # streaming source buffers
    "recovery.retry_budget": 470,    # per-query RetryBudget pool state
    "recovery.checkpoint": 480,      # checkpoint dir init
    "faults.registry": 500,          # fault-injection spec table
    "native.registry": 520,          # pallas kernel registry
    "analysis.recent": 540,          # recent AnalysisReport ring
    # --- metrics (innermost: every layer records into them) ----------
    "metrics.registry": 900,         # metrics._LOCK: event/gauge tables
    "metrics.pipeline_stats": 910,   # PipelineStats._lock
    "metrics.io": 920,               # metrics._IO_LOCK: log-file writes
}


def rank_of(name: str) -> int:
    return LOCK_RANKS[name]


def register_lock(name: str, rank: int) -> None:
    """Extend the hierarchy (extensions/tests).  Refuses to re-rank an
    existing name — the table is the single source of truth."""
    existing = LOCK_RANKS.get(name)
    if existing is not None and existing != rank:
        raise ValueError(
            f"lock {name!r} already registered with rank {existing}")
    LOCK_RANKS[name] = rank


# --------------------------------------------------------------------------
# runtime order validation
# --------------------------------------------------------------------------

_VALIDATE = False
_local = threading.local()

# observation state shared by all threads; guarded by a RAW lock that is
# itself outside the validated world (never wrapped, never recorded).
_OBS_LOCK = threading.Lock()
_EDGES: Set[Tuple[str, str]] = set()          # observed (outer, inner)
_VIOLATIONS: List[dict] = []                  # rank inversions observed
_CYCLES: List[Tuple[str, ...]] = []           # cycles in the edge set
_MAX_VIOLATIONS = 256


def set_validation(on: bool) -> None:
    """Turn runtime lock-order recording on/off.  Proxies check the
    flag per acquire, so this works on locks created long before."""
    global _VALIDATE
    _VALIDATE = bool(on)


def validation_enabled() -> bool:
    return _VALIDATE


def configure(conf) -> None:
    """Wire validation to ``spark.tpu.debug.lockOrder``."""
    try:
        set_validation(bool(conf.get("spark.tpu.debug.lockOrder")))
    except Exception:
        pass


def reset_observations() -> None:
    with _OBS_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()
        _CYCLES.clear()


def order_report() -> dict:
    """Snapshot of everything the validator observed: the edge set,
    rank-inversion violations, and cycles in the observed graph."""
    with _OBS_LOCK:
        return {
            "enabled": _VALIDATE,
            "edges": sorted(_EDGES),
            "violations": list(_VIOLATIONS),
            "cycles": [list(c) for c in _CYCLES],
        }


def _held_stack() -> List[Tuple[str, int]]:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def _find_cycle_locked(start: str, target: str) -> Optional[Tuple[str, ...]]:
    """DFS over _EDGES from ``start`` looking for ``target``; returns
    the node path if adding (target -> start) closes a cycle.  Called
    with _OBS_LOCK held on a small graph (dozens of names)."""
    path: List[str] = [start]
    seen = {start}

    def dfs(node: str) -> bool:
        if node == target:
            return True
        for (a, b) in _EDGES:
            if a == node and b not in seen:
                seen.add(b)
                path.append(b)
                if dfs(b):
                    return True
                path.pop()
        return False

    return tuple(path) if dfs(start) else None


def _note_acquired(name: str, ident: int) -> None:
    """Record that the current thread acquired ``name`` while holding
    everything on its stack; detect rank inversions and new cycles."""
    stack = _held_stack()
    new_edges = []
    for held_name, held_id in stack:
        if held_name == name:
            # same-name re-entry (RLock sharing / sibling instances
            # under one name) is legal by construction
            continue
        edge = (held_name, name)
        r_held = LOCK_RANKS.get(held_name)
        r_new = LOCK_RANKS.get(name)
        bad = (r_held is not None and r_new is not None and r_new <= r_held)
        with _OBS_LOCK:
            fresh = edge not in _EDGES
            if fresh:
                _EDGES.add(edge)
                new_edges.append(edge)
            if bad and len(_VIOLATIONS) < _MAX_VIOLATIONS:
                if fresh or not any(v["edge"] == list(edge)
                                    for v in _VIOLATIONS):
                    _VIOLATIONS.append({
                        "kind": "rank-inversion",
                        "edge": [held_name, name],
                        "ranks": [r_held, r_new],
                        "thread": threading.current_thread().name,
                    })
    # cycle check only on fresh edges (the graph is tiny and edges are
    # recorded once, so this is off the steady-state hot path)
    for (a, b) in new_edges:
        with _OBS_LOCK:
            cyc = _find_cycle_locked(b, a)
            if cyc is not None:
                full = cyc + (b,)          # b -> ... -> a -> b
                if full not in _CYCLES:
                    _CYCLES.append(full)
    stack.append((name, ident))


def _note_released(name: str, ident: int) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == (name, ident):
            del stack[i]
            return


class _NamedLockBase:
    """Thin proxy over a threading lock primitive.  Always constructed
    (so validation can be flipped on mid-process for locks created at
    import time); per-acquire cost when validation is off is a single
    global-flag check."""

    __slots__ = ("name", "rank", "_inner")
    _kind = "lock"

    def __init__(self, name: str, inner) -> None:
        if name not in LOCK_RANKS:
            raise ValueError(
                f"lock name {name!r} is not in locks.LOCK_RANKS — "
                "register it (with a rank) before use")
        self.name = name
        self.rank = LOCK_RANKS[name]
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _VALIDATE:
            _note_acquired(self.name, id(self._inner))
        return got

    def release(self) -> None:
        if _VALIDATE:
            _note_released(self.name, id(self._inner))
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r} rank={self.rank}>"


class NamedLock(_NamedLockBase):
    _kind = "lock"


class NamedRLock(_NamedLockBase):
    _kind = "rlock"

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        raise NotImplementedError


class NamedCondition(_NamedLockBase):
    """Condition proxy: the underlying lock is acquired/released via
    the proxy bookkeeping; wait's internal release-reacquire is not
    modelled (the thread is blocked, so it records no edges)."""

    _kind = "condition"

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._inner.wait(timeout)

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def named_lock(name: str) -> NamedLock:
    return NamedLock(name, threading.Lock())


def named_rlock(name: str) -> NamedRLock:
    return NamedRLock(name, threading.RLock())


def named_condition(name: str) -> NamedCondition:
    return NamedCondition(name, threading.Condition())

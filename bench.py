"""Headline benchmark: ungrouped aggregation throughput.

Mirrors the reference's AggregateBenchmark "agg w/o group" row — 1e9
rows of range() summed — whose checked-in baseline is 932 ms ≈ 2,250 M
rows/s with whole-stage codegen on a Xeon 8370C (reference:
sql/core/benchmarks/AggregateBenchmark-jdk17-results.txt:10, harness
sql/core/src/test/.../benchmark/AggregateBenchmark.scala). Here the
whole query — iota, predicate, sum/count — is one fused XLA program on
the TPU; prints one JSON line with vs_baseline = baseline_ms / our_ms
(>1 means faster than the reference).
"""

import json
import time

import jax

jax.config.update("jax_enable_x64", True)

N = 1 << 30  # ~1.07e9 rows (reference benchmark uses 1e9)
BASELINE_MS = 932.0 * (N / 1e9)  # scale reference ms to our row count


def main():
    from spark_tpu.expr import expressions as E
    from spark_tpu.physical import operators as P
    from spark_tpu.physical.planner import execute

    plan = P.HashAggregateExec(
        (),
        (E.Alias(E.Sum(E.Col("id")), "s"),
         E.Alias(E.Count(None), "n")),
        P.RangeExec(0, N, 1))

    def run():
        batch = execute(plan)
        jax.block_until_ready(batch.data.columns[0].data)
        return batch

    run()  # compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        batch = run()
        times.append((time.perf_counter() - t0) * 1000)
    row = batch.to_pylist()[0]
    assert row["n"] == N, row
    assert row["s"] == N * (N - 1) // 2, row

    ms = min(times)
    print(json.dumps({
        "metric": "agg_no_group_1e9_rows",
        "value": round(ms, 2),
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / ms, 3),
    }))


if __name__ == "__main__":
    main()

"""Headline benchmark: TPC-H q1/q3/q5 wall-clock on the real TPU chip.

This is the scored metric (BASELINE.md: TPC-H wall-clock vs Spark CPU
``local[*]``, result parity; harness model: the reference's
sql/core/src/test/.../benchmark/TPCDSQueryBenchmark.scala:86). Honesty
requirements (round-2 verdict #2):

- inputs are Parquet-written, Parquet-read, device-resident columnar
  batches fed to the jitted stages as ARGUMENTS — the physical plan is
  asserted to contain real data leaves, so XLA cannot constant-fold the
  query away (the round-1/2 bench measured a precomputed constant);
- per-query wall-clock covers the full execute path including blocking
  operators and host syncs, after one warm-up run (compile caches warm,
  matching the reference benchmark's N-iteration protocol);
- implied scan bandwidth is asserted to be below the chip's HBM
  bandwidth — a result faster than physically possible means the
  benchmark is broken, and fails loudly.

Baseline: Spark CPU local[*] is NOT runnable in this image (no JVM), so
``vs_baseline`` uses a documented per-query estimate for Spark 3.5 on a
modern server CPU at SF1, calibrated from the reference's checked-in
benchmark files (AggregateBenchmark-jdk17-results.txt:10 — 2,250 M
simple rows/s ungrouped; TPCDSQueryBenchmark-jdk17-results.txt:5,17,29 —
TPC-DS SF1 q1/q3/q5 = 1178/431/2026 ms on Azure Xeon). TPC-H SF1
estimates used here: q1=900 ms (6M-row scan + 8-expression grouped agg;
Spark's measured grouped-agg rate is far below the ungrouped 2,250 M/s),
q3=700 ms, q5=1100 ms (3- and 6-way joins at SF1, TPC-DS q3/q5-class).
These deliberately favour Spark; treat vs_baseline as indicative, the
absolute ms as the record.
"""

import contextlib
import json
import os
import signal

import time

import numpy as np

SF = float(os.environ.get("BENCH_SF", "1.0"))
# SF>10 runs out-of-HBM (host-streamed chunks): one timed pass, no
# median protocol — a single q1 pass at SF100 is minutes of parquet IO
N_ITER = int(os.environ.get("BENCH_ITERS", "5" if SF <= 10 else "1"))
# BENCH_FULL=1: additionally time ALL 22 TPC-H queries (the BASELINE.md
# target metric is the full suite; q1/q3/q5 stay the headline line)
FULL = os.environ.get("BENCH_FULL", "0") == "1"
HBM_GBPS = 819.0  # v5e peak HBM bandwidth; v5p is higher, so safe bound

# Per-query wall-clock cap. A query that hangs (or an SF that turns out
# to be hours of parquet IO) records {"error": "timeout"} and the run
# moves on — the final JSON stays valid and covers every other query,
# instead of the whole process dying to the harness's timeout(1) with
# no parseable output at all.
QUERY_TIMEOUT_S = float(os.environ.get("BENCH_QUERY_TIMEOUT",
                                       "600" if SF <= 10 else "1200"))
# Snapshot written after every query so even a SIGKILL leaves the
# completed queries' numbers on disk.
PARTIAL_PATH = os.environ.get("BENCH_PARTIAL_PATH", "BENCH_partial.json")

# Global wall-clock budget for the WHOLE bench process. The harness
# runs bench under an external timeout; hitting that kills the process
# (rc=124) with only BENCH_partial.json on disk. Budgeting inside the
# process instead skips remaining phases (marked in the JSON) so the
# final complete document always prints. 0 disables.
WALL_BUDGET_S = float(os.environ.get("BENCH_WALL_BUDGET", "3300"))
_WALL_T0 = time.time()

# BENCH_CACHED=0 skips the HBM-store cached-mode report
CACHED_MODE = os.environ.get("BENCH_CACHED", "1") == "1"

# BENCH_ADAPTIVE=0 skips the adaptive-execution A/B phase (off vs on
# timing + byte-identity + padding-ratio report; needs BENCH_MASTER=
# mesh[N] to actually engage — single-device sessions have no exchange
# stages to re-plan and report {"skipped": ...})


def _wall_remaining() -> float:
    if WALL_BUDGET_S <= 0:
        return float("inf")
    return WALL_BUDGET_S - (time.time() - _WALL_T0)


def _query_deadline(extra_s: float = 0.0, cap_s: float = None) -> float:
    """Per-query alarm, never longer than what the wall budget has
    left (so the last query degrades to a marked timeout instead of
    blowing the whole process budget). ``extra_s`` extends the cap for
    phases where a background fused compile runs concurrently with the
    measured query (compile/service hot-swap) — a query correctly
    served by the chunked tier while XLA compiles off-thread must not
    be marked timed-out just because the compile is still running.
    ``cap_s`` tightens the cap below QUERY_TIMEOUT_S for auxiliary
    phases (see PHASE_BUDGET_S)."""
    base = QUERY_TIMEOUT_S + extra_s
    if cap_s is not None:
        base = min(base, cap_s)
    rem = _wall_remaining()
    if rem == float("inf"):
        return base
    return max(1.0, min(base, rem))


# Per-phase deadline caps. Before these, every auxiliary A/B phase ran
# under the full QUERY_TIMEOUT_S (600s at SF<=10): two slow phases
# could eat 1200s of a 3300s wall budget and starve everything after
# them into "skipped" markers. The headline queries keep the full cap;
# the A/B phases are all sub-minute in the common case and get a cap
# sized ~3x their observed worst case instead.
PHASE_BUDGET_S = {
    "cached": 180.0, "adaptive": 240.0, "serving": 240.0,
    "serve": 240.0, "fleet": 240.0, "mview": 180.0, "agg": 420.0,
    "join": 420.0, "trace": 150.0, "slo": 300.0, "fusion": 240.0,
}


def _phase_deadline(phase: str) -> float:
    return _query_deadline(cap_s=PHASE_BUDGET_S.get(phase))


class _QueryTimeout(Exception):
    pass


@contextlib.contextmanager
def _deadline(seconds: float):
    """Raise _QueryTimeout in the main thread after ``seconds``."""
    if seconds <= 0 or not hasattr(signal, "setitimer"):
        yield
        return

    def _alarm(signum, frame):
        raise _QueryTimeout(f"query exceeded {seconds:.0f}s")

    prev = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)


def _snapshot(payload: dict) -> None:
    try:
        with open(PARTIAL_PATH, "w") as f:
            json.dump(payload, f)
    except OSError:
        pass

# documented Spark CPU local[*] SF1 estimates (see module docstring)
BASELINE_MS = {1: 900.0, 3: 700.0, 5: 1100.0}

# BENCH_WARMUP=0 skips the cold-start A/B phase (first-query latency:
# empty executable store vs populated store vs background-compile path,
# each measured in a FRESH subprocess so jit caches are honestly cold)
WARMUP_MODE = os.environ.get("BENCH_WARMUP", "1") == "1"

# BENCH_MVIEW=0 skips the materialized-view refresh A/B (K appended
# micro-batches x M readers, spark.tpu.mview.incremental off vs on;
# refresh latency + device executions + byte-identity land under
# 'mview' in the result JSON)
MVIEW_MODE = os.environ.get("BENCH_MVIEW", "1") == "1"

# BENCH_AGG=0 skips the adaptive-aggregation A/B (low-NDV / high-NDV /
# skewed group-bys, spark.tpu.adaptive.agg.enabled off vs on; timing +
# byte-identity digest + per-strategy pick counts land under 'agg' in
# the result JSON; needs BENCH_MASTER=mesh[N] to engage)
AGG_MODE = os.environ.get("BENCH_AGG", "1") == "1"

# BENCH_JOIN=0 skips the hybrid-hash-join A/B (an out-of-core join run
# at the full memory budget, at 1/8 of it through the grant-driven
# hybrid join's planned spilling, and through the old reactive OOM
# ladder; replan counts + spill bytes + timing + byte-identity land
# under 'join' in the result JSON)
JOIN_MODE = os.environ.get("BENCH_JOIN", "1") == "1"

# BENCH_TRACE=0 skips the tracing-overhead A/B (q1/q3 timed with the
# span layer off vs always-on vs 10%-sampled; overhead % + byte-identity
# + the host/device/queue/transfer breakdown of one traced q3 land
# under 'trace' in the result JSON)
TRACE_MODE = os.environ.get("BENCH_TRACE", "1") == "1"

# BENCH_FUSION=0 skips the whole-query fusion A/B (q3/q5-shaped
# multi-exchange plans timed staged vs fused under adaptive execution;
# total latency, host/queue trace breakdown before/after, fused span
# counts and byte-identity land under 'fusion' in the result JSON;
# needs BENCH_MASTER=mesh[N] to engage)
FUSION_MODE = os.environ.get("BENCH_FUSION", "1") == "1"

# BENCH_FLEET=0 skips the fleet scaling sweep (QPS vs replica count on
# NON-cacheable unique-plan traffic over a sharded dataset with
# shard-ownership routing on; per-cell byte-identity against the
# 1-replica cell lands under 'fleet' in the result JSON)
FLEET_MODE = os.environ.get("BENCH_FLEET", "1") == "1"

# BENCH_SLO=0 skips the SLO serving A/B (needs --concurrency): the
# golden q1/q3/q5 mix under ~2x closed-loop overload with per-query
# deadlines, FIFO vs SLO mode (EDF + reject-at-admission); successful-
# within-SLO counts, p99, shed counts and byte-identity land under
# 'slo' in the result JSON
SLO_MODE = os.environ.get("BENCH_SLO", "1") == "1"


def _warmup_child() -> None:
    """Subprocess entry for the cold-start A/B (BENCH_WARMUP_CHILD=1):
    a fresh process = honestly cold jit/XLA state. Builds a session
    against the store dir in BENCH_WARMUP_STORE, times the FIRST
    collect of the query (that wall time IS the cold-start number),
    then runs two more collects so the fused re-execution path AOT-
    compiles and persists — populating the store for the next child.
    Prints one marker line of JSON on stdout and exits."""
    import sys

    import jax

    jax.config.update("jax_enable_x64", True)

    from spark_tpu import metrics
    from spark_tpu.api.session import SparkSession
    from spark_tpu.tpch.gen import ensure_dataset, register_views
    from spark_tpu.tpch.queries import QUERIES

    qnum = int(os.environ.get("BENCH_WARMUP_QNUM", "1"))
    store = os.environ.get("BENCH_WARMUP_STORE", "")
    background = os.environ.get("BENCH_WARMUP_BACKGROUND", "0") == "1"

    builder = SparkSession.builder
    if store:
        builder = builder.config("spark.tpu.compile.store.dir", store)
    if background:
        builder = builder.config("spark.tpu.compile.background", "true")
    spark = builder.getOrCreate()
    register_views(spark, path=ensure_dataset(SF))

    df = spark.sql(QUERIES[qnum])
    t0 = time.perf_counter()
    rows = df.collect()
    first_ms = (time.perf_counter() - t0) * 1e3
    digest = __import__("hashlib").sha1(
        repr([tuple(r) for r in rows]).encode()).hexdigest()[:16]
    # two more runs: the traced/fused path compiles (and persists to
    # the store) so the NEXT child's first query can hit the cache
    df.collect()
    df.collect()
    svc = spark.compile_service
    if svc is not None:
        svc.wait_background(timeout=QUERY_TIMEOUT_S)
        post = [tuple(r) for r in df.collect()]
        post_digest = __import__("hashlib").sha1(
            repr(post).encode()).hexdigest()[:16]
    else:
        post_digest = digest
    print("BENCH_WARMUP_CHILD_RESULT " + json.dumps({
        "first_query_ms": round(first_ms, 1),
        "rows": len(rows),
        "digest": digest,
        "post_swap_digest": post_digest,
        "exec_store": metrics.exec_store_stats(),
        "compile_cache": metrics.compile_cache_stats(),
    }), flush=True)
    sys.exit(0)


def _spawn_warmup_child(store: str, background: bool,
                        qnum: int, timeout_s: float) -> dict:
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({
        "BENCH_WARMUP_CHILD": "1",
        "BENCH_WARMUP_STORE": store,
        "BENCH_WARMUP_BACKGROUND": "1" if background else "0",
        "BENCH_WARMUP_QNUM": str(qnum),
    })
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s:.0f}s"}
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_WARMUP_CHILD_RESULT "):
            return json.loads(line.split(" ", 1)[1])
    return {"error": f"child rc={proc.returncode}: "
                     f"{proc.stderr.strip()[-500:]}"}


def _run_warmup_ab(qnum: int = 1) -> dict:
    """Cold-start A/B (ROADMAP item 1 acceptance): first-query latency
    in a fresh process with (a) an empty executable store, (b) the
    store (a) populated — the cross-session cache win, target >= 5x —
    and (c) an empty store with background compile on — the first
    request must be served through the chunked tier without blocking
    on the fused XLA compile. Byte-identity is asserted across all
    three children AND across (c)'s pre-swap/post-swap executions."""
    import tempfile

    store_ab = tempfile.mkdtemp(prefix="bench_exec_store_")
    store_bg = tempfile.mkdtemp(prefix="bench_exec_store_bg_")
    out: dict = {"query": qnum}
    # empty-store cold start: pays trace + XLA compile + store put
    out["cold_empty"] = _spawn_warmup_child(
        store_ab, False, qnum, _query_deadline())
    # populated-store cold start: fresh process, same store dir
    out["cold_populated"] = _spawn_warmup_child(
        store_ab, False, qnum, _query_deadline())
    # background-compile path: chunked serve while XLA compiles
    # off-thread — the child's own runtime covers the compile, so its
    # timeout gets the background allowance (see _query_deadline)
    out["background"] = _spawn_warmup_child(
        store_bg, True, qnum, _query_deadline(extra_s=QUERY_TIMEOUT_S))

    a, b, c = out["cold_empty"], out["cold_populated"], out["background"]
    if "first_query_ms" in a and "first_query_ms" in b:
        out["speedup_populated_vs_empty"] = round(
            a["first_query_ms"] / max(b["first_query_ms"], 1e-3), 2)
        out["store_hit_on_populated"] = \
            b.get("exec_store", {}).get("hits", 0) > 0 \
            or b.get("compile_cache", {}).get("hits", 0) > 0
    digests = {r.get("digest") for r in (a, b, c) if r.get("digest")}
    out["byte_identical"] = len(digests) <= 1 and all(
        r.get("digest") == r.get("post_swap_digest")
        for r in (a, b, c) if r.get("digest"))
    if "exec_store" in c:
        out["background_served_without_blocking"] = \
            c["exec_store"].get("background", 0) > 0
    return out

# robustness events worth surfacing in the result JSON: a benchmark run
# that silently retried stages or degraded to the chunked tier is not
# measuring what the headline number claims
_ROBUSTNESS_KINDS = ("stage_retry", "chunk_retry", "fault_injected",
                     "fault_recovered", "degraded_to_chunked")


def _robustness_counters() -> dict:
    from spark_tpu import metrics

    counts = {k: 0 for k in _ROBUSTNESS_KINDS}
    for ev in metrics.recent(4096):
        kind = ev.get("kind")
        if kind in counts:
            counts[kind] += 1
    return counts


def _shuffle_block() -> dict:
    """Per-query shuffle observability: exchange count, rows actually
    sent over ICI, buffer bytes, padding ratio (dead slots the static
    capacity contract shipped anyway), and any adaptive decisions —
    for the execution that just finished (metrics.last_query)."""
    from spark_tpu import metrics, tracing

    try:
        prof = tracing.exchange_profile(metrics.last_query())
    except Exception:
        return {}
    return {
        "exchanges": prof["exchanges"],
        "rows_sent": prof["rows_sent"],
        "buffer_bytes": prof["buffer_bytes"],
        "padding_ratio": prof["padding_ratio"],
        "aqe": prof["decisions"],
    }


def _query_bytes(plan, conf) -> int:
    """Bytes of live column data in the plan's scan leaves — the
    minimum the query must touch; used for the bandwidth bound. When the
    plan will execute out-of-HBM, the estimate comes from scan row
    counts (physically planning it would materialize the big scans)."""
    from spark_tpu.physical import chunked as CH
    from spark_tpu.plan import logical as L

    if CH.find_chunkable(plan, conf) is not None:
        total = 0
        for s in L.collect_nodes(plan, L.UnresolvedScan):
            total += CH._est_scan(s) or 0
        assert total, "no data leaves: benchmark would constant-fold"
        return total

    from spark_tpu.physical import operators as P
    from spark_tpu.physical.planner import plan_physical

    scans = []

    def collect(p):
        if isinstance(p, P.BatchScanExec):
            scans.append(p)
            return
        for c in p.children():
            collect(c)

    collect(plan_physical(plan))
    assert scans, "no data leaves: benchmark would constant-fold"
    total = 0
    for s in scans:
        for cd in s.batch.data.columns:
            total += cd.data.size * cd.data.dtype.itemsize
    return total


def _percentile(values, q: float) -> float:
    return float(np.percentile(np.asarray(values, dtype=np.float64), q)) \
        if values else 0.0


def _run_serving(spark, concurrency: int, queries: dict,
                 rounds: int = 2) -> dict:
    """Concurrent-clients serving mode: N closed-loop client threads
    each replay the golden query mix ``rounds`` times through the
    multi-tenant scheduler (spark_tpu/scheduler/). Every result is
    checked byte-identical against a serial reference run — a serving
    number from a scheduler that corrupts results under concurrency
    would be worse than no number. Reports QPS, p50/p95 end-to-end
    latency, and p50/p95 admission queue-wait."""
    import threading

    from spark_tpu.scheduler import QueryScheduler

    # serial reference (also the warm-up: compiles once, off the clock)
    ref = {q: spark.sql(sql).toArrow() for q, sql in queries.items()}

    sched = QueryScheduler(spark)
    lock = threading.Lock()
    latencies, waits, mismatched, errors = [], [], [], []

    def client(idx: int) -> None:
        for _ in range(rounds):
            for qnum in sorted(queries):
                sql = queries[qnum]
                t0 = time.perf_counter()
                try:
                    ticket = sched.submit_query(
                        lambda sql=sql: spark.sql(sql),
                        description=f"serving q{qnum} client{idx}")
                    tbl = ticket.result()
                except Exception as e:
                    with lock:
                        errors.append(f"q{qnum}: {type(e).__name__}: {e}")
                    continue
                lat_ms = (time.perf_counter() - t0) * 1e3
                ok = tbl.equals(ref[qnum])
                with lock:
                    latencies.append(lat_ms)
                    waits.append(ticket.queue_wait_ms())
                    if not ok:
                        mismatched.append(qnum)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    sched.stop()
    total = len(latencies)
    return {
        "concurrency": concurrency,
        "rounds": rounds,
        "queries_completed": total,
        "errors": errors[:10],
        "wall_s": round(wall_s, 2),
        "qps": round(total / wall_s, 2) if wall_s else 0.0,
        "p50_ms": round(_percentile(latencies, 50), 1),
        "p95_ms": round(_percentile(latencies, 95), 1),
        "queue_wait_p50_ms": round(_percentile(waits, 50), 1),
        "queue_wait_p95_ms": round(_percentile(waits, 95), 1),
        "byte_identical_to_serial": not mismatched and not errors,
        "mismatched_queries": sorted(set(mismatched)),
    }


def _run_slo_ab(spark, concurrency: int,
                duration_s: float = 6.0,
                slo_multiplier: float = 3.0) -> dict:
    """SLO serving A/B (ROADMAP item 5 acceptance): the golden q1/q3/q5
    mix driven closed-loop at ~2x overload (clients >> workers), each
    query carrying its own deadline (the stated SLO: ``slo_multiplier``
    x that query's warm serial latency), once through the plain FIFO
    scheduler and once with spark.tpu.slo.enabled — per-plan latency
    prediction, EDF ordering, and reject-at-admission. Both arms run
    the same fixed wall-clock window, so the within-SLO counts are
    directly comparable goodput. The claim under test: the SLO arm
    serves MORE queries successfully WITHIN their deadlines (doomed
    queries are shed in milliseconds at admission instead of rotting in
    the queue and making every other query late; tight-deadline queries
    jump the EDF queue instead of waiting behind long scans) and its
    successes meet the stated SLO at p99. Every completed result is
    checked byte-identical against a serial reference — shedding may
    drop queries, it must never change bytes."""
    import threading

    from spark_tpu import metrics
    from spark_tpu.scheduler import QueryScheduler
    from spark_tpu.slo.edf import InfeasibleDeadline
    from spark_tpu.tpch.queries import QUERIES

    queries = {q: QUERIES[q] for q in (1, 3, 5)}
    # serial reference (also the warm-up: compiles once, off the clock)
    ref = {q: spark.sql(sql).toArrow() for q, sql in queries.items()}
    run_ms = {}
    for q, sql in queries.items():
        t0 = time.perf_counter()
        spark.sql(sql).toArrow()
        run_ms[q] = (time.perf_counter() - t0) * 1e3
    deadline_ms = {q: slo_multiplier * v for q, v in run_ms.items()}
    workers = 2
    n_clients = max(2 * workers, concurrency)

    def arm(slo_on: bool) -> dict:
        conf = spark.conf
        conf.set("spark.tpu.scheduler.maxConcurrency", workers)
        conf.set("spark.tpu.scheduler.queueDepth", 64)
        conf.set("spark.tpu.slo.enabled", slo_on)
        if slo_on:
            conf.set("spark.tpu.slo.targetP99Ms",
                     max(deadline_ms.values()))
            # predictions come from warm serial observations but the
            # measured window runs contended; the margin sheds
            # marginal admissions so what IS admitted finishes inside
            # its deadline (the sizing guidance the README documents)
            conf.set("spark.tpu.slo.rejectMargin", 1.5)
            metrics.reset_slo()
        sched = None
        try:
            sched = QueryScheduler(spark)
            # train off the clock — identical protocol both arms (the
            # SLO arm's latency model learns each query's fingerprint;
            # the FIFO arm just re-warms the same caches)
            for q, sql in queries.items():
                for _ in range(2):
                    sched.submit_query(
                        lambda sql=sql: spark.sql(sql),
                        sql=sql).result(QUERY_TIMEOUT_S)
            time.sleep(0.1)  # let the trailing observations land
            lock = threading.Lock()
            lat, ratios, mismatched, errors = [], [], [], []
            within = [0]
            rejected = [0]
            missed = [0]
            t_end = time.perf_counter() + duration_s

            def client(idx: int) -> None:
                i = 0
                order = sorted(queries)
                while time.perf_counter() < t_end:
                    qnum = order[(idx + i) % len(order)]
                    i += 1
                    sql = queries[qnum]
                    t0 = time.perf_counter()
                    try:
                        t = sched.submit_query(
                            lambda sql=sql: spark.sql(sql),
                            deadline_s=deadline_ms[qnum] / 1e3,
                            sql=sql,
                            description=f"slo q{qnum} c{idx}")
                        tbl = t.result(QUERY_TIMEOUT_S)
                    except InfeasibleDeadline:
                        with lock:
                            rejected[0] += 1
                        # the shed cost the client microseconds; a real
                        # caller backs off for its SLO window instead
                        # of hammering admission in a tight loop
                        time.sleep(deadline_ms[qnum] / 1e3)
                        continue
                    except Exception as e:
                        with lock:
                            missed[0] += 1
                            errors.append(
                                f"q{qnum}: {type(e).__name__}")
                        continue
                    ms = (time.perf_counter() - t0) * 1e3
                    okq = tbl.equals(ref[qnum])
                    with lock:
                        lat.append(ms)
                        ratios.append(ms / deadline_ms[qnum])
                        if not okq:
                            mismatched.append(qnum)
                        if ms <= deadline_ms[qnum]:
                            within[0] += 1

            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True)
                       for i in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
        finally:
            if sched is not None:
                sched.stop()
            conf.unset("spark.tpu.scheduler.maxConcurrency")
            conf.unset("spark.tpu.scheduler.queueDepth")
            conf.unset("spark.tpu.slo.enabled")
            if slo_on:
                conf.unset("spark.tpu.slo.targetP99Ms")
                conf.unset("spark.tpu.slo.rejectMargin")
        offered = len(lat) + rejected[0] + missed[0]
        # typed deadline outcomes (late death under FIFO, early shed
        # under SLO) are EXPECTED under overload and reported above;
        # byte-identity is about the bytes actually served
        return {
            "policy": "EDF+reject" if slo_on else "FIFO",
            "offered": offered,
            "completed": len(lat),
            "within_slo": within[0],
            "within_slo_per_s": round(within[0] / wall_s, 2)
            if wall_s else 0.0,
            "rejected_at_admission": rejected[0],
            "missed_or_failed": missed[0],
            "wall_s": round(wall_s, 2),
            "p50_ms": round(_percentile(lat, 50), 1),
            "p99_ms": round(_percentile(lat, 99), 1),
            # latency normalized by each query's OWN deadline: <= 1.0
            # at p99 means the served stream met the stated SLO
            "p99_slo_ratio": round(_percentile(ratios, 99), 2),
            "byte_identical_to_serial": not mismatched,
            "mismatched_queries": sorted(set(mismatched)),
            "errors": errors[:10],
            **({"slo_counters": metrics.slo_stats()} if slo_on else {}),
        }

    out = {"stated_slo": f"{slo_multiplier:g}x warm serial latency "
                         "per query",
           "deadline_ms": {str(q): round(v, 1)
                           for q, v in deadline_ms.items()},
           "workers": workers, "clients": n_clients,
           "duration_s": duration_s,
           "overload_factor": round(n_clients / workers, 1),
           "serial_run_ms": {str(q): round(v, 1)
                             for q, v in run_ms.items()}}
    out["fifo"] = arm(False)
    if _wall_remaining() <= 10:
        out["slo"] = {"error": "skipped: wall budget exhausted"}
        return out
    out["slo"] = arm(True)
    f, s = out["fifo"], out["slo"]
    out["within_slo_improvement"] = (
        round(s["within_slo"] / f["within_slo"], 2)
        if f.get("within_slo") else
        ("inf" if s.get("within_slo") else 0.0))
    # stated SLO met at p99 when the 99th-percentile served latency,
    # each query normalized by its OWN deadline, lands at-or-under 1.0
    out["meets_stated_slo_p99"] = bool(
        s.get("within_slo", 0) > 0
        and s.get("p99_slo_ratio", 99.0) <= 1.0)
    out["byte_identical"] = (
        f.get("byte_identical_to_serial", False)
        and s.get("byte_identical_to_serial", False))
    return out


def _run_serve_ab(spark, concurrency: int, replicas_n: int,
                  rounds: int = 2) -> dict:
    """Federation-tier A/B (spark_tpu/serve/): the same golden q1/q3/q5
    mix driven over REAL HTTP through the FederationRouter, once with a
    single replica and the result cache off (the pre-federation
    serving path) and once with N replicas and the plan-keyed result
    cache on. Every response is checked byte-identical against a
    serial in-process reference — a QPS number from a cache that
    serves stale or corrupted bytes would be worse than no number."""
    import threading

    from spark_tpu.connect.server import Client
    from spark_tpu.serve import serve_fleet
    from spark_tpu.tpch.queries import QUERIES

    queries = {q: QUERIES[q] for q in (1, 3, 5)}
    # serial reference (also the warm-up: compiles once, off the clock)
    ref = {q: spark.sql(sql).toArrow() for q, sql in queries.items()}

    def drive(n_replicas: int, cache_on: bool) -> dict:
        spark.conf.set("spark.tpu.serve.resultCache.enabled", cache_on)
        cache = getattr(spark, "serve_result_cache", None)
        if cache is not None:
            cache.clear()  # each arm starts cold
        fleet = serve_fleet(spark, replicas=n_replicas)
        lock = threading.Lock()
        latencies, mismatched, errors = [], [], []

        def client(idx: int) -> None:
            c = Client(fleet.url, timeout=QUERY_TIMEOUT_S)
            for _ in range(rounds):
                for qnum in sorted(queries):
                    t0 = time.perf_counter()
                    try:
                        tbl = c.sql(queries[qnum])
                    except Exception as e:
                        with lock:
                            errors.append(
                                f"q{qnum}: {type(e).__name__}: {e}")
                        continue
                    lat_ms = (time.perf_counter() - t0) * 1e3
                    ok = tbl.equals(ref[qnum])
                    with lock:
                        latencies.append(lat_ms)
                        if not ok:
                            mismatched.append(qnum)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0
        fleet.stop()
        total = len(latencies)
        from spark_tpu import metrics as _metrics
        return {
            "replicas": n_replicas,
            "cache": "on" if cache_on else "off",
            "queries_completed": total,
            "errors": errors[:10],
            "wall_s": round(wall_s, 2),
            "qps": round(total / wall_s, 2) if wall_s else 0.0,
            "p50_ms": round(_percentile(latencies, 50), 1),
            "p95_ms": round(_percentile(latencies, 95), 1),
            "byte_identical_to_serial": not mismatched and not errors,
            "mismatched_queries": sorted(set(mismatched)),
            "serve_counters": _metrics.serve_stats(),
        }

    from spark_tpu import metrics as _metrics
    out = {"concurrency": concurrency, "rounds": rounds}
    try:
        _metrics.reset_serve()
        out["one_replica_cache_off"] = drive(1, False)
        if _wall_remaining() <= 10:
            out["fleet_cached"] = {
                "error": "skipped: wall budget exhausted"}
            return out
        _metrics.reset_serve()
        out["fleet_cached"] = drive(replicas_n, True)
        base = out["one_replica_cache_off"]
        fleet = out["fleet_cached"]
        if base.get("qps") and fleet.get("qps"):
            out["qps_speedup"] = round(fleet["qps"] / base["qps"], 2)
        if fleet.get("p95_ms") and base.get("p95_ms"):
            out["p95_reduction"] = round(
                base["p95_ms"] / fleet["p95_ms"], 2)
        out["byte_identical_to_serial"] = (
            base.get("byte_identical_to_serial", False)
            and fleet.get("byte_identical_to_serial", False))
    finally:
        spark.conf.unset("spark.tpu.serve.resultCache.enabled")
        cache = getattr(spark, "serve_result_cache", None)
        if cache is not None:
            cache.clear()
    return out


def _run_fleet_bench(spark, concurrency: int = 4,
                     cells: tuple = (1, 2, 4),
                     tables: int = 4, rows_per_table: int = 50_000,
                     queries_per_table: int = 12) -> dict:
    """Fleet scaling sweep (spark_tpu/serve/ownership.py): QPS vs
    replica count on NON-cacheable traffic — every request is a unique
    plan (a fresh literal), so the result cache never hits and the
    number measures the ownership-routed data plane, not memoization.
    The dataset is sharded across ``tables`` parquet tables so the
    rendezvous map spreads owners across the fleet. Every cell replays
    the SAME seeded query list; cells >1 are checked byte-identical
    against the 1-replica cell per query — a QPS curve that changes
    bytes with the replica count would be worse than no number."""
    import shutil
    import tempfile
    import threading

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu import metrics as _metrics
    from spark_tpu.connect.server import Client
    from spark_tpu.serve import serve_fleet

    d = tempfile.mkdtemp(prefix="bench_fleet_")
    rng = np.random.default_rng(1234)
    for t in range(tables):
        i = np.arange(rows_per_table)
        pq.write_table(pa.table({
            "s": pa.array((i % 53).astype(np.int64)),
            "v": pa.array(((i * 7919 + t) % 100_003).astype(np.int64)),
        }), os.path.join(d, f"shard{t}.parquet"))
        (spark.read.parquet(os.path.join(d, f"shard{t}.parquet"))
         .createOrReplaceTempView(f"fleet_b{t}"))
    # one seeded unique-literal query list, identical across cells
    cuts = rng.integers(0, 100_003, size=tables * queries_per_table)
    qlist = [
        (f"SELECT s, SUM(v) AS sv, COUNT(*) AS n FROM fleet_b{j % tables} "
         f"WHERE v >= {int(cuts[j])} GROUP BY s")
        for j in range(tables * queries_per_table)]
    spark.conf.set("spark.tpu.serve.ownership.enabled", True)
    spark.conf.set("spark.tpu.serve.resultCache.enabled", True)
    # warm-up off the clock: the query shape compiles ONCE per table;
    # without this the 1-replica cell absorbs all XLA compile time and
    # the scaling curve flatters the fleet
    for t in range(tables):
        spark.sql(qlist[t]).toArrow()
    reference: dict = {}

    def cell(n_replicas: int) -> dict:
        fleet = serve_fleet(spark, replicas=n_replicas)
        lock = threading.Lock()
        latencies, mismatched, errors = [], [], []
        next_q = [0]
        try:
            fleet.router.federation.probe(force=True)  # learn shards

            def worker() -> None:
                c = Client(fleet.url, timeout=QUERY_TIMEOUT_S)
                while True:
                    with lock:
                        j = next_q[0]
                        if j >= len(qlist):
                            return
                        next_q[0] += 1
                    t0 = time.perf_counter()
                    try:
                        tbl = c.sql(qlist[j])
                    except Exception as e:
                        with lock:
                            errors.append(
                                f"q{j}: {type(e).__name__}: {e}")
                        continue
                    lat_ms = (time.perf_counter() - t0) * 1e3
                    with lock:
                        latencies.append(lat_ms)
                        if n_replicas == cells[0]:
                            reference[j] = tbl
                        else:
                            ref = reference.get(j)
                            if ref is None or not tbl.equals(ref):
                                mismatched.append(j)

            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(concurrency)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
        finally:
            fleet.stop()
        snap = _metrics.serve_stats()
        return {
            "replicas": n_replicas,
            "queries_completed": len(latencies),
            "errors": errors[:10],
            "wall_s": round(wall_s, 2),
            "qps": round(len(latencies) / wall_s, 2) if wall_s else 0.0,
            "p50_ms": round(_percentile(latencies, 50), 1),
            "p95_ms": round(_percentile(latencies, 95), 1),
            "byte_identical_to_single_replica": (
                not mismatched and not errors),
            "mismatched_queries": sorted(set(mismatched))[:10],
            "cache_hits": snap.get("hits", 0),
            "epoch_mints": snap.get("epoch_mints", 0),
        }

    out = {"concurrency": concurrency,
           "tables": tables, "queries": len(qlist)}
    try:
        for n in cells:
            if _wall_remaining() <= 10:
                out[f"replicas_{n}"] = {
                    "error": "skipped: wall budget exhausted"}
                continue
            _metrics.reset_serve()
            out[f"replicas_{n}"] = cell(n)
        base = out.get(f"replicas_{cells[0]}", {})
        top = out.get(f"replicas_{cells[-1]}", {})
        if base.get("qps") and top.get("qps"):
            out["qps_speedup"] = round(top["qps"] / base["qps"], 2)
        out["byte_identical_to_single_replica"] = all(
            out.get(f"replicas_{n}", {}).get(
                "byte_identical_to_single_replica", False)
            for n in cells[1:])
    finally:
        spark.conf.unset("spark.tpu.serve.ownership.enabled")
        spark.conf.unset("spark.tpu.serve.resultCache.enabled")
        cache = getattr(spark, "serve_result_cache", None)
        if cache is not None:
            cache.clear()
        for t in range(tables):
            spark.catalog.dropTempView(f"fleet_b{t}")
        shutil.rmtree(d, ignore_errors=True)
    return out


def _run_mview_ab(spark, appends: int = 8, readers: int = 3,
                  base_rows: int = 200_000, delta_rows: int = 1_000,
                  n_keys: int = 64) -> dict:
    """Materialized-view refresh A/B (spark_tpu/mview/): a re-mergeable
    aggregate (groupBy(k).sum(v)) cached over a parquet directory, then
    K appended micro-batch files. Arm OFF pins mview.incremental=False
    (every refresh is a full recompute over the whole growing source);
    arm ON merges the delta partials into the HBM-resident batch. Per
    append we time the FIRST read (the refresh) and ``readers-1`` extra
    reads (fresh fingerprint hits), count device plan executions via
    the single-device engine entry point, and keep the Arrow IPC bytes
    of every step so the two arms are checked byte-identical — a fast
    refresh that serves different bytes would be worse than no number."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    import spark_tpu.api.functions as F
    from spark_tpu import metrics
    from spark_tpu.physical import planner as _planner
    from spark_tpu.serve import result_cache as rc

    def write_part(d: str, name: str, n: int, offset: int) -> None:
        i = np.arange(offset, offset + n)
        pq.write_table(pa.table({
            "k": pa.array([f"k{j % n_keys}" for j in i]),
            "v": pa.array((i % 97).astype(np.int64)),
        }), os.path.join(d, name))

    real_exec = _planner.execute_logical
    execs = [0]

    def counting_exec(plan, optimize=True):
        execs[0] += 1
        return real_exec(plan, optimize)

    def arm(incremental: bool) -> dict:
        d = tempfile.mkdtemp(prefix="bench_mview_")
        spark.conf.set("spark.tpu.mview.enabled", True)
        spark.conf.set("spark.tpu.mview.incremental", incremental)
        spark.cache_manager.clear()
        metrics.reset_mview()
        try:
            write_part(d, "base.parquet", base_rows, 0)
            df = (spark.read.parquet(d).groupBy("k")
                  .agg(F.sum("v").alias("s")))
            df.cache()
            t0 = time.perf_counter()
            df.collect()  # cold materialize (off the A/B clock)
            cold_ms = (time.perf_counter() - t0) * 1e3
            refresh_ms, read_ms, step_bytes = [], [], []
            _planner.execute_logical = counting_exec
            execs[0] = 0
            try:
                for j in range(appends):
                    write_part(d, f"delta{j:04d}.parquet", delta_rows,
                               base_rows + j * delta_rows)
                    t0 = time.perf_counter()
                    tbl = df.toArrow()  # first reader pays the refresh
                    refresh_ms.append((time.perf_counter() - t0) * 1e3)
                    step_bytes.append(rc.table_to_ipc(tbl))
                    for _ in range(max(0, readers - 1)):
                        t0 = time.perf_counter()
                        df.toArrow()  # fingerprint-fresh store hit
                        read_ms.append(
                            (time.perf_counter() - t0) * 1e3)
            finally:
                _planner.execute_logical = real_exec
            stats = metrics.mview_stats()
            return {
                "incremental": incremental,
                "cold_ms": round(cold_ms, 1),
                "refresh_ms_p50": round(
                    _percentile(refresh_ms, 50), 1),
                "refresh_ms_p95": round(
                    _percentile(refresh_ms, 95), 1),
                "refresh_ms_total": round(sum(refresh_ms), 1),
                "read_hit_ms_p50": round(_percentile(read_ms, 50), 1),
                "device_executions": execs[0],
                "incremental_merges": stats["incremental_merges"],
                "full_recomputes": stats["full_recomputes"],
                "_bytes": step_bytes,
            }
        finally:
            spark.cache_manager.clear()
            spark.conf.unset("spark.tpu.mview.incremental")
            spark.conf.unset("spark.tpu.mview.enabled")
            shutil.rmtree(d, ignore_errors=True)

    out = {"appends": appends, "readers": readers,
           "base_rows": base_rows, "delta_rows": delta_rows}
    off = arm(False)
    on = arm(True)
    identical = (len(off["_bytes"]) == len(on["_bytes"])
                 and all(a == b for a, b in
                         zip(off["_bytes"], on["_bytes"])))
    off.pop("_bytes")
    on.pop("_bytes")
    out["recompute_per_append"] = off
    out["incremental"] = on
    out["byte_identical"] = identical
    if on["refresh_ms_total"]:
        out["refresh_speedup"] = round(
            off["refresh_ms_total"] / on["refresh_ms_total"], 2)
    return out


def main():
    import argparse

    import jax

    if os.environ.get("BENCH_WARMUP_CHILD") == "1":
        _warmup_child()
        return

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--concurrency", type=int,
        default=int(os.environ.get("BENCH_CONCURRENCY", "0")),
        help="N>0 adds a serving benchmark: N concurrent client "
             "threads replay the golden q1/q3/q5 mix through the "
             "multi-tenant scheduler; QPS + p50/p95 latency and "
             "queue-wait land under 'serving' in the result JSON")
    ap.add_argument(
        "--serving-rounds", type=int,
        default=int(os.environ.get("BENCH_SERVING_ROUNDS", "2")),
        help="mix replays per serving client")
    ap.add_argument(
        "--replicas", type=int,
        default=int(os.environ.get("BENCH_REPLICAS", "0")),
        help="N>0 adds the federation A/B (needs --concurrency): the "
             "serving mix over real HTTP through the router, 1 replica "
             "cache off vs N replicas with the plan-keyed result cache "
             "on; qps/p50/p95 + byte-identity land under 'serve'")
    args = ap.parse_args()

    jax.config.update("jax_enable_x64", True)

    from spark_tpu.api.session import SparkSession
    from spark_tpu.plan.optimizer import optimize
    from spark_tpu.plan.subquery import rewrite_subqueries
    from spark_tpu.sql.parser import parse_sql
    from spark_tpu.tpch.gen import ensure_dataset, register_views
    from spark_tpu.tpch.queries import QUERIES

    platform = jax.devices()[0].platform
    builder = SparkSession.builder
    # BENCH_MASTER=mesh[N] runs the whole benchmark distributed (and
    # makes the adaptive A/B phase meaningful — it needs exchanges)
    master = os.environ.get("BENCH_MASTER", "")
    if master:
        builder = builder.master(master)
    spark = builder.getOrCreate()

    t0 = time.time()
    tmp = ensure_dataset(SF)  # generate-once disk cache
    gen_s = time.time() - t0
    t0 = time.time()
    register_views(spark, path=tmp)
    io_s = time.time() - t0

    results = {}
    import sys

    # every phase (or query) skipped because the wall budget ran out,
    # by name — the final JSON carries the explicit list so a reader
    # never has to diff the expected phase set against what's present
    wall_skipped = []

    def _budget_skip(phase: str) -> dict:
        wall_skipped.append(phase)
        return {"error": "skipped: wall budget exhausted",
                "phase": phase, "wall_budget_s": WALL_BUDGET_S}

    def _phase_snapshot(**extra) -> None:
        _snapshot({"partial": True, "sf": SF,
                   "queries": {str(k): v for k, v in results.items()},
                   "wall_budget_skipped": list(wall_skipped),
                   "robustness": _robustness_counters(), **extra})

    for qnum in (1, 3, 5):
        if _wall_remaining() <= 5:
            results[qnum] = _budget_skip(f"headline:q{qnum}")
            continue
        print(f"[bench] q{qnum} starting", file=sys.stderr, flush=True)
        try:
            with _deadline(_query_deadline()):
                results[qnum] = _run_headline(spark, qnum)
        except _QueryTimeout as e:
            print(f"[bench] q{qnum} TIMED OUT: {e}",
                  file=sys.stderr, flush=True)
            results[qnum] = {"error": "timeout",
                             "timeout_s": QUERY_TIMEOUT_S}
        except Exception as e:  # record, don't kill the other queries
            print(f"[bench] q{qnum} FAILED: {e}",
                  file=sys.stderr, flush=True)
            results[qnum] = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot()


    warmup = None
    if WARMUP_MODE:
        if _wall_remaining() <= 5:
            warmup = _budget_skip("warmup")
        else:
            print("[bench] warmup A/B: empty store vs populated store "
                  "vs background compile (fresh subprocesses)",
                  file=sys.stderr, flush=True)
            try:
                warmup = _run_warmup_ab(qnum=1)
            except Exception as e:
                warmup = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(warmup=warmup)

    full = {}
    if FULL:
        budget_s = float(os.environ.get("BENCH_FULL_BUDGET", "1800"))
        sweep_t0 = time.time()
        for qnum in sorted(QUERIES):
            if qnum in results and "ms" in results[qnum]:
                full[qnum] = results[qnum]["ms"]
                continue
            elapsed = time.time() - sweep_t0
            if elapsed > budget_s:
                full[qnum] = f"skipped: sweep budget exhausted (all22:q{qnum})"
                continue
            if _wall_remaining() <= 5:
                wall_skipped.append(f"all22:q{qnum}")
                full[qnum] = f"skipped: wall budget exhausted (all22:q{qnum})"
                continue
            print(f"[bench] q{qnum} (sweep {elapsed:.0f}s)",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_query_deadline()):
                    df = spark.sql(QUERIES[qnum])
                    df.collect()  # warm-up 1: compile + stats
                    df.collect()  # warm-up 2: adaptive stats bound
                    times = []
                    for _ in range(max(2, N_ITER // 2)):
                        t0 = time.perf_counter()
                        df.collect()
                        times.append((time.perf_counter() - t0) * 1000.0)
                    full[qnum] = round(float(np.median(times)), 1)
            except _QueryTimeout:
                full[qnum] = f"error: timeout after {QUERY_TIMEOUT_S:.0f}s"
            except Exception as e:  # record, don't kill the headline
                full[qnum] = f"error: {type(e).__name__}: {e}"
            _phase_snapshot(
                all22_ms={str(k): v for k, v in full.items()})

    cached = None
    if CACHED_MODE:
        if _wall_remaining() <= 5:
            cached = _budget_skip("cached")
        else:
            print("[bench] cached mode: HBM-resident store re-runs",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("cached")):
                    cached = _run_cached(spark, (1, 3, 5))
            except _QueryTimeout:
                cached = {"error": "timeout"}
            except Exception as e:
                cached = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(cached=cached)

    adaptive = None
    if os.environ.get("BENCH_ADAPTIVE", "1") == "1":
        if _wall_remaining() <= 5:
            adaptive = _budget_skip("adaptive")
        else:
            print("[bench] adaptive A/B: spark.tpu.adaptive.enabled "
                  "off vs on", file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("adaptive")):
                    adaptive = _run_adaptive_compare(spark)
            except _QueryTimeout:
                adaptive = {"error": "timeout"}
            except Exception as e:
                adaptive = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(adaptive=adaptive)

    analysis_overhead = None
    if os.environ.get("BENCH_ANALYSIS", "1") == "1":
        if _wall_remaining() <= 5:
            analysis_overhead = _budget_skip("analysis")
        else:
            print("[bench] analyzer overhead: host-side static "
                  "analysis of the full 22-query suite",
                  file=sys.stderr, flush=True)
            try:
                qnums = sorted(QUERIES) if FULL else (1, 3, 5)
                analysis_overhead = _analysis_overhead(spark, qnums)
            except Exception as e:
                analysis_overhead = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(analysis=analysis_overhead)

    serving = None
    if args.concurrency > 0:
        if _wall_remaining() <= 5:
            serving = _budget_skip("serving")
        else:
            print(f"[bench] serving: {args.concurrency} concurrent "
                  "clients", file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("serving")):
                    serving = _run_serving(
                        spark, args.concurrency,
                        {q: QUERIES[q] for q in (1, 3, 5)},
                        rounds=args.serving_rounds)
            except Exception as e:
                serving = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(serving=serving)

    slo_ab = None
    if SLO_MODE and args.concurrency > 0:
        if _wall_remaining() <= 5:
            slo_ab = _budget_skip("slo")
        else:
            print(f"[bench] slo A/B: q1/q3/q5 with deadlines at ~2x "
                  f"closed-loop overload, FIFO vs EDF+reject "
                  f"({max(4, args.concurrency)} clients)",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("slo")):
                    slo_ab = _run_slo_ab(spark, args.concurrency)
            except _QueryTimeout:
                slo_ab = {"error": "timeout"}
            except Exception as e:
                slo_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(slo=slo_ab)

    serve_ab = None
    if args.replicas > 0 and args.concurrency > 0:
        if _wall_remaining() <= 5:
            serve_ab = _budget_skip("serve")
        else:
            print(f"[bench] serve A/B: 1 replica cache off vs "
                  f"{args.replicas} replicas cache on "
                  f"({args.concurrency} clients over HTTP)",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("serve")):
                    serve_ab = _run_serve_ab(
                        spark, args.concurrency, args.replicas,
                        rounds=args.serving_rounds)
            except Exception as e:
                serve_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(serve=serve_ab)

    fleet_bench = None
    if FLEET_MODE:
        if _wall_remaining() <= 5:
            fleet_bench = _budget_skip("fleet")
        else:
            print("[bench] fleet scaling: QPS vs replicas {1,2,4}, "
                  "unique-plan traffic, ownership routing on",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("fleet")):
                    fleet_bench = _run_fleet_bench(spark)
            except Exception as e:
                fleet_bench = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(fleet=fleet_bench)

    mview = None
    if MVIEW_MODE:
        if _wall_remaining() <= 5:
            mview = _budget_skip("mview")
        else:
            print("[bench] mview A/B: appended micro-batches, "
                  "spark.tpu.mview.incremental off vs on",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("mview")):
                    mview = _run_mview_ab(spark)
            except _QueryTimeout:
                mview = {"error": "timeout"}
            except Exception as e:
                mview = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(mview=mview)

    agg_ab = None
    if AGG_MODE:
        if _wall_remaining() <= 5:
            agg_ab = _budget_skip("agg")
        else:
            print("[bench] agg A/B: low/high-NDV, huge-domain, skewed "
                  "and hot-key group-bys, spark.tpu.adaptive.agg off "
                  "vs on vs forced sort/presplit",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("agg")):
                    agg_ab = _run_agg_ab(spark)
            except _QueryTimeout:
                agg_ab = {"error": "timeout"}
            except Exception as e:
                agg_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(agg=agg_ab)

    join_ab = None
    if JOIN_MODE:
        if _wall_remaining() <= 5:
            join_ab = _budget_skip("join")
        else:
            print("[bench] join A/B: grant-driven hybrid hash join at "
                  "full vs 1/8 memory budget vs the old OOM ladder",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("join")):
                    join_ab = _run_join_ab(spark)
            except _QueryTimeout:
                join_ab = {"error": "timeout"}
            except Exception as e:
                join_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(join=join_ab)

    trace_ab = None
    if TRACE_MODE:
        if _wall_remaining() <= 5:
            trace_ab = _budget_skip("trace")
        else:
            print("[bench] trace A/B: q1/q3 span layer off vs on vs "
                  "sampled, + host/device/queue breakdown of one q3",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("trace")):
                    trace_ab = _run_trace_ab(spark)
            except _QueryTimeout:
                trace_ab = {"error": "timeout"}
            except Exception as e:
                trace_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(trace=trace_ab)

    fusion_ab = None
    if FUSION_MODE:
        if _wall_remaining() <= 5:
            fusion_ab = _budget_skip("fusion")
        else:
            print("[bench] fusion A/B: multi-exchange plans staged vs "
                  "fused (spark.tpu.fusion.enabled off vs on)",
                  file=sys.stderr, flush=True)
            try:
                with _deadline(_phase_deadline("fusion")):
                    fusion_ab = _run_fusion_ab(spark)
            except _QueryTimeout:
                fusion_ab = {"error": "timeout"}
            except Exception as e:
                fusion_ab = {"error": f"{type(e).__name__}: {e}"}
        _phase_snapshot(fusion=fusion_ab)

    # totals cover the queries that finished; failed/timed-out ones are
    # reported per-query and excluded so the JSON stays valid and the
    # headline number stays meaningful (flagged via queries_failed)
    ok = {q: r for q, r in results.items() if "ms" in r}
    total_ms = sum(r["ms"] for r in ok.values())
    vs = (sum(BASELINE_MS[q] for q in ok) * SF / total_ms
          if total_ms else 0.0)
    final = {
        "metric": f"tpch_sf{SF:g}_q1q3q5_total",
        "value": round(total_ms, 1),
        "unit": "ms",
        # warmup is accounted SEPARATELY from the headline value: the
        # metric is steady-state wall-clock; cold-start cost has its
        # own A/B block ("warmup") and this total
        "warmup_total_s": round(
            sum(r.get("warmup_s", 0.0) for r in ok.values()), 1),
        "vs_baseline": round(vs, 3),
        "platform": platform,
        "sf": SF,
        "iters": N_ITER,
        "query_timeout_s": QUERY_TIMEOUT_S,
        "queries_failed": sorted(q for q in results if q not in ok),
        "gen_s": round(gen_s, 1),
        "parquet_io_s": round(io_s, 1),
        "baseline": "Spark CPU local[*] SF1 estimate (see bench.py docstring)",
        "robustness": _robustness_counters(),
        "wall_budget_s": WALL_BUDGET_S,
        "wall_used_s": round(time.time() - _WALL_T0, 1),
        "wall_budget_skipped": wall_skipped,
        "queries": {str(k): v for k, v in results.items()},
        **({"warmup": warmup} if warmup is not None else {}),
        **({"cached": cached} if cached is not None else {}),
        **({"adaptive": adaptive} if adaptive is not None else {}),
        **({"serving": serving} if serving is not None else {}),
        **({"slo": slo_ab} if slo_ab is not None else {}),
        **({"serve": serve_ab} if serve_ab is not None else {}),
        **({"fleet": fleet_bench} if fleet_bench is not None else {}),
        **({"mview": mview} if mview is not None else {}),
        **({"agg": agg_ab} if agg_ab is not None else {}),
        **({"join": join_ab} if join_ab is not None else {}),
        **({"trace": trace_ab} if trace_ab is not None else {}),
        **({"fusion": fusion_ab} if fusion_ab is not None else {}),
        **({"analysis": analysis_overhead}
           if analysis_overhead is not None else {}),
        **({"all22_ms": {str(k): v for k, v in full.items()}}
           if full else {}),
    }
    # the complete document also lands at PARTIAL_PATH: a driver that
    # kills the process between here and stdout flush (rc=124 with
    # parsed:null) still finds every completed result on disk
    _snapshot(final)
    print(json.dumps(final))


def _run_cached(spark, qnums, rounds: int = 3) -> dict:
    """Cached-mode report: cache() the TPC-H tables into the
    HBM-resident MemoryStore, then time each query cold (first run —
    materializes the cached tables on device) vs warm (store hits:
    no parquet decode, no dictionary encode, no host->device
    transfer). Every run is checked byte-identical against the
    uncached reference. The warm/cold split is the store's headline
    number: warm re-runs of q1/q3/q5 should be several times faster."""
    from spark_tpu.tpch.queries import QUERIES

    ref = {q: spark.sql(QUERIES[q]).toArrow() for q in qnums}
    tables = [spark.table(t) for t in spark.catalog.listTables()]
    for df in tables:
        df.cache()
    out = {}
    try:
        for q in qnums:
            df = spark.sql(QUERIES[q])
            t0 = time.perf_counter()
            cold_tbl = df.toArrow()
            cold_ms = (time.perf_counter() - t0) * 1e3
            warm_times, identical = [], cold_tbl.equals(ref[q])
            for _ in range(rounds):
                t0 = time.perf_counter()
                tbl = df.toArrow()
                warm_times.append((time.perf_counter() - t0) * 1e3)
                identical = identical and tbl.equals(ref[q])
            warm_ms = float(np.median(warm_times))
            out[q] = {
                "cold_ms": round(cold_ms, 1),
                "warm_ms": round(warm_ms, 1),
                "speedup": round(cold_ms / warm_ms, 2) if warm_ms
                else 0.0,
                "byte_identical": bool(identical),
            }
    finally:
        for df in tables:
            df.unpersist()
    out["store"] = spark.memory_store.stats()
    out["memory"] = spark.memory_manager.snapshot()
    return {str(k): v for k, v in out.items()}


def _run_adaptive_compare(spark) -> dict:
    """Adaptive-vs-static A/B over the distributed engine: the two
    exchange-heavy shapes AQE targets (distributed group-by, join +
    group-by), timed with ``spark.tpu.adaptive.enabled`` off then on.
    Results must be byte-identical — a faster wrong answer is not a
    result — and the padding ratio (dead slots shipped over ICI) should
    drop under adaptive capacity compaction. Skipped on single-device
    sessions, where no exchange stage exists to re-plan (run with
    BENCH_MASTER=mesh[N] to engage)."""
    from spark_tpu import metrics

    if getattr(spark, "_mesh", None) is None:
        return {"skipped": "single-device session (no mesh): no "
                           "exchange stages to re-plan"}
    queries = {
        "groupby": "SELECT l_suppkey, sum(l_quantity) AS s, "
                   "count(*) AS c FROM lineitem GROUP BY l_suppkey "
                   "ORDER BY l_suppkey",
        "join_groupby": "SELECT c_custkey, count(*) AS cnt "
                        "FROM customer, orders "
                        "WHERE c_custkey = o_custkey "
                        "GROUP BY c_custkey ORDER BY c_custkey",
    }
    out = {}
    conf = spark.conf
    try:
        for name, sql in queries.items():
            df = spark.sql(sql)

            def timed(adaptive):
                conf.set("spark.tpu.adaptive.enabled", adaptive)
                ref = df.toArrow()  # warm-up: compile off the clock
                t0 = time.perf_counter()
                got = df.toArrow()
                ms = (time.perf_counter() - t0) * 1000.0
                return ref, got, round(ms, 1), _shuffle_block()

            _, off_tbl, off_ms, off_sh = timed(False)
            _, on_tbl, on_ms, on_sh = timed(True)
            out[name] = {
                "off_ms": off_ms,
                "on_ms": on_ms,
                "byte_identical": bool(on_tbl.equals(off_tbl)),
                "padding_ratio_off": off_sh.get("padding_ratio"),
                "padding_ratio_on": on_sh.get("padding_ratio"),
                "buffer_bytes_off": off_sh.get("buffer_bytes"),
                "buffer_bytes_on": on_sh.get("buffer_bytes"),
                "aqe": on_sh.get("aqe", []),
            }
    finally:
        conf.unset("spark.tpu.adaptive.enabled")
    return out


def _run_agg_ab(spark) -> dict:
    """Adaptive-aggregation A/B: the five key distributions the
    strategy switch discriminates — low NDV (hash-partial territory),
    high NDV ~ rows over a packable domain (partial-bypass:
    pre-aggregation shrinks nothing), high NDV over a HUGE domain (the
    sort rung: range exchange + segmented merge, key-ordered output
    elides the downstream orderBy sort), skewed (the reactive skew fan
    territory), and hot-key (one key dominates hard enough that the
    Count-Min sketch elects proactive pre-splitting) — each timed with
    adaptive execution off (the static partial->final plan, exchanges
    fused at worst-case capacity) then fully on (AQE + the aggregation
    strategy switch). Results must be byte-identical; the JSON records
    the digest, per-strategy pick counts (metrics.agg_stats delta), and
    the measured NDV/rows ratio per workload.

    Fourth arm: per-workload FORCED strategies isolate the new rungs
    against the best pre-existing alternative — ``sort`` forced on
    high_ndv (vs the bypass auto used to pick), ``bypass`` forced on
    huge_domain (vs the auto sort pick), and ``bypass``/``sort``
    forced on hot_key (auto presplit vs the raw-row exchanges whose
    hot destination the destination-reactive skew fan has to absorb).
    Skipped on single-device sessions (run with BENCH_MASTER=mesh[N]
    to engage)."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import metrics
    from spark_tpu.api import functions as F

    if getattr(spark, "_mesh", None) is None:
        return {"skipped": "single-device session (no mesh): no "
                           "partial->final split to adapt"}
    rng = np.random.default_rng(7)
    n = int(os.environ.get("BENCH_AGG_ROWS", "120000"))
    workloads = {
        "low_ndv": rng.integers(0, 64, n),
        "high_ndv": rng.permutation(n).astype(np.int64),
        # near-distinct keys spread over ~1.2e11: beyond both the hash
        # domain limit and sortDomainWidth, so auto lands on the sort
        # rung (and the orderBy("k") below rides its sorted output)
        "huge_domain": rng.permutation(n).astype(np.int64) * 1_000_003,
        "skewed": np.where(rng.random(n) < 0.9, 7,
                           rng.integers(0, 100000, n)),
        # one key carries a third of the rows and the tail is
        # near-distinct over a huge domain: the crossover elects a
        # raw-row exchange (the sort rung), exactly where one hot key
        # overloads a single destination — so the Count-Min estimate
        # drives the pre-split rung instead
        "hot_key": np.where(np.arange(n) % 3 == 0, 7,
                            rng.permutation(n).astype(np.int64)
                            * 1_000_003),
    }
    # fourth arm per workload: forced strategies that pin the baseline
    # the new rung must beat — sort vs the bypass the crossover used
    # to pick on high NDV, and presplit vs the raw-row strategies
    # whose hot destination the reactive skew fan would handle
    forced_arms = {
        "high_ndv": ("sort",), "huge_domain": ("bypass",),
        "skewed": ("partial",), "hot_key": ("bypass", "sort"),
    }
    out = {}
    conf = spark.conf
    try:
        # hot-key threshold at 2x the fair per-device share (the
        # conservative default 4x needs a >50% hot key at 8 devices)
        conf.set("spark.tpu.adaptive.agg.presplitFactor", 2)
        for name, keys in workloads.items():
            if _wall_remaining() <= 30:
                out[name] = {"skipped": "wall budget exhausted"}
                continue
            tbl = pa.table({
                "k": pa.array(keys, pa.int64()),
                "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
            })
            df = (spark.createDataFrame(tbl).groupBy("k")
                  .agg(F.sum("v").alias("s"), F.count("v").alias("c"),
                       F.min("v").alias("mn"), F.max("v").alias("mx"))
                  .orderBy("k"))

            def timed(adaptive_on, agg_on, force=None):
                conf.set("spark.tpu.adaptive.enabled", adaptive_on)
                conf.set("spark.tpu.adaptive.agg.enabled", agg_on)
                if force:
                    conf.set("spark.tpu.adaptive.agg.strategy", force)
                else:
                    conf.unset("spark.tpu.adaptive.agg.strategy")
                df.toArrow()  # warm-up: compile off the clock
                before = metrics.agg_stats()
                t0 = time.perf_counter()
                got = df.toArrow()
                ms = (time.perf_counter() - t0) * 1000.0
                picks = {k: v - before.get(k, 0)
                         for k, v in metrics.agg_stats().items()
                         if v - before.get(k, 0)}
                return got, round(ms, 1), picks

            # four arms: fully static plan / AQE with the static
            # partial->final strategy / AQE + the strategy switch /
            # AQE with a pinned per-workload baseline strategy — so
            # both the switch's own contribution (on top of AQE's
            # capacity compaction) and the new rung's margin over the
            # best pre-existing strategy are visible
            off_tbl, off_ms, _ = timed(False, False)
            _, aqe_ms, _ = timed(True, False)
            on_tbl, on_ms, picks = timed(True, True)
            ev = next((e for e in reversed(metrics.recent(256))
                       if e.get("kind") == "agg"), {})
            forced = {}
            for strat in forced_arms.get(name, ()):
                f_tbl, f_ms, f_picks = timed(True, True, force=strat)
                forced[strat] = {
                    "ms": f_ms,
                    "byte_identical": bool(f_tbl.equals(off_tbl)),
                    "strategy_picks": f_picks,
                }
            out[name] = {
                "rows": n,
                "off_ms": off_ms,
                "aqe_only_ms": aqe_ms,
                "on_ms": on_ms,
                "speedup": round(off_ms / on_ms, 2) if on_ms else None,
                "speedup_vs_aqe": (round(aqe_ms / on_ms, 2)
                                   if on_ms else None),
                "byte_identical": bool(on_tbl.equals(off_tbl)),
                "strategy_picks": picks,
                "ndv_estimate": ev.get("ndv"),
                "ndv_ratio": ev.get("ratio"),
                "hot_keys": ev.get("hot_keys"),
                **({"forced": forced} if forced else {}),
            }
    finally:
        conf.unset("spark.tpu.adaptive.agg.presplitFactor")
        conf.unset("spark.tpu.adaptive.agg.strategy")
        conf.unset("spark.tpu.adaptive.agg.enabled")
        conf.unset("spark.tpu.adaptive.enabled")
    return out


def _run_join_ab(spark) -> dict:
    """Hybrid-hash-join A/B: one out-of-core fact/dim join (SF0.1-ish:
    300k fact rows against a 20k-key dim, both sides over the device
    batch budget so the tier-3 join engages) run three ways —

    - ``hybrid_full``:  hybrid join, full memory budget (the grant
      covers staging: everything stays resident, zero spills);
    - ``hybrid_1_8``:   hybrid join, budget cut to 1/8 of the staged
      bytes (planned spilling: a single pass that spills the
      partitions beyond the grant, still ZERO ladder replans);
    - ``ladder``:       hybrid off and the whole-batch execution killed
      with an injected device OOM — the old reactive path, which pays
      >= 1 ladder replan (a wasted device execution) for the same
      memory pressure.

    Per arm the JSON records wall ms, the recovery replan count, spill
    bytes/partitions, the bytes granted by the unified memory manager,
    and byte-identity against the resident reference run."""
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu import metrics

    rng = np.random.default_rng(17)
    n = int(os.environ.get("BENCH_JOIN_ROWS", "300000"))
    ndim = 20_000
    tmp = tempfile.mkdtemp(prefix="bench_join_")
    fact = pa.table({
        "k": pa.array(rng.integers(0, ndim, n), pa.int64()),
        "v": pa.array(rng.integers(0, 100, n), pa.int64()),
    })
    dim = pa.table({
        "dk": pa.array(np.arange(ndim, dtype=np.int64)),
        "w": pa.array((np.arange(ndim) % 997).astype(np.int64)),
    })
    fp = os.path.join(tmp, "fact.parquet")
    dp = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    spark.read.parquet(fp).createOrReplaceTempView("bj_fact")
    spark.read.parquet(dp).createOrReplaceTempView("bj_dim")
    sql = ("select sum(v * w) as s, count(*) as c "
           "from bj_fact join bj_dim on k = dk")
    conf = spark.conf
    staged = fact.nbytes + dim.nbytes
    out = {"rows": n, "staged_bytes": int(staged)}
    try:
        # resident reference (default budget, default batch bytes)
        t0 = time.perf_counter()
        base = [(r.s, r.c) for r in spark.sql(sql).collect()]
        out["resident_ms"] = round((time.perf_counter() - t0) * 1000, 1)

        def arm(budget, hybrid, inject_oom, batch_bytes):
            if batch_bytes is not None:
                conf.set("spark.tpu.maxDeviceBatchBytes", batch_bytes)
            conf.set("spark.tpu.join.hybrid.enabled", hybrid)
            conf.set("spark.tpu.scheduler.hbmBudgetBytes", budget)
            if inject_oom:
                conf.set("spark.tpu.faultInjection.execute.device",
                         "nth:1:oom")
            try:
                metrics.reset_join()
                metrics.reset_recovery()
                t0 = time.perf_counter()
                got = [(r.s, r.c) for r in spark.sql(sql).collect()]
                ms = (time.perf_counter() - t0) * 1000.0
                js = metrics.join_stats()
                return {
                    "wall_ms": round(ms, 1),
                    "replans": metrics.recovery_stats()["replans"],
                    "spill_bytes": js["spill_bytes"],
                    "spilled_partitions": js["spilled_partitions"],
                    "granted_bytes": js["grant_bytes"],
                    "byte_identical": got == base,
                }
            finally:
                conf.unset("spark.tpu.maxDeviceBatchBytes")
                conf.unset("spark.tpu.join.hybrid.enabled")
                conf.unset("spark.tpu.scheduler.hbmBudgetBytes")
                conf.unset("spark.tpu.faultInjection.execute.device")

        # both sides over a 256 KiB batch budget -> tier-3 hybrid join
        out["hybrid_full"] = arm(2 << 30, True, False, 256 * 1024)
        if _wall_remaining() > 5:
            out["hybrid_1_8"] = arm(max(1 << 16, staged // 8), True,
                                    False, 256 * 1024)
        if _wall_remaining() > 5:
            # old path: resident execution dies with OOM, the reactive
            # ladder replans into the chunked tier
            out["ladder"] = arm(max(1 << 16, staged // 8), False,
                                True, None)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _run_trace_ab(spark) -> dict:
    """Tracing-overhead A/B: q1 and q3 timed (median of 3 warm runs)
    with the span layer off (spark.tpu.trace.enabled=false), always-on
    (the default), and 10%-sampled. The headline number is
    overhead_pct — always-on tracing must stay in the low single
    digits on a warm q1 — and every arm's Arrow output must be
    byte-identical to the untraced run. One fully-traced q3 run is
    then decomposed via tracing.trace_breakdown() into
    host/device/queue/transfer ms so the JSON shows where the wall
    time of a real query actually goes."""
    from spark_tpu import metrics, tracing
    from spark_tpu.tpch.queries import QUERIES

    conf = spark.conf
    out = {}
    try:
        for q in (1, 3):
            df = spark.sql(QUERIES[q])

            def timed(enabled, ratio):
                conf.set("spark.tpu.trace.enabled", enabled)
                conf.set("spark.tpu.trace.sampleRatio", ratio)
                df.toArrow()  # warm-up: compile off the clock
                got, runs = None, []
                for _ in range(3):
                    t0 = time.perf_counter()
                    got = df.toArrow()
                    runs.append((time.perf_counter() - t0) * 1000.0)
                return got, round(sorted(runs)[1], 1)

            off_tbl, off_ms = timed(False, 1.0)
            on_tbl, on_ms = timed(True, 1.0)
            samp_tbl, samp_ms = timed(True, 0.1)
            out[f"q{q}"] = {
                "off_ms": off_ms,
                "on_ms": on_ms,
                "sampled_ms": samp_ms,
                "overhead_pct": (round((on_ms - off_ms) / off_ms * 100, 2)
                                 if off_ms else None),
                "sampled_overhead_pct": (
                    round((samp_ms - off_ms) / off_ms * 100, 2)
                    if off_ms else None),
                "byte_identical": bool(on_tbl.equals(off_tbl)
                                       and samp_tbl.equals(off_tbl)),
            }
        # one fully-traced q3: where did the wall time go?
        conf.set("spark.tpu.trace.enabled", True)
        conf.set("spark.tpu.trace.sampleRatio", 1.0)
        spark.sql(QUERIES[3]).toArrow()
        evs = metrics.last_query()
        bd = tracing.trace_breakdown(evs)
        out["q3_breakdown"] = {
            **{k: round(v, 1) for k, v in bd.items()},
            "spans": sum(1 for e in evs if e.get("kind") == "span"),
            "trace_id": next((e.get("trace_id") for e in evs
                              if e.get("trace_id")), None),
        }
    finally:
        conf.unset("spark.tpu.trace.enabled")
        conf.unset("spark.tpu.trace.sampleRatio")
    return out


def _run_fusion_ab(spark) -> dict:
    """Whole-query fusion A/B: the two multi-exchange shapes the fused
    span targets — a float-sum group-by under a global sort (the q5
    tail: the agg strategy is PINNED by legality, so the capacity
    decision is the only adaptive decision and both exchange+consumer
    pairs fuse) and the same tail behind a join (the q3 shape: the
    broadcast switch stays a host decision and records its bailout,
    the post-join pairs still fuse) — timed with adaptive execution on
    and ``spark.tpu.fusion.enabled`` off (staged: one stats fetch +
    re-trace per exchange) then on (one XLA program, decision on
    device). Workloads are synthesized float columns rather than
    TPC-H SQL because the TPC-H money columns are DECIMAL(12,2) —
    exact int64 aggregates whose strategy crossover is live, which
    correctly bails the whole plan to staged (``agg_strategy``). The
    JSON records total latency AND the trace host/queue components
    before/after: fusion's claim is specifically that inter-stage host
    time goes to ~0 while bytes stay identical. Skipped on
    single-device sessions (run with BENCH_MASTER=mesh[N] to engage)."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import metrics, tracing

    if getattr(spark, "_mesh", None) is None:
        return {"skipped": "single-device session (no mesh): no "
                           "exchange stages to fuse"}
    rng = np.random.default_rng(11)
    n = int(os.environ.get("BENCH_FUSION_ROWS", "400000"))
    spark.createDataFrame(pa.table({
        "k": pa.array(rng.integers(0, 4000, n), pa.int64()),
        "f": pa.array(rng.random(n) * 100.0, pa.float64()),
    })).createOrReplaceTempView("fusion_fact")
    spark.createDataFrame(pa.table({
        "k2": pa.array(np.arange(4000, dtype=np.int64), pa.int64()),
        "w": pa.array(rng.random(4000), pa.float64()),
    })).createOrReplaceTempView("fusion_dim")
    small = int(os.environ.get("BENCH_FUSION_SMALL_ROWS", "4000"))
    spark.createDataFrame(pa.table({
        "k": pa.array(rng.integers(0, 400, small), pa.int64()),
        "f": pa.array(rng.random(small) * 100.0, pa.float64()),
    })).createOrReplaceTempView("fusion_fact_small")
    queries = {
        "groupby_sort": "SELECT k, sum(f) AS s FROM fusion_fact "
                        "GROUP BY k ORDER BY k",
        "join_groupby_sort": "SELECT k, sum(f) AS s "
                             "FROM fusion_fact, fusion_dim "
                             "WHERE k = k2 GROUP BY k ORDER BY k",
        # the dispatch-bound regime: per-stage fixed costs (program
        # launches, stats readbacks) dominate tiny inputs, which is
        # where collapsing k stages into one program pays most
        "groupby_sort_small": "SELECT k, sum(f) AS s "
                              "FROM fusion_fact_small "
                              "GROUP BY k ORDER BY k",
    }
    out = {"rows": n, "rows_small": small}
    conf = spark.conf
    conf.set("spark.tpu.adaptive.enabled", True)
    try:
        for name, sql in queries.items():
            df = spark.sql(sql)

            def timed(fused):
                conf.set("spark.tpu.fusion.enabled", fused)
                df.toArrow()  # warm-up: compile off the clock
                got, runs = None, []
                for _ in range(3):
                    metrics.reset_fusion()  # stats reflect one run
                    metrics.query_start(f"bench-fusion-{name}")
                    t0 = time.perf_counter()
                    got = df.toArrow()
                    runs.append((time.perf_counter() - t0) * 1000.0)
                evs = metrics.last_query()
                bd = tracing.trace_breakdown(evs)
                # the inter-stage host syncs fusion exists to remove:
                # each exchange.stats span is a stats stage dispatch +
                # D-integer readback + host decision between stages
                syncs = [e for e in evs if e.get("kind") == "span"
                         and e.get("name") == "exchange.stats"]
                bd["stats_syncs"] = len(syncs)
                bd["stats_sync_ms"] = round(
                    sum(float(e.get("ms", 0.0)) for e in syncs), 3)
                return (got, round(sorted(runs)[1], 1), bd,
                        metrics.fusion_stats())

            off_tbl, off_ms, off_bd, _ = timed(False)
            on_tbl, on_ms, on_bd, st = timed(True)
            out[name] = {
                "staged_ms": off_ms,
                "fused_ms": on_ms,
                "speedup": round(off_ms / on_ms, 2) if on_ms else 0.0,
                "byte_identical": bool(on_tbl.equals(off_tbl)),
                "trace_breakdown": {
                    "host_ms_staged": off_bd.get("host_ms"),
                    "host_ms_fused": on_bd.get("host_ms"),
                    "queue_ms_staged": off_bd.get("queue_ms"),
                    "queue_ms_fused": on_bd.get("queue_ms"),
                    "device_ms_staged": off_bd.get("device_ms"),
                    "device_ms_fused": on_bd.get("device_ms"),
                    "stats_syncs_staged": off_bd.get("stats_syncs"),
                    "stats_syncs_fused": on_bd.get("stats_syncs"),
                    "stats_sync_ms_staged": off_bd.get("stats_sync_ms"),
                    "stats_sync_ms_fused": on_bd.get("stats_sync_ms"),
                },
                "fused_programs": st.get("fused_programs", 0),
                "fused_spans": st.get("fused_spans", 0),
                "bailouts": st.get("bailouts", 0),
            }
    finally:
        conf.unset("spark.tpu.adaptive.enabled")
        conf.unset("spark.tpu.fusion.enabled")
    return out


def _analysis_overhead(spark, qnums) -> dict:
    """Per-query static-analyzer overhead (spark_tpu/analysis/):
    builds each query lazily and times analysis.analyze() — host-side
    plan walking only, nothing executes, nothing compiles. This is the
    cost the spark.tpu.analysis.level submit gate would add per query;
    it should be low single-digit ms against multi-second queries."""
    from spark_tpu import analysis
    from spark_tpu.tpch.queries import QUERIES

    out = {}
    for q in sorted(qnums):
        try:
            df = spark.sql(QUERIES[q])
            t0 = time.perf_counter()
            report = analysis.analyze(df._plan, spark.conf)
            ms = (time.perf_counter() - t0) * 1e3
            out[str(q)] = {
                "ms": round(ms, 2),
                "diagnostics": len(report.diagnostics),
                "errors": len(report.errors()),
                "fingerprint_stable": report.fingerprint_stable,
            }
        except Exception as e:
            out[str(q)] = {"error": f"{type(e).__name__}: {e}"}
    ok = [v["ms"] for v in out.values() if "ms" in v]
    out["total_ms"] = round(sum(ok), 2)
    out["max_ms"] = round(max(ok), 2) if ok else 0.0
    return out


def _run_headline(spark, qnum: int) -> dict:
    from spark_tpu import analysis
    from spark_tpu.plan.optimizer import optimize
    from spark_tpu.plan.subquery import rewrite_subqueries
    from spark_tpu.tpch.queries import QUERIES

    df = spark.sql(QUERIES[qnum])
    # static-analyzer overhead for THIS query (host-side, no execution)
    t0 = time.perf_counter()
    analysis.analyze(df._plan, spark.conf)
    analysis_ms = (time.perf_counter() - t0) * 1e3
    lp = optimize(rewrite_subqueries(df._plan))
    nbytes = _query_bytes(lp, spark.conf)

    if SF <= 10:
        t0 = time.time()
        rows1 = df.collect()  # warm-up 1: compiles + read + stats
        rows = df.collect()  # warm-up 2: adaptive join stats bound —
        # PK-FK joins fuse into one XLA program; compiles it
        warm_s = time.time() - t0
        assert rows, f"q{qnum} returned no rows"
        # cross-path parity: the first (blocking) execution and the
        # adaptive traced replay must produce the same result set
        # (the full vs-sqlite oracle parity runs in
        # tests/test_tpch.py at a smaller SF; this guards the fast
        # path at BENCH scale)
        assert len(rows1) == len(rows), \
            f"q{qnum}: traced row count differs"
        for a, b in zip(rows1, rows):
            a = a.asDict() if hasattr(a, "asDict") else a
            b = b.asDict() if hasattr(b, "asDict") else b
            for x, y in zip(a.values(), b.values()):
                if isinstance(x, float):
                    assert abs(x - y) <= 1e-6 * max(1.0, abs(x)), \
                        f"q{qnum}: traced value drift {x} vs {y}"
                else:
                    assert x == y, \
                        f"q{qnum}: traced mismatch {x} vs {y}"

        times = []
        for _ in range(N_ITER):
            t0 = time.perf_counter()
            rows = df.collect()
            times.append((time.perf_counter() - t0) * 1000.0)
    else:
        # out-of-HBM scale: every pass re-streams the dataset, so
        # the first (and only, unless BENCH_ITERS>1) pass IS the
        # honest number — compile time amortizes across hundreds of
        # chunk dispatches inside it
        warm_s = 0.0
        times = []
        for _ in range(N_ITER):
            t0 = time.perf_counter()
            rows = df.collect()
            times.append((time.perf_counter() - t0) * 1000.0)
        assert rows, f"q{qnum} returned no rows"
    ms = float(np.median(times))
    gbps = nbytes / (ms / 1e3) / 1e9
    assert gbps < HBM_GBPS, (
        f"q{qnum}: implied {gbps:.0f} GB/s exceeds HBM bandwidth "
        f"({HBM_GBPS} GB/s) — benchmark is measuring a constant")
    return {
        "ms": round(ms, 1),
        "min_ms": round(min(times), 1),
        "analysis_ms": round(analysis_ms, 2),
        "warmup_s": round(warm_s, 1),
        "rows": len(rows),
        "scan_gb": round(nbytes / 1e9, 3),
        "implied_gbps": round(gbps, 1),
        "vs_spark_cpu_est": round(BASELINE_MS[qnum] * SF / ms, 2),
        "shuffle": _shuffle_block(),
    }


if __name__ == "__main__":
    main()

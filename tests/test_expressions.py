import datetime

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu import types as T
from spark_tpu.columnar import from_arrow
from spark_tpu.expr import Env, evaluate
from spark_tpu.expr import expressions as E


def make_env():
    table = pa.table({
        "i": pa.array([1, 2, None, 4], type=pa.int64()),
        "f": pa.array([0.5, 1.5, 2.5, 3.5], type=pa.float64()),
        "s": pa.array(["apple", "banana", "apple", None], type=pa.string()),
        "d": pa.array([datetime.date(1995, 1, 1), datetime.date(1996, 6, 15),
                       datetime.date(1997, 12, 31), datetime.date(2000, 2, 29)],
                      type=pa.date32()),
        "b": pa.array([True, False, True, None], type=pa.bool_()),
    })
    batch = from_arrow(table, capacity=8)
    return Env.from_batch(batch), batch


def live(tv, batch, n=4):
    data = np.asarray(tv.data)[:n]
    valid = (np.ones(n, dtype=bool) if tv.validity is None
             else np.asarray(tv.validity)[:n])
    return [d.item() if v else None for d, v in zip(data, valid)]


def test_arith_null_propagation():
    env, batch = make_env()
    tv = evaluate(E.Col("i") + E.Literal(10), env)
    assert live(tv, batch) == [11, 12, None, 14]


def test_division_by_zero_is_null():
    env, batch = make_env()
    tv = evaluate(E.Col("f") / (E.Col("i") - E.Literal(2)), env)
    out = live(tv, batch)
    assert out[0] == pytest.approx(-0.5)
    assert out[1] is None  # div by zero
    assert out[2] is None  # null operand


def test_comparison_and_kleene_logic():
    env, batch = make_env()
    tv = evaluate((E.Col("i") > 1) & (E.Col("f") < 3.0), env)
    assert live(tv, batch) == [False, True, None, False]
    tv = evaluate((E.Col("i") > 100) | E.Col("b"), env)
    assert live(tv, batch) == [True, False, True, None]
    # Kleene: true AND null -> null (row 2); null AND false -> false (row 3)
    tv = evaluate(E.Col("b") & (E.Col("i") > 100), env)
    assert live(tv, batch) == [False, False, None, False]


def test_string_equality_and_like():
    env, batch = make_env()
    tv = evaluate(E.Col("s") == E.Literal("apple"), env)
    assert live(tv, batch) == [True, False, True, None]
    tv = evaluate(E.Like(E.Col("s"), "%an%"), env)
    assert live(tv, batch) == [False, True, False, None]
    tv = evaluate(E.StringPredicate("startswith", E.Col("s"), "app"), env)
    assert live(tv, batch) == [True, False, True, None]


def test_string_ordering_comparison():
    env, batch = make_env()
    tv = evaluate(E.Cmp("<", E.Col("s"), E.Literal("az")), env)
    assert live(tv, batch) == [True, False, True, None]


def test_in_and_between():
    env, batch = make_env()
    tv = evaluate(E.Col("i").isin(1, 4), env)
    assert live(tv, batch) == [True, False, None, True]
    tv = evaluate(E.Col("f").between(1.0, 3.0), env)
    assert live(tv, batch) == [False, True, True, False]


def test_is_null():
    env, batch = make_env()
    tv = evaluate(E.IsNull(E.Col("i")), env)
    assert live(tv, batch) == [False, False, True, False]


def test_date_compare_and_extract():
    env, batch = make_env()
    tv = evaluate(E.Col("d") < E.Literal(datetime.date(1997, 1, 1)), env)
    assert live(tv, batch) == [True, True, False, False]
    tv = evaluate(E.ExtractDatePart("year", E.Col("d")), env)
    assert live(tv, batch) == [1995, 1996, 1997, 2000]
    tv = evaluate(E.ExtractDatePart("month", E.Col("d")), env)
    assert live(tv, batch) == [1, 6, 12, 2]
    tv = evaluate(E.ExtractDatePart("day", E.Col("d")), env)
    assert live(tv, batch) == [1, 15, 31, 29]


def test_date_arith_and_add_months():
    env, batch = make_env()
    tv = evaluate(E.Col("d") + E.Literal(90), env)
    assert live(tv, batch)[0] == T.date_to_days(datetime.date(1995, 4, 1))
    tv = evaluate(E.AddMonths(E.Col("d"), 3), env)
    expect = [datetime.date(1995, 4, 1), datetime.date(1996, 9, 15),
              datetime.date(1998, 3, 31), datetime.date(2000, 5, 29)]
    assert live(tv, batch) == [T.date_to_days(d) for d in expect]
    # clamp: Jan 31 + 1 month = Feb 28
    tv = evaluate(E.AddMonths(E.Literal(datetime.date(1999, 1, 31)), 1), env)
    assert live(tv, batch)[0] == T.date_to_days(datetime.date(1999, 2, 28))


def test_case_when_string_output():
    env, batch = make_env()
    expr = E.Case(
        branches=((E.Col("s") == E.Literal("apple"), E.Literal("FRUIT_A")),
                  (E.Col("s") == E.Literal("banana"), E.Literal("FRUIT_B"))),
        else_value=E.Literal("OTHER"),
    )
    tv = evaluate(expr, env)
    vals = live(tv, batch)
    decoded = [tv.dictionary[v] if v is not None else None for v in vals]
    assert decoded == ["FRUIT_A", "FRUIT_B", "FRUIT_A", "OTHER"]


def test_case_when_numeric():
    env, batch = make_env()
    expr = E.Case(branches=((E.Col("i") > 1, E.Col("f") * 10),),
                  else_value=E.Literal(0.0))
    tv = evaluate(expr, env)
    assert live(tv, batch) == [0.0, 15.0, 0.0, 35.0]


def test_substring():
    env, batch = make_env()
    tv = evaluate(E.Substring(E.Col("s"), 1, 3), env)
    decoded = [tv.dictionary[v] if v is not None else None
               for v in live(tv, batch)]
    assert decoded == ["app", "ban", "app", None]


def test_cast():
    env, batch = make_env()
    tv = evaluate(E.Cast(E.Col("i"), T.FLOAT64), env)
    assert live(tv, batch) == [1.0, 2.0, None, 4.0]


def test_coalesce():
    env, batch = make_env()
    tv = evaluate(E.Coalesce((E.Col("i"), E.Literal(99))), env)
    assert live(tv, batch) == [1, 2, 99, 4]


def test_mod_sign():
    env, batch = make_env()
    tv = evaluate(E.Arith("%", E.Col("i") - 3, E.Literal(2)), env)
    # SQL: (-2) % 2 = 0, (-1) % 2 = -1 (sign of dividend)
    assert live(tv, batch) == [0, -1, None, 1]

"""Join edge cases flagged by review: empty sides, full outer, duplicate
names (model: reference sql/core JoinSuite.scala / OuterJoinSuite.scala)."""

import pytest

from spark_tpu.api import functions as F


@pytest.fixture(scope="module")
def lr(spark):
    l = spark.createDataFrame([{"k": 1, "v": 10}, {"k": 2, "v": 20},
                               {"k": 5, "v": 50}])
    r = spark.createDataFrame([{"k": 1, "w": 100}, {"k": 3, "w": 300}])
    return l, r


def test_full_outer(lr):
    l, r = lr
    rows = l.join(r, on="k", how="full").orderBy("k").collect()
    got = [(x.k, x.v, x.w) for x in rows]
    assert got == [(1, 10, 100), (2, 20, None), (3, None, 300), (5, 50, None)]


def test_right_outer(lr):
    l, r = lr
    rows = l.join(r, on="k", how="right").orderBy("k").collect()
    assert [(x.k, x.v, x.w) for x in rows] == [(1, 10, 100), (3, None, 300)]


def test_cross_join_empty_right(spark):
    a = spark.createDataFrame([{"x": 1}, {"x": 2}])
    b = spark.createDataFrame([{"y": 10}]).filter(F.col("y") > 100)
    assert a.crossJoin(b).count() == 0
    assert a.crossJoin(b).collect() == []


def test_join_empty_build(spark, lr):
    l, _ = lr
    empty = spark.createDataFrame([{"k": 9, "w": 9}]).filter(F.col("w") < 0)
    assert l.join(empty, on="k").count() == 0
    assert l.join(empty, on="k", how="left").count() == 3
    assert l.join(empty, on="k", how="left_anti").count() == 3


def test_unfinished_when_chain(spark):
    df = spark.createDataFrame([{"v": 10}, {"v": 20}])
    rows = (df.select(F.when(F.col("v") > 15, "big").alias("band"))
            .orderBy("band").collect())
    assert sorted([r.band for r in rows], key=lambda x: (x is None, x)) \
        == ["big", None]


def test_duplicate_column_names_join(spark):
    a = spark.createDataFrame([{"k": 1, "v": 1}])
    b = spark.createDataFrame([{"k": 1, "v": 2}])
    j = a.join(b, on="k")
    assert j.columns == ["k", "v", "v#2"]
    row = j.collect()[0]
    assert row["v"] == 1 and row["v#2"] == 2


def test_null_keys_never_match(spark):
    a = spark.createDataFrame([{"k": 1, "v": 1}, {"k": None, "v": 2}])
    b = spark.createDataFrame([{"k": 1, "w": 3}, {"k": None, "w": 4}])
    assert a.join(b, on="k").count() == 1  # SQL: NULL != NULL
    left = a.join(b, on="k", how="left").orderBy("v").collect()
    assert [(r.v, r.w) for r in left] == [(1, 3), (2, None)]


def test_prune_join_dedup_column(spark):
    """Optimizer column pruning must map '#2'-suffixed output names back
    to right-side source columns (regression)."""
    l = spark.createDataFrame([{"id": 1, "x": 10}, {"id": 2, "x": 20}])
    r = spark.createDataFrame([{"id": 1, "x": 100}, {"id": 2, "x": 200}])
    rows = (l.join(r, on="id", how="inner").select("x#2")
            .sort("x#2").collect())
    assert [row["x#2"] for row in rows] == [100, 200]

"""Join edge cases flagged by review: empty sides, full outer, duplicate
names (model: reference sql/core JoinSuite.scala / OuterJoinSuite.scala)."""

import pytest

from spark_tpu.api import functions as F


@pytest.fixture(scope="module")
def lr(spark):
    l = spark.createDataFrame([{"k": 1, "v": 10}, {"k": 2, "v": 20},
                               {"k": 5, "v": 50}])
    r = spark.createDataFrame([{"k": 1, "w": 100}, {"k": 3, "w": 300}])
    return l, r


def test_full_outer(lr):
    l, r = lr
    rows = l.join(r, on="k", how="full").orderBy("k").collect()
    got = [(x.k, x.v, x.w) for x in rows]
    assert got == [(1, 10, 100), (2, 20, None), (3, None, 300), (5, 50, None)]


def test_right_outer(lr):
    l, r = lr
    rows = l.join(r, on="k", how="right").orderBy("k").collect()
    assert [(x.k, x.v, x.w) for x in rows] == [(1, 10, 100), (3, None, 300)]


def test_cross_join_empty_right(spark):
    a = spark.createDataFrame([{"x": 1}, {"x": 2}])
    b = spark.createDataFrame([{"y": 10}]).filter(F.col("y") > 100)
    assert a.crossJoin(b).count() == 0
    assert a.crossJoin(b).collect() == []


def test_join_empty_build(spark, lr):
    l, _ = lr
    empty = spark.createDataFrame([{"k": 9, "w": 9}]).filter(F.col("w") < 0)
    assert l.join(empty, on="k").count() == 0
    assert l.join(empty, on="k", how="left").count() == 3
    assert l.join(empty, on="k", how="left_anti").count() == 3


def test_unfinished_when_chain(spark):
    df = spark.createDataFrame([{"v": 10}, {"v": 20}])
    rows = (df.select(F.when(F.col("v") > 15, "big").alias("band"))
            .orderBy("band").collect())
    assert sorted([r.band for r in rows], key=lambda x: (x is None, x)) \
        == ["big", None]


def test_duplicate_column_names_join(spark):
    a = spark.createDataFrame([{"k": 1, "v": 1}])
    b = spark.createDataFrame([{"k": 1, "v": 2}])
    j = a.join(b, on="k")
    assert j.columns == ["k", "v", "v#2"]
    row = j.collect()[0]
    assert row["v"] == 1 and row["v#2"] == 2


def test_null_keys_never_match(spark):
    a = spark.createDataFrame([{"k": 1, "v": 1}, {"k": None, "v": 2}])
    b = spark.createDataFrame([{"k": 1, "w": 3}, {"k": None, "w": 4}])
    assert a.join(b, on="k").count() == 1  # SQL: NULL != NULL
    left = a.join(b, on="k", how="left").orderBy("v").collect()
    assert [(r.v, r.w) for r in left] == [(1, 3), (2, None)]


def test_prune_join_dedup_column(spark):
    """Optimizer column pruning must map '#2'-suffixed output names back
    to right-side source columns (regression)."""
    l = spark.createDataFrame([{"id": 1, "x": 10}, {"id": 2, "x": 20}])
    r = spark.createDataFrame([{"id": 1, "x": 100}, {"id": 2, "x": 200}])
    rows = (l.join(r, on="id", how="inner").select("x#2")
            .sort("x#2").collect())
    assert [row["x#2"] for row in rows] == [100, 200]


def test_wide_int64_key_join_hash_fallback(spark):
    """Joins on hash-like int64 keys whose range product overflows the
    packer fall back to hash-with-verify (reference:
    HashedRelation.scala:208 probe-then-confirm)."""
    import numpy as np

    rng = np.random.default_rng(7)
    ids = rng.integers(1 << 40, 1 << 62, size=64)
    tag = rng.integers(1 << 40, 1 << 62, size=64)
    left = spark.createDataFrame(
        [{"a": int(ids[i]), "b": int(tag[i]), "v": i} for i in range(64)])
    right = spark.createDataFrame(
        [{"a": int(ids[i]), "b": int(tag[i]), "w": i * 10}
         for i in range(0, 64, 2)])
    j = left.join(right, on=["a", "b"])
    got = sorted((r.v, r.w) for r in j.collect())
    assert got == [(i, i * 10) for i in range(0, 64, 2)]
    # re-execution exercises the adaptive traced path with hashed packing
    assert sorted((r.v, r.w) for r in j.collect()) == got
    # semi/anti via hashed keys must verify, not trust collisions
    semi = left.join(right, on=["a", "b"], how="left_semi")
    assert sorted(r.v for r in semi.collect()) == list(range(0, 64, 2))
    anti = left.join(right, on=["a", "b"], how="left_anti")
    assert sorted(r.v for r in anti.collect()) == list(range(1, 64, 2))


def test_wide_int64_key_join_mesh(spark):
    import numpy as np

    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    rng = np.random.default_rng(9)
    ids = rng.integers(1 << 40, 1 << 62, size=48)
    tag = rng.integers(1 << 40, 1 << 62, size=48)
    left = spark.createDataFrame(
        [{"a": int(ids[i]), "b": int(tag[i]), "v": i} for i in range(48)])
    right = spark.createDataFrame(
        [{"a": int(ids[i]), "b": int(tag[i]), "w": i} for i in range(0, 48, 3)])
    plan = L.Join(left._plan, right._plan, "inner",
                  (E.Col("a"), E.Col("b")), (E.Col("a"), E.Col("b")))
    ex = MeshExecutor(make_mesh(4), broadcast_threshold=1)  # force exchange
    rows = ex.execute_logical(plan).to_pylist()
    assert sorted((r["v"], r["w"]) for r in rows) == \
        [(i, i) for i in range(0, 48, 3)]


def test_single_wide_key_join(spark):
    """A single join key spanning more than the packer's range uses the
    hash fallback rather than overflowing."""
    vals = [-(1 << 62), (1 << 62) + 5, 17]
    left = spark.createDataFrame([{"a": v, "v": i}
                                  for i, v in enumerate(vals)])
    right = spark.createDataFrame([{"a": v, "w": i * 10}
                                   for i, v in enumerate(vals[:2])])
    j = left.join(right, on="a")
    assert sorted((r.v, r.w) for r in j.collect()) == [(0, 0), (1, 10)]

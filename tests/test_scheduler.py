"""Multi-tenant query scheduler (spark_tpu/scheduler/): fair pools,
HBM admission control, bounded-queue backpressure, cancellation, and
concurrent serving through the connect server.

Every test carries the ``timeout`` deadlock guard — a wedged queue or
gate must fail fast, never hang tier-1.
"""

import json
import threading
import time

import pyarrow as pa
import pytest

from spark_tpu import faults, metrics, tracing
from spark_tpu.conf import RuntimeConf
from spark_tpu.scheduler import (AdmissionController, QueryCancelled,
                                 QueryScheduler, SchedulerQueueFull,
                                 build_pools, estimate_plan_bytes)

pytestmark = pytest.mark.timeout(90)


def make_scheduler(**overrides):
    return QueryScheduler(conf=RuntimeConf(overrides))


# ---- pools & policy ---------------------------------------------------------


def test_pools_from_conf():
    conf = RuntimeConf({
        "spark.tpu.scheduler.pool.etl.weight": 2,
        "spark.tpu.scheduler.pool.etl.minShare": 1,
        "spark.tpu.scheduler.pool.adhoc.weight": 1,
    })
    pools = build_pools(conf)
    assert pools["etl"].weight == 2 and pools["etl"].min_share == 1
    assert pools["adhoc"].weight == 1
    assert "default" in pools  # always present


def test_bad_mode_rejected():
    with pytest.raises(ValueError, match="FIFO or FAIR"):
        make_scheduler(**{"spark.scheduler.mode": "LOTTERY"})


def test_fifo_lifecycle_and_metrics():
    sched = make_scheduler()
    try:
        t = sched.submit(lambda tk: 41 + 1, description="answer")
        assert t.result(timeout=30) == 42
        assert t.state == "FINISHED"
        info = t.info()
        assert info["pool"] == "default"
        assert info["queue_wait_ms"] >= 0.0
        assert any(q["id"] == t.id for q in sched.describe())
        st = sched.status()
        assert st["mode"] == "FIFO" and st["queued"] == 0
        assert st["admission"]["in_use_bytes"] == 0
    finally:
        sched.stop()


def test_fair_weight_ratio_under_contention():
    """FAIR pools with weights 2:1 split device time ~2:1 under
    contention (stride scheduling at the admission gate). Measured on
    the steady-state delta between two snapshots so the startup
    transient (the first dequeues land before any device_ms exists)
    doesn't skew the ratio."""
    sched = make_scheduler(**{
        "spark.scheduler.mode": "FAIR",
        "spark.tpu.scheduler.pool.a.weight": 2,
        "spark.tpu.scheduler.pool.b.weight": 1,
        "spark.tpu.scheduler.hbmBudgetBytes": 1024,  # serial device
        "spark.tpu.scheduler.maxConcurrency": 4,
        "spark.tpu.scheduler.queueDepth": 200,
    })
    try:
        def work(tk):
            time.sleep(0.008)

        for _ in range(40):
            sched.submit(work, pool="a")
            sched.submit(work, pool="b")

        def finished():
            return (sched.pools.get("a").finished
                    + sched.pools.get("b").finished)

        def device_ms():
            return (sched.pools.get("a").device_ms,
                    sched.pools.get("b").device_ms)

        deadline = time.time() + 60
        while finished() < 8 and time.time() < deadline:
            time.sleep(0.005)
        a0, b0 = device_ms()
        while finished() < 40 and time.time() < deadline:
            time.sleep(0.005)
        a1, b1 = device_ms()
        assert finished() >= 40, "scheduler made no progress"
        ratio = (a1 - a0) / max(1e-9, (b1 - b0))
        # 2:1 within 25%
        assert 1.5 <= ratio <= 2.67, f"device-time split {ratio:.2f}:1"
    finally:
        sched.stop()


# ---- HBM admission ----------------------------------------------------------


def test_admission_controller_budget():
    ac = AdmissionController(4096)
    assert ac.fits(2048)
    c1 = ac.acquire(2048)
    assert ac.fits(2048)
    c2 = ac.acquire(2048)
    assert not ac.fits(1)  # budget exhausted
    ac.release(c1)
    ac.release(c2)
    # over-budget query admits alone, charged the whole budget
    assert ac.fits(1 << 40)
    c3 = ac.acquire(1 << 40)
    assert c3 == 4096
    assert not ac.fits(64)
    ac.release(c3)
    assert ac.snapshot()["in_use_bytes"] == 0


def test_estimate_plan_bytes(spark):
    df = spark.createDataFrame([{"k": i % 3, "v": i} for i in range(64)])
    small = estimate_plan_bytes(df._plan, spark.conf)
    assert small > 0
    big = estimate_plan_bytes(
        spark.range(1 << 20)._plan, spark.conf)
    assert big >= 8 * (1 << 20)  # rows x 8-byte column


def test_admission_gates_device_concurrency():
    """With budget for exactly two footprints, a third query waits at
    the gate; nothing exceeds the budget concurrently."""
    sched = make_scheduler(**{
        "spark.tpu.scheduler.hbmBudgetBytes": 4096,
        "spark.tpu.scheduler.maxConcurrency": 4,
    })
    try:
        lock = threading.Lock()
        state = {"now": 0, "peak": 0}

        def work(tk):
            with lock:
                state["now"] += 1
                state["peak"] = max(state["peak"], state["now"])
            time.sleep(0.05)
            with lock:
                state["now"] -= 1

        tickets = [sched.submit(work, est_bytes=2048) for _ in range(4)]
        for t in tickets:
            t.result(timeout=30)
        assert state["peak"] <= 2
        assert state["peak"] == 2  # budget allowed pairs to co-run
    finally:
        sched.stop()


# ---- bounded queue / backpressure ------------------------------------------


def test_queue_full_rejects_submit():
    sched = make_scheduler(**{
        "spark.tpu.scheduler.maxConcurrency": 1,
        "spark.tpu.scheduler.queueDepth": 1,
        "spark.tpu.scheduler.retryAfterSeconds": 0.25,
    })
    try:
        release = threading.Event()
        blocker = sched.submit(lambda tk: release.wait(30))
        deadline = time.time() + 30
        while blocker.state != "RUNNING" and time.time() < deadline:
            time.sleep(0.005)
        queued = sched.submit(lambda tk: None)
        with pytest.raises(SchedulerQueueFull) as ei:
            sched.submit(lambda tk: None)
        assert ei.value.retry_after_s == 0.25
        release.set()
        assert blocker.result(timeout=30) is True
        queued.result(timeout=30)
        assert sched.rejected == 1
    finally:
        release.set()
        sched.stop()


def test_server_returns_429_with_retry_after(spark):
    """A full scheduler queue surfaces as HTTP 429 + Retry-After, not
    an unbounded hang."""
    from spark_tpu.connect.server import Client, ConnectServer

    spark.createDataFrame(
        [{"k": 1, "v": 2}]).createOrReplaceTempView("sched_429_t")
    sched = QueryScheduler(conf=RuntimeConf({
        "spark.tpu.scheduler.queueDepth": 0,
        "spark.tpu.scheduler.retryAfterSeconds": 0.01,
    }))
    srv = ConnectServer(spark, port=0, scheduler=sched).start()
    try:
        c = Client(srv.url, retries=0)
        with pytest.raises(RuntimeError, match="failed after 1 attempt"):
            c.sql("select * from sched_429_t")
        # with retries the client backs off per Retry-After, still 429
        c2 = Client(srv.url, retries=2, backoff_s=0.005)
        t0 = time.time()
        with pytest.raises(RuntimeError, match="failed after 3 attempt"):
            c2.sql("select * from sched_429_t")
        assert time.time() - t0 >= 0.02  # honored the Retry-After floor
    finally:
        srv.stop()


def test_client_retries_flapping_server():
    """Bounded retry with exponential backoff: the client survives a
    server that answers 429 twice and drops one connection before
    serving the result."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from spark_tpu.connect.server import Client

    tbl = pa.table({"a": [1, 2, 3]})
    import io

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    arrow_bytes = sink.getvalue()
    attempts = []

    class Flapping(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            attempts.append(self.path)
            n = len(attempts)
            if n <= 2:  # backpressure twice
                body = json.dumps({"error": "SchedulerQueueFull",
                                   "message": "full",
                                   "retry_after_s": 0.01}).encode()
                self.send_response(429)
                self.send_header("Retry-After", "0.01")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif n == 3:  # flap: drop the connection mid-request
                self.connection.close()
            else:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/vnd.apache.arrow.stream")
                self.send_header("Content-Length",
                                 str(len(arrow_bytes)))
                self.end_headers()
                self.wfile.write(arrow_bytes)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Flapping)
    thr = threading.Thread(target=httpd.serve_forever, daemon=True)
    thr.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        c = Client(url, retries=4, backoff_s=0.005)
        out = c.sql("select 1")
        assert out.equals(tbl)
        assert len(attempts) == 4
        # a client out of retries surfaces the last error, bounded
        attempts.clear()
        c0 = Client(url, retries=1, backoff_s=0.005)
        with pytest.raises(RuntimeError, match="failed after 2 attempt"):
            c0.sql("select 1")
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---- cancellation & deadlines ----------------------------------------------


def test_cancel_mid_queue():
    sched = make_scheduler(**{
        "spark.tpu.scheduler.maxConcurrency": 1,
        "spark.tpu.scheduler.queueDepth": 8,
    })
    try:
        release = threading.Event()
        blocker = sched.submit(lambda tk: release.wait(30))
        deadline = time.time() + 30
        while blocker.state != "RUNNING" and time.time() < deadline:
            time.sleep(0.005)
        queued = sched.submit(lambda tk: "never")
        assert queued.state == "QUEUED"
        assert sched.cancel(queued.id) is True
        assert queued.state == "CANCELLED"
        with pytest.raises(QueryCancelled):
            queued.result(timeout=5)
        release.set()
        blocker.result(timeout=30)
    finally:
        release.set()
        sched.stop()


def test_cancel_mid_run():
    sched = make_scheduler()
    try:
        started = threading.Event()

        def work(tk):
            started.set()
            for _ in range(1000):  # cooperative cancellation seam
                tk.check_cancelled()
                time.sleep(0.005)
            return "ran to completion"

        t = sched.submit(work)
        assert started.wait(30)
        assert t.cancel() is True
        with pytest.raises(QueryCancelled):
            t.result(timeout=30)
        assert t.state == "CANCELLED"
    finally:
        sched.stop()


def test_deadline_expires_in_queue():
    sched = make_scheduler(**{
        "spark.tpu.scheduler.maxConcurrency": 1,
    })
    try:
        release = threading.Event()
        blocker = sched.submit(lambda tk: release.wait(30))
        deadline = time.time() + 30
        while blocker.state != "RUNNING" and time.time() < deadline:
            time.sleep(0.005)
        t = sched.submit(lambda tk: "late", deadline_s=0.05)
        time.sleep(0.1)
        release.set()
        with pytest.raises(QueryCancelled, match="DEADLINE_EXCEEDED"):
            t.result(timeout=30)
        blocker.result(timeout=30)
    finally:
        release.set()
        sched.stop()


# ---- scheduler.admit fault injection ---------------------------------------


def test_admit_fault_transient_recovers():
    conf = RuntimeConf({
        "spark.tpu.faultInjection.scheduler.admit": "nth:1",
    })
    sched = QueryScheduler(conf=conf)
    try:
        t = sched.submit(lambda tk: "ok")
        assert t.result(timeout=30) == "ok"
        assert faults.fire_count(conf, "scheduler.admit") == 1
        kinds = [e["kind"] for e in metrics.recent(512)]
        assert "fault_injected" in kinds
    finally:
        sched.stop()


def test_admit_fault_oom_degrades_estimate():
    conf = RuntimeConf({
        "spark.tpu.faultInjection.scheduler.admit": "nth:1:oom",
    })
    sched = QueryScheduler(conf=conf)
    try:
        t = sched.submit(lambda tk: "ok", est_bytes=1 << 22)
        assert t.result(timeout=30) == "ok"
        # admission-side degradation rung halved the claimed footprint
        assert t.est_bytes == 1 << 21
        degr = [e for e in metrics.recent(512)
                if e.get("kind") == "scheduler"
                and e.get("phase") == "admit_degraded"]
        assert degr and degr[-1]["est_bytes"] == 1 << 21
    finally:
        sched.stop()


def test_admit_fault_corrupt_fails_typed():
    conf = RuntimeConf({
        "spark.tpu.faultInjection.scheduler.admit": "nth:1:corrupt",
    })
    sched = QueryScheduler(conf=conf)
    try:
        t = sched.submit(lambda tk: "ok")
        with pytest.raises(faults.InjectedCorruptionError):
            t.result(timeout=30)
        assert t.state == "FAILED"
    finally:
        sched.stop()


# ---- concurrent serving: byte-identical to serial ---------------------------


STRESS_QUERIES = (
    "select k, sum(v) as s, count(*) as n from st_a "
    "group by k order by k",
    "select a.k, a.v, b.w from st_a a join st_b b on a.k = b.k "
    "order by a.v limit 20",
    "select k, v from st_a where v > 10 order by v",
    "select max(v) as mx, min(v) as mn, avg(v) as av from st_a",
)


def test_concurrent_results_byte_identical_to_serial(spark):
    """8 concurrent clients replaying the query mix through the
    connect server produce byte-identical Arrow to a serial replay —
    the scheduler must never trade correctness for concurrency."""
    from spark_tpu.connect.server import Client, ConnectServer

    spark.createDataFrame(
        [{"k": i % 5, "v": i} for i in range(200)]
    ).createOrReplaceTempView("st_a")
    spark.createDataFrame(
        [{"k": i, "w": i * 7} for i in range(5)]
    ).createOrReplaceTempView("st_b")

    srv = ConnectServer(spark, port=0).start()
    try:
        serial = Client(srv.url)
        ref = {q: serial.sql(q) for q in STRESS_QUERIES}

        mismatches = []
        errors = []

        def client_loop(idx: int):
            c = Client(srv.url, retries=3, backoff_s=0.01)
            for _ in range(2):
                for q in STRESS_QUERIES:
                    try:
                        out = c.sql(q)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))
                        continue
                    if not out.equals(ref[q]):
                        mismatches.append((idx, q))

        threads = [threading.Thread(target=client_loop, args=(i,),
                                    daemon=True) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors[:3]
        assert not mismatches, mismatches[:3]

        # the lifecycle surface saw the traffic
        q = serial.queries()
        assert q["status"]["pools"]
        assert any(rec["state"] == "FINISHED" for rec in q["queries"])
    finally:
        srv.stop()


# ---- observability: profile, /health, UI -----------------------------------


def test_scheduler_profile_rollup():
    sched = make_scheduler()
    try:
        sched.submit(lambda tk: 1, pool="default").result(timeout=30)
        prof = tracing.scheduler_profile()
        assert prof.get("default", {}).get("finished", 0) >= 1
        text = tracing.format_scheduler_profile(prof)
        assert "default" in text
    finally:
        sched.stop()


def test_health_and_ui_report_scheduler(spark):
    import urllib.request

    from spark_tpu import ui as UI
    from spark_tpu.connect.server import Client, ConnectServer

    srv = ConnectServer(spark, port=0).start()
    ui_srv = UI.StatusServer(spark)
    try:
        h = Client(srv.url).health()
        assert h["scheduler"]["queue_depth"] >= 0
        assert any(p["name"] == "default"
                   for p in h["scheduler"]["pools"])

        with urllib.request.urlopen(
                f"{ui_srv.url}/api/v1/status", timeout=10) as resp:
            status = json.loads(resp.read())
        assert status["scheduler"] is not None
        assert "queued" in status["scheduler"]

        with urllib.request.urlopen(ui_srv.url + "/",
                                    timeout=10) as resp:
            html = resp.read().decode()
        assert "Scheduler" in html and "pool default" in html
    finally:
        ui_srv.stop()
        srv.stop()


def test_deadlock_guard_marker_registered(request):
    """All scheduler tests run under the timeout deadlock guard."""
    assert request.node.get_closest_marker("timeout") is not None

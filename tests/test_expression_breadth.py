"""Math/string/regexp/date expression batch + sketches, oracle-checked
against sqlite3 where it implements the function, python otherwise
(reference: catalyst expressions/ packages,
common/sketch/.../CountMinSketch.java:54)."""

import math
import sqlite3

import pytest

from spark_tpu.api import functions as F

ROWS = [
    {"s": "  Hello ", "x": 2.567, "n": 4, "d": "1995-03-17"},
    {"s": "WORLD", "x": -3.21, "n": 9, "d": "1996-12-01"},
    {"s": "claude v5", "x": 0.5, "n": 16, "d": "2000-02-29"},
]


@pytest.fixture(scope="module")
def edf(spark):
    import datetime

    rows = [dict(r, d=datetime.date.fromisoformat(r["d"])) for r in ROWS]
    df = spark.createDataFrame(rows)
    df.createOrReplaceTempView("exprs")
    conn = sqlite3.connect(":memory:")
    conn.execute("create table exprs (s text, x real, n int, d text)")
    conn.executemany("insert into exprs values (?,?,?,?)",
                     [(r["s"], r["x"], r["n"], r["d"]) for r in ROWS])
    return spark, conn


@pytest.mark.parametrize("fn", ["upper(s)", "lower(s)", "trim(s)",
                                "ltrim(s)", "rtrim(s)", "length(s)",
                                "abs(x)", "round(x)"])
def test_sqlite_checked(edf, fn):
    spark, conn = edf
    got = sorted(str(r.asDict()["v"]) for r in
                 spark.sql(f"select {fn} as v from exprs").collect())
    want = sorted(str(v[0]) for v in
                  conn.execute(f"select {fn} from exprs").fetchall())
    assert got == want, f"{fn}: {got} != {want}"


def test_signum(spark):
    # Spark's signum returns DOUBLE (sqlite's sign returns int)
    rows = spark.sql("select sign(x) as g from exprs").collect()
    assert sorted(r.g for r in rows) == [-1.0, 1.0, 1.0]


def test_math_functions(spark):
    rows = spark.sql(
        "select sqrt(n) as sq, exp(0.0) as e, ln(n) as l, log10(n) as lg,"
        " power(n, 2) as p, floor(x) as f, ceil(x) as c, round(x, 1) as r "
        "from exprs").collect()
    by_n = {round(r.sq ** 2): r for r in rows}
    assert by_n[4].sq == pytest.approx(2.0)
    assert by_n[4].e == pytest.approx(1.0)
    assert by_n[16].l == pytest.approx(math.log(16))
    assert by_n[16].lg == pytest.approx(math.log10(16))
    assert by_n[9].p == pytest.approx(81.0)
    assert (by_n[4].f, by_n[4].c) == (2, 3)
    assert by_n[9].r == pytest.approx(-3.2)
    assert by_n[4].r == pytest.approx(2.6)  # HALF_UP, not banker's


def test_round_half_up(spark):
    df = spark.createDataFrame([{"v": 2.5}, {"v": 3.5}, {"v": -2.5}])
    got = sorted(r.r for r in
                 df.select(F.round("v").alias("r")).collect())
    assert got == [-3.0, 3.0, 4.0]  # HALF_UP like Spark, not half-even


def test_replace_literal_backslash(edf):
    """REPLACE is literal on both sides: a replacement containing
    backslashes must not act as an re.sub template ('\\1' used to be a
    backreference into the escaped — group-free — pattern)."""
    spark, _ = edf
    rows = spark.sql(
        "select replace(s, 'l', '\\1') as r from exprs").collect()
    vals = sorted(r.r for r in rows)
    assert "WORLD" in vals           # no 'l': untouched
    assert "c\\1aude v5" in vals     # literal backslash-one inserted

    df = spark.createDataFrame([{"s": "a_b_c"}])
    out = df.select(F.replace(F.col("s"), "_", "\\").alias("r")).collect()
    assert out[0].r == "a\\b\\c"     # lone backslash: was 'bad escape'


def test_regexp(spark):
    rows = spark.sql(
        "select regexp_extract(s, '([a-z]+) v([0-9]+)', 2) as ver, "
        "regexp_replace(s, '[aeiou]', '_') as repl, "
        "regexp_like(s, '^[A-Z]+$') as caps from exprs").collect()
    by_repl = {r.repl: r for r in rows}
    assert any(r.ver == "5" for r in rows)
    assert "cl__d_ v5" in by_repl
    assert by_repl["WORLD"].caps is True
    assert by_repl["cl__d_ v5"].caps is False


def test_date_trunc_last_day(spark):
    import datetime

    rows = spark.sql(
        "select date_trunc('month', d) as m, date_trunc('year', d) as y, "
        "last_day(d) as ld from exprs").collect()
    got = {(r.m, r.y, r.ld) for r in rows}
    assert (datetime.date(2000, 2, 1), datetime.date(2000, 1, 1),
            datetime.date(2000, 2, 29)) in got  # leap year
    assert (datetime.date(1995, 3, 1), datetime.date(1995, 1, 1),
            datetime.date(1995, 3, 31)) in got


def test_approx_count_distinct(spark):
    df = spark.createDataFrame([{"k": i % 7, "g": i % 2}
                                for i in range(200)])
    df.createOrReplaceTempView("acd")
    rows = spark.sql(
        "select g, approx_count_distinct(k) as n from acd "
        "group by g order by g").collect()
    assert [(r.g, r.n) for r in rows] == [(0, 7), (1, 7)]
    out = df.agg(F.approx_count_distinct("k").alias("n")).collect()
    assert out[0].n == 7


def test_count_min_sketch():
    import numpy as np

    from spark_tpu.sketch import CountMinSketch

    rng = np.random.default_rng(3)
    vals = rng.integers(0, 50, 5000)
    cms = CountMinSketch(depth=5, width=4096).add(vals)
    truth = {v: int((vals == v).sum()) for v in range(50)}
    for v in range(50):
        est = cms.estimate(v)
        assert est >= truth[v]             # never under-counts
        assert est <= truth[v] + 30        # tight at this width
    # mergeability (the per-device psum pattern)
    half = CountMinSketch(depth=5, width=4096)
    a, b = half.add(vals[:2500]), half.add(vals[2500:])
    merged = a.merge(b)
    assert merged.estimate(7) == cms.estimate(7)


def test_bloom_filter():
    import numpy as np

    from spark_tpu.sketch import BloomFilter

    rng = np.random.default_rng(4)
    present = rng.integers(0, 1 << 40, 2000)
    absent = rng.integers(1 << 41, 1 << 42, 2000)
    bf = BloomFilter.for_items(2000, fpp=0.03).add(present)
    assert bool(bf.might_contain(present).all())  # no false negatives
    fp = float(np.asarray(bf.might_contain(absent)).mean())
    assert fp < 0.1
    # merge
    b1 = BloomFilter.for_items(2000, fpp=0.03).add(present[:1000])
    b2 = BloomFilter.for_items(2000, fpp=0.03).add(present[1000:])
    assert bool(b1.merge(b2).might_contain(present).all())


def test_round_negative_scale_integral(spark):
    df = spark.createDataFrame([{"i": 1234}, {"i": 1285}])
    got = sorted(r.r for r in
                 df.select(F.round("i", -2).alias("r")).collect())
    assert got == [1200, 1300]


def test_floor_large_int_identity(spark):
    big = (1 << 60) + 1
    df = spark.createDataFrame([{"i": big}])
    assert df.select(F.floor("i").alias("f")).collect()[0].f == big


# ---- composition batch (greatest/least, datetime parts, pads...) ----------


def test_greatest_least(spark):
    rows = spark.sql(
        "select greatest(n, 7) as g, least(x, 0.0) as l from exprs"
    ).collect()
    assert sorted(r["g"] for r in rows) == [7, 9, 16]
    assert sorted(r["l"] for r in rows) == [-3.21, 0.0, 0.0]


def test_greatest_skips_nulls(spark):
    import pyarrow as pa

    df = spark.createDataFrame(pa.table({
        "a": pa.array([1, None, None], pa.int64()),
        "b": pa.array([None, 5, None], pa.int64())}))
    df.createOrReplaceTempView("gn")
    rows = spark.sql("select greatest(a, b) as g from gn").collect()
    assert [r["g"] for r in rows] == [1, 5, None]


def test_ifnull_nvl2(spark):
    import pyarrow as pa

    df = spark.createDataFrame(pa.table({
        "a": pa.array([None, 3], pa.int64())}))
    df.createOrReplaceTempView("nv")
    rows = spark.sql(
        "select ifnull(a, -1) as i, nvl2(a, 100, 200) as v from nv"
    ).collect()
    assert [(r["i"], r["v"]) for r in rows] == [(-1, 200), (3, 100)]


def test_datetime_parts(spark):
    import datetime

    rows = spark.sql("""
      select quarter(d) as q, dayofweek(d) as dw, weekday(d) as wd,
             dayofyear(d) as dy, d from exprs order by d
    """).collect()
    for r in rows:
        d = r["d"]
        assert r["q"] == (d.month - 1) // 3 + 1
        # Spark: 1=Sunday..7=Saturday; python weekday(): 0=Monday
        assert r["dw"] == (d.weekday() + 1) % 7 + 1
        assert r["wd"] == d.weekday()
        assert r["dy"] == d.timetuple().tm_yday


def test_months_between(spark):
    rows = spark.sql("""
      select months_between(date '1997-02-28', date '1996-10-30') as a,
             months_between(date '1997-02-28', date '1996-11-30') as b,
             months_between(date '1997-03-15', date '1997-01-15') as c
    """).collect()[0]
    # 1996-10-30 -> 1997-02-28: 4 months minus 2/31 (Spark: 3.93548387)
    assert abs(rows["a"] - (4 - 2 / 31)) < 1e-9
    # both month-ends: whole number
    assert rows["b"] == 3.0
    assert rows["c"] == 2.0


def test_math_breadth2(spark):
    import math

    rows = spark.sql(
        "select log2(n) as l2, degrees(x) as dg, pmod(-7, 3) as pm "
        "from exprs").collect()
    assert sorted(r["l2"] for r in rows) == [2.0, pytest.approx(
        math.log2(9)), 4.0]
    assert rows[0]["pm"] == 2  # Spark pmod(-7, 3) == 2


def test_string_pads(spark):
    rows = spark.sql("""
      select lpad(trim(s), 10, '*') as lp, rpad(trim(s), 4, 'x') as rp,
             reverse(trim(s)) as rv, initcap(trim(s)) as ic,
             repeat('ab', 3) as rpt,
             translate(s, 'lo', 'LO') as tr
      from exprs where s = 'WORLD'
    """).collect()[0]
    assert rows["lp"] == "*****WORLD"
    assert rows["rp"] == "WORL"
    assert rows["rv"] == "DLROW"
    assert rows["ic"] == "World"
    assert rows["rpt"] == "ababab"
    assert rows["tr"] == "WORLD"


def test_concat_ws_translate(spark):
    rows = spark.sql(
        "select concat_ws('-', trim(s), 'z') as c, "
        "translate('banana', 'an', 'AN') as t from exprs limit 1"
    ).collect()[0]
    assert rows["c"].endswith("-z")
    assert rows["t"] == "bANANA"


def test_timestamp_parts(spark):
    import datetime

    import pyarrow as pa

    ts = datetime.datetime(2001, 7, 4, 13, 45, 30)
    df = spark.createDataFrame(pa.table({
        "t": pa.array([ts], pa.timestamp("us"))}))
    df.createOrReplaceTempView("tsv")
    r = spark.sql(
        "select hour(t) as h, minute(t) as m, second(t) as s from tsv"
    ).collect()[0]
    assert (r["h"], r["m"], r["s"]) == (13, 45, 30)


def test_nullif_typed(spark):
    """nullif on non-boolean operands; NULL arm is typed to the operand
    (reference: NullIf → If(EqualTo(l, r), Literal(null, l.dataType), l))."""
    import pyarrow as pa

    d = spark.createDataFrame(pa.table({
        "a": pa.array([1, 2, 2, None], pa.int64()),
        "s": pa.array(["x", "y", "x", None]),
    }))
    out = d.select(F.nullif("a", F.lit(2)).alias("v")).collect()
    assert [r["v"] for r in out] == [1, None, None, None]
    out2 = d.select(F.nullif("s", F.lit("x")).alias("v")).collect()
    assert [r["v"] for r in out2] == [None, "y", None, None]
    assert spark.sql("select nullif(1, 2) as v").collect()[0]["v"] == 1


def test_lpad_multichar_head_aligned(spark):
    """lpad cycles the pad from its START (reference StringLPad:
    lpad('abc', 6, 'xy') = 'xyxabc')."""
    import pyarrow as pa

    d = spark.createDataFrame(pa.table({"s": pa.array(["abc", "hello!"])}))
    out = d.select(F.lpad("s", 6, "xy").alias("v")).collect()
    assert [r["v"] for r in out] == ["xyxabc", "hello!"]
    out2 = d.select(F.rpad("s", 6, "xy").alias("v")).collect()
    assert [r["v"] for r in out2] == ["abcxyx", "hello!"]
    # non-positive length -> '' (UTF8String.lpad substring(0, len))
    out3 = d.select(F.lpad("s", -1, "x").alias("v"),
                    F.rpad("s", 0, "x").alias("w")).collect()
    assert [r["v"] for r in out3] == ["", ""]
    assert [r["w"] for r in out3] == ["", ""]


def test_concat_ws_skips_nulls(spark):
    """concat_ws skips null arguments with their separators (reference:
    ConcatWs, stringExpressions.scala) — result is never null."""
    import pyarrow as pa

    d = spark.createDataFrame(pa.table({
        "a": pa.array(["x", None, "p"]),
        "b": pa.array(["y", "q", None]),
        "c": pa.array([None, "z", None]),
    }))
    out = d.select(F.concat_ws("-", "a", "b", "c").alias("v")).collect()
    assert [r["v"] for r in out] == ["x-y", "q-z", "p"]
    d.createOrReplaceTempView("cws")
    out2 = spark.sql("select concat_ws('-', a, b, c) as v from cws").collect()
    assert [r["v"] for r in out2] == ["x-y", "q-z", "p"]

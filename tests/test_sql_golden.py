"""Golden-file SQL contract (reference: SQLQueryTestSuite.scala:133).

Every query in tests/sql_golden/inputs/*.sql must reproduce its
checked-in golden rows. sqlite-oracled files carry results produced by
an INDEPENDENT implementation (dialect cross-check); engine-oracled
files are regression locks for features sqlite lacks. Regenerate with
``python -m tests.sql_golden.regen``."""

import os

import pytest

from tests.sql_golden import harness as H


@pytest.fixture(scope="module")
def golden_spark(spark):
    H.setup_engine(spark)
    return spark


def _cases():
    out = []
    for fname in H.input_files():
        gpath = os.path.join(H.GOLDENS, fname[:-4] + ".out")
        if not os.path.exists(gpath):
            out.append(pytest.param(fname, None, None,
                                    id=f"{fname}:MISSING-GOLDEN"))
            continue
        for i, (sql, rows) in enumerate(H.read_golden(gpath)):
            out.append(pytest.param(fname, sql, rows, id=f"{fname}:{i}"))
    return out


@pytest.mark.parametrize("fname,sql,want", _cases())
def test_golden(golden_spark, fname, sql, want):
    assert sql is not None, (
        f"{fname} has no golden file — run python -m tests.sql_golden.regen")
    got = H.run_engine(golden_spark, sql)
    assert got == want, (
        f"{fname}: result drift for:\n{sql}\n"
        f"got : {got}\nwant: {want}")

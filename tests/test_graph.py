"""Pregel/GraphX subset (reference: graphx/Pregel.scala:59,
lib/PageRank.scala, lib/ConnectedComponents.scala)."""

import numpy as np
import pytest

from spark_tpu.graph import Graph


def test_pagerank_star():
    # hub 0 pointed at by 1..4: hub rank must dominate
    g = Graph(vertex_ids=[0, 1, 2, 3, 4],
              edge_src=[1, 2, 3, 4], edge_dst=[0, 0, 0, 0])
    pr = np.asarray(g.pagerank(num_iters=30))
    assert pr[0] > pr[1]
    assert np.allclose(pr[1:], pr[1])  # leaves symmetric
    # leaves have no in-edges: rank = reset_prob
    assert pr[1] == pytest.approx(0.15)
    assert pr[0] == pytest.approx(0.15 + 0.85 * 4 * 0.15, rel=1e-5)


def test_pagerank_cycle_uniform():
    n = 8
    g = Graph(vertex_ids=list(range(n)),
              edge_src=list(range(n)),
              edge_dst=[(i + 1) % n for i in range(n)])
    pr = np.asarray(g.pagerank(num_iters=50))
    assert np.allclose(pr, 1.0, atol=1e-4)  # symmetric cycle: all equal


def test_connected_components():
    # two components {10,11,12} (chain) and {20,21}; singleton {30}
    g = Graph(vertex_ids=[10, 11, 12, 20, 21, 30],
              edge_src=[10, 11, 20], edge_dst=[11, 12, 21])
    labels = g.connected_components()
    by_id = dict(zip(g.vertex_ids.tolist(), labels.tolist()))
    assert by_id[10] == by_id[11] == by_id[12] == 10
    assert by_id[20] == by_id[21] == 20
    assert by_id[30] == 30


def test_connected_components_random():
    rng = np.random.default_rng(5)
    # 3 random blobs connected internally by random spanning chains
    ids, src, dst = [], [], []
    for b in range(3):
        nodes = list(range(b * 100, b * 100 + 30))
        ids.extend(nodes)
        perm = rng.permutation(nodes)
        src.extend(perm[:-1])
        dst.extend(perm[1:])
    g = Graph(ids, src, dst)
    labels = g.connected_components()
    by_id = dict(zip(g.vertex_ids.tolist(), labels.tolist()))
    for b in range(3):
        vals = {by_id[v] for v in range(b * 100, b * 100 + 30)}
        assert vals == {b * 100}


def test_custom_pregel_shortest_path():
    import jax.numpy as jnp

    # single-source shortest path by min-propagation with edge weights
    g = Graph(vertex_ids=[0, 1, 2, 3],
              edge_src=[0, 0, 1, 2], edge_dst=[1, 2, 3, 3],
              edge_attr=[1.0, 4.0, 1.0, 1.0])
    inf = 1e18
    init = jnp.asarray([0.0, inf, inf, inf])

    def message(src_dist, w):
        return src_dist + w

    def update(dist, best_in):
        return jnp.minimum(dist, best_in)

    out = np.asarray(g.pregel(init, message, update, num_iters=4,
                              merge="min", default_msg=inf))
    assert out.tolist() == [0.0, 1.0, 4.0, 2.0]


def test_triangle_count():
    # triangle 0-1-2 plus a dangling edge 2-3
    g = Graph(vertex_ids=[0, 1, 2, 3],
              edge_src=[0, 1, 2, 2], edge_dst=[1, 2, 0, 3])
    assert g.triangle_count() == 1

"""Runtime cross-check of the static lock hierarchy
(spark_tpu/locks.py) + the concurrency fixes it guards.

- the lock-order validator (spark.tpu.debug.lockOrder) detects a
  seeded rank inversion and a seeded cycle,
- a real workload (warm TPC-H q1, cached DataFrames, scheduler
  round-trips) runs with the validator ON and records ZERO violations
  and ZERO cycles — the runtime graph agrees with the hierarchy the
  static linter enforces,
- the validator's per-acquire cost is micro (design target: <3%
  overhead on a warm q1; asserted here as an absolute per-pair bound
  plus a loose warm-query ratio so CI stays deterministic),
- single-flight followers in the serve result cache time out on a
  wedged owner (typed FlightWaitTimeout in the event log) and fall
  through to their own execution,
- an owner's QueryCancelled is owner-local: followers re-execute
  instead of inheriting the cancellation,
- every session-owned daemon thread quiesces on stop
  (test_threads_quiesce).
"""

import threading
import time

import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu import locks, metrics
from spark_tpu.conf import RuntimeConf
from spark_tpu.scheduler import QueryScheduler
from spark_tpu.scheduler.scheduler import QueryCancelled
from spark_tpu.serve import result_cache as rc
from spark_tpu.tpch.gen import generate_tables, register_views
from spark_tpu.tpch.queries import QUERIES

pytestmark = pytest.mark.timeout(180)


@pytest.fixture()
def validator():
    """Validation ON with a clean slate; always restored OFF."""
    locks.reset_observations()
    locks.set_validation(True)
    try:
        yield
    finally:
        locks.set_validation(False)
        locks.reset_observations()


@pytest.fixture(scope="module")
def tpch(spark):
    tables = generate_tables(0.01, seed=7)
    register_views(spark, tables)
    return spark


# ---- seeded runtime violations ----------------------------------------------


def test_validator_detects_seeded_inversion(validator):
    outer = locks.named_rlock("storage.unified")      # rank 400
    inner = locks.named_lock("session.cache.registry")  # rank 100
    with outer:
        with inner:
            pass
    rep = locks.order_report()
    assert rep["enabled"]
    assert ["storage.unified", "session.cache.registry"] in \
        [v["edge"] for v in rep["violations"]]
    v = next(v for v in rep["violations"]
             if v["edge"] == ["storage.unified",
                              "session.cache.registry"])
    assert v["kind"] == "rank-inversion" and v["ranks"] == [400, 100]


def test_validator_detects_seeded_cycle(validator):
    # register_lock is idempotent for an unchanged rank, so the test
    # can re-run in one process; a->b is rank-legal, b->a closes the
    # cycle (and is itself an inversion — ranks are a total order, so
    # every cycle contains one)
    locks.register_lock("test.cycle.a", 10_001)
    locks.register_lock("test.cycle.b", 10_002)
    a = locks.named_lock("test.cycle.a")
    b = locks.named_lock("test.cycle.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = locks.order_report()
    assert rep["cycles"], rep
    assert set(rep["cycles"][0]) >= {"test.cycle.a", "test.cycle.b"}
    assert ["test.cycle.b", "test.cycle.a"] in \
        [v["edge"] for v in rep["violations"]]


def test_validator_same_name_reentry_is_legal(validator):
    # sibling instances under one registry name (per-entry locks) and
    # RLock re-entry must not register edges or violations
    l1 = locks.named_lock("session.cache.entry")
    l2 = locks.named_lock("session.cache.entry")
    with l1:
        with l2:
            pass
    r = locks.named_rlock("storage.unified")
    with r:
        with r:
            pass
    rep = locks.order_report()
    assert rep["violations"] == [] and rep["edges"] == []


# ---- real workload: zero violations with the validator on -------------------


def test_workload_zero_violations(tpch, validator):
    spark = tpch
    # warm q1: scheduler, cache and storage locks all see traffic
    spark.sql(QUERIES[1]).collect()
    df = spark.createDataFrame(
        pd.DataFrame({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]}))
    df.groupBy("k").count().collect()
    cached = df.cache()
    cached.collect()
    cached.collect()
    sched = QueryScheduler(conf=RuntimeConf({}))
    try:
        tasks = [sched.submit(lambda tk, i=i: i) for i in range(4)]
        assert [t.result(timeout=30) for t in tasks] == [0, 1, 2, 3]
    finally:
        sched.stop()
    rep = locks.order_report()
    assert rep["violations"] == [], rep["violations"]
    assert rep["cycles"] == [], rep["cycles"]
    # the run actually nested locks (scheduler cond over metrics et
    # al.) — an empty edge set would mean the proxies were bypassed
    assert rep["edges"], "validator observed no lock nesting at all"


def test_validator_per_acquire_overhead_micro():
    lk = locks.named_lock("metrics.registry")
    n = 20000

    def bench():
        t0 = time.perf_counter()
        for _ in range(n):
            with lk:
                pass
        return time.perf_counter() - t0

    locks.set_validation(False)
    off = min(bench() for _ in range(3))
    locks.reset_observations()
    locks.set_validation(True)
    try:
        on = min(bench() for _ in range(3))
    finally:
        locks.set_validation(False)
        locks.reset_observations()
    # the <3% warm-q1 budget translates to single-digit microseconds
    # per acquire/release pair; 50us absolute keeps CI deterministic
    assert (on - off) / n < 50e-6, f"on={on:.4f}s off={off:.4f}s"


def test_validator_overhead_warm_q1(tpch):
    spark = tpch
    run = lambda: spark.sql(QUERIES[1]).collect()  # noqa: E731
    run()  # compile + trace warm-up
    locks.set_validation(False)
    times_off = []
    for _ in range(2):
        t0 = time.perf_counter()
        run()
        times_off.append(time.perf_counter() - t0)
    locks.reset_observations()
    locks.set_validation(True)
    try:
        times_on = []
        for _ in range(2):
            t0 = time.perf_counter()
            run()
            times_on.append(time.perf_counter() - t0)
        rep = locks.order_report()
    finally:
        locks.set_validation(False)
        locks.reset_observations()
    assert rep["violations"] == [] and rep["cycles"] == []
    # design target is <3%; the micro test above pins the mechanism,
    # this one only guards against a gross regression (best-of-2 with
    # generous absolute slack so CI noise cannot flake it)
    assert min(times_on) <= min(times_off) * 1.30 + 0.05, \
        f"on={min(times_on):.4f}s off={min(times_off):.4f}s"


# ---- serve result cache: bounded follower wait ------------------------------


def _cache(**overrides):
    base = {"spark.tpu.serve.resultCache.enabled": True}
    base.update(overrides)
    return rc.ResultCache(RuntimeConf(base))


def test_flight_wait_timeout_falls_through(monkeypatch):
    monkeypatch.setattr(rc, "_FLIGHT_WAIT_S", 0.2)
    cache = _cache()
    tbl = pa.table({"x": [1, 2, 3]})
    started, release = threading.Event(), threading.Event()

    def wedged_owner():
        started.set()
        release.wait(timeout=30)
        return tbl

    before = metrics.serve_stats().get("wait_timeouts", 0)
    owner_res = {}
    th = threading.Thread(
        target=lambda: owner_res.update(
            r=cache.get_or_execute("q", wedged_owner)),
        daemon=True)
    th.start()
    assert started.wait(timeout=10)
    # follower must NOT wait forever on the wedged owner: typed
    # timeout recorded, then it executes independently
    blob, status = cache.get_or_execute("q", lambda: tbl)
    assert status == "timeout"
    assert pa.ipc.open_stream(blob).read_all().equals(tbl)
    after = metrics.serve_stats().get("wait_timeouts", 0)
    assert after == before + 1
    release.set()
    th.join(timeout=30)
    assert owner_res["r"][1] == "miss"


def test_flight_wait_timeout_is_typed():
    e = rc.FlightWaitTimeout("abcd1234", 0.25)
    assert isinstance(e, RuntimeError)
    assert e.key_digest == "abcd1234" and e.waited_s == 0.25
    assert "abcd1234" in str(e)


def test_owner_cancellation_not_inherited_by_followers():
    cache = _cache()
    tbl = pa.table({"x": [7]})
    started, proceed = threading.Event(), threading.Event()

    def cancelled_owner():
        started.set()
        proceed.wait(timeout=30)
        raise QueryCancelled("owner-local deadline")

    owner_res = {}

    def owner():
        try:
            cache.get_or_execute("qc", cancelled_owner)
        except QueryCancelled as e:
            owner_res["e"] = e

    to = threading.Thread(target=owner, daemon=True)
    to.start()
    assert started.wait(timeout=10)
    follower_res = {}
    tf = threading.Thread(
        target=lambda: follower_res.update(
            r=cache.get_or_execute("qc", lambda: tbl)),
        daemon=True)
    tf.start()
    time.sleep(0.1)  # let the follower park on the flight event
    proceed.set()
    to.join(timeout=30)
    tf.join(timeout=30)
    # the owner sees ITS cancellation; the follower does not inherit
    # it — it loops, takes ownership and executes
    assert isinstance(owner_res["e"], QueryCancelled)
    assert follower_res["r"][1] == "miss"
    assert pa.ipc.open_stream(follower_res["r"][0]).read_all() \
        .equals(tbl)


# ---- every session daemon thread quiesces on stop ---------------------------


def test_threads_quiesce(spark):
    from spark_tpu.connect.server import ConnectServer

    srv = ConnectServer(spark, port=0).start()
    _ = spark.compile_service  # materialize the lazy service
    sched = spark.query_scheduler
    assert sched is not None
    t = sched.submit(lambda tk: 41 + 1)
    assert t.result(timeout=30) == 42
    alive = {th.name for th in threading.enumerate()}
    assert any(n.startswith("spark-tpu-") for n in alive), alive
    srv.stop()
    # _stop_services (used by SparkSession.stop) joins everything the
    # session owns without tearing down the singleton the shared
    # `spark` fixture holds; lazy services re-materialize on demand
    spark._stop_services()
    prefixes = ("spark-tpu-", "chunk-pipeline")
    deadline = time.time() + 15
    leftover = ["unchecked"]
    while time.time() < deadline:
        leftover = [th.name for th in threading.enumerate()
                    if th.name.startswith(prefixes)]
        if not leftover:
            break
        time.sleep(0.05)
    assert leftover == [], f"threads survived stop: {leftover}"

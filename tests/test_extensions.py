"""Session extension points (spark_tpu/extensions.py; reference:
SparkSessionExtensions.scala, SparkPlugin.java:37)."""

import pytest

from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


def test_inject_function_sql_and_resolution(spark):
    spark.extensions.inject_function(
        "double_it", lambda e: E.Alias(e * 2, "double_it"))
    try:
        rows = spark.sql("select double_it(id) as d from "
                         "(select 21 as id)").collect()
        assert rows[0]["d"] == 42
    finally:
        spark.extensions._functions.clear()


def test_inject_optimizer_rule_runs(spark):
    seen = {"n": 0}

    def rule(plan):
        seen["n"] += 1
        return plan

    spark.extensions.inject_optimizer_rule(rule)
    try:
        spark.range(10).filter("id > 3").count()
        assert seen["n"] >= 1
    finally:
        spark.extensions._optimizer_rules.clear()


def test_inject_parser_hook(spark):
    def hook(sql, catalog, default_parse):
        if sql.strip() == "SHOW MAGIC":
            return L.Range(0, 3, 1, "magic")
        return None

    spark.extensions.inject_parser(hook)
    try:
        rows = spark.sql("SHOW MAGIC").collect()
        assert [r["magic"] for r in rows] == [0, 1, 2]
        # everything else still parses normally
        assert spark.sql("select 1 as x").collect()[0]["x"] == 1
    finally:
        spark.extensions._parser_hooks.clear()


class _Plugin:
    inited = 0
    shut = 0

    def init(self, session):
        _Plugin.inited += 1

    def shutdown(self):
        _Plugin.shut += 1


def test_plugin_lifecycle(spark):
    spark.conf.set("spark.plugins", f"{__name__}:_Plugin")
    try:
        spark.extensions.load_plugins(spark)
        assert _Plugin.inited == 1
        spark.extensions.shutdown_plugins()
        assert _Plugin.shut == 1
    finally:
        spark.conf.set("spark.plugins", "")


def test_unknown_function_still_errors(spark):
    from spark_tpu.sql.parser import SQLParseError

    with pytest.raises(SQLParseError, match="unknown function"):
        spark.sql("select no_such_fn(1)")

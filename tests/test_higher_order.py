"""Higher-order array functions + collection aggregates + percentiles
(reference: expressions/higherOrderFunctions.scala,
expressions/aggregate/collect.scala, ApproximatePercentile.scala:81).
The TPU build vectorizes lambdas over the padded element plane and
computes percentiles exactly via per-group rank gathers."""

import pytest

from spark_tpu.api import functions as F


@pytest.fixture()
def hdf(spark):
    df = spark.createDataFrame([
        {"k": 1, "a": [1, 2, 3], "b": 10},
        {"k": 1, "a": [4], "b": 20},
        {"k": 2, "a": [], "b": 30},
        {"k": 2, "a": [7, 7, 8], "b": 40},
    ])
    df.createOrReplaceTempView("hof")
    return df


def test_transform(spark, hdf):
    got = spark.sql("select transform(a, x -> x * 2) as t from hof").collect()
    assert [r.t for r in got] == [[2, 4, 6], [8], [], [14, 14, 16]]


def test_transform_outer_column_and_index(spark, hdf):
    got = spark.sql(
        "select transform(a, x -> x + b) as t from hof").collect()
    assert [r.t for r in got] == [[11, 12, 13], [24], [], [47, 47, 48]]
    got2 = hdf.select(F.transform("a", lambda x, i: x * 10 + i)
                      .alias("t")).collect()
    assert [r.t for r in got2] == [[10, 21, 32], [40], [], [70, 71, 82]]


def test_filter_exists_forall(spark, hdf):
    got = spark.sql(
        "select filter(a, x -> x % 2 = 0) as f, exists(a, x -> x > 5) "
        "as e, forall(a, x -> x < 5) as fa from hof").collect()
    assert [r.f for r in got] == [[2], [4], [], [8]]
    assert [r.e for r in got] == [False, False, False, True]
    assert [r.fa for r in got] == [True, True, True, False]


def test_exists_subquery_still_parses(spark, hdf):
    got = spark.sql(
        "select k from hof h where exists "
        "(select 1 from hof i where i.k = h.k and i.b > 35)").collect()
    assert sorted(r.k for r in got) == [2, 2]


def test_aggregate_fold(spark, hdf):
    got = spark.sql(
        "select aggregate(a, 0, (acc, x) -> acc + x) as s, "
        "aggregate(a, 1, (acc, x) -> acc * x, acc -> -acc) as p "
        "from hof").collect()
    assert [r.s for r in got] == [6, 4, 0, 22]
    assert [r.p for r in got] == [-6, -4, -1, -392]


def test_collect_list_and_set(spark, hdf):
    got = spark.sql(
        "select k, collect_list(b) as l, collect_set(b % 20) as s "
        "from hof group by k order by k").collect()
    assert [r.l for r in got] == [[10, 20], [30, 40]]
    assert [sorted(r.s) for r in got] == [[0, 10], [0, 10]]


def test_collect_list_strings_and_nulls(spark):
    df = spark.createDataFrame([
        {"k": 1, "s": "b"}, {"k": 1, "s": "a"}, {"k": 1, "s": None},
        {"k": 2, "s": "a"}, {"k": 1, "s": "a"},
    ])
    df.createOrReplaceTempView("cstr")
    got = spark.sql("select k, collect_list(s) as l, collect_set(s) as d "
                    "from cstr group by k order by k").collect()
    # nulls are excluded (collect.scala semantics)
    assert got[0].l == ["b", "a", "a"] and sorted(got[0].d) == ["a", "b"]
    assert got[1].l == ["a"] and got[1].d == ["a"]


def test_collect_roundtrip_to_arrow(spark, hdf):
    tbl = (hdf.groupBy("k").agg(F.collect_list("b").alias("l"))
           .orderBy("k").toArrow())
    assert tbl.column("l").to_pylist() == [[10, 20], [30, 40]]


def test_percentile_and_median(spark):
    df = spark.createDataFrame(
        [{"k": i % 2, "v": float(i)} for i in range(1, 11)])
    df.createOrReplaceTempView("pct")
    got = spark.sql(
        "select k, percentile_approx(v, 0.5) as p, median(v) as m, "
        "percentile(v, 0.25) as q from pct group by k order by k"
    ).collect()
    # k=0: values 2,4,6,8,10; k=1: 1,3,5,7,9
    assert [r.p for r in got] == [6.0, 5.0]
    assert [r.m for r in got] == [6.0, 5.0]
    assert got[0].q == pytest.approx(4.0)
    assert got[1].q == pytest.approx(3.0)


def test_median_interpolates_even_count(spark):
    df = spark.createDataFrame([{"v": v} for v in [1.0, 2.0, 10.0, 20.0]])
    r = df.agg(F.median("v").alias("m"),
               F.percentile_approx("v", 0.5).alias("p")).collect()[0]
    assert r.m == pytest.approx(6.0)  # (2+10)/2
    assert r.p == 2.0  # the actual element at rank ceil(0.5*4)


def test_percentile_nulls_and_global(spark):
    df = spark.createDataFrame(
        [{"v": 1.0}, {"v": None}, {"v": 3.0}, {"v": None}])
    r = df.agg(F.median("v").alias("m")).collect()[0]
    assert r.m == pytest.approx(2.0)
    import pyarrow as pa

    empty = spark.createDataFrame(
        pa.table({"v": pa.array([None, None], pa.float64())}))
    r2 = empty.agg(F.median("v").alias("m")).collect()[0]
    assert r2.m is None


def test_transform_nullable_body_refuses(spark):
    df = spark.createDataFrame([{"a": [1, 2], "n": 5},
                                {"a": [3], "n": None}])
    with pytest.raises(NotImplementedError, match="nullable"):
        df.select(F.transform("a", lambda x: x + F.col("n"))
                  .alias("t")).collect()

"""Structured streaming: micro-batch incremental aggregation, state
checkpoints, watermarked append mode (reference test model:
sql/core/src/test/.../streaming/StreamTest.scala:342 AddData/CheckAnswer
over MemoryStream)."""

import pyarrow as pa
import pytest

from spark_tpu.api import functions as F
from spark_tpu.streaming import MemoryStream


def _counts(spark, name):
    rows = spark.sql(f"select * from {name}").collect()
    return {tuple(r.values())[0]: tuple(r.values())[1:] for r in
            (r.asDict() for r in rows)}


def test_incremental_grouped_count(spark):
    src = MemoryStream(pa.schema([("k", pa.string()), ("v", pa.int64())]))
    df = spark.readStream.load(src)
    agg = df.groupBy("k").agg(F.count("v").alias("n"),
                              F.sum("v").alias("s"))
    q = agg.writeStream.outputMode("complete").queryName("cnt1").start()

    src.add_data([{"k": "a", "v": 1}, {"k": "b", "v": 2}])
    q.process_all_available()
    assert _counts(spark, "cnt1") == {"a": (1, 1), "b": (1, 2)}

    src.add_data([{"k": "a", "v": 10}])
    q.process_all_available()
    assert _counts(spark, "cnt1") == {"a": (2, 11), "b": (1, 2)}

    # no new data: no state change
    q.process_all_available()
    assert _counts(spark, "cnt1") == {"a": (2, 11), "b": (1, 2)}


def test_incremental_avg_min_max(spark):
    src = MemoryStream(pa.schema([("k", pa.string()), ("v", pa.int64())]))
    df = spark.readStream.load(src)
    agg = df.groupBy("k").agg(F.avg("v").alias("a"),
                              F.min("v").alias("lo"),
                              F.max("v").alias("hi"))
    q = agg.writeStream.outputMode("complete").queryName("avg1").start()
    src.add_data([{"k": "x", "v": 10}, {"k": "x", "v": 20}])
    q.process_all_available()
    src.add_data([{"k": "x", "v": 60}, {"k": "y", "v": 5}])
    q.process_all_available()
    got = _counts(spark, "avg1")
    assert got["x"] == (30.0, 10, 60)
    assert got["y"] == (5.0, 5, 5)


def test_stateless_append(spark):
    src = MemoryStream(pa.schema([("v", pa.int64())]))
    df = spark.readStream.load(src).filter(F.col("v") % 2 == 0) \
        .select((F.col("v") * 10).alias("w"))
    q = df.writeStream.outputMode("append").queryName("flt1").start()
    src.add_data([{"v": i} for i in range(5)])
    q.process_all_available()
    src.add_data([{"v": 6}])
    q.process_all_available()
    rows = sorted(r.w for r in spark.sql("select * from flt1").collect())
    assert rows == [0, 20, 40, 60]


def test_checkpoint_restart_exactly_once(spark, tmp_path):
    ckpt = str(tmp_path / "ck")
    src = MemoryStream(pa.schema([("k", pa.string()), ("v", pa.int64())]))
    df = spark.readStream.load(src)
    agg = df.groupBy("k").agg(F.sum("v").alias("s"))
    q = agg.writeStream.outputMode("complete").queryName("ck1") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"k": "a", "v": 5}])
    q.process_all_available()
    src.add_data([{"k": "a", "v": 7}])
    q.process_all_available()
    assert _counts(spark, "ck1") == {"a": (12,)}
    q.stop()

    # restart from the checkpoint: state restored, already-processed
    # offsets are NOT reprocessed, new data continues the totals
    q2 = agg.writeStream.outputMode("complete").queryName("ck2") \
        .option("checkpointLocation", ckpt).start()
    q2.process_all_available()  # nothing new
    assert _counts(spark, "ck2") == {"a": (12,)}
    src.add_data([{"k": "a", "v": 1}])
    q2.process_all_available()
    assert _counts(spark, "ck2") == {"a": (13,)}


def test_watermark_append_mode_evicts_closed_windows(spark):
    src = MemoryStream(pa.schema([("ts", pa.int64()), ("v", pa.int64())]))
    df = spark.readStream.load(src).withWatermark("ts", 10)
    # tumbling 10-unit windows: F.window carries the width so eviction
    # closes a window only when the watermark passes its END
    win = F.window(F.col("ts"), 10).alias("wstart")
    agg = df.groupBy(win).agg(F.count("v").alias("n"))
    q = agg.writeStream.outputMode("append").queryName("wm1").start()

    src.add_data([{"ts": 1, "v": 1}, {"ts": 5, "v": 1}, {"ts": 12, "v": 1}])
    q.process_all_available()
    # watermark = 12-10 = 2: no window closed yet
    assert spark.sql("select * from wm1").collect() == []

    src.add_data([{"ts": 25, "v": 1}])
    q.process_all_available()
    # watermark = 15: window [0,10) closed with 2 rows
    got = {(r.wstart, r.n) for r in
           spark.sql("select * from wm1").collect()}
    assert got == {(0, 2)}

    src.add_data([{"ts": 41, "v": 1}])
    q.process_all_available()
    # watermark = 31: windows [10,20) and [20,30) closed
    got = {(r.wstart, r.n) for r in
           spark.sql("select * from wm1").collect()}
    assert got == {(0, 2), (10, 1), (20, 1)}


@pytest.mark.slow
def test_streaming_on_mesh(spark):
    """The same incremental machinery runs on the distributed engine."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh

    class MeshSession:
        def __init__(self, inner):
            self._inner = inner
            self.catalog = inner.catalog
            self.mesh_executor = MeshExecutor(make_mesh(4))

    src = MemoryStream(pa.schema([("k", pa.int64()), ("v", pa.int64())]))
    from spark_tpu.api.dataframe import DataFrame
    from spark_tpu.streaming.execution import StreamingQuery, \
        StreamingSource
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    plan = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(E.Col("v")), "s")),
        StreamingSource(src))
    q = StreamingQuery(MeshSession(spark), plan, "mesh1", "complete")
    src.add_data([{"k": i % 3, "v": i} for i in range(30)])
    q.process_all_available()
    src.add_data([{"k": 0, "v": 1000}])
    q.process_all_available()
    got = _counts(spark, "mesh1")
    assert got[0] == (sum(i for i in range(30) if i % 3 == 0) + 1000,)
    assert got[1] == (sum(i for i in range(30) if i % 3 == 1),)


def test_rate_source_schema(spark):
    df = spark.readStream.format("rate").option("rowsPerSecond", 5).load()
    assert df.isStreaming
    assert list(df._plan.schema.names) == ["timestamp", "value"]


def test_late_rows_below_watermark_dropped(spark):
    src = MemoryStream(pa.schema([("ts", pa.int64()), ("v", pa.int64())]))
    df = spark.readStream.load(src).withWatermark("ts", 0)
    agg = df.groupBy(F.window(F.col("ts"), 10).alias("w")) \
        .agg(F.count("v").alias("n"))
    q = agg.writeStream.outputMode("append").queryName("late1").start()
    src.add_data([{"ts": 5, "v": 1}, {"ts": 6, "v": 1}])
    q.process_all_available()
    src.add_data([{"ts": 25, "v": 1}])  # wm -> 25, closes [0,10)
    q.process_all_available()
    src.add_data([{"ts": 6, "v": 1}])   # LATE: below wm, must be dropped
    q.process_all_available()
    src.add_data([{"ts": 100, "v": 1}])
    q.process_all_available()
    got = sorted((r.w, r.n) for r in
                 spark.sql("select * from late1").collect())
    assert got == [(0, 2), (20, 1)]  # window 0 emitted exactly once


def test_watermark_survives_restart(spark, tmp_path):
    ckpt = str(tmp_path / "wmck")
    src = MemoryStream(pa.schema([("ts", pa.int64()), ("v", pa.int64())]))
    df = spark.readStream.load(src).withWatermark("ts", 0)
    agg = df.groupBy(F.window(F.col("ts"), 10).alias("w")) \
        .agg(F.count("v").alias("n"))
    q = agg.writeStream.outputMode("append").queryName("wr1") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"ts": 5, "v": 1}, {"ts": 25, "v": 1}])
    q.process_all_available()
    q.stop()
    q2 = agg.writeStream.outputMode("append").queryName("wr2") \
        .option("checkpointLocation", ckpt).start()
    assert q2._max_event_time == 25  # restored from the commit log
    src.add_data([{"ts": 3, "v": 1}])  # late after restart: dropped
    q2.process_all_available()
    src.add_data([{"ts": 100, "v": 1}])
    q2.process_all_available()
    got = sorted((r.w, r.n) for r in
                 spark.sql("select * from wr2").collect())
    assert got == [(20, 1)]  # [0,10) already emitted pre-restart... or


def test_batch_window_function(spark):
    df = spark.createDataFrame([{"ts": t} for t in (1, 5, 12, 25)])
    out = df.groupBy(F.window("ts", 10).alias("w")) \
        .agg(F.count("ts").alias("n")).orderBy("w")
    assert [(r.w, r.n) for r in out.collect()] == [(0, 2), (10, 1), (20, 1)]


def test_update_mode_with_agg_rejected(spark):
    src = MemoryStream(pa.schema([("k", pa.int64())]))
    df = spark.readStream.load(src)
    agg = df.groupBy("k").agg(F.count("k").alias("n"))
    with pytest.raises(NotImplementedError):
        agg.writeStream.outputMode("update").queryName("u1").start()


def test_ops_above_streaming_agg_rejected(spark):
    src = MemoryStream(pa.schema([("k", pa.int64())]))
    df = spark.readStream.load(src)
    agg = df.groupBy("k").agg(F.count("k").alias("n")) \
        .filter(F.col("n") > 5)
    with pytest.raises(NotImplementedError):
        agg.writeStream.outputMode("complete").queryName("x1").start()


def test_append_agg_without_time_key_rejected(spark):
    src = MemoryStream(pa.schema([("k", pa.int64())]))
    agg = spark.readStream.load(src).groupBy("k") \
        .agg(F.count("k").alias("n"))
    with pytest.raises(NotImplementedError):
        agg.writeStream.outputMode("append").queryName("x2").start()


def test_session_window_merging(spark):
    """Gap-based sessions merge across micro-batches (reference:
    MergingSessionsExec): events within gap=5 of each other chain into
    one session; append mode emits a session when the watermark passes
    its end."""
    src = MemoryStream(pa.schema([("ts", pa.int64()), ("k", pa.string()),
                                  ("v", pa.int64())]))
    df = spark.readStream.load(src).withWatermark("ts", 0)
    sess = F.session_window(F.col("ts"), 5).alias("sstart")
    agg = df.groupBy(sess, F.col("k")).agg(F.count("v").alias("n"),
                                           F.sum("v").alias("s"))
    q = agg.writeStream.outputMode("append").queryName("sw1").start()

    # a: 1,3,6 chain (gaps < 5); b: 2 alone
    src.add_data([{"ts": 1, "k": "a", "v": 10},
                  {"ts": 3, "k": "a", "v": 20},
                  {"ts": 2, "k": "b", "v": 5}])
    q.process_all_available()
    src.add_data([{"ts": 6, "k": "a", "v": 30}])
    q.process_all_available()
    # watermark = 6: b's session [2,7) not yet closed; nothing emitted
    # for a (session end now 11)
    src.add_data([{"ts": 30, "k": "c", "v": 1}])
    q.process_all_available()
    # watermark = 30: a's [1,11) and b's [2,7) close
    got = {(r.sstart, r.k): (r.n, r.s) for r in
           spark.sql("select * from sw1").collect()}
    assert got[(1, "a")] == (3, 60)   # merged across two batches
    assert got[(2, "b")] == (1, 5)


def test_session_window_gap_split(spark):
    """Events farther apart than the gap form separate sessions."""
    src = MemoryStream(pa.schema([("ts", pa.int64()), ("v", pa.int64())]))
    df = spark.readStream.load(src).withWatermark("ts", 0)
    agg = df.groupBy(F.session_window(F.col("ts"), 3).alias("st")) \
        .agg(F.count("v").alias("n"))
    q = agg.writeStream.outputMode("append").queryName("sw2").start()
    src.add_data([{"ts": 1, "v": 1}, {"ts": 2, "v": 1},
                  {"ts": 10, "v": 1}, {"ts": 12, "v": 1}])
    q.process_all_available()
    src.add_data([{"ts": 50, "v": 1}])
    q.process_all_available()
    got = {(r.st, r.n) for r in spark.sql("select * from sw2").collect()}
    assert got == {(1, 2), (10, 2)}


def test_flat_map_groups_processing_time_timeout(spark):
    """flatMapGroupsWithState with ProcessingTimeTimeout: a group whose
    deadline expires with no new data fires once with hasTimedOut=True
    (reference: FlatMapGroupsWithStateExec.scala:373)."""
    import pandas as pd

    src = MemoryStream(pa.schema([("k", pa.string()), ("v", pa.int64())]))
    df = spark.readStream.load(src)

    def fn(key, pdf, state):
        if state.hasTimedOut:
            total = state.get()
            state.remove()
            return pd.DataFrame({"k": [key[0]], "total": [total],
                                 "reason": ["timeout"]})
        cur = state.getOption() or 0
        state.update(cur + int(pdf["v"].sum()))
        state.setTimeoutDuration(0)  # expire immediately on next batch
        return None

    q = (df.groupBy("k")
         .applyInPandasWithState(fn, "k string, total bigint, reason string",
                                 timeoutConf="ProcessingTimeTimeout")
         .writeStream.outputMode("append").queryName("fmt1").start())
    src.add_data([{"k": "a", "v": 1}, {"k": "a", "v": 2}])
    q.process_all_available()
    assert spark.sql("select * from fmt1").collect() == []
    # next batch (new key only): a's deadline has passed -> timeout fires
    src.add_data([{"k": "b", "v": 9}])
    q.process_all_available()
    rows = {(r.k, r.total, r.reason) for r in
            spark.sql("select * from fmt1").collect()}
    assert rows == {("a", 3, "timeout")}
    # a's state removed: fresh data starts a new accumulation
    src.add_data([{"k": "a", "v": 7}])
    q.process_all_available()
    src.add_data([{"k": "c", "v": 1}])
    q.process_all_available()
    rows = {(r.k, r.total, r.reason) for r in
            spark.sql("select * from fmt1").collect()}
    assert ("a", 7, "timeout") in rows

"""Distributed (mesh) execution vs single-device oracle.

The local-mesh harness from SURVEY.md §4: 8 virtual CPU devices
(conftest sets xla_force_host_platform_device_count) stand in for a TPU
slice, the single-device engine is the correctness oracle — the same
role DAGSchedulerSuite's mock backend and local-cluster[n,c,m] play in
the reference (reference: core/.../scheduler/DAGSchedulerSuite.scala:159,
SchedulerIntegrationSuite.scala:50).
"""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.expr.expressions as E
import spark_tpu.plan.logical as L
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.parallel.executor import MeshExecutor
from spark_tpu.parallel.mesh import make_mesh
from spark_tpu.physical.planner import execute_logical


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


@pytest.fixture(scope="module")
def ex(mesh):
    return MeshExecutor(mesh)


def _rows(batch, sort_keys=None):
    rows = [tuple(d.values()) for d in batch.to_pylist()]
    if sort_keys is not None:
        rows.sort(key=lambda r: tuple(
            (v is None, v) for v in (r[i] for i in sort_keys)))
    return rows


def check(ex, plan, ordered=False, order_cols=None):
    """Distributed result == single-device result. Unordered plans
    compare as multisets; ordered plans additionally compare the
    sort-key column sequence (ties may permute — SQL sorts are not
    stable, and neither is Spark's)."""
    got = ex.execute_logical(plan)
    want = execute_logical(plan)
    all_keys = list(range(len(want.schema.names)))
    assert _rows(got, all_keys) == _rows(want, all_keys)
    if ordered:
        names = list(want.schema.names)
        idx = [names.index(c) for c in (order_cols or names)]
        got_keys = [tuple(r[i] for i in idx) for r in _rows(got)]
        want_keys = [tuple(r[i] for i in idx) for r in _rows(want)]
        assert got_keys == want_keys


def table(rng, n=5000, with_nulls=True):
    ks = rng.integers(0, 50, n)
    vs = (rng.normal(size=n) * 10).astype(object)
    if with_nulls:
        vs[rng.random(n) < 0.1] = None
    tag = np.array(["red", "green", "blue", "gold"])[rng.integers(0, 4, n)]
    return from_arrow(pa.table({
        "k": pa.array(ks, pa.int64()),
        "v": pa.array(list(vs), pa.float64()),
        "tag": pa.array(list(tag), pa.string()),
    }))


@pytest.fixture(scope="module")
def rel(rng):
    return L.Relation(table(rng))


def test_filter_project(ex, rel):
    plan = L.Project((E.Col("k"), E.Alias(E.Col("v") * 2.0, "v2")),
                     L.Filter(E.Col("k") > 25, rel))
    check(ex, plan)


def test_range(ex):
    plan = L.Filter(E.Col("id") % 7 == 0, L.Range(0, 10000, 3))
    check(ex, plan)


def test_global_agg(ex, rel):
    plan = L.Aggregate(
        (), (E.Alias(E.Sum(E.Col("v")), "s"),
             E.Alias(E.Count(None), "n"),
             E.Alias(E.Avg(E.Col("v")), "a"),
             E.Alias(E.Min(E.Col("k")), "mn"),
             E.Alias(E.Max(E.Col("k")), "mx"),
             E.Alias(E.StddevVariance("stddev_samp", E.Col("v")), "sd")),
        rel)
    got = ex.execute_logical(plan).to_pylist()[0]
    want = execute_logical(plan).to_pylist()[0]
    for key in ("s", "a", "sd"):
        assert got[key] == pytest.approx(want[key], rel=1e-9)
    assert got["n"] == want["n"]
    assert got["mn"] == want["mn"]
    assert got["mx"] == want["mx"]


def test_direct_group_agg_psum(ex, rel):
    """String-dictionary keys -> PSumAgg path (no shuffle)."""
    plan = L.Aggregate(
        (E.Col("tag"),),
        (E.Col("tag"), E.Alias(E.Sum(E.Col("v")), "s"),
         E.Alias(E.Count(None), "n")),
        rel)
    got = {r[0]: r[1:] for r in _rows(ex.execute_logical(plan))}
    want = {r[0]: r[1:] for r in _rows(execute_logical(plan))}
    assert set(got) == set(want)
    for k in want:
        assert got[k][0] == pytest.approx(want[k][0], rel=1e-9)
        assert got[k][1] == want[k][1]


@pytest.mark.slow
def test_shuffle_group_agg(ex, rel):
    """int keys -> hash exchange + sort-agg path."""
    plan = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(E.Col("v")), "s"),
         E.Alias(E.Count(E.Col("v")), "n"),
         E.Alias(E.Avg(E.Col("v")), "a")),
        rel)
    got = {r[0]: r[1:] for r in _rows(ex.execute_logical(plan))}
    want = {r[0]: r[1:] for r in _rows(execute_logical(plan))}
    assert set(got) == set(want)
    for k in want:
        assert got[k][0] == pytest.approx(want[k][0], rel=1e-9)
        assert got[k][1] == want[k][1]


def test_sort_global(ex, rel):
    plan = L.Sort((E.SortOrder(E.Col("v"), ascending=True),
                   E.SortOrder(E.Col("k"), ascending=False)), rel)
    check(ex, plan, ordered=True, order_cols=["v", "k"])


def test_sort_desc_nulls(ex, rel):
    plan = L.Sort((E.SortOrder(E.Col("v"), ascending=False),), rel)
    check(ex, plan, ordered=True, order_cols=["v"])


def test_sort_string_key(ex, rel):
    plan = L.Sort((E.SortOrder(E.Col("tag")),
                   E.SortOrder(E.Col("k"))), rel)
    check(ex, plan, ordered=True, order_cols=["tag", "k"])


def _check_limit(ex, plan, key_name):
    """limit keeps a tie-dependent subset; compare the key column
    sequence only (Spark gives the same non-guarantee on ties)."""
    got = ex.execute_logical(plan)
    want = execute_logical(plan)
    ki = list(want.schema.names).index(key_name)
    assert [r[ki] for r in _rows(got)] == [r[ki] for r in _rows(want)]


def test_limit_after_sort(ex, rel):
    plan = L.Limit(17, L.Sort((E.SortOrder(E.Col("v")),), rel))
    _check_limit(ex, plan, "v")


def test_limit_offset(ex, rel):
    plan = L.Limit(10, L.Sort((E.SortOrder(E.Col("v")),), rel), offset=5)
    _check_limit(ex, plan, "v")


def test_distinct(ex, rel):
    plan = L.Distinct(L.Project((E.Col("k"),), rel))
    check(ex, plan)


def test_union(ex, rel, rng):
    other = L.Relation(table(rng, n=1000))
    plan = L.Union(L.Filter(E.Col("k") < 10, rel),
                   L.Filter(E.Col("k") >= 40, other))
    check(ex, plan)


def test_repartition(ex, rel):
    plan = L.Repartition(8, (E.Col("k"),), rel)
    check(ex, plan)


# ---- joins ------------------------------------------------------------------


@pytest.fixture(scope="module")
def join_sides(rng):
    n = 2000
    left = from_arrow(pa.table({
        "id": pa.array(rng.integers(0, 300, n), pa.int64()),
        "x": pa.array(rng.normal(size=n), pa.float64()),
    }))
    m = 400
    right = from_arrow(pa.table({
        "id": pa.array(rng.integers(0, 300, m), pa.int64()),
        "name": pa.array(
            list(np.array(["a", "b", "c"])[rng.integers(0, 3, m)]),
            pa.string()),
    }))
    return L.Relation(left), L.Relation(right)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_join_partitioned(ex, join_sides, how):
    l, r = join_sides
    plan = L.Join(l, r, how, (E.Col("id"),), (E.Col("id"),))
    big = MeshExecutor(ex.mesh, broadcast_threshold=1)  # force partition
    got = big.execute_logical(plan)
    want = execute_logical(plan)
    keys = list(range(len(want.schema.names)))
    assert _rows(got, keys) == _rows(want, keys)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_join_broadcast(ex, join_sides, how):
    l, r = join_sides
    plan = L.Join(l, r, how, (E.Col("id"),), (E.Col("id"),))
    check(ex, plan)


def test_join_with_condition(ex, join_sides):
    l, r = join_sides
    plan = L.Join(l, r, "inner", (E.Col("id"),), (E.Col("id"),),
                  condition=E.Col("x") > 0.0)
    check(ex, plan)


def test_join_string_key(ex, rng):
    n = 1500
    left = L.Relation(from_arrow(pa.table({
        "tag": pa.array(
            list(np.array(["red", "green", "blue"])[rng.integers(0, 3, n)])),
        "v": pa.array(rng.normal(size=n))})))
    right = L.Relation(from_arrow(pa.table({
        "tag": pa.array(
            list(np.array(["green", "blue", "gold"])[rng.integers(0, 3, 100)])),
        "w": pa.array(rng.normal(size=100))})))
    plan = L.Join(left, right, "inner", (E.Col("tag"),), (E.Col("tag"),))
    big = MeshExecutor(ex.mesh, broadcast_threshold=1)
    got = big.execute_logical(plan)
    want = execute_logical(plan)
    keys = list(range(len(want.schema.names)))
    assert _rows(got, keys) == _rows(want, keys)


def test_cross_join(ex, rng):
    left = L.Relation(from_arrow(pa.table({"a": np.arange(37)})))
    right = L.Relation(from_arrow(pa.table({"b": np.arange(11)})))
    plan = L.Join(left, right, "cross", (), ())
    check(ex, plan)


# ---- regressions ------------------------------------------------------------


def test_relation_cache_no_id_aliasing(ex):
    """Fresh Batch objects reusing a dead Batch's id must not serve stale
    shards (cache keys are weak object refs, not id())."""
    for i in range(6):
        b = from_arrow(pa.table({"x": np.full(100, i, dtype=np.int64)}))
        plan = L.Distinct(L.Relation(b))
        got = {r[0] for r in _rows(ex.execute_logical(plan))}
        assert got == {i}, (i, got)


def test_join_computed_string_key(ex, rng):
    """Computed (non-Col) string join keys: union dictionaries must come
    from the evaluated keys, not static schema analysis."""
    n = 300
    left = L.Relation(from_arrow(pa.table({
        "tag": pa.array(
            list(np.array(["xred", "xgreen", "xblue"])[rng.integers(0, 3, n)])),
        "v": pa.array(rng.normal(size=n))})))
    right = L.Relation(from_arrow(pa.table({
        "t2": pa.array(
            list(np.array(["red", "green", "gold"])[rng.integers(0, 3, 80)])),
        "w": pa.array(rng.normal(size=80))})))
    key = E.Substring(E.Col("tag"), 2, 100)
    plan = L.Join(left, right, "inner", (key,), (E.Col("t2"),))
    for threshold in (1, 1 << 20):  # partitioned and broadcast paths
        mex = MeshExecutor(ex.mesh, broadcast_threshold=threshold)
        got = mex.execute_logical(plan)
        want = execute_logical(plan)
        keys = list(range(len(want.schema.names)))
        assert _rows(got, keys) == _rows(want, keys), threshold


def test_limit_unsorted_flat_order(ex):
    """Flat array order == global row order: limit over an unsorted
    relation returns the same leading rows as single-device."""
    b = from_arrow(pa.table({"x": np.arange(2000, dtype=np.int64)}))
    plan = L.Limit(7, L.Relation(b))
    got = ex.execute_logical(plan)
    want = execute_logical(plan)
    assert _rows(got) == _rows(want)


def test_sample_varies_across_devices(ex):
    plan = L.Sample(0.5, 7, L.Range(0, 4096, 1))
    got = ex.execute_logical(plan)
    kept = np.sort(np.array([r[0] for r in _rows(got)]))
    n = kept.size
    assert 1500 < n < 2600
    # a correlated per-device pattern would keep aligned runs; check the
    # kept set is not simply blocks of consecutive ids
    gaps = np.diff(kept)
    assert (gaps == 1).mean() < 0.8


# ---- end-to-end through the session API ------------------------------------


def test_session_mesh_master(rng):
    from spark_tpu.api.session import SparkSession
    import spark_tpu.api.functions as F

    SparkSession._reset()
    try:
        spark = (SparkSession.builder.master("mesh[8]")
                 .appName("dist-test").getOrCreate())
        assert spark.mesh_executor is not None
        df = spark.range(1000).withColumn(
            "g", (E.Col("id") % 10).alias("g"))
        out = (df.groupBy("g").agg(F.count("*").alias("n"),
                                   F.sum("id").alias("s"))
               .sort("g").collect())
        assert len(out) == 10
        assert all(r["n"] == 100 for r in out)
        assert sum(r["s"] for r in out) == 999 * 1000 // 2
        assert df.count() == 1000
    finally:
        SparkSession._reset()


def test_skew_join_rebalances_to_broadcast(spark):
    """90%-one-key join: the hash exchange would land ~all pairs on one
    device (and static shapes size EVERY device at that capacity); the
    skew detector re-plans as a broadcast join over the balanced
    pre-exchange distribution (reference: OptimizeSkewedJoin.scala:37 /
    DynamicJoinSelection). Asserts bounded per-device pair capacity AND
    row parity."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import metrics
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.sql.parser import parse_sql

    rng = np.random.default_rng(17)
    n = 40_000
    hot = rng.random(n) < 0.9
    keys = np.where(hot, 7, rng.integers(0, 1000, n))
    spark.createDataFrame(pa.table({
        "k": pa.array(keys, pa.int64()),
        "v": pa.array(np.arange(n), pa.int64()),
    })).createOrReplaceTempView("skew_probe")
    spark.createDataFrame(pa.table({
        "k": pa.array(np.arange(1000), pa.int64()),
        "w": pa.array(np.arange(1000) * 10, pa.int64()),
    })).createOrReplaceTempView("skew_build")
    # force the exchange path: drop the broadcast threshold (on the
    # EXECUTOR's conf) so the skew detector has to fire
    from spark_tpu import conf as _conf

    metrics.reset()
    sql = ("select count(*) as c, sum(w) as s from skew_probe "
           "join skew_build on skew_probe.k = skew_build.k")
    plan = parse_sql(sql, spark.catalog)
    ex = MeshExecutor(make_mesh(8))
    ex.conf.set(_conf.BROADCAST_THRESHOLD.key, 1)
    ex.conf.set(_conf.SKEW_MIN_PAIRS.key, 5000)
    from spark_tpu.parallel import operators as D

    apply_caps = []
    real_run_stage = ex._run_stage

    def spy(stage):
        if isinstance(stage, D.JoinApplyExec):
            apply_caps.append(stage.pair_capacity)
        return real_run_stage(stage)

    ex._run_stage = spy
    got = ex.execute_logical(plan).to_pylist()[0]
    evs = [e for e in metrics.recent(300)
           if e["kind"] == "skew_join_broadcast"]
    assert evs, "skew detector did not fire"
    # bounded capacity: the apply stage sizes near total/d, NOT near the
    # hot device's pre-rebalance count (~0.9 * n)
    assert apply_caps, "no JoinApplyExec observed"
    assert max(apply_caps) <= (n // 8) * 2 + 2048, apply_caps
    want = spark.sql(sql).collect()[0]
    assert got["c"] == want["c"] == n
    assert got["s"] == want["s"]


def test_multi_distinct_different_columns_global(spark):
    """Global aggregate mixing DISTINCT aggs over DIFFERENT columns
    (reference: RewriteDistinctAggregates.scala:1) — previously a
    NotImplementedError cliff."""
    from spark_tpu.expr import expressions as E
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L

    rows = [{"a": i % 7, "b": i % 11, "v": i} for i in range(2000)]
    df = spark.createDataFrame(rows)
    plan = L.Aggregate(
        (), (E.Alias(E.Count(E.Col("a"), distinct=True), "da"),
             E.Alias(E.Count(E.Col("b"), distinct=True), "db"),
             E.Alias(E.Sum(E.Col("v")), "s"),
             E.Alias(E.Count(None), "n")),
        df._plan)
    ex = MeshExecutor(make_mesh(8))
    r = ex.execute_logical(plan).to_pylist()[0]
    assert (r["da"], r["db"], r["s"], r["n"]) == (
        7, 11, sum(x["v"] for x in rows), 2000)


def test_windows_with_different_partition_keys(spark):
    """Two window specs with DIFFERENT partition key sets in one
    SELECT chain exchanges (WindowExec ClusteredDistribution cascade)."""
    import sqlite3

    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan.optimizer import optimize
    from spark_tpu.plan.subquery import rewrite_subqueries
    from spark_tpu.sql.parser import parse_sql

    rows = [{"g": i % 3, "h": i % 5, "v": i} for i in range(200)]
    spark.createDataFrame(rows).createOrReplaceTempView("mw")
    sql = ("select g, h, v, sum(v) over (partition by g) as sg, "
           "sum(v) over (partition by h) as sh, "
           "row_number() over (order by v) as rn from mw "
           "order by v")
    plan = optimize(rewrite_subqueries(
        parse_sql(sql, catalog=spark.catalog)))
    ex = MeshExecutor(make_mesh(8))
    got = [(r["g"], r["h"], r["v"], r["sg"], r["sh"], r["rn"])
           for r in ex.execute_logical(plan).to_pylist()]

    conn = sqlite3.connect(":memory:")
    conn.execute("create table mw(g int, h int, v int)")
    conn.executemany("insert into mw values (?,?,?)",
                     [(r["g"], r["h"], r["v"]) for r in rows])
    want = conn.execute(sql).fetchall()
    assert got == [tuple(w) for w in want]

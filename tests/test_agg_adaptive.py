"""Runtime-adaptive aggregation (reference contrast: the reference
always plans partial->final at compile time, AggUtils.scala; here the
AQE stats stage carries a distinct-key sketch and the executor picks
the strategy per aggregate AT RUNTIME).

The hard invariant under test: every strategy the switch can pick —
partial->final (the static plan), partial-bypass (raw rows exchanged
straight to the final aggregate), hash-partial (measured packed-code
domain), the sort rung (range exchange + sorted segmented merge,
key-ordered output), and hot-key pre-splitting (Count-Min heavy
hitters salted over all devices before the exchange) — produces
BYTE-IDENTICAL results to the static plan, across device counts, key
distributions, key types, forced and auto modes, and under injected
sketch/presplit faults of every kind.
"""

import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

import spark_tpu.expr.expressions as E
import spark_tpu.plan.logical as L
from spark_tpu import faults, metrics, tracing
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.conf import RuntimeConf
from spark_tpu.parallel.executor import MeshExecutor
from spark_tpu.parallel.mesh import make_mesh

pytestmark = pytest.mark.agg

_MESHES = {}


def _mesh(d):
    if d not in _MESHES:
        _MESHES[d] = make_mesh(d)
    return _MESHES[d]


def _executor(d, adaptive, **overrides):
    conf = RuntimeConf({"spark.tpu.adaptive.enabled": bool(adaptive),
                        **overrides})
    return MeshExecutor(_mesh(d), conf=conf)


def _rows(batch):
    return [tuple(d.values()) for d in batch.to_pylist()]


def _table(keys, vals):
    return L.Relation(from_arrow(pa.table({
        "k": pa.array(np.asarray(keys, np.int64), pa.int64()),
        "v": pa.array(np.asarray(vals, np.int64), pa.int64()),
    })))


def _agg_plan(rel, value_col="v"):
    """group-by with every strategy-legal accumulator class, sorted so
    comparisons are order-free."""
    v = E.Col(value_col)
    return L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(v), "s"), E.Alias(E.Count(v), "n"),
         E.Alias(E.Min(v), "mn"), E.Alias(E.Max(v), "mx")),
        rel))


def _dataset(dist, rng, n=3000):
    if dist == "uniform":
        keys = rng.integers(0, 50, n)          # low NDV, small domain
    elif dist == "skewed":
        keys = np.where(rng.random(n) < 0.9, 7,
                        rng.integers(0, 5000, n))
    elif dist == "hot":
        # one heavy hitter riding a near-distinct huge-domain tail:
        # high NDV ratio + unpackable domain puts the crossover on a
        # raw-row-exchange strategy (sort), exactly where a hot key
        # imbalances the exchange and the Count-Min probe pre-splits
        keys = np.where(np.arange(n) % 3 == 0, 7,
                        np.arange(n, dtype=np.int64) * 1_000_003)
    else:  # all-distinct: NDV == rows, pre-aggregation is pure waste
        keys = np.arange(n)
    return _table(keys, rng.integers(0, 1000, n))


def _agg_events():
    return [e for e in metrics.recent(4096) if e.get("kind") == "agg"]


# ---- the hard invariant: byte-identity across the whole sweep ---------------


@pytest.mark.parametrize("devices", [1, 2, 8])
@pytest.mark.parametrize("dist", ["uniform", "skewed", "distinct",
                                  "hot"])
@pytest.mark.timeout(300)
def test_byte_identity_strategy_sweep(devices, dist, rng):
    plan = _agg_plan(_dataset(dist, rng))
    off = _rows(_executor(devices, False).execute_logical(plan))
    for strategy in ("auto", "partial", "bypass", "hash", "sort",
                     "presplit"):
        # presplit thresholds low enough that (hot, d=8) genuinely
        # pre-splits instead of degrading everywhere
        on = _rows(_executor(
            devices, True,
            **{"spark.tpu.adaptive.agg.strategy": strategy,
               "spark.tpu.adaptive.agg.presplitMinRows": 64,
               "spark.tpu.adaptive.agg.presplitFactor": 2},
        ).execute_logical(plan))
        assert on == off, (devices, dist, strategy)


@pytest.mark.timeout(300)
def test_byte_identity_string_keys(rng):
    n = 2000
    words = [f"key-{i}" for i in range(40)]
    keys = [words[i] for i in rng.integers(0, len(words), n)]
    rel = L.Relation(from_arrow(pa.table({
        "k": pa.array(keys, pa.string()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })))
    plan = _agg_plan(rel)
    off = _rows(_executor(2, False).execute_logical(plan))
    for strategy in ("auto", "partial", "bypass", "hash", "sort",
                     "presplit"):
        on = _rows(_executor(
            2, True, **{"spark.tpu.adaptive.agg.strategy": strategy},
        ).execute_logical(plan))
        assert on == off, strategy


@pytest.mark.timeout(300)
def test_byte_identity_compound_string_key(rng):
    # a dictionary string key alone always takes the static packed-key
    # direct path; pairing it with an int key defeats that, so a STRING
    # key rides through every strategy cell of the runtime switch
    # (including the sort rung, whose output must NOT claim a global
    # string order: codes sort locally, ranks partition globally)
    n = 2000
    ik = np.arange(n, dtype=np.int64) * 1_000_003
    words = [f"w{i % 37}" for i in range(n)]
    rel = L.Relation(from_arrow(pa.table({
        "k": pa.array(ik, pa.int64()),
        "s": pa.array(words, pa.string()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })))
    v = E.Col("v")
    plan = L.Sort(
        (E.SortOrder(E.Col("k")), E.SortOrder(E.Col("s"))),
        L.Aggregate(
            (E.Col("k"), E.Col("s")),
            (E.Col("k"), E.Col("s"), E.Alias(E.Sum(v), "sv"),
             E.Alias(E.Count(v), "n")), rel))
    off = _rows(_executor(2, False).execute_logical(plan))
    for strategy in ("auto", "partial", "bypass", "hash", "sort",
                     "presplit"):
        on = _rows(_executor(
            2, True, **{"spark.tpu.adaptive.agg.strategy": strategy},
        ).execute_logical(plan))
        assert on == off, strategy


# ---- auto mode picks the right strategy -------------------------------------


@pytest.mark.timeout(300)
def test_auto_picks_bypass_on_all_distinct(rng):
    metrics.reset_agg()
    plan = _agg_plan(_dataset("distinct", rng))
    _executor(2, True).execute_logical(plan)
    ev = _agg_events()[-1]
    assert ev["strategy"] == "bypass" and ev["mode"] == "auto"
    assert ev["ratio"] >= 0.5
    assert metrics.agg_stats()["bypass"] == 1


@pytest.mark.timeout(300)
def test_auto_picks_hash_on_small_domain(rng):
    metrics.reset_agg()
    plan = _agg_plan(_dataset("uniform", rng))
    got = _rows(_executor(2, True).execute_logical(plan))
    ev = _agg_events()[-1]
    assert ev["strategy"] == "hash" and ev["mode"] == "auto"
    assert 0 < ev["domain"] <= 1024
    assert len(got) == len({r[0] for r in got})


@pytest.mark.timeout(300)
def test_auto_falls_back_to_partial_on_wide_domain(rng):
    # mid ratio + domain beyond the limit: neither bypass nor hash wins
    n = 3000
    keys = rng.integers(0, 1 << 30, n) * 2     # huge sparse domain
    keys[n // 2:] = keys[: n - n // 2]         # ~50% duplication
    metrics.reset_agg()
    plan = _agg_plan(_table(keys, rng.integers(0, 1000, n)))
    _executor(2, True,
              **{"spark.tpu.adaptive.agg.bypassNdvRatio": 0.9},
              ).execute_logical(plan)
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "auto"


@pytest.mark.timeout(300)
def test_auto_picks_sort_on_huge_domain(rng):
    # NDV ~ rows AND the packed domain far beyond sortDomainWidth: the
    # crossover picks the sort rung, whose key-ordered output then
    # elides the downstream global sort entirely
    n = 3000
    keys = np.arange(n, dtype=np.int64) * 1_000_003
    plan = _agg_plan(_table(keys, rng.integers(0, 1000, n)))
    off = _rows(_executor(2, False).execute_logical(plan))
    metrics.reset_agg()
    on = _rows(_executor(2, True).execute_logical(plan))
    assert on == off
    ev = _agg_events()[-1]
    assert ev["strategy"] == "sort" and ev["mode"] == "auto"
    assert ev["ratio"] >= 0.5 and ev["domain"] > (1 << 20)
    st = metrics.agg_stats()
    assert st["sort"] == 1 and st["sort_elided"] == 1


@pytest.mark.timeout(300)
def test_auto_picks_presplit_on_hot_key(rng):
    # one key is half of all rows over an otherwise near-distinct
    # huge-domain tail: the crossover would exchange raw rows (sort
    # rung) and the Count-Min probe sees a heavy hitter whose
    # frequency alone overloads a device — so it pre-splits the key
    # over the whole mesh BEFORE the exchange
    plan = _agg_plan(_dataset("hot", rng))
    off = _rows(_executor(8, False).execute_logical(plan))
    metrics.reset_agg()
    on = _rows(_executor(
        8, True, **{"spark.tpu.adaptive.agg.presplitMinRows": 64,
                    "spark.tpu.adaptive.agg.presplitFactor": 2},
    ).execute_logical(plan))
    assert on == off
    ev = _agg_events()[-1]
    assert ev["strategy"] == "presplit" and ev["mode"] == "auto"
    assert ev["hot_keys"] >= 1
    assert metrics.agg_stats()["presplit"] == 1


@pytest.mark.timeout(300)
def test_auto_keeps_partial_on_low_ndv_skew(rng):
    # 90% one key but LOW NDV ratio: the partial strategy collapses
    # the hot key to one row per device before its exchange, so
    # pre-splitting would only add an extra raw-row exchange — the
    # ladder must keep the crossover's partial pick
    plan = _agg_plan(_dataset("skewed", rng))
    metrics.reset_agg()
    _executor(8, True,
              **{"spark.tpu.adaptive.agg.presplitMinRows": 64},
              ).execute_logical(plan)
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "auto"
    assert ev["hot_keys"] >= 1  # detected, deliberately not acted on


@pytest.mark.timeout(300)
def test_forced_presplit_degrades_without_hot_keys(rng):
    # uniform keys have no heavy hitter: forcing presplit degrades to
    # the static plan instead of salting cold keys
    plan = _agg_plan(_dataset("uniform", rng))
    off = _rows(_executor(2, False).execute_logical(plan))
    metrics.reset_agg()
    on = _rows(_executor(
        2, True, **{"spark.tpu.adaptive.agg.strategy": "presplit"},
    ).execute_logical(plan))
    assert on == off
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "forced"


@pytest.mark.timeout(60)
def test_strategy_crossover_boundary_cells():
    """The pure crossover rule the runtime switch, its EXPLAIN
    diagnostic and these cells all share — pinned exactly at the
    conf-documented boundaries."""
    from spark_tpu.analysis.legality import strategy_crossover

    bypass_r, hash_w, sort_w = 0.5, 1024, 1 << 20

    def cell(ratio, width):
        return strategy_crossover(ratio, width, bypass_r, hash_w,
                                  sort_w)

    # the four corners of the matrix
    assert cell(0.1, 100) == "hash"
    assert cell(0.1, hash_w + 1) == "partial"
    assert cell(0.9, sort_w) == "bypass"
    assert cell(0.9, sort_w + 1) == "sort"
    # boundary cells: ratio threshold inclusive, width limits inclusive
    assert cell(bypass_r, sort_w) == "bypass"
    assert cell(float(np.nextafter(bypass_r, 0)), 100) == "hash"
    assert cell(0.9, hash_w) == "bypass"
    assert cell(0.1, hash_w) == "hash"
    # unbounded/unpackable domain (-1): string keys, overflowing packs
    assert cell(0.9, -1) == "sort"
    assert cell(0.1, -1) == "partial"


@pytest.mark.timeout(300)
def test_float_sum_pins_to_partial(rng):
    # float Sum partials are order-dependent: the switch must pin to
    # the static plan even when the conf FORCES another strategy
    n = 2000
    rel = L.Relation(from_arrow(pa.table({
        "k": pa.array(np.arange(n), pa.int64()),
        "f": pa.array(rng.random(n), pa.float64()),
    })))
    plan = L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(E.Col("f")), "fs")), rel))
    off = _rows(_executor(2, False).execute_logical(plan))
    metrics.reset_agg()
    on = _rows(_executor(
        2, True, **{"spark.tpu.adaptive.agg.strategy": "bypass"},
    ).execute_logical(plan))
    assert on == off
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "pinned"
    assert metrics.agg_stats()["pinned"] == 1


@pytest.mark.timeout(300)
def test_forced_hash_falls_back_without_key_stats(rng):
    # float group keys cannot range-compress: forced hash degrades to
    # partial instead of failing the query
    n = 1000
    rel = L.Relation(from_arrow(pa.table({
        "k": pa.array(rng.integers(0, 20, n).astype(np.float64),
                      pa.float64()),
        "v": pa.array(rng.integers(0, 100, n), pa.int64()),
    })))
    plan = L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Count(E.Col("v")), "n")), rel))
    off = _rows(_executor(2, False).execute_logical(plan))
    metrics.reset_agg()
    on = _rows(_executor(
        2, True, **{"spark.tpu.adaptive.agg.strategy": "hash"},
    ).execute_logical(plan))
    assert on == off
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "forced"


# ---- sketch accuracy --------------------------------------------------------


@pytest.mark.parametrize("true_ndv", [10, 100, 1000, 5000])
@pytest.mark.timeout(300)
def test_hll_estimate_accuracy(true_ndv):
    """Host-side oracle over the same register construction the stats
    stage traces: m=512 registers give ~1.04/sqrt(m) = 4.6% standard
    error; linear counting covers the small range. Bound at 4 sigma."""
    rng = np.random.default_rng(true_ndv)
    m, p = 512, 9
    # full-width 64-bit hashes (two 32-bit draws: integers() cannot
    # express high=2**64) — a short top bit would bias every rank +1
    h = ((rng.integers(0, 1 << 32, true_ndv, dtype=np.uint64)
          << np.uint64(32))
         | rng.integers(0, 1 << 32, true_ndv, dtype=np.uint64))
    idx = (h & np.uint64(m - 1)).astype(np.int64)
    w = h >> np.uint64(p)
    nbits = 64 - p
    rho = np.where(w == 0, nbits + 1,
                   nbits - np.floor(np.log2(np.maximum(
                       w.astype(np.float64), 1.0))))
    regs = np.zeros(m, dtype=np.int64)
    np.maximum.at(regs, idx, rho.astype(np.int64))
    est = MeshExecutor._hll_estimate(regs)
    assert abs(est - true_ndv) <= max(4, 4 * 1.04 / np.sqrt(m) * true_ndv)


@pytest.mark.parametrize("true_ndv", [64, 3000])
@pytest.mark.timeout(300)
def test_hyperloglog_host_class_accuracy(true_ndv):
    """The consolidated host HyperLogLog (spark_tpu/sketch.py) against
    exact distinct counts, including the chunked-update + merge path
    the hybrid hash join's partition oracle uses."""
    from spark_tpu.sketch import HyperLogLog

    rng = np.random.default_rng(true_ndv)
    vals = rng.choice(1 << 40, true_ndv, replace=False).astype(np.int64)
    a, b = HyperLogLog(512), HyperLogLog(512)
    a.update(vals[: true_ndv // 2])
    b.update(vals[true_ndv // 3:])          # overlapping chunks
    est = a.merge(b).estimate()
    assert abs(est - true_ndv) <= max(8, 4 * 1.04 / np.sqrt(512)
                                      * true_ndv)


@pytest.mark.parametrize("width", [64, 256])
@pytest.mark.timeout(300)
def test_countmin_host_oracle_small_widths(width):
    """Count-Min never under-counts, and at small widths the collision
    over-count stays within the classic 2N/width bound (x4 slack for
    the skewed stream) — the property the pre-split threshold relies
    on: a heavy hitter is never missed, a cold key is at worst salted
    harmlessly."""
    from spark_tpu.sketch import CountMinSketch

    rng = np.random.default_rng(width)
    n, k = 20000, 500
    keys = np.where(rng.random(n) < 0.4, 7,
                    rng.integers(0, k, n)).astype(np.int64)
    cm = CountMinSketch(depth=4, width=width).add(keys)
    uniq, counts = np.unique(keys, return_counts=True)
    for v, c in zip(uniq[:64], counts[:64]):
        est = cm.estimate(int(v))
        assert est >= int(c), (v, est, c)
        assert est <= int(c) + 4 * (2 * n // width), (v, est, c)
    hot = int(uniq[np.argmax(counts)])
    assert hot == 7 and cm.estimate(7) >= int(counts.max())


@pytest.mark.timeout(300)
def test_sketch_ndv_end_to_end(rng):
    # the measured event's NDV estimate lands within the sketch's noise
    n, true_ndv = 4000, 200
    metrics.reset_agg()
    plan = _agg_plan(_table(rng.integers(0, true_ndv, n),
                            rng.integers(0, 1000, n)))
    _executor(2, True,
              **{"spark.tpu.adaptive.agg.hashDomainLimit": 16},
              ).execute_logical(plan)
    ev = _agg_events()[-1]
    assert ev["rows"] == n
    assert abs(ev["ndv"] - true_ndv) <= 0.25 * true_ndv


# ---- Pallas kernels vs numpy oracles (interpret mode) -----------------------


def _oracle_reduce(data, seg, mask, k, red, init):
    out = np.full(k, init, dtype=np.float64)
    for s, d, m in zip(seg, data, mask):
        if m and 0 <= s < k:
            out[s] = red(out[s], d)
    return out


@pytest.mark.parametrize("k", [65, 257, 1024])
@pytest.mark.timeout(300)
def test_pallas_minmax_interpret_oracle(k, rng):
    from spark_tpu.ops import pallas_seg_minmax

    n = 5000
    data = rng.standard_normal(n).astype(np.float32) * 100
    seg = rng.integers(0, k, n).astype(np.int32)
    mask = rng.random(n) < 0.8
    seg[seg == k // 2] = k - 1  # leave group k//2 empty
    got_min = np.asarray(pallas_seg_minmax(
        jnp.asarray(data), jnp.asarray(seg), jnp.asarray(mask), k,
        is_max=False, interpret=True))
    got_max = np.asarray(pallas_seg_minmax(
        jnp.asarray(data), jnp.asarray(seg), jnp.asarray(mask), k,
        is_max=True, interpret=True))
    want_min = _oracle_reduce(data, seg, mask, k, min, np.inf)
    want_max = _oracle_reduce(data, seg, mask, k, max, -np.inf)
    np.testing.assert_array_equal(got_min, want_min.astype(np.float32))
    np.testing.assert_array_equal(got_max, want_max.astype(np.float32))
    assert got_min[k // 2] == np.inf and got_max[k // 2] == -np.inf


@pytest.mark.timeout(300)
def test_pallas_sum_count_mean_interpret_oracle(rng):
    from spark_tpu.ops import pallas_seg_sum

    n, k = 5000, 300
    data = rng.integers(0, 100, n).astype(np.float32)
    seg = rng.integers(0, k, n).astype(np.int32)
    mask = rng.random(n) < 0.7
    jd, js, jm = jnp.asarray(data), jnp.asarray(seg), jnp.asarray(mask)
    got_sum = np.asarray(pallas_seg_sum(jd, js, jm, k, interpret=True))
    got_cnt = np.asarray(pallas_seg_sum(
        jm.astype(jnp.float32), js, jm, k, interpret=True,
        exact_int=True))
    want_sum = np.zeros(k)
    want_cnt = np.zeros(k, dtype=np.int64)
    for s, d, m in zip(seg, data, mask):
        if m:
            want_sum[s] += d
            want_cnt[s] += 1
    np.testing.assert_array_equal(got_sum, want_sum.astype(np.float32))
    np.testing.assert_array_equal(got_cnt, want_cnt)
    # mean = sum/count with empty groups NaN, the maybe_ contract
    mean = np.where(got_cnt > 0, got_sum / np.maximum(got_cnt, 1),
                    np.nan)
    want_mean = np.where(want_cnt > 0,
                         want_sum / np.maximum(want_cnt, 1), np.nan)
    np.testing.assert_allclose(mean, want_mean, rtol=1e-6)


# ---- fault injection: the sketch is advisory --------------------------------


@pytest.mark.parametrize("kind", list(faults.KINDS))
@pytest.mark.timeout(300)
def test_sketch_fault_falls_back_to_static(kind, rng):
    """ANY injected fault at agg.strategy — even 'corrupt', because the
    estimate is discarded and never merged into results — degrades to
    the static partial->final plan with identical bytes."""
    plan = _agg_plan(_dataset("distinct", rng))
    off = _rows(_executor(2, False).execute_logical(plan))
    metrics.reset_agg()
    ex = _executor(2, True, **{
        "spark.tpu.faultInjection.agg.strategy": f"nth:1:{kind}"})
    on = _rows(ex.execute_logical(plan))
    assert on == off
    assert faults.fire_count(ex.conf, "agg.strategy") == 1
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["mode"] == "fallback"
    assert metrics.agg_stats()["sketch_failures"] == 1


@pytest.mark.parametrize("kind", list(faults.KINDS))
@pytest.mark.timeout(300)
def test_presplit_fault_falls_back_to_static(kind, rng):
    """ANY injected fault at agg.presplit — fired after the Count-Min
    probe elects pre-splitting, before the salted exchange exists —
    discards the whole candidate list and degrades to the static
    partial->final plan with identical bytes."""
    plan = _agg_plan(_dataset("hot", rng))
    off = _rows(_executor(8, False).execute_logical(plan))
    metrics.reset_agg()
    ex = _executor(8, True, **{
        "spark.tpu.adaptive.agg.presplitMinRows": 64,
        "spark.tpu.adaptive.agg.presplitFactor": 2,
        "spark.tpu.faultInjection.agg.presplit": f"nth:1:{kind}"})
    on = _rows(ex.execute_logical(plan))
    assert on == off
    assert faults.fire_count(ex.conf, "agg.presplit") == 1
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial"
    assert ev["mode"] == "presplit_fallback"
    assert metrics.agg_stats()["presplit_failures"] == 1


# ---- observability ----------------------------------------------------------


@pytest.mark.timeout(300)
def test_aggregation_profile_rolls_up(rng):
    metrics.reset_agg()
    _executor(2, True).execute_logical(_agg_plan(_dataset("distinct",
                                                          rng)))
    prof = tracing.aggregation_profile()
    assert prof["strategies"].get("bypass", 0) >= 1
    assert prof["recent"] and prof["recent"][-1]["strategy"] == "bypass"
    text = tracing.format_aggregation_profile(prof)
    assert "bypass" in text


@pytest.mark.timeout(300)
def test_empty_input_defaults_to_partial(rng):
    plan = _agg_plan(_table(np.array([], np.int64),
                            np.array([], np.int64)))
    metrics.reset_agg()
    got = _rows(_executor(2, True).execute_logical(plan))
    assert got == []
    ev = _agg_events()[-1]
    assert ev["strategy"] == "partial" and ev["rows"] == 0

"""Incrementally-maintained materialized views (spark_tpu/mview/):
delta classification, registration via cache(), incremental re-merge
vs full recompute with byte-identity under the on/off conf sweep,
the mview.refresh fault matrix, streaming convergence with WAL-replay
dedup, store update accounting, and serve-tier repopulation."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import conf as CF
from spark_tpu import faults, metrics
from spark_tpu.api import functions as F
from spark_tpu.columnar.arrow import to_arrow
from spark_tpu.io.fingerprint import classify_delta, stat_paths
from spark_tpu.serve.result_cache import table_to_ipc

pytestmark = pytest.mark.mview


# ---- helpers ----------------------------------------------------------------


def _write(d, name, ks, vs, key_type=pa.string()):
    pq.write_table(pa.table({"k": pa.array(ks, key_type),
                             "v": pa.array(vs, pa.int64())}),
                   os.path.join(d, name))


def _base(d, key_type=pa.string()):
    if key_type == pa.string():
        ks = [f"k{i % 13}" for i in range(400)]
    else:
        ks = [i % 13 for i in range(400)]
    _write(d, "base.parquet", ks, [i % 97 for i in range(400)],
           key_type)


@pytest.fixture
def mview_on(spark):
    """Arm the subsystem on the shared session, restoring afterwards
    (registration happens at cache() time, so the flag must be set
    before the test touches cache())."""
    spark.conf.set("spark.tpu.mview.enabled", "true")
    yield spark.conf
    for key in ("spark.tpu.mview.enabled", "spark.tpu.mview.incremental",
                "spark.tpu.mview.refreshRetries",
                "spark.tpu.faultInjection.mview.refresh"):
        try:
            spark.conf.unset(key)
        except KeyError:
            pass
    faults.reset(spark.conf)
    spark.cache_manager.clear()


def _sum_df(spark, d):
    return spark.read.parquet(d).groupBy("k").agg(F.sum("v").alias("s"))


def _rows(df):
    return sorted(tuple(r.values()) for r in
                  (r.asDict() for r in df.collect()))


# ---- delta classification (io/fingerprint) ----------------------------------


def test_classify_delta(tmp_path):
    d = str(tmp_path)
    _write(d, "a.parquet", ["x"], [1])
    fp1 = stat_paths([d])
    assert classify_delta(fp1, fp1) == ("unchanged", ())

    _write(d, "b.parquet", ["y"], [2])
    fp2 = stat_paths([d])
    kind, added = classify_delta(fp1, fp2)
    assert kind == "appended"
    assert [os.path.basename(p) for p in added] == ["b.parquet"]

    # rewrite of an existing file: mtime/size move -> changed
    _write(d, "a.parquet", ["x", "x"], [1, 1])
    kind, added = classify_delta(fp2, stat_paths([d]))
    assert (kind, added) == ("changed", ())

    # deletion -> changed
    os.remove(os.path.join(d, "b.parquet"))
    kind, added = classify_delta(fp2, stat_paths([d]))
    assert (kind, added) == ("changed", ())


# ---- registration + inspection ----------------------------------------------


def test_inspect_plan_verdicts(spark, tmp_path):
    import dataclasses

    from spark_tpu.mview import inspect_plan

    d = str(tmp_path)
    _base(d)
    scan_df = spark.read.parquet(d)

    ok = inspect_plan(scan_df.groupBy("k").agg(
        F.sum("v").alias("s"))._plan)
    assert ok.registrable and ok.incremental and ok.kind == "file"
    assert ok.diagnostics[0][0] == "PLAN-MVIEW-OK"
    assert ok.merge_spec.key_names == ("k",)

    avg = inspect_plan(scan_df.groupBy("k").agg(
        F.avg("v").alias("a"))._plan)
    assert avg.registrable and not avg.incremental
    assert avg.diagnostics[0][0] == "PLAN-MVIEW-RECOMPUTE"

    shape = inspect_plan(scan_df.groupBy("k").agg(
        F.sum("v").alias("s")).filter(F.col("s") > 0)._plan)
    assert not shape.registrable
    assert shape.diagnostics[0][0] == "PLAN-MVIEW-SHAPE"

    mem = inspect_plan(spark.createDataFrame(
        [{"k": "a", "v": 1}]).groupBy("k").agg(
        F.sum("v").alias("s"))._plan)
    assert not mem.registrable
    assert mem.diagnostics[0][0] == "PLAN-MVIEW-SOURCE"

    # grouping key not carried through to the output
    plan = scan_df.groupBy("k").agg(F.sum("v").alias("s"))._plan
    keyless = dataclasses.replace(plan, aggregates=plan.aggregates[1:])
    nk = inspect_plan(keyless)
    assert nk.registrable and not nk.incremental
    assert any(c == "PLAN-MVIEW-KEYS" for c, _, _ in nk.diagnostics)


def test_registration_rides_on_cache(spark, mview_on, tmp_path):
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    assert spark.mview_manager.views() == []
    df.cache()
    try:
        views = spark.mview_manager.views()
        assert len(views) == 1 and views[0]["incremental"]
    finally:
        df.unpersist()
    assert spark.mview_manager.views() == []


def test_disabled_means_no_views(spark, tmp_path):
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        assert spark.mview_manager.views() == []
    finally:
        df.unpersist()


# ---- freshness: the stale-cache hole this subsystem closes ------------------


def test_view_refreshes_where_plain_cache_is_stale(spark, mview_on,
                                                   tmp_path):
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        r1 = _rows(df)
        view = spark.mview_manager.views()[0]
        _write(d, "delta.parquet", ["k0", "zz"], [1000, 7])
        r2 = _rows(df)
        assert r2 != r1, "view must refresh after an append"
        assert r2 == _rows(_sum_df(spark, d))  # == uncached recompute
        view = spark.mview_manager.views()[0]
        assert view["refreshes"] == 1
        assert view["incremental_merges"] == 1
        assert view["full_recomputes"] == 0
        # unchanged source: fresh hit, no further refresh
        assert _rows(df) == r2
        assert spark.mview_manager.views()[0]["refreshes"] == 1
    finally:
        df.unpersist()


def test_rewrite_forces_full_recompute(spark, mview_on, tmp_path):
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        _rows(df)
        # rewrite base: not an append, merge would double-count
        _base(d)
        os.utime(os.path.join(d, "base.parquet"))
        _write(d, "extra.parquet", ["k1"], [5])
        assert _rows(df) == _rows(_sum_df(spark, d))
        view = spark.mview_manager.views()[0]
        assert view["full_recomputes"] == 1
        assert view["incremental_merges"] == 0
    finally:
        df.unpersist()


def test_eviction_then_refresh_recovers(spark, mview_on, tmp_path):
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        r1 = _rows(df)
        with spark.memory_manager.lock:
            spark.memory_store._evict_locked(1 << 62, floor=0,
                                             reason="execution")
        assert _rows(df) == r1  # re-materializes, not an error
        _write(d, "post.parquet", ["k2"], [11])
        assert _rows(df) == _rows(_sum_df(spark, d))
    finally:
        df.unpersist()


# ---- byte identity: incremental on/off × devices × data shape ---------------


class _FakeSession:
    def __init__(self, conf):
        self.conf = conf


_MESHES = {}


def _mesh(d):
    from spark_tpu.parallel.mesh import make_mesh

    if d not in _MESHES:
        _MESHES[d] = make_mesh(d)
    return _MESHES[d]


def _sweep(spark, root, devices, incremental, agg_fn, steps,
           key_type=pa.string()):
    """One (devices, incremental) configuration: replay the identical
    base+appends file evolution in a private dir through a standalone
    CacheManager+ViewManager pair executing on a d-device mesh;
    returns ([ipc_bytes per step], view_counters)."""
    from spark_tpu.api.session import CacheManager
    from spark_tpu.mview.manager import ViewManager
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.storage import MemoryStore, UnifiedMemoryManager

    d = os.path.join(root, f"dev{devices}_{'on' if incremental else 'off'}")
    os.makedirs(d)
    _base(d, key_type)
    conf = CF.RuntimeConf({"spark.tpu.mview.enabled": True,
                           "spark.tpu.mview.incremental": incremental})
    ex = MeshExecutor(_mesh(devices), conf=conf)
    cm = CacheManager(store=MemoryStore(  # private store/budget
        UnifiedMemoryManager(budget_bytes=1 << 30)))
    mgr = ViewManager(_FakeSession(conf))
    cm._mview = mgr
    plan = agg_fn(spark.read.parquet(d))._plan
    cm.add(plan)

    def run(p):
        return ex.execute_logical(p)

    out = [table_to_ipc(to_arrow(cm.apply(plan, run).batch))]
    for i, (ks, vs) in enumerate(steps):
        _write(d, f"app{i}.parquet", ks, vs, key_type)
        out.append(table_to_ipc(to_arrow(cm.apply(plan, run).batch)))
    view = mgr.view_for(plan.structural_key())
    return out, view


UNIFORM = [([f"k{i % 13}" for i in range(50)], list(range(50))),
           ([f"k{i % 13}" for i in range(60)], list(range(60)))]
#: appends concentrated on one hot key plus NEW keys the base never
#: saw (dictionary grows, merge capacity moves)
SKEWED = [(["k0"] * 80 + ["new_a", "new_b"], list(range(82))),
          (["k0"] * 70 + ["new_c"], list(range(71)))]


@pytest.mark.timeout(600)
@pytest.mark.parametrize("devices", [1, 2, 8])
@pytest.mark.parametrize("shape", ["uniform", "skewed"])
def test_byte_identity_on_off_sweep(spark, tmp_path, devices, shape):
    """The acceptance gate: for every device count and data shape, the
    incremental path's serialized bytes equal the full-recompute
    path's, step by step — and the incremental run actually merged."""
    steps = UNIFORM if shape == "uniform" else SKEWED
    agg = lambda df: df.groupBy("k").agg(  # noqa: E731
        F.sum("v").alias("s"), F.max("v").alias("m"))
    on, view_on = _sweep(spark, str(tmp_path), devices, True, agg, steps)
    off, view_off = _sweep(spark, str(tmp_path), devices, False, agg,
                           steps)
    assert on == off, (
        f"incremental vs recompute bytes diverge at devices={devices} "
        f"shape={shape}")
    assert view_on.incremental_merges == len(steps)
    assert view_on.full_recomputes == 0
    assert view_off.full_recomputes == len(steps)
    assert view_off.incremental_merges == 0


@pytest.mark.timeout(600)
@pytest.mark.parametrize("devices", [1, 2, 8])
def test_byte_identity_integer_keys(spark, tmp_path, devices):
    """Numeric group keys take the sort-path aggregate; identity must
    hold there too."""
    steps = [([i % 13 for i in range(50)], list(range(50))),
             ([99, 99, 100], [1, 2, 3])]
    agg = lambda df: df.groupBy("k").agg(  # noqa: E731
        F.sum("v").alias("s"), F.min("v").alias("m"))
    on, view_on = _sweep(spark, str(tmp_path), devices, True, agg,
                         steps, key_type=pa.int64())
    off, _ = _sweep(spark, str(tmp_path), devices, False, agg, steps,
                    key_type=pa.int64())
    assert on == off
    assert view_on.incremental_merges == len(steps)


# ---- non-mergeable plans fall back transparently ----------------------------


def test_nonmergeable_avg_falls_back(spark, mview_on, tmp_path):
    d = str(tmp_path)
    _base(d)
    df = spark.read.parquet(d).groupBy("k").agg(F.avg("v").alias("a"))
    df.cache()
    try:
        views = spark.mview_manager.views()
        assert len(views) == 1 and not views[0]["incremental"]
        _rows(df)
        _write(d, "delta.parquet", ["k0"], [12345])
        assert _rows(df) == _rows(spark.read.parquet(d).groupBy("k")
                                  .agg(F.avg("v").alias("a")))
        view = spark.mview_manager.views()[0]
        assert view["full_recomputes"] == 1
        assert view["incremental_merges"] == 0
    finally:
        df.unpersist()


def test_float_sum_falls_back(spark, mview_on, tmp_path):
    d = str(tmp_path)
    pq.write_table(pa.table({"k": pa.array(["a", "b", "a"]),
                             "v": pa.array([1.5, 2.5, 3.5],
                                           pa.float64())}),
                   os.path.join(d, "f0.parquet"))
    df = _sum_df(spark, d)
    df.cache()
    try:
        views = spark.mview_manager.views()
        assert len(views) == 1 and not views[0]["incremental"]
    finally:
        df.unpersist()


# ---- fault matrix: mview.refresh --------------------------------------------


@pytest.mark.parametrize("kind", ["transient", "hang", "oom", "corrupt"])
def test_refresh_fault_matrix(spark, mview_on, tmp_path, kind):
    """One injected fault at the refresh seam: transient kinds retry
    and the merge still lands; non-retryable kinds fall back to a full
    recompute — in every case the query sees correct rows and no
    error."""
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        _rows(df)
        spark.conf.set("spark.tpu.faultInjection.hangSeconds", 0.02)
        spark.conf.set("spark.tpu.faultInjection.mview.refresh",
                       f"nth:1:{kind}")
        faults.reset(spark.conf)
        metrics.reset_mview()
        _write(d, "delta.parquet", ["k0", "zz"], [1000, 7])
        assert _rows(df) == _rows(_sum_df(spark, d))
        assert faults.fire_count(spark.conf, "mview.refresh") == 1
        st = metrics.mview_stats()
        view = spark.mview_manager.views()[0]
        if kind in ("transient", "hang"):
            assert st["refresh_retries"] == 1
            assert st["refresh_fallbacks"] == 0
            assert view["incremental_merges"] == 1
        else:
            assert st["refresh_retries"] == 0
            assert st["refresh_fallbacks"] == 1
            assert view["full_recomputes"] == 1
    finally:
        df.unpersist()


def test_refresh_retry_exhaustion_falls_back(spark, mview_on, tmp_path):
    """Every attempt fails transiently: retries are bounded by
    spark.tpu.mview.refreshRetries, then the refresh falls back to a
    full recompute with correct bytes."""
    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        _rows(df)
        spark.conf.set("spark.tpu.mview.refreshRetries", 2)
        spark.conf.set("spark.tpu.faultInjection.mview.refresh",
                       "prob:1.0:7:transient")
        faults.reset(spark.conf)
        metrics.reset_mview()
        _write(d, "delta.parquet", ["k1"], [55])
        assert _rows(df) == _rows(_sum_df(spark, d))
        assert faults.fire_count(spark.conf, "mview.refresh") == 3
        st = metrics.mview_stats()
        assert st["refresh_retries"] == 2
        assert st["refresh_fallbacks"] == 1
        assert spark.mview_manager.views()[0]["full_recomputes"] == 1
    finally:
        df.unpersist()


# ---- streaming convergence --------------------------------------------------


def _stream_setup(spark, tmp_path, name):
    from spark_tpu.streaming import MemoryStream

    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    agg = spark.readStream.load(src).groupBy("k").agg(
        F.sum("v").alias("s"))
    q = agg.writeStream.outputMode("complete").queryName(name) \
        .option("checkpointLocation", str(tmp_path / "ck")).start()
    return src, agg, q


def test_stream_view_merges_micro_batches(spark, tmp_path):
    src, agg, q = _stream_setup(spark, tmp_path, "mvs1")
    mgr = spark.mview_manager
    mgr.register_stream_view("sv1", agg._plan, "mvs1")
    try:
        src.add_data([{"k": "a", "v": 1}, {"k": "b", "v": 2}])
        q.process_all_available()
        src.add_data([{"k": "a", "v": 10}])
        q.process_all_available()
        got = _rows(mgr.read("sv1"))
        assert got == [("a", 11), ("b", 2)]
        view = mgr.stream_view("sv1")
        assert view.incremental_merges == 2
    finally:
        q.stop()
        mgr.drop_stream_view("sv1")


def test_stream_view_replay_never_double_merges(spark, fconf_like,
                                                tmp_path):
    """Crash at the commit seam AFTER the view merged the delta: the
    WAL replay redelivers the same batch id, which the watermark drops
    — the view's sum counts every row exactly once."""
    src, agg, q = _stream_setup(spark, tmp_path, "mvs2")
    mgr = spark.mview_manager
    mgr.register_stream_view("sv2", agg._plan, "mvs2")
    try:
        src.add_data([{"k": "a", "v": 5}])
        q.process_all_available()
        fconf_like.set("spark.tpu.faultInjection.streaming.commit",
                       "nth:1:corrupt")
        faults.reset(fconf_like)
        src.add_data([{"k": "a", "v": 7}, {"k": "b", "v": 1}])
        with pytest.raises(faults.InjectedCorruptionError):
            q.process_all_available()
        q.stop()
        fconf_like.unset("spark.tpu.faultInjection.streaming.commit")
        # the view already merged batch 2 (published pre-commit)
        assert _rows(mgr.read("sv2")) == [("a", 12), ("b", 1)]
        dedups0 = metrics.mview_stats()["stream_dedups"]

        q2 = agg.writeStream.outputMode("complete").queryName("mvs2") \
            .option("checkpointLocation", str(tmp_path / "ck")).start()
        q2.process_all_available()  # WAL replay of the same batch id
        q2.stop()
        assert metrics.mview_stats()["stream_dedups"] == dedups0 + 1
        assert _rows(mgr.read("sv2")) == [("a", 12), ("b", 1)], \
            "replay must not double-merge"
    finally:
        mgr.drop_stream_view("sv2")


@pytest.fixture
def fconf_like(spark):
    conf = spark.conf
    faults.reset(conf)
    yield conf
    for key in ("spark.tpu.faultInjection.streaming.commit",
                "spark.tpu.faultInjection.mview.refresh"):
        try:
            conf.unset(key)
        except KeyError:
            pass
    faults.reset(conf)


def test_stream_view_rejects_nonmergeable(spark, tmp_path):
    from spark_tpu.streaming import MemoryStream

    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    agg = spark.readStream.load(src).groupBy("k").agg(
        F.avg("v").alias("a"))
    with pytest.raises(ValueError):
        spark.mview_manager.register_stream_view("bad", agg._plan, "x")


# ---- store update accounting ------------------------------------------------


class _FakeBatch:
    def __init__(self, n):
        self._n = n

    def device_nbytes(self):
        return self._n


def test_memory_store_update_accounting():
    from spark_tpu.storage import MemoryStore, UnifiedMemoryManager

    m = UnifiedMemoryManager(budget_bytes=1 << 30)
    store = MemoryStore(m)
    assert store.put("v", _FakeBatch(1000))
    assert store.bytes_used() == 1000
    assert store.update("v", _FakeBatch(1500))
    assert store.bytes_used() == 1500
    assert store.update("v", _FakeBatch(300))
    assert store.bytes_used() == 300
    assert store.get("v").device_nbytes() == 300
    # update of an absent key falls through to put
    assert store.update("w", _FakeBatch(100))
    assert store.bytes_used() == 400


def test_memory_store_update_rejects_oversize_and_drops_stale():
    from spark_tpu.storage import MemoryStore, UnifiedMemoryManager

    m = UnifiedMemoryManager(budget_bytes=10_000)
    store = MemoryStore(m)
    assert store.put("v", _FakeBatch(1000))
    assert not store.update("v", _FakeBatch(10**9))
    # serving stale bytes is worse than recomputing: the entry is gone
    assert store.get("v") is None
    assert store.bytes_used() == 0


# ---- serve-tier repopulation ------------------------------------------------


def test_serve_cache_repopulated_after_refresh(spark, mview_on,
                                               tmp_path):
    from spark_tpu.serve import result_cache as rc

    d = str(tmp_path)
    _base(d)
    spark.conf.set("spark.tpu.serve.resultCache.enabled", "true")
    spark.serve_result_cache = rc.ResultCache(spark.conf)
    df = _sum_df(spark, d)
    df.cache()
    try:
        _rows(df)
        _write(d, "delta.parquet", ["k3", "q"], [42, 9])
        expected = df.toArrow()
        key = rc.plan_result_key(df._plan)
        blob = spark.serve_result_cache.lookup(key)
        assert blob is not None, "refresh must pre-warm the serve cache"
        assert blob == rc.table_to_ipc(expected), \
            "repopulated bytes must equal what serving would produce"
        assert metrics.mview_stats()["serve_repopulations"] >= 1
    finally:
        df.unpersist()
        spark.conf.unset("spark.tpu.serve.resultCache.enabled")
        del spark.serve_result_cache


# ---- diagnostics + conf + observability -------------------------------------


def test_plan_mview_diagnostics_via_analyze(spark, tmp_path):
    from spark_tpu.analysis import analyze

    d = str(tmp_path)
    _base(d)
    ok = analyze(_sum_df(spark, d)._plan, spark.conf)
    assert any(dg.code == "PLAN-MVIEW-OK" for dg in ok.diagnostics)
    rec = analyze(spark.read.parquet(d).groupBy("k")
                  .agg(F.avg("v").alias("a"))._plan, spark.conf)
    assert any(dg.code == "PLAN-MVIEW-RECOMPUTE"
               for dg in rec.diagnostics)


def test_conf_keys_registered():
    assert CF.MVIEW_ENABLED.key == "spark.tpu.mview.enabled"
    assert CF.MVIEW_ENABLED.default is False
    assert CF.MVIEW_INCREMENTAL.default is True
    assert CF.MVIEW_REFRESH_RETRIES.default == 2
    assert CF.MVIEW_SERVE_REPOPULATE.default is True
    assert "mview.refresh" in faults.POINTS


def test_mview_profile_renders(spark, mview_on, tmp_path):
    from spark_tpu import tracing

    d = str(tmp_path)
    _base(d)
    df = _sum_df(spark, d)
    df.cache()
    try:
        _rows(df)
        _write(d, "delta.parquet", ["k5"], [3])
        _rows(df)
        text = tracing.format_mview_profile()
        assert "incremental" in text
        prof = tracing.mview_profile()
        assert prof["totals"]["incremental_merges"] >= 1
    finally:
        df.unpersist()

"""Native C++ string kernels (spark_tpu/native; reference native-eq
tier: UTF8String.java, codegen'd LIKE in regexpExpressions.scala).

Parity: the C++ matcher must agree byte-for-byte with the pure-Python
dictionary path in expr/compiler.py for every pattern class, including
multibyte UTF-8 ('_' matches one codepoint, not one byte)."""

import random
import string
import time

import numpy as np
import pytest

from spark_tpu import native
from spark_tpu.expr.compiler import _dict_table, _like_to_regex

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain available")


def _py_like(dictionary, pattern):
    rx = _like_to_regex(pattern)
    return _dict_table(dictionary, lambda s: rx.match(s) is not None)


WORDS = ["special", "requests", "green", "BRASS", "yellow metallic",
         "über", "naïve", "日本語テキスト", "", "%literal", "a_b",
         "ends%", "x" * 300]


def _random_dict(n=500, seed=3):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        parts = rng.choices(WORDS + list(string.ascii_lowercase), k=3)
        out.append(rng.choice(["", " "]).join(parts))
    return tuple(out)


@pytest.mark.parametrize("pattern", [
    "%special%requests%", "green%", "%BRASS", "a_b", "_", "%", "",
    "%über%", "日本語%", "____", "%metallic", "x%x", "%a%b%c%",
])
def test_like_parity(pattern):
    d = _random_dict()
    want = _py_like(d, pattern)
    got = native.like_table(d, pattern)
    np.testing.assert_array_equal(got, want)


def test_like_utf8_underscore_counts_codepoints():
    d = ("über", "uber", "ber", "übe", "日本", "日本語")
    # 4 codepoints each for über/uber; 日本 is 2
    np.testing.assert_array_equal(
        native.like_table(d, "____"),
        np.array([True, True, False, False, False, False]))
    np.testing.assert_array_equal(
        native.like_table(d, "__"),
        np.array([False, False, False, False, True, False]))


@pytest.mark.parametrize("op,needle", [
    ("contains", "metal"), ("contains", ""), ("startswith", "gre"),
    ("endswith", "BRASS"), ("startswith", ""), ("endswith", ""),
    ("contains", "über"),
])
def test_predicate_parity(op, needle):
    d = _random_dict()
    fn = {
        "startswith": lambda s: s.startswith(needle),
        "endswith": lambda s: s.endswith(needle),
        "contains": lambda s: needle in s,
    }[op]
    want = _dict_table(d, fn)
    got = native.predicate_table(d, op, needle)
    np.testing.assert_array_equal(got, want)


def test_hash_table64_stable_and_spread():
    d = _random_dict(2000)
    h1 = native.hash_table64(d)
    h2 = native.hash_table64(d)
    np.testing.assert_array_equal(h1, h2)
    # distinct strings overwhelmingly hash apart
    uniq = len(set(d))
    assert len(np.unique(h1)) >= uniq - 2
    assert (native.hash_table64(d, seed=1) != h1).any()


def test_compiler_routes_large_dicts_native(monkeypatch):
    """Above the threshold the compiler uses the C++ table — and the
    answer matches the Python path (engine-level parity on a LIKE)."""
    import spark_tpu.expr.compiler as C

    d = tuple(f"comment {i} special packages" if i % 7 == 0
              else f"regular order {i}" for i in range(3000))
    calls = {"native": 0}
    real = native.like_table

    def spy(dictionary, pattern):
        calls["native"] += 1
        return real(dictionary, pattern)

    monkeypatch.setattr(native, "like_table", spy)
    want = _py_like(d, "%special%")
    got = None
    # go through the engine: dictionary column + LIKE filter
    import pyarrow as pa

    from spark_tpu.api.session import SparkSession

    spark = SparkSession.builder.getOrCreate()
    df = spark.createDataFrame(pa.table({"c": pa.array(list(d))}))
    n = df.filter(df["c"].like("%special%")).count()
    assert n == int(want.sum())
    assert calls["native"] >= 1


def test_native_speedup_smoke():
    """Not a perf assertion, just evidence the path is worth having:
    C++ should not be slower than Python on a big dictionary. Both
    sides take the MEDIAN of 5 interleaved runs (py, cc, py, cc, ...)
    so a scheduler hiccup, a GC pause, or noisy-neighbor load during
    either side's window cannot flake the comparison the way best-of-3
    back-to-back blocks could; a relative-tolerance floor on top makes
    the assertion vacuous when both sides finish so fast the timer
    noise dominates the signal."""
    d = tuple(f"order comment number {i} with padding text" +
              ("special requests" if i % 11 == 0 else "")
              for i in range(50000))

    def timed(fn):
        t0 = time.perf_counter()
        out = fn()
        return out, time.perf_counter() - t0

    py = lambda: _py_like(d, "%special%requests%")  # noqa: E731
    cc = lambda: native.like_table(d, "%special%requests%")  # noqa: E731
    t_pys, t_ccs = [], []
    for _ in range(5):  # interleaved: ambient load hits both sides
        want, t = timed(py)
        t_pys.append(t)
        got, t = timed(cc)
        t_ccs.append(t)
        np.testing.assert_array_equal(got, want)
    t_py = sorted(t_pys)[2]
    t_cc = sorted(t_ccs)[2]
    # 2x slack + a 5ms absolute floor: when both medians sit inside
    # timer/scheduler noise there is no speedup signal to assert on
    assert t_cc < max(t_py * 2, t_py + 0.005), (t_cc, t_py)

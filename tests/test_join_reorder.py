"""Cost-based join reordering (plan/join_reorder.py; reference:
CostBasedJoinReorder.scala:1).

Checks that (a) a fact-first join chain is rewritten to join the small
dimensions first, (b) results are identical with the rule on and off,
(c) out-of-scope shapes (duplicate column names) are left untouched.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.plan.join_reorder import estimate_rows, reorder_joins
from spark_tpu.plan.optimizer import optimize


def _rel(table: pa.Table) -> L.Relation:
    return L.Relation(from_arrow(table))


@pytest.fixture
def star():
    rng = np.random.default_rng(7)
    fact = _rel(pa.table({
        "f_id": pa.array(np.arange(2000), pa.int64()),
        "f_a": pa.array(rng.integers(0, 10, 2000), pa.int64()),
        "f_b": pa.array(rng.integers(0, 5, 2000), pa.int64()),
    }))
    dim_a = _rel(pa.table({
        "a_id": pa.array(np.arange(10), pa.int64()),
        "a_name": pa.array([f"a{i}" for i in range(10)]),
    }))
    dim_b = _rel(pa.table({
        "b_id": pa.array(np.arange(5), pa.int64()),
        "b_name": pa.array([f"b{i}" for i in range(5)]),
    }))
    return fact, dim_a, dim_b


def _chain(fact, dim_a, dim_b) -> L.Join:
    j1 = L.Join(fact, dim_a, "inner", (E.Col("f_a"),), (E.Col("a_id"),))
    return L.Join(j1, dim_b, "inner", (E.Col("f_b"),), (E.Col("b_id"),))


def test_small_relations_join_first(star):
    fact, dim_a, dim_b = star
    plan = reorder_joins(_chain(fact, dim_a, dim_b))
    joins = L.collect_nodes(plan, L.Join)
    assert len(joins) == 2
    inner = joins[-1]  # deepest
    # greedy starts from a small dimension (capacity 1024), not the
    # 2048-row fact the original chain led with
    assert "f_id" not in inner.left.schema.names
    # schema (names + order) preserved for parents
    assert plan.schema.names == _chain(fact, dim_a, dim_b).schema.names


def test_results_identical_on_off(spark, star):
    fact, dim_a, dim_b = star
    from spark_tpu.api.dataframe import DataFrame

    plan = _chain(fact, dim_a, dim_b)
    agg = L.Aggregate(
        (E.Col("a_name"),),
        (E.Col("a_name"), E.Alias(E.Count(None), "n")),
        plan)

    def run():
        rows = DataFrame(spark, agg).collect()
        return sorted((r["a_name"], r["n"]) for r in rows)

    spark.conf.set("spark.sql.cbo.joinReorder.enabled", False)
    try:
        off = run()
    finally:
        spark.conf.set("spark.sql.cbo.joinReorder.enabled", True)
    on = run()
    assert on == off
    assert sum(n for _, n in on) == 2000


def test_duplicate_names_not_reordered():
    t = pa.table({"id": pa.array(np.arange(50), pa.int64()),
                  "v": pa.array(np.arange(50), pa.int64())})
    a, b, c = _rel(t), _rel(t), _rel(t)
    j1 = L.Join(a, b, "inner", (E.Col("id"),), (E.Col("id"),))
    j2 = L.Join(j1, c, "inner", (E.Col("id"),), (E.Col("id"),))
    out = reorder_joins(j2)
    assert out.tree_string() == j2.tree_string()


def test_estimates_exact_at_leaves(star):
    fact, dim_a, dim_b = star
    assert estimate_rows(fact) >= 2000  # capacity-padded
    assert estimate_rows(L.Limit(7, fact)) == 7.0
    filt = L.Filter(E.Col("f_a") == 3, fact)
    assert estimate_rows(filt) < estimate_rows(fact)


def test_optimize_pipeline_applies_reorder(star):
    fact, dim_a, dim_b = star
    plan = optimize(_chain(fact, dim_a, dim_b))
    joins = L.collect_nodes(plan, L.Join)
    assert len(joins) == 2
    assert "f_id" not in joins[-1].left.schema.names

import datetime

import numpy as np
import pyarrow as pa

from spark_tpu import types as T
from spark_tpu.columnar import from_arrow, from_numpy, to_arrow
from spark_tpu.types import Field, Schema


def test_from_numpy_roundtrip():
    schema = Schema((Field("a", T.INT64, False), Field("b", T.FLOAT64, False)))
    batch = from_numpy(schema, [np.arange(10), np.arange(10) * 0.5])
    assert batch.capacity == 1024
    assert batch.num_valid_rows() == 10
    rows = batch.to_pylist()
    assert rows[3] == {"a": 3, "b": 1.5}


def test_arrow_roundtrip_nulls_strings_dates():
    table = pa.table({
        "i": pa.array([1, None, 3], type=pa.int64()),
        "s": pa.array(["x", "y", None], type=pa.string()),
        "d": pa.array([datetime.date(1995, 3, 15), None,
                       datetime.date(1998, 12, 1)], type=pa.date32()),
        "f": pa.array([1.5, 2.5, 3.5], type=pa.float64()),
    })
    batch = from_arrow(table)
    assert batch.schema.field("s").dtype == T.STRING
    assert batch.schema.field("s").dictionary is not None
    rows = batch.to_pylist()
    assert rows[0]["i"] == 1 and rows[1]["i"] is None
    assert rows[0]["s"] == "x" and rows[2]["s"] is None
    assert rows[0]["d"] == datetime.date(1995, 3, 15)
    assert rows[1]["d"] is None

    back = to_arrow(batch)
    assert back.column("i").to_pylist() == [1, None, 3]
    assert back.column("s").to_pylist() == ["x", "y", None]
    assert back.column("d").to_pylist() == [
        datetime.date(1995, 3, 15), None, datetime.date(1998, 12, 1)]


def test_decimal_roundtrips_exact():
    import decimal
    table = pa.table({
        "p": pa.array([decimal.Decimal("12.34"), decimal.Decimal("-56.78"),
                       None],
                      type=pa.decimal128(12, 2)),
    })
    batch = from_arrow(table)
    assert isinstance(batch.schema.field("p").dtype, T.DecimalType)
    rows = batch.to_pylist()
    # scaled-int64 device repr -> EXACT python Decimals back
    assert rows[0]["p"] == decimal.Decimal("12.34")
    assert rows[1]["p"] == decimal.Decimal("-56.78")
    assert rows[2]["p"] is None

"""ML pipeline subset (reference: ml/Pipeline.scala:93 + feature/
regression/classification/clustering suites)."""

import numpy as np
import pytest

from spark_tpu.api import functions as F
from spark_tpu.ml import (KMeans, LinearRegression, LogisticRegression,
                          Pipeline, StandardScaler, StringIndexer)


@pytest.fixture(scope="module")
def reg_df(spark):
    rng = np.random.default_rng(21)
    n = 2000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n) * 3 + 1
    y = 2.5 * x1 - 1.25 * x2 + 0.75 + rng.normal(size=n) * 0.01
    return spark.createDataFrame(
        [{"x1": float(a), "x2": float(b), "y": float(c)}
         for a, b, c in zip(x1, x2, y)])


def test_linear_regression_recovers_coefficients(reg_df):
    model = LinearRegression(["x1", "x2"], "y").fit(reg_df)
    assert model.coefficients[0] == pytest.approx(2.5, abs=0.01)
    assert model.coefficients[1] == pytest.approx(-1.25, abs=0.01)
    assert model.intercept == pytest.approx(0.75, abs=0.01)
    out = model.transform(reg_df)
    diff = F.col("prediction") - F.col("y")
    err = out.select((diff * diff).alias("se"))
    rmse = err.agg(F.avg("se").alias("m")).collect()[0].m ** 0.5
    assert rmse < 0.05


def test_logistic_regression_separates(spark):
    rng = np.random.default_rng(22)
    n = 1000
    x = rng.normal(size=(n, 2))
    label = (x[:, 0] + 2 * x[:, 1] > 0).astype(float)
    df = spark.createDataFrame(
        [{"a": float(r[0]), "b": float(r[1]), "lbl": float(l)}
         for r, l in zip(x, label)])
    model = LogisticRegression(["a", "b"], "lbl", maxIter=300).fit(df)
    out = model.transform(df)
    acc = out.select(
        F.when(F.col("prediction") == F.col("lbl"), 1.0)
        .otherwise(0.0).alias("ok")).agg(F.avg("ok").alias("a")) \
        .collect()[0].a
    assert acc > 0.97


def test_kmeans_three_blobs(spark):
    rng = np.random.default_rng(23)
    centers = np.array([[0.0, 0.0], [10.0, 10.0], [-10.0, 10.0]])
    pts, true = [], []
    for i, c in enumerate(centers):
        blob = rng.normal(size=(150, 2)) * 0.5 + c
        pts.append(blob)
        true.extend([i] * 150)
    pts = np.concatenate(pts)
    df = spark.createDataFrame(
        [{"px": float(p[0]), "py": float(p[1]), "t": t}
         for p, t in zip(pts, true)])
    model = KMeans(["px", "py"], k=3, maxIter=30).fit(df)
    out = model.transform(df).collect()
    # each true blob maps to exactly one predicted cluster
    mapping = {}
    for r in out:
        mapping.setdefault(r.t, set()).add(r.prediction)
    assert all(len(v) == 1 for v in mapping.values())
    assert len({next(iter(v)) for v in mapping.values()}) == 3


def test_pipeline_scaler_indexer_lr(spark):
    rng = np.random.default_rng(24)
    n = 600
    x = rng.normal(size=n) * 7 + 3
    cat = rng.choice(["red", "green", "blue"], size=n,
                     p=[0.5, 0.3, 0.2])
    y = 3 * ((x - 3) / 7) + (cat == "red") * 2.0 + 0.5
    df = spark.createDataFrame(
        [{"x": float(a), "cat": str(c), "y": float(v)}
         for a, c, v in zip(x, cat, y)])
    pipe = Pipeline([
        StandardScaler(["x"]),
        StringIndexer("cat"),
        LinearRegression(["x_scaled", "cat_idx"], "y"),
    ])
    model = pipe.fit(df)
    out = model.transform(df)
    se = out.select(((F.col("prediction") - F.col("y"))
                     * (F.col("prediction") - F.col("y"))).alias("se"))
    mse = se.agg(F.avg("se").alias("m")).collect()[0].m
    # cat-idx is only an ordinal encoding, so fit is approximate but
    # must explain most of the variance
    assert mse < 1.0


def test_string_indexer_frequency_order(spark):
    df = spark.createDataFrame(
        [{"c": v} for v in ["b", "a", "b", "b", "a", "c"]])
    model = StringIndexer("c").fit(df)
    assert model.labels == ["b", "a", "c"]  # by desc frequency
    out = {(r.c, r.c_idx) for r in model.transform(df).collect()}
    assert ("b", 0.0) in out and ("a", 1.0) in out and ("c", 2.0) in out


def _xor_df(spark, n=4000, seed=3):
    """Nonlinear (XOR-ish) data: linear models cap near 50%, trees
    should exceed 90%."""
    import numpy as np

    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = ((x1 > 0) ^ (x2 > 0)).astype(np.float64)
    return spark.createDataFrame(
        [{"x1": float(a), "x2": float(b), "label": float(c)}
         for a, b, c in zip(x1, x2, y)])


def test_decision_tree_beats_logistic_on_xor(spark):
    from spark_tpu.ml import (DecisionTreeClassifier, LogisticRegression,
                              MulticlassClassificationEvaluator)

    df = _xor_df(spark)
    ev = MulticlassClassificationEvaluator(labelCol="label")
    tree = DecisionTreeClassifier(["x1", "x2"], "label", maxDepth=4)
    tree_acc = ev.evaluate(tree.fit(df).transform(df))
    lr = LogisticRegression(["x1", "x2"], "label", maxIter=100)
    lr_acc = ev.evaluate(lr.fit(df).transform(df))
    assert tree_acc > 0.9, tree_acc
    assert tree_acc > lr_acc + 0.2, (tree_acc, lr_acc)


def test_random_forest_regression(spark):
    import numpy as np

    from spark_tpu.ml import RandomForestRegressor, RegressionEvaluator

    rng = np.random.default_rng(5)
    n = 3000
    x = rng.uniform(-2, 2, size=(n, 2))
    y = np.sin(x[:, 0]) * 2 + np.where(x[:, 1] > 0, 3.0, -3.0)
    df = spark.createDataFrame(
        [{"a": float(r[0]), "b": float(r[1]), "label": float(t)}
         for r, t in zip(x, y)])
    rf = RandomForestRegressor(["a", "b"], "label", numTrees=10,
                               maxDepth=5, featureSubsetStrategy=1.0)
    pred = rf.fit(df).transform(df)
    rmse = RegressionEvaluator(labelCol="label").evaluate(pred)
    assert rmse < 1.0, rmse  # label std is ~3.3: the forest must learn


def test_cross_validator_picks_deeper_tree(spark):
    from spark_tpu.ml import (CrossValidator, DecisionTreeClassifier,
                              MulticlassClassificationEvaluator,
                              ParamGridBuilder)

    df = _xor_df(spark, n=2500)
    tree = DecisionTreeClassifier(["x1", "x2"], "label")
    grid = (ParamGridBuilder()
            .addGrid("max_depth", [1, 4])
            .build())
    cv = CrossValidator(tree, grid,
                        MulticlassClassificationEvaluator(
                            labelCol="label"),
                        numFolds=3)
    model = cv.fit(df)
    # depth 1 cannot represent XOR; CV must pick depth 4
    assert model.bestParams == {"max_depth": 4}, (
        model.bestParams, cv.avg_metrics)
    acc = MulticlassClassificationEvaluator(labelCol="label").evaluate(
        model.transform(df))
    assert acc > 0.9, acc
